"""Prometheus-compatible metric primitives + text-format registry.

Replaces the prometheus client_golang dependency (reference
pkg/metrics/registry/registry.go, types/ttl/gauge.go) with a small
threadsafe implementation that renders the v0 text exposition format.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Optional, Sequence

DEFAULT_DURATION_BUCKETS = (0.5, 1, 5, 10, 50, 100, 150, 200, 250, 300, 350, 400, 600, 1000)


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == int(v):
        return str(int(v))
    return repr(v)


def _fmt_labels(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
        for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()

    def render(self) -> str:
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        super().__init__(name, help_, label_names)
        self._values: dict[tuple, float] = {}

    def labels(self, *values: str) -> "_CounterChild":
        if len(values) != len(self.label_names):
            raise ValueError(f"{self.name}: expected {len(self.label_names)} labels")
        return _CounterChild(self, tuple(str(v) for v in values))

    def inc(self, amount: float = 1.0) -> None:
        if self.label_names:
            raise ValueError(f"{self.name}: labelled counter needs .labels(...)")
        self._inc((), amount)

    def _inc(self, key: tuple, amount: float) -> None:
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, *values: str) -> float:
        with self._lock:
            return self._values.get(tuple(str(v) for v in values), 0.0)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = sorted(self._values.items()) or ([((), 0.0)] if not self.label_names else [])
        for key, val in items:
            lines.append(f"{self.name}{_fmt_labels(self.label_names, key)} {_fmt_value(val)}")
        return "\n".join(lines)


class _CounterChild:
    def __init__(self, parent: Counter, key: tuple):
        self._parent = parent
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        self._parent._inc(self._key, amount)


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        super().__init__(name, help_, label_names)
        self._values: dict[tuple, float] = {}

    def labels(self, *values: str) -> "_GaugeChild":
        if len(values) != len(self.label_names):
            raise ValueError(f"{self.name}: expected {len(self.label_names)} labels")
        return _GaugeChild(self, tuple(str(v) for v in values))

    def set(self, value: float) -> None:
        self._set((), value)

    def _set(self, key: tuple, value: float) -> None:
        with self._lock:
            self._values[key] = float(value)

    def value(self, *values: str) -> Optional[float]:
        with self._lock:
            return self._values.get(tuple(str(v) for v in values))

    def remove(self, *values: str) -> None:
        with self._lock:
            self._values.pop(tuple(str(v) for v in values), None)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = sorted(self._values.items()) or ([((), 0.0)] if not self.label_names else [])
        for key, val in items:
            lines.append(f"{self.name}{_fmt_labels(self.label_names, key)} {_fmt_value(val)}")
        return "\n".join(lines)


class _GaugeChild:
    def __init__(self, parent: Gauge, key: tuple):
        self._parent = parent
        self._key = key

    def set(self, value: float) -> None:
        self._parent._set(self._key, value)


class TTLGauge(Gauge):
    """Gauge whose series expire `ttl` seconds after their last set —
    daemon-event style metrics vanish when the daemon stops reporting
    (reference types/ttl/gauge.go)."""

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = (), ttl_sec: float = 120.0,
                 clock=time.monotonic):
        super().__init__(name, help_, label_names)
        self.ttl = ttl_sec
        self._clock = clock
        self._stamps: dict[tuple, float] = {}

    def _set(self, key: tuple, value: float) -> None:
        with self._lock:
            self._values[key] = float(value)
            self._stamps[key] = self._clock()

    def _expire(self) -> None:
        now = self._clock()
        for key in [k for k, t in self._stamps.items() if now - t > self.ttl]:
            self._stamps.pop(key, None)
            self._values.pop(key, None)

    def render(self) -> str:
        with self._lock:
            self._expire()
        return super().render()


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_DURATION_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}

    def labels(self, *values: str) -> "_HistChild":
        if len(values) != len(self.label_names):
            raise ValueError(f"{self.name}: expected {len(self.label_names)} labels")
        return _HistChild(self, tuple(str(v) for v in values))

    def observe(self, value: float) -> None:
        self._observe((), value)

    def _observe(self, key: tuple, value: float) -> None:
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def cumulative_le(self, threshold: float) -> dict[tuple, tuple[int, int]]:
        """{label_values: (observations in buckets <= threshold, total)}.

        ``threshold`` is resolved to the smallest bucket upper bound that
        is >= it (the SLO engine aligns thresholds to bucket boundaries);
        past the last bucket every observation qualifies.
        """
        idx = None
        for i, ub in enumerate(self.buckets):
            if threshold <= ub:
                idx = i
                break
        with self._lock:
            out = {}
            for key, counts in self._counts.items():
                total = self._totals.get(key, 0)
                good = total if idx is None else counts[idx]
                out[key] = (good, total)
            return out

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            keys = sorted(self._counts)
            for key in keys:
                cum = 0
                for i, ub in enumerate(self.buckets):
                    cum = self._counts[key][i]
                    lines.append(
                        f"{self.name}_bucket"
                        f"{_fmt_labels(tuple(self.label_names) + ('le',), key + (_fmt_value(ub),))} {cum}"
                    )
                lines.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels(tuple(self.label_names) + ('le',), key + ('+Inf',))} {self._totals[key]}"
                )
                lines.append(f"{self.name}_sum{_fmt_labels(self.label_names, key)} {_fmt_value(self._sums[key])}")
                lines.append(f"{self.name}_count{_fmt_labels(self.label_names, key)} {self._totals[key]}")
        return "\n".join(lines)


class _HistChild:
    def __init__(self, parent: Histogram, key: tuple):
        self._parent = parent
        self._key = key

    def observe(self, value: float) -> None:
        self._parent._observe(self._key, value)

    def time_ms(self):
        """Context manager observing elapsed milliseconds (the
        NewSnapshotMetricsTimer pattern wrapping snapshotter methods)."""
        return _Timer(self)


class _Timer:
    def __init__(self, child: _HistChild):
        self._child = child

    def __enter__(self):
        self._start = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._child.observe((time.monotonic() - self._start) * 1000.0)
        return False


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                return existing
            self._metrics[metric.name] = metric
            return metric

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        return "\n".join(m.render() for m in metrics) + "\n"


default_registry = Registry()
