"""Metrics server: periodic collection + /v1/metrics HTTP listener.

Reference pkg/metrics/serve.go:44-189 + listener.go:32-53. Collection
cadence: 1 minute for snapshotter/fs/daemon collectors, 10 seconds for
inflight-hung IO.
"""

from __future__ import annotations

import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterable, Optional

from nydus_snapshotter_tpu.analysis import runtime as _an
from nydus_snapshotter_tpu.metrics import data
from nydus_snapshotter_tpu.metrics.collector import (
    DaemonResourceCollector,
    FsMetricsCollector,
    InflightMetricsCollector,
    SnapshotterMetricsCollector,
)
from nydus_snapshotter_tpu.metrics.registry import Registry, default_registry

logger = logging.getLogger(__name__)

COLLECT_INTERVAL_SEC = 60.0
INFLIGHT_INTERVAL_SEC = 10.0


class MetricsServer:
    def __init__(
        self,
        managers: Iterable = (),
        cache_dir: str = "",
        registry: Optional[Registry] = None,
        collect_interval_sec: float = COLLECT_INTERVAL_SEC,
        inflight_interval_sec: float = INFLIGHT_INTERVAL_SEC,
    ):
        managers = list(managers)
        self.registry = registry or default_registry
        self.sn_collector = SnapshotterMetricsCollector(cache_dir)
        self.fs_collector = FsMetricsCollector(managers)
        self.daemon_collector = DaemonResourceCollector(managers)
        self.inflight_collector = InflightMetricsCollector(managers)
        self._collect_interval = collect_interval_sec
        self._inflight_interval = inflight_interval_sec
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._httpd: Optional[ThreadingHTTPServer] = None
        # Cached collect_once+render snapshot (see snapshot()): the fleet
        # scoreboard and other summary consumers share ONE collection
        # round per max-age window instead of re-running the collectors
        # inline per request.
        self._snap_lock = _an.make_lock("metrics.snapshot")
        self._snap_text = ""
        self._snap_time = -1.0e18
        self._snap_refreshing = False

    def snapshot(self, max_age_sec: float = 5.0) -> tuple[str, float]:
        """(rendered registry text, age in seconds) from a cached
        collection round at most ``max_age_sec`` old.

        At most one caller refreshes at a time, and the collectors run
        OUTSIDE the cache lock: while a refresh is in flight (a slow
        collector, a hung daemon RPC), every concurrent caller gets the
        previous snapshot immediately instead of queueing behind it.
        """
        now = time.monotonic()
        with self._snap_lock:
            age = now - self._snap_time
            if age <= max_age_sec or self._snap_refreshing:
                return self._snap_text, max(0.0, age)
            self._snap_refreshing = True
        try:
            self.collect_once()
            text = self.registry.render()
        finally:
            with self._snap_lock:
                self._snap_refreshing = False
        with self._snap_lock:
            self._snap_text = text
            self._snap_time = time.monotonic()
            return self._snap_text, 0.0

    def collect_once(self) -> None:
        # Per-collector isolation: one failing collector must not skip the
        # remaining ones, and each failure is counted per collector so a
        # broken collector is visible on the exposition, not just the log.
        # Each round is also timed per collector: a collector sliding
        # toward the federation deadline shows up in
        # ntpu_metrics_collector_seconds long before it wedges a round.
        for name, c in (
            ("snapshotter", self.sn_collector),
            ("fs", self.fs_collector),
            ("daemon", self.daemon_collector),
        ):
            t0 = time.perf_counter()
            try:
                c.collect()
            except Exception:
                data.MetricsCollectionErrors.labels(name).inc()
                logger.exception("metrics collection failed (collector=%s)", name)
            finally:
                data.CollectorSeconds.labels(name).observe(
                    time.perf_counter() - t0
                )

    def _collect_loop(self) -> None:
        while not self._stop.wait(self._collect_interval):
            self.collect_once()

    def _inflight_loop(self) -> None:
        while not self._stop.wait(self._inflight_interval):
            t0 = time.perf_counter()
            try:
                self.inflight_collector.collect()
            except Exception:
                data.MetricsCollectionErrors.labels("inflight").inc()
                logger.exception("inflight metrics collection failed")
            finally:
                data.CollectorSeconds.labels("inflight").observe(
                    time.perf_counter() - t0
                )

    def start_collecting(self) -> None:
        for fn in (self._collect_loop, self._inflight_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)

    # -- HTTP listener (listener.go:32-53) ------------------------------------

    def serve(self, addr: str) -> ThreadingHTTPServer:
        """Start the /v1/metrics listener on ``host:port``; returns the
        running server."""
        host, _, port = addr.rpartition(":")
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path not in ("/v1/metrics", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = server.registry.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port)), Handler)
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        return self._httpd

    @property
    def address(self) -> str:
        assert self._httpd is not None
        host, port = self._httpd.server_address[:2]
        return f"{host}:{port}"

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
