"""Fleet metrics federation: scrape every member, one namespaced view.

Each process in the deployment (snapshotter, spawned daemons, standalone
dict services, peer servers) keeps its own in-process metrics registry.
This module gives the system controller one cluster-wide view:

- :class:`FleetFederator` scrapes every registered member's ``/metrics``
  endpoint on a timer (``[fleet] scrape_interval_secs``), keeps the last
  good exposition per member, and re-serves the union on
  ``/api/v1/fleet/metrics`` with ``node``/``component`` labels injected
  into every series — Prometheus federation semantics without the
  Prometheus server;
- a **health scoreboard** (:meth:`FleetFederator.scoreboard`) derives the
  operational ratios an operator actually pages on — blobcache hit rate,
  readahead accuracy, peer egress ratio, dict RPC health, QoS admission
  queue depths, host-health cooldowns — per member, from the scraped
  samples;
- **degradation over wedging**: a member that dies mid-scrape is marked
  unreachable/stale (``ntpu_fleet_member_up``, ``stale`` flags in the
  scoreboard) and its last-good series age out of the view; the scrape
  loop and the serving endpoints never propagate the failure
  (``ntpu_fleet_scrape_errors_total{member}`` counts it instead). The
  ``fleet.scrape`` failpoint injects exactly this failure mode in chaos
  tests.

The local (controller) process is itself a member: its "scrape" goes
through the metrics server's cached ``collect_once`` snapshot
(:meth:`MetricsServer.snapshot`), so serving the scoreboard never runs
the collectors inline per request.
"""

from __future__ import annotations

import logging
import re
import time
from typing import Callable, Iterable, Optional

from nydus_snapshotter_tpu import failpoint
from nydus_snapshotter_tpu.analysis import runtime as _an
from nydus_snapshotter_tpu.metrics import registry as _metrics
from nydus_snapshotter_tpu.remote import mirror as mirror_mod
from nydus_snapshotter_tpu.utils import udshttp

logger = logging.getLogger(__name__)

_reg = _metrics.default_registry

FLEET_MEMBERS = _reg.register(
    _metrics.Gauge(
        "ntpu_fleet_members",
        "Members currently registered with the fleet plane, per component",
        ("component",),
    )
)
FLEET_SCRAPES = _reg.register(
    _metrics.Counter(
        "ntpu_fleet_scrapes_total", "Completed fleet federation scrape rounds"
    )
)
FLEET_SCRAPE_ERRORS = _reg.register(
    _metrics.Counter(
        "ntpu_fleet_scrape_errors_total",
        "Per-member scrape/trace-pull failures; a dead member degrades the "
        "scoreboard instead of wedging the round",
        ("member",),
    )
)
FLEET_MEMBER_UP = _reg.register(
    _metrics.Gauge(
        "ntpu_fleet_member_up",
        "1 when the member's last scrape succeeded, 0 when it is unreachable",
        ("member",),
    )
)
FLEET_SCRAPE_MS = _reg.register(
    _metrics.Histogram(
        "ntpu_fleet_scrape_duration_milliseconds",
        "Wall time of one full federation scrape round across all members",
    )
)

METRICS_PATH = "/metrics"

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+([^ ]+)\s*$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str) -> dict[str, list[tuple[dict, float]]]:
    """Prometheus text exposition → {metric: [(labels, value), ...]}.

    Tolerant by design: unparseable lines are skipped (a member running
    a newer build must not break the whole federation round).
    """
    out: dict[str, list[tuple[dict, float]]] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, _, labelstr, raw = m.groups()
        labels = {
            k: v.replace('\\"', '"').replace("\\\\", "\\")
            for k, v in _LABEL_RE.findall(labelstr or "")
        }
        try:
            value = float(raw)
        except ValueError:
            continue
        out.setdefault(name, []).append((labels, value))
    return out


def _inject_labels(text: str, extra: dict[str, str]) -> str:
    """Re-emit an exposition with ``extra`` labels on every sample line.
    Comment (# HELP/# TYPE) lines pass through unchanged."""
    prefix = ",".join(f'{k}="{v}"' for k, v in extra.items())
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            out.append(line)
            continue
        name, _, labelstr, raw = m.groups()
        inner = f"{prefix},{labelstr}" if labelstr else prefix
        out.append(f"{name}{{{inner}}} {raw}")
    return "\n".join(out)


def _sum(samples: dict, metric: str, labels: Optional[dict] = None) -> Optional[float]:
    rows = samples.get(metric)
    if rows is None:
        return None
    total = 0.0
    for lab, v in rows:
        if labels is not None and any(lab.get(k) != v2 for k, v2 in labels.items()):
            continue
        total += v
    return total


def _by_label(samples: dict, metric: str, label: str) -> dict[str, float]:
    rows = samples.get(metric) or ()
    out: dict[str, float] = {}
    for lab, v in rows:
        key = lab.get(label, "")
        out[key] = out.get(key, 0.0) + v
    return out


def _ratio(num: Optional[float], den: Optional[float]) -> Optional[float]:
    if num is None or not den:
        return None
    return round(num / den, 4)


class _MemberState:
    __slots__ = ("text", "samples", "last_ok", "last_err", "ok")

    def __init__(self):
        self.text = ""
        self.samples: dict = {}
        self.last_ok = 0.0
        self.last_err = ""
        self.ok = False


class FleetFederator:
    """Scrapes members, serves the federated exposition + scoreboard.

    ``members`` is a callable returning the current registry listing
    (duck-typed: ``name``/``component``/``address``/``pid``/``local``/
    ``registered_at``), so this module needs no import of the registry.
    ``local_metrics`` renders the controller process's own exposition —
    wired to :meth:`MetricsServer.snapshot` when a metrics server runs,
    ``default_registry.render`` otherwise.
    """

    def __init__(
        self,
        members: Callable[[], Iterable],
        local_metrics: Callable[[], str],
        stale_after_secs: float = 45.0,
        timeout_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._members = members
        self._local_metrics = local_metrics
        self.stale_after = float(stale_after_secs)
        self.timeout_s = timeout_s
        self._clock = clock
        self._lock = _an.make_lock("fleet.federation")
        self._state_shared = _an.shared("fleet.federation.state")
        self._state: dict[str, _MemberState] = {}
        self._seen_components: set[str] = set()

    # -- scraping ------------------------------------------------------------

    def _fetch_member(self, member) -> str:
        failpoint.hit("fleet.scrape")
        if member.local:
            return self._local_metrics()
        status, body = udshttp.request(
            member.address, METRICS_PATH, timeout=self.timeout_s
        )
        if status != 200:
            raise OSError(f"{member.address} {METRICS_PATH} -> {status}")
        return body.decode("utf-8", "replace")

    def scrape_once(self) -> dict:
        """One federation round over the current member list. Per-member
        isolation: a failing member is flagged and counted, never raised."""
        t0 = time.perf_counter()
        members = list(self._members())
        counts: dict[str, int] = {}
        errors = 0
        live = set()
        for member in members:
            counts[member.component] = counts.get(member.component, 0) + 1
            live.add(member.name)
            try:
                text = self._fetch_member(member)
                samples = parse_exposition(text)
            except Exception as e:  # noqa: BLE001 — degradation is the contract
                errors += 1
                FLEET_SCRAPE_ERRORS.labels(member.name).inc()
                FLEET_MEMBER_UP.labels(member.name).set(0)
                with self._lock:
                    self._state_shared.write()
                    st = self._state.setdefault(member.name, _MemberState())
                    st.ok = False
                    st.last_err = str(e)
                logger.warning("fleet scrape of %s failed: %s", member.name, e)
                continue
            FLEET_MEMBER_UP.labels(member.name).set(1)
            with self._lock:
                self._state_shared.write()
                st = self._state.setdefault(member.name, _MemberState())
                st.text = text
                st.samples = samples
                st.last_ok = self._clock()
                st.last_err = ""
                st.ok = True
        with self._lock:
            self._state_shared.write()
            for name in [n for n in self._state if n not in live]:
                del self._state[name]
                FLEET_MEMBER_UP.remove(name)
        for comp in self._seen_components - set(counts):
            FLEET_MEMBERS.labels(comp).set(0)
        self._seen_components |= set(counts)
        for comp, n in counts.items():
            FLEET_MEMBERS.labels(comp).set(n)
        FLEET_SCRAPES.inc()
        FLEET_SCRAPE_MS.observe((time.perf_counter() - t0) * 1000.0)
        return {"members": len(members), "errors": errors}

    def _snapshot(self) -> dict[str, _MemberState]:
        with self._lock:
            self._state_shared.read()
            return dict(self._state)

    # -- exports -------------------------------------------------------------

    def render(self) -> str:
        """The federated exposition: every member's last good scrape with
        ``node``/``component`` labels injected. Stale members' series stay
        visible (flagged by ntpu_fleet_member_up / the scoreboard) so a
        flapping member doesn't blink its history away."""
        state = self._snapshot()
        members = {m.name: m for m in self._members()}
        parts = []
        for name in sorted(state):
            member = members.get(name)
            st = state[name]
            if member is None or not st.text:
                continue
            parts.append(
                _inject_labels(
                    st.text, {"node": name, "component": member.component}
                )
            )
        return "\n".join(parts) + "\n"

    def member_samples(self) -> dict[str, dict]:
        """{member: parsed samples} of the last good scrape per member —
        the SLO engine's federated histogram source."""
        return {name: st.samples for name, st in self._snapshot().items() if st.ok or st.samples}

    def liveness(self) -> dict[str, dict]:
        """{member: {"up", "stale", "age_s"}} from the last scrape round —
        the cheap health view dynamic peer membership routes on (a member
        never scraped yet is up-but-stale-unknown: treated live so a peer
        racing the first scrape round isn't shunned at birth)."""
        now = self._clock()
        out: dict[str, dict] = {}
        for name, st in self._snapshot().items():
            age = (now - st.last_ok) if st.last_ok else None
            out[name] = {
                "up": st.ok,
                "stale": (not st.ok) or (age is not None and age > self.stale_after),
                "age_s": None if age is None else round(age, 3),
            }
        return out

    def scoreboard(self) -> dict:
        """Derived per-member health view. Every field is best-effort:
        a ratio whose inputs a member doesn't export is None, a member
        that stopped answering is carried with ``up: false`` and its
        last-good numbers — degraded, never absent."""
        now = self._clock()
        state = self._snapshot()
        members = sorted(self._members(), key=lambda m: m.name)
        rows = {}
        seen_pids: set[int] = set()
        up = stale = 0
        for member in members:
            st = state.get(member.name) or _MemberState()
            s = st.samples
            age = (now - st.last_ok) if st.last_ok else (now - member.registered_at)
            is_stale = (not st.ok) or age > self.stale_after
            up += 1 if st.ok else 0
            stale += 1 if is_stale else 0
            hit = _sum(s, "ntpu_blobcache_hit_bytes")
            miss = _sum(s, "ntpu_blobcache_miss_bytes")
            ra = _sum(s, "ntpu_blobcache_readahead_bytes")
            ra_hit = _sum(s, "ntpu_blobcache_readahead_hit_bytes")
            served = _sum(s, "ntpu_peer_served_bytes")
            fetched = _sum(s, "ntpu_peer_fetch_bytes")
            duplicate = member.pid in seen_pids
            seen_pids.add(member.pid)
            rows[member.name] = {
                "component": member.component,
                "address": member.address,
                "pid": member.pid,
                "up": st.ok,
                "stale": is_stale,
                "age_s": round(age, 3),
                "last_err": st.last_err,
                # Two registrations from one OS process (e.g. a daemon
                # that also runs a peer server) share counters; fleet
                # aggregates must count the pid once.
                "duplicate_pid": duplicate,
                "scrape_errors": FLEET_SCRAPE_ERRORS.value(member.name),
                "cache": {
                    "hit_bytes": hit,
                    "miss_bytes": miss,
                    "hit_rate": _ratio(hit, (hit or 0) + (miss or 0)),
                    "readahead_accuracy": _ratio(ra_hit, ra),
                    "evicted_bytes": _sum(s, "ntpu_blobcache_evicted_bytes"),
                },
                "peer": {
                    "served_bytes": served,
                    "fetched_bytes": fetched,
                    # Peer-tier leverage: bytes this node served peers per
                    # byte it pulled from peers itself.
                    "egress_ratio": _ratio(served, fetched),
                    "fallbacks": _sum(s, "ntpu_peer_fetch_fallbacks"),
                },
                "dict": {
                    "rpcs": _sum(s, "ntpu_dict_rpc_total"),
                    "rpc_errors": _sum(s, "ntpu_dict_rpc_errors_total"),
                    "insert_entries": _sum(s, "ntpu_dict_insert_entries"),
                    "rebuilds": _sum(s, "ntpu_dict_rebuilds"),
                },
                "admission": {
                    "queued": _by_label(s, "ntpu_admission_queued", "lane"),
                    "tenant_inflight_bytes": _by_label(
                        s, "ntpu_admission_tenant_inflight_bytes", "tenant"
                    ),
                },
                "traces": {
                    "spans_total": _sum(s, "ntpu_trace_spans_total"),
                    "dropped": _sum(s, "ntpu_trace_dropped_spans_total"),
                    "slow_ops": _sum(s, "ntpu_trace_slow_ops_total"),
                },
            }
        # Host-health cooldowns are in-process state (no exported series):
        # report the controller process's shared table — every component in
        # this process (mirrors, lazy-read fetcher, peer router) scores
        # through it.
        cooldowns = {
            host: h
            for host, h in mirror_mod.global_health_registry().snapshot().items()
            if not h["available"]
        }
        return {
            "members": rows,
            "fleet": {
                "registered": len(members),
                "up": up,
                "stale": stale,
                "host_cooldowns": cooldowns,
            },
        }
