"""Mount-slice synthesis.

Reference snapshot/snapshot.go:825-985 (bind/overlay/proxy/remote mounts)
and snapshot/mount_option.go (``extraoption=`` base64 payloads, Kata
virtual-volume encoding with its 8 volume types, dm-verity validation).
"""

from __future__ import annotations

import base64
import json
import re
from dataclasses import dataclass, field
from typing import Mapping, Optional

from nydus_snapshotter_tpu import constants as C
from nydus_snapshotter_tpu.utils import errdefs

KATA_VOLUME_DEFAULT_SOURCE = "overlay"
KATA_VOLUME_DUMMY_SOURCE = "dummy-image-reference"
KATA_VOLUME_OPTION_NAME = "io.katacontainers.volume"

# Kata virtual volume types (mount_option.go:310-320)
KATA_DIRECT_BLOCK = "direct_block"
KATA_IMAGE_RAW_BLOCK = "image_raw_block"
KATA_LAYER_RAW_BLOCK = "layer_raw_block"
KATA_IMAGE_NYDUS_BLOCK = "image_nydus_block"
KATA_LAYER_NYDUS_BLOCK = "layer_nydus_block"
KATA_IMAGE_NYDUS_FS = "image_nydus_fs"
KATA_LAYER_NYDUS_FS = "layer_nydus_fs"
KATA_IMAGE_GUEST_PULL = "image_guest_pull"

_KATA_VOLUME_TYPES = (
    KATA_DIRECT_BLOCK,
    KATA_IMAGE_RAW_BLOCK,
    KATA_LAYER_RAW_BLOCK,
    KATA_IMAGE_NYDUS_BLOCK,
    KATA_LAYER_NYDUS_BLOCK,
    KATA_IMAGE_NYDUS_FS,
    KATA_LAYER_NYDUS_FS,
    KATA_IMAGE_GUEST_PULL,
)

_MIN_BLOCK_SIZE = 1 << 9
_MAX_BLOCK_SIZE = 1 << 19


@dataclass
class Mount:
    """One containerd mount entry (type/source/options)."""

    type: str
    source: str
    options: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"type": self.type, "source": self.source, "options": list(self.options)}


def bind_mount(source: str, ro_flag: str) -> list[Mount]:
    return [Mount(type="bind", source=source, options=[ro_flag, "rbind"])]


def overlay_mount(options: list[str]) -> list[Mount]:
    return [Mount(type="overlay", source="overlay", options=list(options))]


@dataclass
class ExtraOption:
    """The ``extraoption=`` payload consumed by the nydus-overlayfs mount
    helper (mount_option.go:35-40): bootstrap path, full daemon config,
    snapshot dir, and RAFS version."""

    source: str
    config: str
    snapshotdir: str
    fs_version: str

    def encode(self) -> str:
        payload = json.dumps(
            {
                "source": self.source,
                "config": self.config,
                "snapshotdir": self.snapshotdir,
                "fs_version": self.fs_version,
            }
        )
        return "extraoption=" + base64.b64encode(payload.encode()).decode()

    @classmethod
    def decode(cls, option: str) -> "ExtraOption":
        if not option.startswith("extraoption="):
            raise errdefs.InvalidArgument("not an extraoption mount option")
        d = json.loads(base64.b64decode(option[len("extraoption=") :]))
        return cls(
            source=d["source"],
            config=d["config"],
            snapshotdir=d["snapshotdir"],
            fs_version=d["fs_version"],
        )


def _validate_block_size(size: int) -> bool:
    return _MIN_BLOCK_SIZE <= size <= _MAX_BLOCK_SIZE and (size & (size - 1)) == 0


@dataclass
class DmVerityInfo:
    """Dm-verity configuration (mount_option.go:326-420)."""

    hashtype: str = "sha256"
    hash: str = ""
    blocknum: int = 0
    blocksize: int = 512
    hashsize: int = 4096
    offset: int = 0

    def validate(self) -> None:
        ht = self.hashtype.lower()
        want_len = {"sha256": 64, "sha1": 40}.get(ht)
        if want_len is None:
            raise errdefs.InvalidArgument(f"unsupported dm-verity hash algorithm {self.hashtype}")
        if len(self.hash) != want_len or not re.fullmatch(r"[0-9a-fA-F]+", self.hash or "x"):
            raise errdefs.InvalidArgument(f"invalid {ht} hash {self.hash!r}")
        if self.blocknum == 0 or self.blocknum > 0xFFFFFFFF:
            raise errdefs.InvalidArgument(f"zero block count for dm-verity device {self.hash}")
        if not _validate_block_size(self.blocksize) or not _validate_block_size(self.hashsize):
            raise errdefs.InvalidArgument(
                f"unsupported verity block size: data={self.blocksize} hash={self.hashsize}"
            )
        if self.offset % self.hashsize != 0 or self.offset < self.blocksize * self.blocknum:
            raise errdefs.InvalidArgument(
                f"invalid hash offset {self.offset} for dm-verity device {self.hash}"
            )

    def to_dict(self) -> dict:
        return {
            "hashtype": self.hashtype,
            "hash": self.hash,
            "blocknum": self.blocknum,
            "blocksize": self.blocksize,
            "hashsize": self.hashsize,
            "offset": self.offset,
        }


def parse_tarfs_dm_verity(info: str) -> DmVerityInfo:
    """Parse the `"<datablocks>,<hashoffset>,sha256:<roothash>"` string the
    tarfs exporter emits (mount_option.go:281-303)."""
    m = re.fullmatch(r"(\d+),(\d+),sha256:([0-9a-fA-F]+)", info.strip())
    if not m:
        raise errdefs.InvalidArgument(f"invalid dm-verity information: {info!r}")
    di = DmVerityInfo(
        hashtype="sha256",
        hash=m.group(3),
        blocknum=int(m.group(1)),
        blocksize=512,
        hashsize=4096,
        offset=int(m.group(2)),
    )
    di.validate()
    return di


@dataclass
class ImagePullVolume:
    metadata: dict[str, str] = field(default_factory=dict)


@dataclass
class NydusImageVolume:
    config: str = ""
    snapshot_dir: str = ""


@dataclass
class KataVirtualVolume:
    """Kata virtual-volume descriptor passed through mount options
    (mount_option.go:422-476)."""

    volume_type: str
    source: str = ""
    fs_type: str = ""
    options: list[str] = field(default_factory=list)
    dm_verity: Optional[DmVerityInfo] = None
    image_pull: Optional[ImagePullVolume] = None
    nydus_image: Optional[NydusImageVolume] = None

    def validate(self) -> bool:
        if self.volume_type not in _KATA_VOLUME_TYPES:
            return False
        if self.volume_type in (
            KATA_DIRECT_BLOCK,
            KATA_IMAGE_RAW_BLOCK,
            KATA_LAYER_RAW_BLOCK,
        ):
            if not self.source:
                return False
            if self.dm_verity is not None:
                try:
                    self.dm_verity.validate()
                except errdefs.InvalidArgument:
                    return False
            return True
        if self.volume_type in (KATA_IMAGE_NYDUS_BLOCK, KATA_LAYER_NYDUS_BLOCK):
            return bool(self.source) and self.nydus_image is not None
        if self.volume_type in (KATA_IMAGE_NYDUS_FS, KATA_LAYER_NYDUS_FS):
            return bool(self.source)
        if self.volume_type == KATA_IMAGE_GUEST_PULL:
            return self.image_pull is not None
        return False

    def to_dict(self) -> dict:
        d: dict = {"volume_type": self.volume_type, "source": self.source}
        if self.fs_type:
            d["fs_type"] = self.fs_type
        if self.options:
            d["options"] = list(self.options)
        if self.dm_verity is not None:
            d["dm_verity"] = self.dm_verity.to_dict()
        if self.image_pull is not None:
            d["image_pull"] = {"metadata": dict(self.image_pull.metadata)}
        if self.nydus_image is not None:
            d["nydus_image"] = {
                "config": self.nydus_image.config,
                "snapshot_dir": self.nydus_image.snapshot_dir,
            }
        return d

    def encode_option(self) -> str:
        if not self.validate():
            raise errdefs.InvalidArgument(f"invalid kata volume {self.to_dict()}")
        b64 = base64.b64encode(json.dumps(self.to_dict()).encode()).decode()
        return f"{KATA_VOLUME_OPTION_NAME}={b64}"

    @classmethod
    def decode_option(cls, option: str) -> "KataVirtualVolume":
        prefix = KATA_VOLUME_OPTION_NAME + "="
        if not option.startswith(prefix):
            raise errdefs.InvalidArgument("not a kata volume mount option")
        d = json.loads(base64.b64decode(option[len(prefix) :]))
        vol = cls(
            volume_type=d["volume_type"],
            source=d.get("source", ""),
            fs_type=d.get("fs_type", ""),
            options=list(d.get("options", [])),
        )
        if "dm_verity" in d:
            vol.dm_verity = DmVerityInfo(**d["dm_verity"])
        if "image_pull" in d:
            vol.image_pull = ImagePullVolume(metadata=dict(d["image_pull"].get("metadata", {})))
        if "nydus_image" in d:
            vol.nydus_image = NydusImageVolume(
                config=d["nydus_image"].get("config", ""),
                snapshot_dir=d["nydus_image"].get("snapshot_dir", ""),
            )
        return vol


def prepare_kata_virtual_volume(
    block_type: str,
    source: str,
    volume_type: str,
    fs_type: str,
    options: list[str],
    labels: Mapping[str, str],
) -> str:
    """Build the encoded kata-volume option for a block/proxy mount
    (mount_option.go:250-279)."""
    vol = KataVirtualVolume(
        volume_type=volume_type, source=source, fs_type=fs_type, options=list(options)
    )
    if block_type in (C.NYDUS_IMAGE_BLOCK_INFO, C.NYDUS_LAYER_BLOCK_INFO):
        info = labels.get(block_type, "")
        if info:
            vol.dm_verity = parse_tarfs_dm_verity(info)
    elif block_type == C.NYDUS_PROXY_MODE:
        vol.image_pull = ImagePullVolume(metadata=dict(labels))
    return vol.encode_option()
