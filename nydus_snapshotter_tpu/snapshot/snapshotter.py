"""The snapshotter core (reference snapshot/snapshot.go:64-1090).

Implements the containerd snapshots.v1 method surface —
Prepare/View/Mounts/Commit/Remove/Stat/Update/Usage/Walk/Cleanup/Close —
over the sqlite MetaStore, with the reference's label-driven per-layer
processor routing (snapshot/process.go:26-183) and overlay/bind/proxy/remote
mount-slice synthesis (snapshot.go:825-985, mount_option.go).

The `fs` collaborator is the L3 filesystem facade
(:mod:`nydus_snapshotter_tpu.filesystem`); any object with the same duck
type works, which is how unit tests drive the routing logic without
daemons.
"""

from __future__ import annotations

import functools
import logging
import os
import shutil
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional, Protocol

from nydus_snapshotter_tpu import constants as C
from nydus_snapshotter_tpu import failpoint
from nydus_snapshotter_tpu import trace
from nydus_snapshotter_tpu.snapshot import labels as label
from nydus_snapshotter_tpu.snapshot import metastore as ms
from nydus_snapshotter_tpu.snapshot.async_work import (
    PrepareBoard,
    UsageAccountant,
    resolve_snapshots_config,
)
from nydus_snapshotter_tpu.snapshot.metastore import Info, MetaStore, Snapshot, Usage
from nydus_snapshotter_tpu.snapshot.mount import (
    KATA_IMAGE_RAW_BLOCK,
    KATA_LAYER_RAW_BLOCK,
    ExtraOption,
    Mount,
    bind_mount,
    overlay_mount,
    prepare_kata_virtual_volume,
)
from nydus_snapshotter_tpu.metrics.collector import snapshot_timer
from nydus_snapshotter_tpu.utils import errdefs


def upper_path(root: str, sid: str) -> str:
    """Canonical upper-dir layout ``<root>/snapshots/<sid>/fs`` — the single
    encoding shared by the snapshotter and the adaptor wiring."""
    return os.path.join(root, "snapshots", sid, "fs")


def _timed(operation: str):
    """Method-latency histogram wrapper (reference snapshot.go:303-592
    collector.NewSnapshotMetricsTimer around Mounts/Prepare/Remove/Cleanup)
    + the op's trace span, so the histograms and the span tree meter one
    and the same window."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            attrs = {"key": args[0]} if args and isinstance(args[0], str) else {}
            with trace.span(f"snapshot.{operation}", **attrs), snapshot_timer(
                operation
            ):
                return fn(self, *args, **kwargs)

        return wrapper

    return deco

logger = logging.getLogger(__name__)


class FilesystemLike(Protocol):
    """What the snapshotter needs from the L3 filesystem facade."""

    def mount(self, snapshot_id: str, labels: dict, snapshot: Optional[Snapshot]) -> None: ...
    def umount(self, snapshot_id: str) -> None: ...
    def wait_until_ready(self, snapshot_id: str) -> None: ...
    def mount_point(self, snapshot_id: str) -> str: ...
    def bootstrap_file(self, snapshot_id: str) -> str: ...
    def remove_cache(self, blob_digest: str) -> None: ...
    def cache_usage(self, blob_digest: str) -> Usage: ...
    def teardown(self) -> None: ...
    def try_stop_shared_daemon(self) -> None: ...
    def check_referrer(self, labels: dict) -> bool: ...
    def referrer_detect_enabled(self) -> bool: ...
    def try_fetch_metadata(self, labels: dict, meta_path: str) -> None: ...
    def stargz_enabled(self) -> bool: ...
    def is_stargz_data_layer(self, labels: dict) -> tuple[bool, object]: ...
    def prepare_stargz_meta_layer(self, blob, storage_path: str, labels: dict) -> None: ...
    def merge_stargz_meta_layer(self, snapshot: Snapshot) -> None: ...
    def soci_enabled(self) -> bool: ...
    def is_soci_data_layer(self, labels: dict) -> tuple[bool, object]: ...
    def prepare_soci_meta_layer(self, blob, storage_path: str, labels: dict) -> None: ...
    def merge_soci_meta_layer(self, snapshot: Snapshot) -> None: ...
    def tarfs_enabled(self) -> bool: ...
    def prepare_tarfs_layer(self, labels: dict, snapshot_id: str, upper_path: str) -> None: ...
    def merge_tarfs_layers(self, snapshot: Snapshot, path_fn: Callable[[str], str]) -> None: ...
    def export_block_data(
        self, snapshot: Snapshot, per_layer: bool, labels: dict, path_fn: Callable[[str], str]
    ) -> list[str]: ...
    def detach_tarfs_layer(self, snapshot_id: str) -> None: ...
    def tarfs_export_enabled(self) -> bool: ...
    def get_instance_extra_option(self, snapshot_id: str) -> Optional[ExtraOption]: ...


def _disk_usage(path: str) -> Usage:
    size = 0
    inodes = 0
    for root, dirs, files in os.walk(path):
        inodes += len(dirs) + len(files)
        for f in files:
            try:
                size += os.lstat(os.path.join(root, f)).st_size
            except OSError:
                continue
    return Usage(size=size, inodes=inodes)


class Snapshotter:
    def __init__(
        self,
        root: str,
        fs: FilesystemLike,
        fs_driver: str = C.DEFAULT_FS_DRIVER,
        enable_nydus_overlayfs: bool = False,
        enable_kata_volume: bool = False,
        daemon_mode: str = C.DEFAULT_DAEMON_MODE,
        sync_remove: bool = False,
        cleanup_on_close: bool = False,
        nydus_overlayfs_path: str = "",
        read_pool: Optional[int] = None,
        prepare_fanout: Optional[int] = None,
        usage_workers: Optional[int] = None,
        cleanup_workers: Optional[int] = None,
        ancestor_cache: Optional[int] = None,
    ):
        self.root = root
        self.fs = fs
        self.fs_driver = fs_driver
        self.enable_nydus_overlayfs = enable_nydus_overlayfs
        self.enable_kata_volume = enable_kata_volume
        self.daemon_mode = daemon_mode
        self.sync_remove = sync_remove
        self.cleanup_on_close = cleanup_on_close
        self.nydus_overlayfs_path = nydus_overlayfs_path
        # Control-plane concurrency knobs ([snapshots] / NTPU_SNAPSHOT*);
        # explicit arguments win, 0 anywhere falls back to the serial path.
        ccfg = resolve_snapshots_config()
        self.prepare_fanout = ccfg.prepare_fanout if prepare_fanout is None else prepare_fanout
        self.usage_workers = ccfg.usage_workers if usage_workers is None else usage_workers
        self.cleanup_workers = max(
            1, ccfg.cleanup_workers if cleanup_workers is None else cleanup_workers
        )
        os.makedirs(self.snapshot_root(), exist_ok=True)
        self.ms = MetaStore(
            os.path.join(root, "snapshots", "metadata.db"),
            read_pool=ccfg.read_pool if read_pool is None else read_pool,
            ancestor_cache=ancestor_cache,
        )
        self._board = PrepareBoard(self.prepare_fanout)
        self._usage_acct = UsageAccountant(
            scan=_disk_usage,
            write=self.ms.set_usages,
            workers=self.usage_workers,
            pre_wait=self._board.wait_quiet,
        )
        # In-flight prepare temp dirs ("new-*"): the Cleanup GC must not
        # reap a sibling RPC's staging dir mid-rename (the orphan sweep
        # only targets crash leftovers, which are never in this set).
        self._inflight_tmp: set[str] = set()
        self._inflight_mu = threading.Lock()

    # -- path layout ---------------------------------------------------------

    def snapshot_root(self) -> str:
        return os.path.join(self.root, "snapshots")

    def snapshot_dir(self, sid: str) -> str:
        return os.path.join(self.snapshot_root(), sid)

    def upper_path(self, sid: str) -> str:
        return upper_path(self.root, sid)

    def work_path(self, sid: str) -> str:
        return os.path.join(self.root, "snapshots", sid, "work")

    def lower_path(self, sid: str) -> str:
        """Rootdir of nydus image contents: the RAFS mountpoint when an
        instance exists, else the snapshot fs dir (snapshot.go:703-711)."""
        try:
            return self.fs.mount_point(sid)
        except errdefs.NotFound:
            return os.path.join(self.root, "snapshots", sid, "fs")

    # -- snapshots.v1 methods -------------------------------------------------

    def stat(self, key: str) -> Info:
        _, info, _ = self.ms.get_info(key)
        return info

    def update(self, info: Info, *fieldpaths: str) -> Info:
        return self.ms.update_info(info, *fieldpaths)

    def usage(self, key: str) -> Usage:
        # Join any pending async accounting scan first so the row read
        # below reflects it (a failed scan surfaces here, once).
        self._usage_acct.join(key)
        sid, info, usage = self.ms.get_info(key)
        if info.kind == ms.KIND_ACTIVE:
            usage = _disk_usage(self.upper_path(sid))
        elif info.kind == ms.KIND_COMMITTED and (
            label.is_nydus_data_layer(info.labels) or label.is_tarfs_data_layer(info.labels)
        ):
            blob_digest = info.labels.get(C.CRI_LAYER_DIGEST, "")
            if blob_digest:
                usage.add(self.fs.cache_usage(blob_digest))
        return usage

    @_timed("mounts")
    def mounts(self, key: str) -> list[Mount]:
        need_remote = False
        meta_sid = ""
        sid, info, _ = self.ms.get_info(key)
        # Join point of the overlapped prepare: background work for this
        # snapshot (daemon readiness, stargz bootstrap build) must have
        # finished — and a failed background prepare surfaces HERE, it is
        # never swallowed by the worker thread.
        self._board.join(sid)

        if info.kind == ms.KIND_VIEW:
            if label.is_nydus_meta_layer(info.labels):
                try:
                    self.fs.wait_until_ready(sid)
                    need_remote, meta_sid = True, sid
                except errdefs.NotFound:
                    # Client (e.g. buildkit) is unpacking nydus artifacts
                    # itself; no daemon was ever started (snapshot.go:385-396).
                    pass
            elif (self.fs.tarfs_enabled() and label.is_tarfs_data_layer(info.labels)) or (
                label.is_nydus_proxy_mode(info.labels)
            ):
                need_remote, meta_sid = True, sid
        elif info.kind == ms.KIND_ACTIVE and info.parent:
            p_sid, p_info, _ = self.ms.get_info(info.parent)
            self._board.join(p_sid)
            if label.is_nydus_meta_layer(p_info.labels):
                self.fs.wait_until_ready(p_sid)
                need_remote, meta_sid = True, p_sid
            elif (self.fs.tarfs_enabled() and label.is_tarfs_data_layer(p_info.labels)) or (
                label.is_nydus_proxy_mode(p_info.labels)
            ):
                need_remote, meta_sid = True, p_sid

        if self.fs.referrer_detect_enabled() and not need_remote:
            try:
                rid, _ = self._find_referrer_layer(key)
                need_remote, meta_sid = True, rid
            except errdefs.NotFound:
                pass

        snap = self.ms.get_snapshot(key)
        if self._treat_as_proxy_driver(info.labels):
            return self._mount_proxy(snap)
        if need_remote:
            return self._mount_remote(info.labels, snap, meta_sid, key)
        return self._mount_native(info.labels, snap)

    @_timed("prepare")
    def prepare(self, key: str, parent: str, snap_labels: Optional[dict] = None) -> list[Mount]:
        info, s = self._create_snapshot(ms.KIND_ACTIVE, key, parent, snap_labels)
        handler, target = self._choose_processor(s, key, parent, info.labels)
        skip, mounts = handler()
        if skip and target:
            # Remote snapshot ready: commit in place so containerd skips the
            # download (process.go skipHandler + Prepare needCommit,
            # snapshot.go:470-477).
            try:
                self.commit(target, key, snap_labels=info.labels)
            except errdefs.AlreadyExists:
                pass
            raise errdefs.AlreadyExists(f"target snapshot {target!r}")
        return mounts

    def view(self, key: str, parent: str, snap_labels: Optional[dict] = None) -> list[Mount]:
        p_sid, p_info, _ = self.ms.get_info(parent)
        self._board.join(p_sid)
        need_remote = False
        meta_sid = ""
        if label.is_nydus_meta_layer(p_info.labels):
            try:
                self.fs.wait_until_ready(p_sid)
            except errdefs.NotFound:
                self.fs.mount(p_sid, p_info.labels, None)
                self.fs.wait_until_ready(p_sid)
            need_remote, meta_sid = True, p_sid
        elif label.is_nydus_data_layer(p_info.labels):
            raise errdefs.InvalidArgument("only can view nydus topmost layer")

        base, s = self._create_snapshot(ms.KIND_VIEW, key, parent, snap_labels)

        if self.fs.tarfs_enabled() and label.is_tarfs_data_layer(p_info.labels):
            self._merge_tarfs(s, p_sid, p_info)
            self.fs.mount(p_sid, p_info.labels, s)
            need_remote, meta_sid = True, p_sid

        if need_remote:
            return self._mount_remote(base.labels, s, meta_sid, key)
        return self._mount_native(base.labels, s)

    @_timed("commit")
    def commit(self, name: str, key: str, snap_labels: Optional[dict] = None) -> None:
        failpoint.hit("snapshot.commit")
        sid, info, _ = self.ms.get_info(key)
        # One timestamp and one write transaction for the whole commit
        # (rename + label merge); the upper-dir usage scan moves off the
        # critical path into the async accountant, which backfills the row
        # and is joined by usage().
        self.ms.commit_active(
            key, name, Usage(), now=time.time(), extra_labels=snap_labels or None
        )
        self._usage_acct.submit(name, self.upper_path(sid), sid=sid)

    @_timed("remove")
    def remove(self, key: str) -> None:
        sid, info, _ = self.ms.get_info(key)
        if info.kind == ms.KIND_COMMITTED:
            blob_digest = info.labels.get(C.CRI_LAYER_DIGEST, "")
            if blob_digest:
                threading.Thread(
                    target=self._remove_cache_quietly, args=(blob_digest,), daemon=True
                ).start()
        self.ms.remove(key)
        self._board.discard(sid)
        self._usage_acct.discard(key)
        if self.sync_remove:
            for d in self._get_cleanup_directories():
                self._cleanup_snapshot_directory(d)

    def walk(self, fn: Callable[[str, Info], None]) -> None:
        self.ms.walk(fn)

    @_timed("cleanup")
    def cleanup(self) -> None:
        dirs = self._get_cleanup_directories()
        if not dirs:
            return
        if self.cleanup_workers > 1 and len(dirs) > 1:
            # Pool workers have no contextvars: carry the cleanup span's
            # context so per-dir spans hang off the Cleanup root.
            ctx = trace.capture()

            def one(d: str) -> None:
                with trace.with_context(ctx):
                    self._cleanup_snapshot_directory(d)

            with ThreadPoolExecutor(
                max_workers=min(self.cleanup_workers, len(dirs)),
                thread_name_prefix="ntpu-snap-clean",
            ) as ex:
                for fut in [ex.submit(one, d) for d in dirs]:
                    fut.result()
        else:
            for d in dirs:
                self._cleanup_snapshot_directory(d)

    def close(self) -> None:
        # Quiesce background work first: prepare jobs may still touch the
        # fs facade, and pending usage scans must land in the metastore
        # before it closes.
        self._board.close()
        self._usage_acct.flush()
        self._usage_acct.close()
        if self.cleanup_on_close:
            try:
                self.fs.teardown()
            except Exception:
                logger.exception("failed to tear down remote snapshots")
        self.fs.try_stop_shared_daemon()
        self.ms.close()

    # -- processor routing (reference snapshot/process.go) --------------------

    def _choose_processor(
        self, s: Snapshot, key: str, parent: str, snap_labels: dict
    ) -> tuple[Callable[[], tuple[bool, list[Mount]]], str]:
        """Return (handler, target). handler() -> (skip_download, mounts)."""

        def default_handler():
            return False, self._mount_native(snap_labels, s)

        def skip_handler():
            return True, []

        def remote_handler(sid: str, rl: dict):
            def run():
                # Surface any failed background prep of the layer we are
                # about to mount over, then mount synchronously (cheap
                # registration + spawn kick; the mountpoint feeds lowerdir
                # synthesis below). The slow part — daemon readiness — is
                # deferred to the board, joined at mounts().
                self._board.join(sid)
                self.fs.mount(sid, rl, s)
                self._board.submit(s.id, functools.partial(self.fs.wait_until_ready, sid))
                return False, self._mount_remote(rl, s, sid, key)

            return run

        def proxy_handler():
            return False, self._mount_proxy(s)

        target = snap_labels.get(C.TARGET_SNAPSHOT_REF, "")
        handler = None

        if target:  # ro layer during image pull
            if self.fs_driver == C.FS_DRIVER_PROXY:
                if snap_labels.get(C.CRI_LAYER_DIGEST, ""):
                    snap_labels[C.NYDUS_PROXY_MODE] = "true"
                    handler = skip_handler
                else:
                    raise errdefs.InvalidArgument(
                        f"missing CRI reference annotation for snapshot {s.id}"
                    )
            elif label.is_nydus_meta_layer(snap_labels):
                handler = default_handler
            elif label.is_nydus_data_layer(snap_labels):
                handler = skip_handler
            elif self.fs.check_referrer(snap_labels):
                handler = skip_handler
            else:
                if self.fs.stargz_enabled():
                    ok, blob = self.fs.is_stargz_data_layer(snap_labels)
                    if ok:
                        if self._board.enabled:
                            # Optimistic skip: detection already succeeded, so
                            # the heavy TOC→bootstrap build overlaps on the
                            # board while containerd issues the next layer's
                            # Prepare; a failure sticks to this snapshot id
                            # and surfaces at mounts()/the child prepare.
                            self._board.submit(
                                s.id,
                                functools.partial(
                                    self.fs.prepare_stargz_meta_layer,
                                    blob,
                                    self.upper_path(s.id),
                                    dict(snap_labels),
                                ),
                            )
                            snap_labels[C.STARGZ_LAYER] = "true"
                            handler = skip_handler
                        else:
                            try:
                                self.fs.prepare_stargz_meta_layer(
                                    blob, self.upper_path(s.id), snap_labels
                                )
                            except Exception:
                                logger.exception(
                                    "prepare stargz layer of snapshot %s", s.id
                                )
                            else:
                                snap_labels[C.STARGZ_LAYER] = "true"
                                handler = skip_handler
                if handler is None and self.fs.soci_enabled():
                    # Seekable-OCI: claim the ordinary gzip or zstd layer
                    # nobody will ever convert. Runs after the stargz arm
                    # so cooperative estargz images keep their TOC path;
                    # detection is the FormatRouter's two ranged probe
                    # reads (4 head bytes + one tail read), which pick a
                    # lazy backend by modeled cold-read cost or raise to
                    # fall through to ordinary conversion (soci/router.py).
                    ok, blob = self.fs.is_soci_data_layer(snap_labels)
                    if ok:
                        route = getattr(blob, "route", None)
                        if route is not None:
                            snap_labels[C.SOCI_ROUTE] = route.backend
                        if self._board.enabled:
                            # Optimistic skip, like stargz: the heavy
                            # first-pull index build overlaps on the board
                            # while containerd issues the next layer's
                            # Prepare; a failure sticks to this snapshot
                            # id and surfaces at mounts()/child prepare.
                            self._board.submit(
                                s.id,
                                functools.partial(
                                    self.fs.prepare_soci_meta_layer,
                                    blob,
                                    self.upper_path(s.id),
                                    dict(snap_labels),
                                ),
                            )
                            snap_labels[C.SOCI_LAYER] = "true"
                            handler = skip_handler
                        else:
                            try:
                                self.fs.prepare_soci_meta_layer(
                                    blob, self.upper_path(s.id), snap_labels
                                )
                            except Exception:
                                logger.exception(
                                    "prepare soci layer of snapshot %s", s.id
                                )
                            else:
                                snap_labels[C.SOCI_LAYER] = "true"
                                handler = skip_handler
                if handler is None and self.fs.tarfs_enabled():
                    try:
                        self.fs.prepare_tarfs_layer(snap_labels, s.id, self.upper_path(s.id))
                    except Exception:
                        logger.warning(
                            "snapshot %s can't be converted into tarfs, fallback", s.id
                        )
                    else:
                        if self.fs.tarfs_export_enabled():
                            self.fs.export_block_data(s, True, snap_labels, self.upper_path)
                        handler = skip_handler
        else:  # container writable layer
            p_sid, p_info = "", None
            p_err: Optional[Exception] = None
            try:
                p_sid, p_info, _ = self.ms.get_info(parent)
            except errdefs.NotFound as e:
                p_err = e

            if p_info is not None and self._treat_as_proxy_driver(p_info.labels):
                handler = proxy_handler
            if p_err is None and p_info is not None and label.is_nydus_proxy_mode(p_info.labels):
                handler = remote_handler(p_sid, p_info.labels)

            if handler is None:
                try:
                    mid, m_info = self._find_meta_layer(key)
                    handler = remote_handler(mid, m_info.labels)
                except errdefs.NotFound:
                    pass

            if handler is None and self.fs.referrer_detect_enabled():
                try:
                    rid, r_info = self._find_referrer_layer(key)
                    meta_path = os.path.join(self.snapshot_dir(rid), "fs", "image.boot")
                    self.fs.try_fetch_metadata(r_info.labels, meta_path)
                    handler = remote_handler(rid, r_info.labels)
                except errdefs.NotFound:
                    pass

            if (
                handler is None
                and p_err is None
                and p_info is not None
                and self.fs.stargz_enabled()
                and label.is_stargz_layer(p_info.labels)
            ):
                # The parent's bootstrap may still be building in the
                # background — this is its other join point.
                self._board.join(p_sid)
                self.fs.merge_stargz_meta_layer(s)
                handler = remote_handler(p_sid, p_info.labels)

            if (
                handler is None
                and p_err is None
                and p_info is not None
                and self.fs.soci_enabled()
                and label.is_soci_layer(p_info.labels)
            ):
                # The parent's index-on-first-pull build may still be
                # running in the background — this is its join point.
                self._board.join(p_sid)
                self.fs.merge_soci_meta_layer(s)
                handler = remote_handler(p_sid, p_info.labels)

            if (
                handler is None
                and p_err is None
                and p_info is not None
                and self.fs.tarfs_enabled()
                and label.is_tarfs_data_layer(p_info.labels)
            ):
                self._board.join(p_sid)
                self._merge_tarfs(s, p_sid, p_info)
                handler = remote_handler(p_sid, p_info.labels)

        if handler is None:
            handler = default_handler
        return handler, target

    # -- internals ------------------------------------------------------------

    def _remove_cache_quietly(self, blob_digest: str) -> None:
        try:
            self.fs.remove_cache(blob_digest)
        except Exception:
            logger.exception("failed to remove cache %s", blob_digest)

    def _treat_as_proxy_driver(self, snap_labels: dict) -> bool:
        # A snapshot prepared by another snapshotter (pause image) shows a CRI
        # image ref without nydus/proxy labels (snapshot.go:1086-1090).
        return (
            self.fs_driver == C.FS_DRIVER_PROXY
            and not label.is_nydus_proxy_mode(snap_labels)
            and C.CRI_IMAGE_REF in snap_labels
        )

    def _find_meta_layer(self, key: str) -> tuple[str, Info]:
        return self.ms.iterate_parent_snapshots(
            key, lambda _sid, info: label.is_nydus_meta_layer(info.labels)
        )

    def _find_referrer_layer(self, key: str) -> tuple[str, Info]:
        return self.ms.iterate_parent_snapshots(
            key, lambda _sid, info: self.fs.check_referrer(info.labels)
        )

    def _create_snapshot(
        self, kind: str, key: str, parent: str, snap_labels: Optional[dict]
    ) -> tuple[Info, Snapshot]:
        base_labels = dict(snap_labels or {})
        # mkdtemp + registration are atomic w.r.t. the GC's
        # list-then-check (see _get_cleanup_directories ordering): any
        # staging dir the GC can observe is already registered.
        with self._inflight_mu:
            td = tempfile.mkdtemp(prefix="new-", dir=self.snapshot_root())
            td_name = os.path.basename(td)
            self._inflight_tmp.add(td_name)
        path = ""
        s: Optional[Snapshot] = None
        try:
            os.makedirs(os.path.join(td, "fs"), exist_ok=True)
            if kind == ms.KIND_ACTIVE:
                os.makedirs(os.path.join(td, "work"), mode=0o711, exist_ok=True)
            s = self.ms.create_snapshot(kind, key, parent, base_labels)
            if s.parent_ids:
                st = os.stat(self.upper_path(s.parent_ids[0]))
                try:
                    os.chown(os.path.join(td, "fs"), st.st_uid, st.st_gid)
                except PermissionError:
                    pass
            path = self.snapshot_dir(s.id)
            os.rename(td, path)
            td = ""
        except BaseException:
            # Roll back the metastore row so a retried prepare(key) isn't
            # poisoned with AlreadyExists (the reference's bolt txn rollback).
            if s is not None:
                try:
                    self.ms.remove(key)
                except errdefs.NydusError:
                    pass
            raise
        finally:
            with self._inflight_mu:
                self._inflight_tmp.discard(td_name)
            if td:
                shutil.rmtree(td, ignore_errors=True)
        _, info, _ = self.ms.get_info(key)
        return info, s

    def _merge_tarfs(self, s: Snapshot, p_sid: str, p_info: Info) -> None:
        self.fs.merge_tarfs_layers(s, self.upper_path)
        if self.fs.tarfs_export_enabled():
            update_fields = self.fs.export_block_data(s, False, p_info.labels, self.upper_path)
            if update_fields:
                self.ms.update_info(p_info, *update_fields)

    # -- mount synthesis ------------------------------------------------------

    def _overlay_mount_type(self) -> str:
        if self.nydus_overlayfs_path:
            return f"fuse.{self.nydus_overlayfs_path}"
        return "fuse.nydus-overlayfs"

    def _mount_native(self, snap_labels: dict, s: Snapshot) -> list[Mount]:
        if not s.parent_ids:
            ro = "ro" if s.kind == ms.KIND_VIEW else "rw"
            return bind_mount(self.upper_path(s.id), ro)
        options: list[str] = []
        if s.kind == ms.KIND_ACTIVE:
            options += [f"workdir={self.work_path(s.id)}", f"upperdir={self.upper_path(s.id)}"]
            if label.is_volatile(snap_labels):
                options.append("volatile")
        elif len(s.parent_ids) == 1:
            return bind_mount(self.upper_path(s.id), "ro")
        parents = [self.upper_path(pid) for pid in s.parent_ids]
        options.append("lowerdir=" + ":".join(parents))
        return overlay_mount(options)

    def _mount_proxy(self, s: Snapshot) -> list[Mount]:
        options: list[str] = []
        if s.kind == ms.KIND_ACTIVE:
            options += [f"workdir={self.work_path(s.id)}", f"upperdir={self.upper_path(s.id)}"]
        parents = (
            [self.upper_path(pid) for pid in s.parent_ids]
            if s.parent_ids
            else [self.snapshot_root()]
        )
        options.append("lowerdir=" + ":".join(parents))
        options.append(
            prepare_kata_virtual_volume(
                C.NYDUS_PROXY_MODE,
                "dummy-image-reference",
                "image_guest_pull",
                "",
                [],
                {},
            )
        )
        return [Mount(type=self._overlay_mount_type(), source="overlay", options=options)]

    def _mount_remote(
        self, snap_labels: dict, s: Snapshot, meta_sid: str, key: str
    ) -> list[Mount]:
        options: list[str] = []
        if label.is_volatile(snap_labels):
            options.append("volatile")

        lower_paths: list[str] = []
        if self.fs.referrer_detect_enabled():
            # Layers between the upmost snapshot and the nydus meta snapshot
            # (snapshot.go:908-921).
            for pid in s.parent_ids:
                if pid == meta_sid:
                    break
                lower_paths.append(self.upper_path(pid))
        lower_paths.append(self.lower_path(meta_sid))

        if s.kind == ms.KIND_ACTIVE:
            options += [f"workdir={self.work_path(s.id)}", f"upperdir={self.upper_path(s.id)}"]
        elif s.kind == ms.KIND_VIEW:
            lower_paths.append(self.lower_path(s.id))

        options.append("lowerdir=" + ":".join(lower_paths))

        if self.enable_kata_volume:
            return self._mount_with_kata_volume(meta_sid, options, key)
        if self.enable_nydus_overlayfs or self.daemon_mode == C.DAEMON_MODE_NONE:
            return self._remote_mount_with_extra_options(s, meta_sid, options)
        return overlay_mount(options)

    def _remote_mount_with_extra_options(
        self, s: Snapshot, meta_sid: str, options: list[str]
    ) -> list[Mount]:
        extra = self.fs.get_instance_extra_option(meta_sid)
        if extra is not None:
            options.append(extra.encode())
        return [Mount(type=self._overlay_mount_type(), source="overlay", options=options)]

    def _mount_with_kata_volume(self, meta_sid: str, options: list[str], key: str) -> list[Mount]:
        """Kata-volume mount synthesis (reference mount_option.go:117-243):
        tarfs snapshots carry raw-block volumes pointing at the exported
        EROFS disk images (whole-image or one per layer, with dm-verity
        info from the block-info labels); nydus-fs snapshots carry the
        extraoption-backed image_nydus_fs volume."""
        ann = {}
        if self.fs.tarfs_enabled():
            ann = self.fs.get_instance_annotations(meta_sid)
        if C.NYDUS_TARFS_LAYER in ann:
            if C.NYDUS_IMAGE_BLOCK_INFO in ann:
                path = self.fs.tarfs_image_disk_path(ann[C.NYDUS_TARFS_LAYER])
                options.append(
                    prepare_kata_virtual_volume(
                        C.NYDUS_IMAGE_BLOCK_INFO,
                        path,
                        KATA_IMAGE_RAW_BLOCK,
                        "erofs",
                        ["ro"],
                        ann,
                    )
                )
            elif C.NYDUS_LAYER_BLOCK_INFO in ann:
                # One raw-block volume per tarfs layer, appended in
                # parent-walk order — topmost committed layer first —
                # exactly as the reference emits them while walking the
                # chain down (mount_option.go:211-242).
                vols: list[str] = []

                def visit(_sid: str, info: Info) -> bool:
                    blob_id = info.labels.get(C.NYDUS_TARFS_LAYER, "")
                    if blob_id:
                        vols.append(
                            prepare_kata_virtual_volume(
                                C.NYDUS_LAYER_BLOCK_INFO,
                                self.fs.tarfs_layer_disk_path(blob_id),
                                KATA_LAYER_RAW_BLOCK,
                                "erofs",
                                ["ro"],
                                dict(info.labels),
                            )
                        )
                    return False  # walk the whole chain

                try:
                    self.ms.iterate_parent_snapshots(key, visit)
                except errdefs.NotFound:
                    pass  # chain exhausted — expected
                options.extend(vols)  # top layer first (parent-walk order)
            return [
                Mount(
                    type=self._overlay_mount_type(),
                    source="overlay",
                    options=options,
                )
            ]
        extra = self.fs.get_instance_extra_option(meta_sid)
        if extra is not None:
            vol_opt = prepare_kata_virtual_volume(
                "",
                extra.source,
                "image_nydus_fs",
                extra.fs_version,
                [],
                {},
            )
            options.append(vol_opt)
        return [Mount(type=self._overlay_mount_type(), source="overlay", options=options)]

    # -- GC -------------------------------------------------------------------

    def _get_cleanup_directories(self) -> list[str]:
        # Ordering against concurrent prepares: list FIRST, then read the
        # id map and the in-flight set. A staging dir created after the
        # listing isn't in `dirs`; one created before is registered
        # (mkdtemp+add are atomic) and gets skipped; and a dir RENAMED to
        # its final id between the two reads had its metastore row
        # created before the rename, so a LATER id_map() must contain it
        # — reading ids before listdir reopened exactly that window (the
        # GC would reap a just-created live snapshot).
        try:
            dirs = os.listdir(self.snapshot_root())
        except FileNotFoundError:
            return []
        ids = self.ms.id_map()
        with self._inflight_mu:
            inflight = set(self._inflight_tmp)
        return [
            self.snapshot_dir(d)
            for d in dirs
            if d not in ids
            and d not in inflight  # a sibling RPC's staging dir, not an orphan
            and d != "metadata.db"
            and not d.endswith(("-wal", "-shm"))
        ]

    @trace.traced("snapshot.cleanup.dir")
    def _cleanup_snapshot_directory(self, d: str) -> None:
        failpoint.hit("snapshot.cleanup")
        sid = os.path.basename(d)
        self._board.discard(sid)
        try:
            self.fs.umount(sid)
        except (errdefs.NotFound, FileNotFoundError):
            pass
        except Exception:
            logger.exception("failed to unmount %s", d)
        if self.fs.tarfs_enabled():
            try:
                self.fs.detach_tarfs_layer(sid)
            except (errdefs.NotFound, FileNotFoundError):
                pass
        shutil.rmtree(d, ignore_errors=True)
