"""Snapshot label predicates (reference pkg/label/label.go:17-88).

All key strings live in :mod:`nydus_snapshotter_tpu.constants` so converter
annotations and snapshot labels share one vocabulary; this module adds the
predicates the processor routing (snapshot/process.go) keys off.
"""

from __future__ import annotations

from typing import Mapping

from nydus_snapshotter_tpu import constants as C


def is_nydus_data_layer(labels: Mapping[str, str]) -> bool:
    return C.NYDUS_DATA_LAYER in labels


def is_nydus_meta_layer(labels: Mapping[str, str]) -> bool:
    return C.NYDUS_META_LAYER in labels


def is_tarfs_data_layer(labels: Mapping[str, str]) -> bool:
    return C.NYDUS_TARFS_LAYER in labels


def is_nydus_proxy_mode(labels: Mapping[str, str]) -> bool:
    return C.NYDUS_PROXY_MODE in labels


def has_tarfs_hint(labels: Mapping[str, str]) -> bool:
    return C.TARFS_HINT in labels


def is_stargz_layer(labels: Mapping[str, str]) -> bool:
    return C.STARGZ_LAYER in labels


def is_soci_layer(labels: Mapping[str, str]) -> bool:
    return C.SOCI_LAYER in labels


def is_volatile(labels: Mapping[str, str]) -> bool:
    return C.OVERLAYFS_VOLATILE_OPT in labels
