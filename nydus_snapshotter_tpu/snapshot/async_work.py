"""Background machinery for the concurrent snapshot control plane.

Three pieces, consumed by :mod:`nydus_snapshotter_tpu.snapshot.snapshotter`
and :mod:`nydus_snapshotter_tpu.snapshot.metastore`:

- :func:`resolve_snapshots_config` — the ``[snapshots]`` knobs (read pool
  size, prepare fanout, usage workers, …) resolved env > config > defaults,
  the same layering the ``[convert]`` / ``[blobcache]`` sections use;
- :class:`PrepareBoard` — deferred per-snapshot prepare work keyed by
  snapshot id, so containerd's serial per-layer Prepare RPCs pipeline:
  each Prepare returns as soon as the routing decision and mount synthesis
  are done, while the slow tail (daemon readiness, stargz bootstrap build)
  runs on a bounded pool. ``join`` is the read barrier at ``mounts()``;
- :class:`UsageAccountant` — async disk-usage accounting: ``commit`` no
  longer walks the upper dir inline; scans run on a worker that backfills
  Usage through ONE batched metastore transaction per drain, and
  ``usage()`` joins any pending scan before reading.

Failure contract (chaos-tested in tests/test_snapshot_concurrency.py): a
failed background prepare STICKS on the board — every ``join`` for that
snapshot re-raises it until ``discard`` at remove/cleanup — so an error
surfaces at ``mounts()`` instead of vanishing into a worker thread. A
failed usage scan surfaces once at the joining ``usage()`` call; the
committed row keeps its last stored value.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, Optional

from nydus_snapshotter_tpu import failpoint
from nydus_snapshotter_tpu import trace
from nydus_snapshotter_tpu.analysis import runtime as _an
from nydus_snapshotter_tpu.metrics import data as metrics_data

DEFAULT_READ_POOL = 8
DEFAULT_PREPARE_FANOUT = 4
DEFAULT_USAGE_WORKERS = 1
DEFAULT_CLEANUP_WORKERS = 4
DEFAULT_ANCESTOR_CACHE = 1024

# One usage-scan drain writes at most this many rows per transaction; a
# storm of commits cannot make a single write transaction unbounded.
USAGE_BATCH_MAX = 64


@dataclass
class SnapshotsRuntimeConfig:
    """Resolved ``[snapshots]`` section. Worker counts of 0 mean inline
    (synchronous) execution — the serial control plane of PR 3 and earlier."""

    read_pool: int = DEFAULT_READ_POOL
    prepare_fanout: int = DEFAULT_PREPARE_FANOUT
    usage_workers: int = DEFAULT_USAGE_WORKERS
    cleanup_workers: int = DEFAULT_CLEANUP_WORKERS
    ancestor_cache: int = DEFAULT_ANCESTOR_CACHE


def _env_int(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, ""))
        return v if v >= 0 else default
    except ValueError:
        return default


def _global_snapshots_config():
    """The snapshotter's ``[snapshots]`` section when a global config is
    set (config/config.py); None in library / test use."""
    try:
        from nydus_snapshotter_tpu.config import config as _cfg

        return _cfg.get_global_config().snapshots
    except Exception:
        return None


def resolve_snapshots_config() -> SnapshotsRuntimeConfig:
    """Resolve control-plane knobs: ``NTPU_SNAPSHOT*`` env > ``[snapshots]``
    config > defaults."""
    sc = _global_snapshots_config()

    def pick(env: str, attr: str, default: int) -> int:
        v = _env_int(env, -1)
        if v >= 0:
            return v
        got = getattr(sc, attr, None)
        return got if got is not None else default

    return SnapshotsRuntimeConfig(
        read_pool=max(1, pick("NTPU_SNAPSHOT_READ_POOL", "read_pool", DEFAULT_READ_POOL)),
        prepare_fanout=pick(
            "NTPU_SNAPSHOT_PREPARE_FANOUT", "prepare_fanout", DEFAULT_PREPARE_FANOUT
        ),
        usage_workers=pick(
            "NTPU_SNAPSHOT_USAGE_WORKERS", "usage_workers", DEFAULT_USAGE_WORKERS
        ),
        cleanup_workers=max(
            1,
            pick("NTPU_SNAPSHOT_CLEANUP_WORKERS", "cleanup_workers", DEFAULT_CLEANUP_WORKERS),
        ),
        ancestor_cache=pick(
            "NTPU_SNAPSHOT_ANCESTOR_CACHE", "ancestor_cache", DEFAULT_ANCESTOR_CACHE
        ),
    )


class PrepareBoard:
    """Background per-snapshot prepare work keyed by snapshot id.

    ``fanout`` of 0 runs every submission inline (serial behavior). The
    ``snapshot.prepare`` failpoint fires at the submitted-work boundary in
    both modes, so chaos coverage is identical serial and concurrent.
    """

    def __init__(self, fanout: int):
        self.fanout = max(0, fanout)
        self._lock = _an.make_lock("snapshot.prepare_board")
        self._exec: Optional[ThreadPoolExecutor] = None
        self._pending: dict[str, Future] = {}
        # Lockset annotation: the pending-futures board is only ever
        # touched under self._lock (NTPU_ANALYZE=1 verifies).
        self._pending_shared = _an.shared("snapshot.prepare_board.pending")
        self._closed = False

    @property
    def enabled(self) -> bool:
        return self.fanout > 0

    def _executor(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._exec is None:
                self._exec = ThreadPoolExecutor(
                    max_workers=self.fanout, thread_name_prefix="ntpu-snap-prep"
                )
            return self._exec

    def _gauge(self) -> None:
        metrics_data.SnapshotPendingPrepares.set(len(self._pending))

    def submit(self, sid: str, fn: Callable[[], None]) -> None:
        if not self.enabled or self._closed:
            with trace.span("snapshot.prepare.bg", sid=sid, inline=True):
                failpoint.hit("snapshot.prepare")
                fn()
            return
        with self._lock:
            self._pending_shared.write()
            prev = self._pending.pop(sid, None)
        # Executor threads have no contextvars: carry the submitting
        # Prepare's trace context so the deferred slow tail (daemon
        # readiness, stargz bootstrap build) lands in its span tree.
        ctx = trace.capture()

        def run() -> None:
            if prev is not None:
                # Per-sid ordering: chained work waits for (and propagates
                # the failure of) whatever was already in flight.
                prev.result()
            with trace.with_context(ctx), trace.span(
                "snapshot.prepare.bg", sid=sid
            ):
                failpoint.hit("snapshot.prepare")
                fn()

        fut = self._executor().submit(run)
        with self._lock:
            self._pending_shared.write()
            self._pending[sid] = fut
            self._gauge()

    def join(self, sid: str) -> None:
        """Block until sid's background work completes; re-raise its
        failure. Success clears the entry; failure sticks (every later
        join raises again) until :meth:`discard`."""
        with self._lock:
            self._pending_shared.read()
            fut = self._pending.get(sid)
        if fut is None:
            return
        try:
            fut.result()
        except BaseException:
            raise
        else:
            with self._lock:
                if self._pending.get(sid) is fut:
                    self._pending.pop(sid, None)
                self._gauge()

    def wait_quiet(self, sid: Optional[str]) -> None:
        """Wait for sid's work without consuming or raising its outcome —
        the usage accountant's pre-scan barrier (the error still surfaces
        at the next ``join``)."""
        if sid is None:
            return
        with self._lock:
            fut = self._pending.get(sid)
        if fut is None:
            return
        try:
            fut.result()
        except BaseException:
            pass

    def discard(self, sid: str) -> None:
        with self._lock:
            self._pending.pop(sid, None)
            self._gauge()

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            ex = self._exec
            self._exec = None
        if ex is not None:
            ex.shutdown(wait=True, cancel_futures=True)


class _Scan:
    __slots__ = ("key", "path", "sid", "done", "exc", "ctx")

    def __init__(self, key: str, path: str, sid: Optional[str]):
        self.key = key
        self.path = path
        self.sid = sid
        self.done = threading.Event()
        self.exc: Optional[BaseException] = None
        # Trace context of the submitting commit, so the async usage scan
        # is attributed to the Commit that queued it.
        self.ctx = trace.capture()


class UsageAccountant:
    """Async disk-usage accounting queue backfilling committed Usage.

    ``scan(path) -> Usage`` and ``write({key: Usage}) -> ts`` are injected
    (the snapshotter passes ``_disk_usage`` and ``MetaStore.set_usages``),
    so one drain lands every completed scan in a single batched write
    transaction. ``pre_wait(sid)`` (the prepare board's quiet barrier)
    keeps a scan from measuring a layer whose background prep is still
    writing into it.
    """

    def __init__(
        self,
        scan: Callable[[str], object],
        write: Callable[[dict], object],
        workers: int = DEFAULT_USAGE_WORKERS,
        pre_wait: Optional[Callable[[Optional[str]], None]] = None,
    ):
        self._scan = scan
        self._write = write
        self._pre_wait = pre_wait
        self.workers = max(0, workers)
        self._cond = _an.make_condition("snapshot.usage_accountant")
        self._queue: deque[_Scan] = deque()
        self._pending: dict[str, _Scan] = {}
        self._threads: list[threading.Thread] = []
        self._closed = False

    def _gauge_locked(self) -> None:
        metrics_data.SnapshotPendingUsageScans.set(len(self._pending))

    def _run_inline(self, entry: _Scan) -> None:
        with trace.span("snapshot.usage.scan", key=entry.key, inline=True):
            if self._pre_wait is not None:
                self._pre_wait(entry.sid)
            failpoint.hit("snapshot.usage")
            self._write({entry.key: self._scan(entry.path)})

    def submit(self, key: str, path: str, sid: Optional[str] = None) -> None:
        """Queue a scan of ``path`` whose result backfills snapshot ``key``.
        With 0 workers the scan runs inline and errors propagate to the
        caller — the serial commit path."""
        entry = _Scan(key, path, sid)
        if self.workers == 0 or self._closed:
            self._run_inline(entry)
            return
        with self._cond:
            self._pending[key] = entry
            self._queue.append(entry)
            self._gauge_locked()
            while len(self._threads) < min(self.workers, len(self._queue)):
                t = threading.Thread(
                    target=self._worker,
                    name=f"ntpu-snap-usage-{len(self._threads)}",
                    daemon=True,
                )
                self._threads.append(t)
                t.start()
            self._cond.notify()

    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if self._closed and not self._queue:
                    return
                batch = [
                    self._queue.popleft()
                    for _ in range(min(USAGE_BATCH_MAX, len(self._queue)))
                ]
            results: dict[str, object] = {}
            scanned: list[_Scan] = []
            for e in batch:
                try:
                    with trace.with_context(e.ctx), trace.span(
                        "snapshot.usage.scan", key=e.key
                    ):
                        if self._pre_wait is not None:
                            self._pre_wait(e.sid)
                        failpoint.hit("snapshot.usage")
                        results[e.key] = self._scan(e.path)
                    scanned.append(e)
                except BaseException as exc:  # noqa: BLE001 - stored, surfaced at join
                    e.exc = exc
            if results:
                try:
                    self._write(results)
                except BaseException as exc:  # noqa: BLE001
                    for e in scanned:
                        e.exc = exc
            with self._cond:
                for e in batch:
                    if e.exc is None and self._pending.get(e.key) is e:
                        self._pending.pop(e.key, None)
                self._gauge_locked()
            for e in batch:
                e.done.set()

    def join(self, key: str) -> None:
        """Wait for any pending scan of ``key``; a failed scan raises here
        ONCE (the entry is consumed) and the stored Usage is left at its
        last value."""
        with self._cond:
            entry = self._pending.get(key)
        if entry is None:
            return
        entry.done.wait()
        with self._cond:
            if self._pending.get(key) is entry:
                self._pending.pop(key, None)
            self._gauge_locked()
        if entry.exc is not None:
            raise entry.exc

    def discard(self, key: str) -> None:
        with self._cond:
            self._pending.pop(key, None)
            self._gauge_locked()

    def pending_count(self) -> int:
        with self._cond:
            return len(self._pending)

    def flush(self) -> None:
        """Block until everything queued so far has been scanned and
        written (errors stay parked for their joins)."""
        with self._cond:
            entries = list(self._pending.values())
        for e in entries:
            e.done.wait()

    def close(self, timeout: float = 10.0) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=timeout)
