"""Snapshot metadata store.

The reference leans on containerd's ``storage.MetaStore`` (bbolt,
snapshot/snapshot.go:272) for snapshot parentage, kinds, labels, and usage,
plus the helpers in pkg/snapshot/storage.go:19-108 (get/walk/update info,
``IterateParentSnapshots``). This module reproduces those semantics on
sqlite (stdlib, WAL, transactional):

- snapshots are addressed by *key* (client name) and carry an internal
  monotonic numeric *id* used for on-disk directory names;
- kinds: view / active / committed; Commit turns an active snapshot into a
  committed one under a new name;
- ``Snapshot.parent_ids`` is the full ancestor id chain, immediate parent
  first — what overlay lowerdir synthesis consumes;
- usage (size, inodes) recorded at commit time (and backfilled
  asynchronously via :meth:`MetaStore.set_usages`).

Concurrency model (the concurrent control plane, PR 4): WAL gives one
writer + any number of readers, so the store splits into

- a **read pool** of dedicated connections (``row_factory`` set ONCE per
  connection — the old shared-connection mutation was a latent race) used
  by ``get_snapshot``/``get_info``/``walk``/``id_map``/``usage``; each
  read op runs inside its own read transaction for a stable snapshot and
  never takes the writer lock, and
- a single **serialized writer** connection behind an RLock whose
  :meth:`write_txn` context manager batches nested mutations into one
  ``BEGIN IMMEDIATE`` … ``COMMIT`` (one fsync per batch).

Ancestor chains are memoized in a bounded LRU (``parent key`` →
``parent_ids``), replacing the per-call recursive parent queries. Only
``remove`` (and commit's key rename) can change what a chain resolves to,
and remove refuses while children exist — so a chain cached under key K
can only go stale when K itself is removed or (re)committed, and targeted
invalidation of K is sufficient.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

from nydus_snapshotter_tpu import failpoint
from nydus_snapshotter_tpu.analysis import runtime as _an
from nydus_snapshotter_tpu import trace
from nydus_snapshotter_tpu.metrics import data as metrics_data
from nydus_snapshotter_tpu.snapshot.async_work import resolve_snapshots_config
from nydus_snapshotter_tpu.utils import errdefs

KIND_VIEW = "view"
KIND_ACTIVE = "active"
KIND_COMMITTED = "committed"


@dataclass
class Usage:
    size: int = 0
    inodes: int = 0

    def add(self, other: "Usage") -> None:
        self.size += other.size
        self.inodes += other.inodes


@dataclass
class Info:
    kind: str
    name: str
    parent: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    created: float = 0.0
    updated: float = 0.0


@dataclass
class Snapshot:
    id: str
    kind: str
    parent_ids: list[str] = field(default_factory=list)


class CommitResult(str):
    """The committed snapshot id, with the transaction timestamp attached
    (``.now``) so callers can meter commit latency against one clock read."""

    now: float

    def __new__(cls, sid: str, now: float) -> "CommitResult":
        self = super().__new__(cls, sid)
        self.now = now
        return self


class RemoveResult(tuple):
    """``(id, kind)`` — unpacks like the historical return — with the
    operation timestamp attached (``.now``) for metrics."""

    now: float

    def __new__(cls, sid: str, kind: str, now: float) -> "RemoveResult":
        self = super().__new__(cls, (sid, kind))
        self.now = now
        return self


class _AncestorCache:
    """Bounded LRU of parent-key -> ancestor id chain (immediate parent
    first). ``maxsize`` 0 disables caching entirely."""

    def __init__(self, maxsize: int):
        self.maxsize = max(0, maxsize)
        self._lock = _an.make_lock("metastore.ancestor_cache")
        self._map: OrderedDict[str, tuple[str, ...]] = OrderedDict()

    def get(self, key: str) -> Optional[tuple[str, ...]]:
        if self.maxsize == 0:
            return None
        with self._lock:
            chain = self._map.get(key)
            if chain is not None:
                self._map.move_to_end(key)
                metrics_data.SnapshotAncestorCacheHits.inc()
            else:
                metrics_data.SnapshotAncestorCacheMisses.inc()
            return chain

    def put(self, key: str, chain: tuple[str, ...]) -> None:
        if self.maxsize == 0:
            return
        with self._lock:
            self._map[key] = chain
            self._map.move_to_end(key)
            while len(self._map) > self.maxsize:
                self._map.popitem(last=False)

    def invalidate(self, key: str) -> None:
        with self._lock:
            self._map.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._map.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)


def _connect(path: str) -> sqlite3.Connection:
    # isolation_level=None: the stdlib's implicit-BEGIN machinery is off;
    # write_txn()/_read() own transaction boundaries explicitly.
    conn = sqlite3.connect(path, check_same_thread=False, isolation_level=None)
    # One row factory per connection, set once at creation: the seed
    # mutated row_factory on the single shared connection per call, which
    # raced concurrent readers into tuple/Row type confusion.
    conn.row_factory = sqlite3.Row
    conn.execute("PRAGMA busy_timeout=10000")
    conn.execute("PRAGMA journal_mode=WAL")
    conn.execute("PRAGMA synchronous=NORMAL")
    return conn


class _ReadPool:
    """Bounded pool of read-only-by-convention connections. Acquisition
    wait lands in the ``ntpu_snapshot_read_pool_wait_milliseconds``
    histogram — pool-size pressure is observable, not guessable."""

    def __init__(self, path: str, size: int):
        self._path = path
        self.size = max(1, size)
        self._sem = threading.BoundedSemaphore(self.size)
        self._lock = threading.Lock()
        self._free: list[sqlite3.Connection] = []
        self._all: list[sqlite3.Connection] = []
        self._closed = False

    @contextmanager
    def connection(self) -> Iterator[sqlite3.Connection]:
        t0 = time.perf_counter()
        self._sem.acquire()
        metrics_data.SnapshotReadPoolWait.observe((time.perf_counter() - t0) * 1000.0)
        try:
            with self._lock:
                if self._closed:
                    raise sqlite3.ProgrammingError(
                        "Cannot operate on a closed database."
                    )
                conn = self._free.pop() if self._free else None
            if conn is None:
                conn = _connect(self._path)
                with self._lock:
                    self._all.append(conn)
            try:
                yield conn
            finally:
                with self._lock:
                    if self._closed:
                        conn.close()
                    else:
                        self._free.append(conn)
        finally:
            self._sem.release()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            conns = list(self._all)
            self._all = []
            self._free = []
        for c in conns:
            c.close()


class MetaStore:
    """Transactional snapshot metadata store keyed by snapshot name."""

    def __init__(
        self,
        path: str,
        read_pool: Optional[int] = None,
        ancestor_cache: Optional[int] = None,
    ):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        cfg = resolve_snapshots_config()
        self._path = path
        self._wlock = _an.make_rlock("metastore.wlock")
        self._txn_depth = 0
        self._writer = _connect(path)
        with self._writer:
            self._writer.execute(
                "CREATE TABLE IF NOT EXISTS snapshots ("
                " id INTEGER PRIMARY KEY AUTOINCREMENT,"
                " key TEXT UNIQUE NOT NULL,"
                " kind TEXT NOT NULL,"
                " parent TEXT NOT NULL DEFAULT '',"
                " labels TEXT NOT NULL DEFAULT '{}',"
                " size INTEGER NOT NULL DEFAULT 0,"
                " inodes INTEGER NOT NULL DEFAULT 0,"
                " created REAL NOT NULL,"
                " updated REAL NOT NULL)"
            )
        self._pool = _ReadPool(
            path, cfg.read_pool if read_pool is None else read_pool
        )
        self._chain_cache = _AncestorCache(
            cfg.ancestor_cache if ancestor_cache is None else ancestor_cache
        )

    def close(self) -> None:
        self._pool.close()
        with self._wlock:
            self._writer.close()

    # -- transactions --------------------------------------------------------

    @contextmanager
    def write_txn(self) -> Iterator[sqlite3.Connection]:
        """Serialized writer path. Nests: inner ``write_txn`` blocks join
        the outer transaction, so multi-statement ops (and external
        batches like the usage accountant's drain) commit with one fsync."""
        t0 = time.perf_counter()
        self._wlock.acquire()
        try:
            if self._txn_depth == 0:
                metrics_data.SnapshotWriteLockWait.observe(
                    (time.perf_counter() - t0) * 1000.0
                )
                self._writer.execute("BEGIN IMMEDIATE")
            self._txn_depth += 1
            try:
                yield self._writer
            except BaseException:
                self._txn_depth -= 1
                if self._txn_depth == 0 and self._writer.in_transaction:
                    self._writer.execute("ROLLBACK")
                raise
            else:
                self._txn_depth -= 1
                if self._txn_depth == 0 and self._writer.in_transaction:
                    self._writer.execute("COMMIT")
        finally:
            self._wlock.release()

    @contextmanager
    def _read(self) -> Iterator[sqlite3.Connection]:
        """One pooled connection inside its own read transaction: a stable
        WAL snapshot for multi-statement reads, zero writer contention."""
        with self._pool.connection() as conn:
            conn.execute("BEGIN")
            try:
                yield conn
            finally:
                try:
                    conn.execute("COMMIT")
                except sqlite3.Error:
                    pass

    # -- internal ------------------------------------------------------------

    def _row(self, conn: sqlite3.Connection, key: str) -> sqlite3.Row:
        row = conn.execute("SELECT * FROM snapshots WHERE key=?", (key,)).fetchone()
        if row is None:
            raise errdefs.NotFound(f"snapshot {key!r} not found")
        return row

    def _info(self, row: sqlite3.Row) -> Info:
        return Info(
            kind=row["kind"],
            name=row["key"],
            parent=row["parent"],
            labels=json.loads(row["labels"]),
            created=row["created"],
            updated=row["updated"],
        )

    def _parent_ids(self, conn: sqlite3.Connection, parent_key: str) -> list[str]:
        cached = self._chain_cache.get(parent_key)
        if cached is not None:
            return list(cached)
        ids: list[str] = []
        key = parent_key
        while key:
            row = self._row(conn, key)
            ids.append(str(row["id"]))
            key = row["parent"]
        self._chain_cache.put(parent_key, tuple(ids))
        return ids

    # -- storage API (containerd storage package parity) ---------------------

    @trace.traced("metastore.create_snapshot")
    def create_snapshot(
        self, kind: str, key: str, parent: str = "", labels: Optional[dict[str, str]] = None
    ) -> Snapshot:
        failpoint.hit("metastore.create")
        if kind not in (KIND_VIEW, KIND_ACTIVE):
            raise errdefs.InvalidArgument(f"snapshot kind {kind!r} not creatable")
        if not key:
            raise errdefs.InvalidArgument("snapshot key is empty")
        with self.write_txn() as conn:
            if parent:
                prow = self._row(conn, parent)
                if prow["kind"] != KIND_COMMITTED:
                    raise errdefs.InvalidArgument(f"parent {parent!r} is not committed")
            now = time.time()
            try:
                cur = conn.execute(
                    "INSERT INTO snapshots (key, kind, parent, labels, created, updated)"
                    " VALUES (?,?,?,?,?,?)",
                    (key, kind, parent, json.dumps(labels or {}), now, now),
                )
            except sqlite3.IntegrityError:
                raise errdefs.AlreadyExists(f"snapshot {key!r} already exists") from None
            return Snapshot(
                id=str(cur.lastrowid),
                kind=kind,
                parent_ids=self._parent_ids(conn, parent) if parent else [],
            )

    @trace.traced("metastore.get_snapshot")
    def get_snapshot(self, key: str) -> Snapshot:
        with self._read() as conn:
            row = self._row(conn, key)
            return Snapshot(
                id=str(row["id"]),
                kind=row["kind"],
                parent_ids=self._parent_ids(conn, row["parent"]) if row["parent"] else [],
            )

    @trace.traced("metastore.get_info")
    def get_info(self, key: str) -> tuple[str, Info, Usage]:
        with self._read() as conn:
            row = self._row(conn, key)
            return str(row["id"]), self._info(row), Usage(row["size"], row["inodes"])

    @trace.traced("metastore.update_info")
    def update_info(self, info: Info, *fieldpaths: str) -> Info:
        """Update mutable snapshot fields; with fieldpaths only the named
        `labels.*` / `labels` paths change (containerd Update contract)."""
        with self.write_txn() as conn:
            row = self._row(conn, info.name)
            labels = json.loads(row["labels"])
            if fieldpaths:
                for fp in fieldpaths:
                    if fp == "labels":
                        labels = dict(info.labels)
                    elif fp.startswith("labels."):
                        k = fp[len("labels.") :]
                        if k in info.labels:
                            labels[k] = info.labels[k]
                        else:
                            labels.pop(k, None)
                    else:
                        raise errdefs.InvalidArgument(f"cannot update field {fp!r}")
            else:
                labels = dict(info.labels)
            now = time.time()
            conn.execute(
                "UPDATE snapshots SET labels=?, updated=? WHERE key=?",
                (json.dumps(labels), now, info.name),
            )
            row = self._row(conn, info.name)
            return self._info(row)

    @trace.traced("metastore.commit_active")
    def commit_active(
        self,
        key: str,
        name: str,
        usage: Usage,
        now: Optional[float] = None,
        extra_labels: Optional[dict[str, str]] = None,
    ) -> CommitResult:
        """Commit active snapshot `key` as committed snapshot `name`;
        returns the (unchanged) snapshot id with the transaction timestamp
        attached. One `now` stamps the whole operation, and any
        ``extra_labels`` merge in the same statement — one transaction
        where the seed used three."""
        failpoint.hit("metastore.commit")
        if not name:
            raise errdefs.InvalidArgument("committed name is empty")
        with self.write_txn() as conn:
            row = self._row(conn, key)
            if row["kind"] != KIND_ACTIVE:
                raise errdefs.InvalidArgument(f"snapshot {key!r} is not active")
            dup = conn.execute("SELECT 1 FROM snapshots WHERE key=?", (name,)).fetchone()
            if dup is not None:
                raise errdefs.AlreadyExists(f"snapshot {name!r} already exists")
            ts = time.time() if now is None else now
            labels = json.loads(row["labels"])
            if extra_labels:
                labels.update(extra_labels)
            conn.execute(
                "UPDATE snapshots SET key=?, kind=?, labels=?, size=?, inodes=?,"
                " updated=? WHERE key=?",
                (name, KIND_COMMITTED, json.dumps(labels), usage.size, usage.inodes, ts, key),
            )
        self._chain_cache.invalidate(key)
        self._chain_cache.invalidate(name)
        return CommitResult(str(row["id"]), ts)

    @trace.traced("metastore.remove")
    def remove(self, key: str, now: Optional[float] = None) -> RemoveResult:
        """Remove snapshot `key`; returns (id, kind) with the operation
        timestamp attached. Fails while children reference it (containerd
        Remove contract)."""
        failpoint.hit("metastore.remove")
        with self.write_txn() as conn:
            row = self._row(conn, key)
            child = conn.execute(
                "SELECT 1 FROM snapshots WHERE parent=?", (key,)
            ).fetchone()
            if child is not None:
                raise errdefs.FailedPrecondition(f"snapshot {key!r} has children")
            ts = time.time() if now is None else now
            conn.execute("DELETE FROM snapshots WHERE key=?", (key,))
        # Chains cached under OTHER keys cannot contain `key`: a chain
        # entry implies a child row referencing it, and remove refuses
        # while children exist — targeted invalidation is complete.
        self._chain_cache.invalidate(key)
        return RemoveResult(str(row["id"]), row["kind"], ts)

    @trace.traced("metastore.set_usages")
    def set_usages(self, usages: dict[str, Usage], now: Optional[float] = None) -> float:
        """Backfill usage for committed snapshots — one batched write
        transaction for the whole dict (the async accountant's drain).
        Rows that vanished (removed while the scan ran) are skipped
        silently. Returns the stamp used."""
        ts = time.time() if now is None else now
        if not usages:
            return ts
        with self.write_txn() as conn:
            for key, u in usages.items():
                conn.execute(
                    "UPDATE snapshots SET size=?, inodes=?, updated=? WHERE key=?",
                    (u.size, u.inodes, ts, key),
                )
        return ts

    def set_usage(self, key: str, usage: Usage, now: Optional[float] = None) -> float:
        return self.set_usages({key: usage}, now=now)

    def walk(self, fn: Callable[[str, Info], None]) -> None:
        with self._read() as conn:
            rows = conn.execute("SELECT * FROM snapshots ORDER BY id").fetchall()
        for row in rows:
            fn(str(row["id"]), self._info(row))

    def id_map(self) -> dict[str, str]:
        """id -> key for every stored snapshot (storage.IDMap, used by
        orphan-directory cleanup snapshot.go:1006-1038)."""
        with self._read() as conn:
            rows = conn.execute("SELECT id, key FROM snapshots").fetchall()
        return {str(row["id"]): row["key"] for row in rows}

    def usage(self, key: str) -> Usage:
        with self._read() as conn:
            row = self._row(conn, key)
            return Usage(row["size"], row["inodes"])

    def dump(self) -> str:
        """Canonical, id-normalized JSON dump: rows sorted by key, internal
        ids replaced by the ancestor *key* chain, timestamps excluded.
        Two stores that served the same logical op history dump
        identically regardless of id-assignment interleaving — the
        identity gate in tools/snapshot_profile.py and the concurrency
        property tests compare exactly this."""
        with self._read() as conn:
            rows = conn.execute("SELECT * FROM snapshots ORDER BY key").fetchall()
        out = [
            {
                "key": r["key"],
                "kind": r["kind"],
                "parent": r["parent"],
                "labels": json.loads(r["labels"]),
                "size": r["size"],
                "inodes": r["inodes"],
            }
            for r in rows
        ]
        return json.dumps(out, sort_keys=True)

    def cache_stats(self) -> dict[str, float]:
        return {
            "entries": len(self._chain_cache),
            "hits": metrics_data.SnapshotAncestorCacheHits.value(),
            "misses": metrics_data.SnapshotAncestorCacheMisses.value(),
        }

    # -- helpers (reference pkg/snapshot/storage.go) -------------------------

    def iterate_parent_snapshots(
        self, key: str, fn: Callable[[str, Info], bool]
    ) -> tuple[str, Info]:
        """Walk the parent chain starting at `key` until fn returns True
        (reference storage.go:79-108 IterateParentSnapshots); raises
        NotFound when the chain is exhausted."""
        cur = key
        while cur:
            sid, info, _ = self.get_info(cur)
            if fn(sid, info):
                return sid, info
            cur = info.parent
        raise errdefs.NotFound(f"no matching parent snapshot for {key!r}")
