"""Snapshot metadata store.

The reference leans on containerd's ``storage.MetaStore`` (bbolt,
snapshot/snapshot.go:272) for snapshot parentage, kinds, labels, and usage,
plus the helpers in pkg/snapshot/storage.go:19-108 (get/walk/update info,
``IterateParentSnapshots``). This module reproduces those semantics on
sqlite (stdlib, WAL, transactional):

- snapshots are addressed by *key* (client name) and carry an internal
  monotonic numeric *id* used for on-disk directory names;
- kinds: view / active / committed; Commit turns an active snapshot into a
  committed one under a new name;
- ``Snapshot.parent_ids`` is the full ancestor id chain, immediate parent
  first — what overlay lowerdir synthesis consumes;
- usage (size, inodes) recorded at commit time.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Optional

from nydus_snapshotter_tpu import failpoint
from nydus_snapshotter_tpu.utils import errdefs

KIND_VIEW = "view"
KIND_ACTIVE = "active"
KIND_COMMITTED = "committed"


@dataclass
class Usage:
    size: int = 0
    inodes: int = 0

    def add(self, other: "Usage") -> None:
        self.size += other.size
        self.inodes += other.inodes


@dataclass
class Info:
    kind: str
    name: str
    parent: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    created: float = 0.0
    updated: float = 0.0


@dataclass
class Snapshot:
    id: str
    kind: str
    parent_ids: list[str] = field(default_factory=list)


class MetaStore:
    """Transactional snapshot metadata store keyed by snapshot name."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.RLock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        with self._conn:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS snapshots ("
                " id INTEGER PRIMARY KEY AUTOINCREMENT,"
                " key TEXT UNIQUE NOT NULL,"
                " kind TEXT NOT NULL,"
                " parent TEXT NOT NULL DEFAULT '',"
                " labels TEXT NOT NULL DEFAULT '{}',"
                " size INTEGER NOT NULL DEFAULT 0,"
                " inodes INTEGER NOT NULL DEFAULT 0,"
                " created REAL NOT NULL,"
                " updated REAL NOT NULL)"
            )

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    # -- internal ------------------------------------------------------------

    def _row(self, key: str) -> sqlite3.Row:
        self._conn.row_factory = sqlite3.Row
        row = self._conn.execute("SELECT * FROM snapshots WHERE key=?", (key,)).fetchone()
        if row is None:
            raise errdefs.NotFound(f"snapshot {key!r} not found")
        return row

    def _info(self, row: sqlite3.Row) -> Info:
        return Info(
            kind=row["kind"],
            name=row["key"],
            parent=row["parent"],
            labels=json.loads(row["labels"]),
            created=row["created"],
            updated=row["updated"],
        )

    def _parent_ids(self, parent_key: str) -> list[str]:
        ids: list[str] = []
        key = parent_key
        while key:
            row = self._row(key)
            ids.append(str(row["id"]))
            key = row["parent"]
        return ids

    # -- storage API (containerd storage package parity) ---------------------

    def create_snapshot(
        self, kind: str, key: str, parent: str = "", labels: Optional[dict[str, str]] = None
    ) -> Snapshot:
        failpoint.hit("metastore.create")
        if kind not in (KIND_VIEW, KIND_ACTIVE):
            raise errdefs.InvalidArgument(f"snapshot kind {kind!r} not creatable")
        if not key:
            raise errdefs.InvalidArgument("snapshot key is empty")
        with self._lock:
            if parent:
                prow = self._row(parent)
                if prow["kind"] != KIND_COMMITTED:
                    raise errdefs.InvalidArgument(f"parent {parent!r} is not committed")
            now = time.time()
            try:
                with self._conn:
                    cur = self._conn.execute(
                        "INSERT INTO snapshots (key, kind, parent, labels, created, updated)"
                        " VALUES (?,?,?,?,?,?)",
                        (key, kind, parent, json.dumps(labels or {}), now, now),
                    )
            except sqlite3.IntegrityError:
                raise errdefs.AlreadyExists(f"snapshot {key!r} already exists") from None
            return Snapshot(
                id=str(cur.lastrowid),
                kind=kind,
                parent_ids=self._parent_ids(parent) if parent else [],
            )

    def get_snapshot(self, key: str) -> Snapshot:
        with self._lock:
            row = self._row(key)
            return Snapshot(
                id=str(row["id"]),
                kind=row["kind"],
                parent_ids=self._parent_ids(row["parent"]) if row["parent"] else [],
            )

    def get_info(self, key: str) -> tuple[str, Info, Usage]:
        with self._lock:
            row = self._row(key)
            return str(row["id"]), self._info(row), Usage(row["size"], row["inodes"])

    def update_info(self, info: Info, *fieldpaths: str) -> Info:
        """Update mutable snapshot fields; with fieldpaths only the named
        `labels.*` / `labels` paths change (containerd Update contract)."""
        with self._lock:
            row = self._row(info.name)
            labels = json.loads(row["labels"])
            if fieldpaths:
                for fp in fieldpaths:
                    if fp == "labels":
                        labels = dict(info.labels)
                    elif fp.startswith("labels."):
                        k = fp[len("labels.") :]
                        if k in info.labels:
                            labels[k] = info.labels[k]
                        else:
                            labels.pop(k, None)
                    else:
                        raise errdefs.InvalidArgument(f"cannot update field {fp!r}")
            else:
                labels = dict(info.labels)
            now = time.time()
            with self._conn:
                self._conn.execute(
                    "UPDATE snapshots SET labels=?, updated=? WHERE key=?",
                    (json.dumps(labels), now, info.name),
                )
            row = self._row(info.name)
            return self._info(row)

    def commit_active(self, key: str, name: str, usage: Usage) -> str:
        """Commit active snapshot `key` as committed snapshot `name`;
        returns the (unchanged) snapshot id."""
        failpoint.hit("metastore.commit")
        if not name:
            raise errdefs.InvalidArgument("committed name is empty")
        with self._lock:
            row = self._row(key)
            if row["kind"] != KIND_ACTIVE:
                raise errdefs.InvalidArgument(f"snapshot {key!r} is not active")
            dup = self._conn.execute("SELECT 1 FROM snapshots WHERE key=?", (name,)).fetchone()
            if dup is not None:
                raise errdefs.AlreadyExists(f"snapshot {name!r} already exists")
            with self._conn:
                self._conn.execute(
                    "UPDATE snapshots SET key=?, kind=?, size=?, inodes=?, updated=?"
                    " WHERE key=?",
                    (name, KIND_COMMITTED, usage.size, usage.inodes, time.time(), key),
                )
            return str(row["id"])

    def remove(self, key: str) -> tuple[str, str]:
        """Remove snapshot `key`; returns (id, kind). Fails while children
        reference it (containerd Remove contract)."""
        failpoint.hit("metastore.remove")
        with self._lock:
            row = self._row(key)
            child = self._conn.execute(
                "SELECT 1 FROM snapshots WHERE parent=?", (key,)
            ).fetchone()
            if child is not None:
                raise errdefs.FailedPrecondition(f"snapshot {key!r} has children")
            with self._conn:
                self._conn.execute("DELETE FROM snapshots WHERE key=?", (key,))
            return str(row["id"]), row["kind"]

    def walk(self, fn: Callable[[str, Info], None]) -> None:
        with self._lock:
            self._conn.row_factory = sqlite3.Row
            rows = self._conn.execute("SELECT * FROM snapshots ORDER BY id").fetchall()
        for row in rows:
            fn(str(row["id"]), self._info(row))

    def id_map(self) -> dict[str, str]:
        """id -> key for every stored snapshot (storage.IDMap, used by
        orphan-directory cleanup snapshot.go:1006-1038)."""
        with self._lock:
            rows = self._conn.execute("SELECT id, key FROM snapshots").fetchall()
        return {str(i): k for i, k in rows}

    def usage(self, key: str) -> Usage:
        with self._lock:
            row = self._row(key)
            return Usage(row["size"], row["inodes"])

    # -- helpers (reference pkg/snapshot/storage.go) -------------------------

    def iterate_parent_snapshots(
        self, key: str, fn: Callable[[str, Info], bool]
    ) -> tuple[str, Info]:
        """Walk the parent chain starting at `key` until fn returns True
        (reference storage.go:79-108 IterateParentSnapshots); raises
        NotFound when the chain is exhausted."""
        cur = key
        while cur:
            sid, info, _ = self.get_info(cur)
            if fn(sid, info):
                return sid, info
            cur = info.parent
        raise errdefs.NotFound(f"no matching parent snapshot for {key!r}")
