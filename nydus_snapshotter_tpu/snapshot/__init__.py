"""Snapshotter core (reference snapshot/ + pkg/label + pkg/snapshot)."""
