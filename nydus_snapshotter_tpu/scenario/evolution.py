"""Corpus-evolution model: images age between soak waves (docs/scenarios.md).

A year of production does not redeploy the same image: base layers get
patched, packages upgrade, configs churn. The chunk-dict/zdict planes
must keep earning their dedup under that drift, not just against the
frozen fixture trees. This module models the drift with the same
mechanism the committed tree2 manifest uses for its derivation: a file
"changes" by bumping its :func:`~.corpus.synth_content` generation, so
every unchanged byte stays bit-identical (and keeps deduping) while
changed files diverge realistically.

Determinism contract (same as :mod:`.arrivals`): whether path ``p``
mutates in epoch ``e`` is a keyed-hash coin ``unit_draw(seed, e,
"evolve|p") < drift_rate`` — a pure function of the spec, independent of
execution order. Generations are cumulative (a file that mutated in
epochs 2 and 5 is at ``base_gen + 2`` from epoch 5 on), so an epoch's
corpus can be re-materialized in isolation for serial replay.

Because the coin is a fixed uniform compared against ``drift_rate``, the
mutated set grows monotonically with ``drift_rate`` (and with epoch):
:func:`shared_fraction` — the fraction of bytes still at their base
generation, a proxy for the dict plane's dedup opportunity — decays
monotonically. ``tests/test_scenario_arrivals.py`` pins that property.
"""

from __future__ import annotations

import stat as statmod

from nydus_snapshotter_tpu.scenario.arrivals import unit_draw
from nydus_snapshotter_tpu.scenario.corpus import manifest_members

__all__ = ["mutations", "gen_of", "evolved_members", "shared_fraction"]


def mutations(seed: int, drift_rate: float, path: str, epoch: int) -> int:
    """How many times ``path`` has mutated by ``epoch`` (cumulative).

    Epoch 0 is the pristine corpus; the first coin lands in epoch 1.
    """
    g = 0
    for e in range(1, epoch + 1):
        if unit_draw(seed, e, f"evolve|{path}") < drift_rate:
            g += 1
    return g


def gen_of(manifest: dict, seed: int, drift_rate: float, epoch: int):
    """A ``gen_of(path)`` hook for :func:`~.corpus.manifest_members`.

    Drift stacks ON TOP of the manifest's own generations: tree2's
    derivation gens keep the cross-tree dedup relationship, and soak
    mutations age both trees coherently (a shared path that mutates
    reaches the same generation in either tree, so it still dedups).
    """
    base = {e["path"]: e.get("gen", 0) for e in manifest["entries"]}

    def _gen(path: str) -> int:
        return base.get(path, 0) + mutations(seed, drift_rate, path, epoch)

    return _gen


def evolved_members(manifest: dict, seed: int, drift_rate: float,
                    epoch: int) -> list:
    """The manifest's tar members as of ``epoch`` under the drift model."""
    return manifest_members(
        manifest, gen_of=gen_of(manifest, seed, drift_rate, epoch)
    )


def shared_fraction(manifest: dict, seed: int, drift_rate: float,
                    epoch: int) -> float:
    """Fraction of regular-file bytes still at their base generation.

    An analytic proxy for the dict plane's dedup opportunity against the
    pristine corpus — cheap enough for property tests (no conversion
    needed), monotone nonincreasing in both ``drift_rate`` and ``epoch``
    by construction.
    """
    total = changed = 0
    for e in manifest["entries"]:
        if not statmod.S_ISREG(e["mode"]) or e["size"] <= 0:
            continue
        total += e["size"]
        if mutations(seed, drift_rate, e["path"], epoch) > 0:
            changed += e["size"]
    return 1.0 if total == 0 else (total - changed) / total
