"""Leak sentinels: process-resource snapshots + growth-bound fitting.

A worst-day storm proves correctness under chaos; only a long soak
proves the stack is not *slowly* losing — RSS creeping per epoch, fds
left open by a teardown path, metastore rows surviving their remove,
cache entries outliving GC, trace spans dropped because the ring never
drains. This module is the shared measurement core (grown out of
``orchestrator.audit()``, which keeps the row/cache *consistency* side):

* :func:`snapshot` — one point-in-time sample: RSS (``/proc/self/status``
  ``VmRSS``, ``resource.getrusage`` fallback), open fds
  (``/proc/self/fd``), thread count, trace-ring drop total, plus any
  caller-supplied series (the soak feeds ``metastore_rows`` /
  ``cache_entries`` from the per-epoch audit).
* :class:`SentinelSeries` — accumulates one sample per epoch and fits a
  least-squares growth slope per series. A series whose slope exceeds
  its configured per-epoch bound is a leak finding: loud, named, and
  fatal to the run that asked.

Consumers: ``scenario/soak.py`` (per-epoch, fatal on violation),
``tools/scenario_storm.py`` (storm-scoped fd/thread growth gate) and
``tools/soak_profile.py`` (banked slopes in ``SOAK_r01.json``).
Metrics: the ``ntpu_soak_*`` gauges mirror the latest sample;
``ntpu_soak_leak_alerts_total`` counts bound violations by series.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from nydus_snapshotter_tpu import trace
from nydus_snapshotter_tpu.metrics import registry as _metrics

_reg = _metrics.default_registry

SOAK_RSS = _reg.register(
    _metrics.Gauge(
        "ntpu_soak_rss_bytes",
        "Resident set size at the last leak-sentinel sample",
    )
)
SOAK_FDS = _reg.register(
    _metrics.Gauge(
        "ntpu_soak_open_fds",
        "Open file descriptors at the last leak-sentinel sample",
    )
)
SOAK_THREADS = _reg.register(
    _metrics.Gauge(
        "ntpu_soak_threads",
        "Live Python threads at the last leak-sentinel sample",
    )
)
SOAK_ROWS = _reg.register(
    _metrics.Gauge(
        "ntpu_soak_metastore_rows",
        "Metastore snapshot rows at the last leak-sentinel sample",
    )
)
SOAK_CACHE_ENTRIES = _reg.register(
    _metrics.Gauge(
        "ntpu_soak_cache_entries",
        "Cache-dir entries at the last leak-sentinel sample",
    )
)
LEAK_ALERTS = _reg.register(
    _metrics.Counter(
        "ntpu_soak_leak_alerts_total",
        "Leak-sentinel growth-bound violations, by series",
        ("series",),
    )
)

# Gauge mirror for the caller-supplied series names the soak feeds.
_SERIES_GAUGES = {
    "rss_bytes": SOAK_RSS,
    "open_fds": SOAK_FDS,
    "threads": SOAK_THREADS,
    "metastore_rows": SOAK_ROWS,
    "cache_entries": SOAK_CACHE_ENTRIES,
}


def rss_bytes() -> int:
    """Resident set size, bytes. ``/proc`` when available (Linux),
    peak-RSS via ``resource`` otherwise (coarser, but monotone — a
    growth bound on it still catches a leak)."""
    try:
        with open("/proc/self/status", "r", encoding="ascii") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) << 10
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss << 10
    except Exception:
        return 0


def open_fds() -> int:
    """Open descriptor count via ``/proc/self/fd``; -1 when the platform
    has no cheap enumeration (series is then skipped by the fitter)."""
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1


def snapshot(extra: Optional[dict] = None) -> dict:
    """One sentinel sample; ``extra`` merges caller-owned series (e.g.
    the audit's row/cache-entry counts). Mirrors known series into the
    ``ntpu_soak_*`` gauges."""
    s = {
        "rss_bytes": rss_bytes(),
        "open_fds": open_fds(),
        "threads": threading.active_count(),
        "trace_drops": trace.dropped(),
    }
    if extra:
        s.update(extra)
    for name, gauge in _SERIES_GAUGES.items():
        if name in s and s[name] >= 0:
            gauge.set(float(s[name]))
    return s


def fit_slope(values: list, warmup: int = 1) -> float:
    """Least-squares growth per sample over a series. The first
    ``warmup`` samples are dropped once at least 2 non-warmup samples
    remain — the ramp epochs (imports, pools, per-shape JIT compiles)
    are allocation, not leak, and they dominate any short fit."""
    xs = [float(v) for v in values]
    drop = max(0, int(warmup))
    if len(xs) >= drop + 2:
        xs = xs[drop:]
    n = len(xs)
    if n < 2:
        return 0.0
    mean_i = (n - 1) / 2.0
    mean_v = sum(xs) / n
    num = sum((i - mean_i) * (v - mean_v) for i, v in enumerate(xs))
    den = sum((i - mean_i) ** 2 for i in range(n))
    return num / den if den else 0.0


class SentinelSeries:
    """One sample per epoch; slope-vs-bound verdicts on demand.

    ``bounds`` maps series name -> max allowed per-epoch growth (same
    unit as the series). Series without a bound are tracked and reported
    but never gate. A negative sample value marks the series unavailable
    on this platform and exempts it. ``warmup`` leading samples are
    excluded from every fit (see :func:`fit_slope`); gating starts at
    ``min_samples``, which is clamped to leave at least 2 fitted points
    past the warmup.
    """

    def __init__(self, bounds: dict, min_samples: int = 3, warmup: int = 1):
        self.bounds = dict(bounds)
        self.warmup = max(0, int(warmup))
        self.min_samples = max(2, self.warmup + 2, min_samples)
        self.samples: list[dict] = []

    def sample(self, extra: Optional[dict] = None) -> dict:
        s = snapshot(extra)
        self.samples.append(s)
        return s

    def series(self, name: str) -> list:
        return [s[name] for s in self.samples if name in s]

    def slopes(self) -> dict:
        names: list[str] = []
        for s in self.samples:
            for k in s:
                if k not in names:
                    names.append(k)
        out = {}
        for name in names:
            vals = self.series(name)
            if vals and min(vals) >= 0:
                out[name] = round(fit_slope(vals, warmup=self.warmup), 4)
        return out

    def check(self) -> list:
        """Bound violations as human-readable issue strings (and the
        ``ntpu_soak_leak_alerts_total`` bump) — empty means healthy."""
        issues = []
        if len(self.samples) < self.min_samples:
            return issues
        slopes = self.slopes()
        for name, bound in sorted(self.bounds.items()):
            slope = slopes.get(name)
            if slope is None:
                continue
            if slope > bound:
                LEAK_ALERTS.labels(name).inc()
                issues.append(
                    f"leak sentinel: {name} grows {slope:+.2f}/epoch "
                    f"(bound {bound:+.2f}/epoch over {len(self.samples)} samples)"
                )
        return issues

    def report(self) -> dict:
        return {
            "samples": len(self.samples),
            "slopes": self.slopes(),
            "bounds": dict(self.bounds),
            "issues": self.check(),
        }
