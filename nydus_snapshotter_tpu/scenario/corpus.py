"""Deterministic scenario corpora: real-derived trees + adversarial layers.

Every generator is a pure function of its seed/parameters — two calls
with the same arguments produce byte-identical tars on any host, so
scenario runs replay exactly and a storm's serial oracle re-derives the
same corpus without shipping blobs around.

Real trees
----------
``real_tree_members()`` materializes the committed manifest of the
reference's REAL Ubuntu v6 fixture (``misc/fixtures/
ubuntu_v6_manifest.json.gz``, extracted by ``tools/
extract_real_manifest.py``): real paths, modes, sizes, symlink targets
and per-file chunk runs; file CONTENT is synthesized deterministically
per ``(path, generation)``. ``real_tree2_members()`` is the second
real-derived tree (``ubuntu_v6_tree2_manifest.json.gz``): a sibling
image sharing the fixture's real base — a deterministic package subset
with a deterministic changed-file delta — used for **real-vs-real**
cross-tree dedup against a real bootstrap dict
(:func:`cross_tree_dedup`). Content-synthesis caveat: the fixture ships
no blob bytes, so shared paths dedup through identical *synthesized*
content; the measured ratio reflects real tree-shape/path overlap and
the real chunk grid, not byte-level CDC behavior of real payloads
(VERDICT r5 #7).

Adversarial layers
------------------
- :func:`incompressible_layer` — pure high-entropy bytes (the PR 10
  bypass must engage; a codec that compresses this is burning CPU);
- :func:`compressible_layer` — the control arm (bypass must NOT engage);
- :func:`cdc_resonant_data` — chunk-boundary-resonant bytes built from
  the gear table itself: ``mode="min"`` crafts a unit whose final
  32-byte window hashes to ``h & mask_small == 0`` so EVERY chunk cuts
  at ``min_size`` (maximum chunk count — chunk-index/dict pressure);
  ``mode="max"`` picks a constant byte whose steady-state gear hash
  misses both FastCDC masks so NO content cut ever fires and every chunk
  is a forced ``max_size`` cut (degenerate candidate-free streams);
- :func:`tiny_files_layer` — the million-tiny-file class (count is a
  parameter: storms size it to the box, the class is the point);
- :func:`single_huge_file_layer` — one file owning the whole layer;
- :func:`corrupt_variant` — truncated / bit-flipped / zero-filled blob
  variants for hostile-peer injection (guaranteed ``!= data``).
"""

from __future__ import annotations

import gzip
import hashlib
import io
import json
import os
import stat as statmod
import tarfile

import numpy as np

from nydus_snapshotter_tpu.ops import gear
from nydus_snapshotter_tpu.ops.cdc import CDCParams

_FIXTURES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "misc",
    "fixtures",
)

MANIFEST_TREE1 = "ubuntu_v6_manifest.json.gz"
MANIFEST_TREE2 = "ubuntu_v6_tree2_manifest.json.gz"


def load_manifest(name: str = MANIFEST_TREE1) -> dict:
    """Load a committed real-tree manifest (path/mode/size/symlink/chunks)."""
    with gzip.open(os.path.join(_FIXTURES, name), "rb") as f:
        return json.load(f)


def synth_content(path: str, generation: int, size: int) -> bytes:
    """Deterministic file content for a manifest entry.

    Per ``(path, generation)``: bumping a file's generation models a
    changed file in an upgraded image while every other byte stays
    identical — the SAME function for every tree, so shared paths at the
    same generation dedup across trees by construction.
    """
    seed = int.from_bytes(
        hashlib.sha256(f"{path}:{generation}".encode()).digest()[:8], "little"
    )
    rng = np.random.default_rng(seed)
    if seed % 5 < 3:  # text-ish: low-entropy, compressible
        base = rng.integers(32, 127, max(1, size // 6 + 1), dtype=np.uint8)
        return np.tile(base, 7)[:size].tobytes()
    return rng.integers(0, 256, size, dtype=np.uint8).tobytes()


def manifest_members(manifest: dict, gen_of=None) -> list:
    """Materialize a manifest as tar members ``(path, mode, data, link)``.

    ``gen_of(path)`` overrides the per-entry generation (tree2 entries
    carry their own ``gen``; tree1 defaults to 0).
    """
    members = []
    for e in manifest["entries"]:
        p = e["path"].lstrip("/")
        if not p:
            continue
        mode = e["mode"]
        if statmod.S_ISDIR(mode):
            members.append((p, mode, None, e.get("symlink")))
        elif statmod.S_ISLNK(mode):
            members.append((p, mode, None, e["symlink"]))
        elif statmod.S_ISREG(mode):
            gen = gen_of(e["path"]) if gen_of is not None else e.get("gen", 0)
            members.append((p, mode, synth_content(e["path"], gen, e["size"]), None))
    return members


def real_tree_members(gen_of=None) -> list:
    return manifest_members(load_manifest(MANIFEST_TREE1), gen_of=gen_of)


def real_tree2_members(gen_of=None) -> list:
    return manifest_members(load_manifest(MANIFEST_TREE2), gen_of=gen_of)


def members_to_tar(members) -> bytes:
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w", format=tarfile.GNU_FORMAT) as tf:
        for p, mode, data, link in members:
            ti = tarfile.TarInfo(p)
            ti.mode = mode & 0o7777
            if data is None and link is not None:
                ti.type = tarfile.SYMTYPE
                ti.linkname = link
                tf.addfile(ti)
            elif data is None:
                ti.type = tarfile.DIRTYPE
                tf.addfile(ti)
            else:
                ti.size = len(data)
                tf.addfile(ti, io.BytesIO(data))
    return buf.getvalue()


def _tar_of_files(files: list) -> bytes:
    """tar of ``[(path, bytes), ...]`` regular files (0o644)."""
    return members_to_tar([(p, 0o100644, data, None) for p, data in files])


# ---------------------------------------------------------------------------
# Adversarial generators
# ---------------------------------------------------------------------------


def incompressible_layer(seed: int, mib: int, files: int = 4) -> bytes:
    """All-incompressible layer: ``files`` files of pure random bytes.

    The PR 10 probe must route every chunk of this to the store-raw
    bypass; a run where it doesn't is a storm-scale bypass regression.
    """
    rng = np.random.default_rng(seed)
    per = max(1, (mib << 20) // max(1, files))
    return _tar_of_files(
        [
            (f"opaque/blob{i:02d}.bin", rng.integers(0, 256, per, dtype=np.uint8).tobytes())
            for i in range(files)
        ]
    )


def compressible_layer(seed: int, mib: int, files: int = 4) -> bytes:
    """Control arm: low-entropy text-like content (bypass must NOT engage)."""
    rng = np.random.default_rng(seed)
    per = max(1, (mib << 20) // max(1, files))
    out = []
    for i in range(files):
        # 8 KiB-period repetition: well inside every codec's match
        # window, so the content is unambiguously compressible.
        base = rng.integers(32, 127, max(1, per // 32 + 1), dtype=np.uint8)
        out.append((f"text/doc{i:02d}.txt", np.tile(base, 33)[:per].tobytes()))
    return _tar_of_files(out)


def _min_resonant_unit(seed: int, params: CDCParams) -> bytes:
    """A ``min_size`` unit whose final gear window is a small-mask
    candidate: repeated, every FastCDC chunk cuts at exactly
    ``min_size`` — the earliest judged position is the designed hit, so
    no accidental candidate can precede it.
    """
    table = gear.gear_table()
    rng = np.random.default_rng(seed)
    unit = rng.integers(0, 256, params.min_size, dtype=np.uint8)
    # Hash at the unit's last byte covers its final GEAR_WINDOW bytes:
    # h = sum_k table[u[-1-k]] << k (mod 2^32 — uint32 wrap IS the gear
    # semantics). Fix the last 3 bytes by search.
    ks = np.arange(3, gear.GEAR_WINDOW, dtype=np.uint32)
    base = np.sum(
        table[unit[-1 - np.arange(3, gear.GEAR_WINDOW)]].astype(np.uint32) << ks,
        dtype=np.uint32,
    )
    t0 = table.astype(np.uint32)
    mask = np.uint32(params.mask_small)
    ta = (t0 << np.uint32(2))[:, None]  # byte at -3
    tb = (t0 << np.uint32(1))[None, :]  # byte at -2
    pair = base + ta + tb  # uint32[256, 256]
    for c in range(256):
        hit = np.nonzero(((pair + t0[c]) & mask) == 0)
        if len(hit[0]):
            a, b = int(hit[0][0]), int(hit[1][0])
            unit[-3], unit[-2], unit[-1] = a, b, c
            return unit.tobytes()
    raise ValueError(
        f"no 3-byte resonant suffix for mask {params.mask_small:#x} "
        f"(avg {params.avg_size:#x} too large for this construction)"
    )


def _max_antiresonant_byte(params: CDCParams) -> int:
    """A constant byte whose steady-state gear hash misses BOTH FastCDC
    masks: a constant stream of it has zero candidates, so every chunk
    is a forced ``max_size`` cut."""
    table = gear.gear_table()
    for c in range(256):
        ss = (-int(table[c])) & 0xFFFFFFFF  # steady state of a constant stream
        if ss & params.mask_small and ss & params.mask_large:
            return c
    raise ValueError("no anti-resonant byte for these masks")  # pragma: no cover


def cdc_resonant_data(seed: int, size: int, avg_size: int, mode: str = "min") -> bytes:
    """Chunk-boundary-resonant content for the FastCDC engine.

    ``mode="min"``: every chunk cuts at exactly ``min_size`` (maximum
    chunk count). ``mode="max"``: no content cut ever fires — every
    chunk is a forced ``max_size`` cut (zero candidates). Deterministic
    in ``(seed, size, avg_size, mode)``.
    """
    params = CDCParams(avg_size)
    if mode == "min":
        unit = _min_resonant_unit(seed, params)
        reps = size // len(unit) + 1
        return (unit * reps)[:size]
    if mode == "max":
        return bytes([_max_antiresonant_byte(params)]) * size
    raise ValueError(f"cdc_resonant mode must be 'min' or 'max', got {mode!r}")


def cdc_resonant_layer(seed: int, mib: int, avg_size: int, mode: str = "min") -> bytes:
    return _tar_of_files(
        [(f"resonant/{mode}.bin", cdc_resonant_data(seed, mib << 20, avg_size, mode))]
    )


def tiny_files_layer(seed: int, count: int, fanout: int = 256) -> bytes:
    """The million-tiny-file class: ``count`` files of 1–64 bytes spread
    over ``fanout``-way directories (inode/metadata pressure; the blob is
    almost all chunk-table and bootstrap overhead)."""
    rng = np.random.default_rng(seed)
    sizes = rng.integers(1, 65, count)
    pool = rng.integers(32, 127, 64 * max(1, count // 64) + 64, dtype=np.uint8).tobytes()
    files = []
    for i in range(count):
        off = (i * 37) % (len(pool) - 64)
        files.append(
            (f"tiny/d{i % fanout:03d}/f{i:07d}", pool[off : off + int(sizes[i])])
        )
    return _tar_of_files(files)


def single_huge_file_layer(seed: int, mib: int) -> bytes:
    """One file owning the whole layer: the opposite degenerate shape —
    a single inode whose chunk run is the entire blob."""
    rng = np.random.default_rng(seed)
    size = mib << 20
    base = rng.integers(0, 256, max(1, size // 3 + 1), dtype=np.uint8)
    return _tar_of_files([("huge/image.raw", np.tile(base, 4)[:size].tobytes())])


def corrupt_variant(data: bytes, seed: int, mode: str = "flip") -> bytes:
    """Deterministically corrupted blob variant (always ``!= data``).

    ``flip`` XORs a seeded sample of bytes, ``truncate`` drops the tail,
    ``zero`` blanks a seeded extent — the three shapes a hostile or
    failing peer serves (tests pin that the CRC frame rejects each).
    """
    if not data:
        raise ValueError("cannot corrupt an empty blob")
    rng = np.random.default_rng(seed)
    arr = np.frombuffer(data, dtype=np.uint8).copy()
    if mode == "flip":
        idx = rng.integers(0, len(arr), max(1, len(arr) // 1024))
        arr[idx] ^= np.uint8(0xA5)
        return arr.tobytes()
    if mode == "truncate":
        keep = int(len(arr) * 0.75) if len(arr) > 4 else len(arr) - 1
        return arr[:keep].tobytes()
    if mode == "zero":
        lo = int(rng.integers(0, max(1, len(arr) // 2)))
        hi = min(len(arr), lo + max(1, len(arr) // 8))
        arr[lo:hi] = 0
        out = arr.tobytes()
        return out if out != data else bytes([data[0] ^ 0xFF]) + data[1:]
    raise ValueError(f"corrupt mode must be flip|truncate|zero, got {mode!r}")


# ---------------------------------------------------------------------------
# Real-vs-real cross-tree dedup (VERDICT r5 #8)
# ---------------------------------------------------------------------------

CROSS_TREE_CAVEAT = (
    "real layout (paths/modes/sizes/chunk grid from the reference's v6 "
    "fixture, tree2 a real-derived sibling subset), synthesized content: "
    "shared paths dedup through identical per-(path,gen) synthesized "
    "bytes, so the ratio measures real tree overlap on the real chunk "
    "grid, not byte-level CDC of real payloads (VERDICT r5 #7)"
)


def cross_tree_dedup(opt=None) -> dict:
    """Convert the real tree, round-trip its merged bootstrap through the
    REAL v6 on-disk layout into a chunk dict, then convert the second
    real-derived tree against it — the real-vs-real ratio counts tree2's
    bytes resolved into tree1's blobs (``--chunk-dict bootstrap=<real
    image>``, cross-image)."""
    from dataclasses import replace

    from nydus_snapshotter_tpu.converter.convert import (
        Merge,
        bootstrap_from_layer_blob,
        pack_layer,
    )
    from nydus_snapshotter_tpu.converter.types import MergeOption, PackOption
    from nydus_snapshotter_tpu.models.bootstrap import Bootstrap, ChunkDict
    from nydus_snapshotter_tpu.models.nydus_real import load_any_bootstrap
    from nydus_snapshotter_tpu.models.nydus_real_write import (
        real_from_bootstrap,
        write_real_v6,
    )

    # REAL v6 images are fixed-chunked (the on-disk chunk index is a
    # fixed grid), so both trees pack fixed for a valid round trip.
    opt = replace(opt, chunking="fixed") if opt is not None else PackOption(
        chunking="fixed", backend="numpy"
    )
    tar_a = members_to_tar(real_tree_members())
    blob_a, _res_a = pack_layer(tar_a, opt)
    merged = Merge([blob_a], MergeOption(with_tar=False))
    real_v6 = write_real_v6(real_from_bootstrap(Bootstrap.from_bytes(merged.bootstrap)))
    cdict = ChunkDict(load_any_bootstrap(real_v6))

    tar_b = members_to_tar(real_tree2_members())
    blob_b, res_b = pack_layer(tar_b, opt, chunk_dict=cdict)
    bs_b = bootstrap_from_layer_blob(blob_b)
    own = {res_b.blob_id}
    dedup_bytes = sum(
        c.uncompressed_size
        for c in bs_b.chunks
        if bs_b.blobs[c.blob_index].blob_id not in own
    )
    total = sum(c.uncompressed_size for c in bs_b.chunks)
    m2 = load_manifest(MANIFEST_TREE2)
    return {
        "tree1_mib": round(len(tar_a) / (1 << 20), 1),
        "tree2_mib": round(len(tar_b) / (1 << 20), 1),
        "tree2_inodes": m2["inodes"],
        "tree2_derivation": m2.get("derivation", ""),
        "dict_source": "REAL v6 layout round trip (write_real_v6 -> "
        "load_any_bootstrap)",
        "dict_chunks": len(cdict),
        "dedup_ratio": round(dedup_bytes / max(1, total), 4),
        "caveat": CROSS_TREE_CAVEAT,
    }
