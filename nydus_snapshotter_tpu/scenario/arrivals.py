"""Seeded arrival-process model for the soak engine (docs/scenarios.md).

A production year is not a constant load: demand breathes with the day,
spikes on deploy storms, and occasionally goes vertical when something
goes viral. The soak models that as three multiplicative terms, every
one a pure function of ``(seed, epoch)`` so a schedule is reproducible
bit-for-bit from the spec alone:

* **Poisson baseline** — the wave's pod count is drawn from a Poisson
  distribution around ``base_pods`` via a per-epoch derived
  ``np.random.default_rng`` stream.
* **Diurnal curve** — a cosine with period ``epochs_per_day`` and
  amplitude ``diurnal_amplitude`` modulates the mean (epoch 0 is the
  overnight trough, ``epochs_per_day / 2`` the midday peak).
* **Flash crowds** — with probability ``flash_prob`` an epoch is a flash
  crowd and the mean is multiplied by ``flash_factor``. The coin is a
  keyed blake2b hash, not an RNG stream, so arming or reordering other
  draws can never shift which epochs flash.

No wall-clock anywhere: ``schedule(soak, seed)`` is the same tuple on
every host, which is what lets ``tools/soak_profile.py`` replay single
epochs serially and demand byte-identity.
"""

from __future__ import annotations

import hashlib
import math
from dataclasses import dataclass

import numpy as np

from nydus_snapshotter_tpu.scenario.spec import SoakSpec

__all__ = ["Wave", "unit_draw", "diurnal_factor", "wave_for", "schedule"]


@dataclass(frozen=True)
class Wave:
    """One epoch's arrival decision, fully determined by (seed, epoch)."""

    epoch: int
    pods: int
    reads_per_pod: int
    flash: bool
    diurnal: float
    rate: float  # the modulated Poisson mean the pod count was drawn from

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch, "pods": self.pods,
            "reads_per_pod": self.reads_per_pod, "flash": self.flash,
            "diurnal": self.diurnal, "rate": self.rate,
        }


def unit_draw(seed: int, epoch: int, salt: str) -> float:
    """Deterministic uniform in [0, 1) keyed by (seed, epoch, salt).

    A keyed hash rather than an RNG stream: adding a new draw elsewhere
    can never shift this one, so flash epochs (and the evolution model's
    mutation coins, which share this primitive) are stable across
    versions of the soak loop.
    """
    h = hashlib.blake2b(
        f"{seed}|{epoch}|{salt}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "big") / float(1 << 64)


def diurnal_factor(epoch: int, epochs_per_day: int, amplitude: float) -> float:
    """Cosine day curve: epoch 0 = trough, epochs_per_day/2 = peak."""
    if epochs_per_day <= 1 or amplitude <= 0.0:
        return 1.0
    phase = 2.0 * math.pi * (epoch % epochs_per_day) / epochs_per_day
    return 1.0 - amplitude * math.cos(phase)


def wave_for(soak: SoakSpec, seed: int, epoch: int) -> Wave:
    """The arrival decision for one epoch (pure in seed+epoch)."""
    diurnal = diurnal_factor(epoch, soak.epochs_per_day, soak.diurnal_amplitude)
    flash = unit_draw(seed, epoch, "flash") < soak.flash_prob
    rate = soak.base_pods * diurnal * (soak.flash_factor if flash else 1.0)
    # Derived per-epoch stream: the draw for epoch e never depends on
    # how many draws epoch e-1 consumed.
    rng = np.random.default_rng(seed * 100003 + epoch)
    # Clamp the Poisson tail at ~2x the mean: a one-in-a-thousand draw
    # must not turn a soak epoch into an unbounded thread storm.
    pods = max(1, min(int(rng.poisson(rate)), int(rate * 2.0) + 2))
    return Wave(
        epoch=epoch,
        pods=pods,
        reads_per_pod=soak.reads_per_pod,
        flash=flash,
        diurnal=diurnal,
        rate=rate,
    )


def schedule(soak: SoakSpec, seed: int) -> tuple:
    """The full wave schedule — ``soak.epochs`` deterministic waves."""
    return tuple(wave_for(soak, seed, e) for e in range(soak.epochs))
