"""Scenario engine: declarative worst-day-in-production storms.

Every per-subsystem gate in this tree (pipeline determinism, blobcache
chaos, snapshot storms, peer churn, SLO actuation) exercises ONE layer
at a time on synthesized-content corpora. A production fleet sees all of
them at once: adversarial layers, corrupt peers, and mixed
convert+deploy+remove+GC churn with daemons crashing mid-storm. This
package composes **corpus generators × fault schedules × lifecycle
phases** into one gated end-to-end run:

- :mod:`scenario.corpus` — deterministic corpus generators: real-derived
  trees from the committed Ubuntu fixture manifests (including the
  second tree for real-vs-real cross-tree dedup), plus adversarial
  inputs — all-incompressible layers, chunk-boundary-resonant CDC
  content, tiny-file floods, single huge files, and corrupt/truncated
  blob variants for the peer tier;
- :mod:`scenario.spec` — a TOML scenario spec
  (``[[scenario.phases]]``) describing the phase sequence, corpus
  bindings, fault schedule and SLO budget;
- :mod:`scenario.orchestrator` — the runner: drives the REAL converter,
  snapshot control plane, blobcache/peer data plane, cache GC and SLO
  engine through the spec, replayable serially for byte-identity, with
  an end-state metastore/cache audit.

The gated profile lives in ``tools/scenario_storm.py`` and the spec
catalog in ``misc/scenarios/``. ``ntpuctl scenario`` lists specs and the
last banked gate results.

Failpoint: ``scenario.phase`` fires at every phase entry (an armed error
fails the run loudly, naming the phase). Metrics: ``ntpu_scenario_*``.
Config: ``[scenario]`` with ``NTPU_SCENARIO*`` env overrides.
"""

from __future__ import annotations

import os

from nydus_snapshotter_tpu.metrics import registry as _metrics

_reg = _metrics.default_registry

PHASES_TOTAL = _reg.register(
    _metrics.Counter(
        "ntpu_scenario_phases_total",
        "Scenario phases executed, by lifecycle op "
        "(convert/deploy/remove/gc/crash_restart)",
        ("op",),
    )
)
RUNS_TOTAL = _reg.register(
    _metrics.Counter(
        "ntpu_scenario_runs_total",
        "Scenario runs completed, by outcome (pass/fail)",
        ("outcome",),
    )
)
FAULTS_ARMED = _reg.register(
    _metrics.Counter(
        "ntpu_scenario_faults_armed_total",
        "Failpoint arms performed by scenario fault schedules",
    )
)


class ScenarioRuntimeConfig:
    __slots__ = ("spec_dir", "report_path", "seed", "pods")

    def __init__(self, spec_dir: str, report_path: str, seed: int, pods: int):
        self.spec_dir = spec_dir
        self.report_path = report_path
        self.seed = seed
        self.pods = pods


def _global_scenario_config():
    try:
        from nydus_snapshotter_tpu.config import config as _cfg

        return _cfg.get_global_config().scenario
    except Exception:
        return None


def resolve_scenario_config() -> ScenarioRuntimeConfig:
    """env (``NTPU_SCENARIO*``) > ``[scenario]`` global config > defaults."""
    from nydus_snapshotter_tpu.daemon.fetch_sched import _env_int

    sc = _global_scenario_config()
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    spec_dir = os.environ.get(
        "NTPU_SCENARIO_SPEC_DIR",
        getattr(sc, "spec_dir", "") or os.path.join(repo, "misc", "scenarios"),
    )
    report_path = os.environ.get(
        "NTPU_SCENARIO_REPORT",
        getattr(sc, "report_path", "") or os.path.join(repo, "SCENARIO_STORM_r01.json"),
    )
    return ScenarioRuntimeConfig(
        spec_dir=spec_dir,
        report_path=report_path,
        seed=_env_int("NTPU_SCENARIO_SEED", getattr(sc, "seed", 7)),
        pods=max(1, _env_int("NTPU_SCENARIO_PODS", getattr(sc, "pods", 16))),
    )
