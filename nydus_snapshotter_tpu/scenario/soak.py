"""Long-soak orchestrator: a production year compressed into epochs.

The worst-day storm (``tools/scenario_storm.py``) proves the stack
survives a hand-written bad afternoon; the soak proves it survives a
YEAR of ordinary ones. :class:`SoakRunner` holds the full scenario
stack — snapshot control plane, blobcache/peer data plane, GC, SLO
judge, soci arm, whatever the template phases enable — under continuous
convert/deploy/read/remove/GC churn across N **epochs**, where each
epoch is one wave of the seeded arrival process (:mod:`.arrivals`) over
a corpus aged by the drift model (:mod:`.evolution`):

1. ``soak.wave`` fires; the wave's pod count is the deterministic
   Poisson × diurnal × flash-crowd draw for ``(seed, epoch)``.
2. ``soak.evolve`` fires; the real-tree corpora are re-materialized at
   this epoch's generations and re-converted — the chunk-dict/zdict
   planes age exactly as registries do in production.
3. The wave deploys (template deploy phase, pods from the wave), demand
   reads run, a deterministic fraction is removed, GC sweeps.
4. The scale-up policy (:class:`~nydus_snapshotter_tpu.metrics.slo.
   SloScaleUp`) ticks on the wave's demand-pressure signal: clean burn
   but growing queues spawns serve-only peer members for the NEXT wave
   (``extra_serve_pods``), quiet retires them. A failed spawn degrades
   to shed-only — pinned by the ``soak.scaleup`` chaos suite.
5. Leak sentinels sample (RSS, fds, threads, metastore rows, cache
   entries, trace drops) and the end-state :meth:`~.orchestrator.
   ScenarioRunner.audit` runs — ANY audit issue or fitted growth-bound
   violation fails the epoch loudly.

Identity: every epoch's corpus, wave and read set are pure functions of
``(seed, epoch)``, so :meth:`SoakRunner.replay_epoch` re-runs one epoch
in a fresh serial runner and must reproduce the epoch's read digests
and blob ids byte-for-byte — the spot-check gate in
``tools/soak_profile.py`` (full-run identity stays the worst-day gate's
job; a soak's value is the *churn*, not a 30-minute serial oracle).

Config: ``[soak]`` with ``NTPU_SOAK*`` env overrides (epochs/report
path); the per-spec knobs live in ``[scenario.soak]`` (spec.py).
"""

from __future__ import annotations

import os
import time
from dataclasses import replace as _dc_replace
from typing import Optional

from nydus_snapshotter_tpu import failpoint
from nydus_snapshotter_tpu.scenario import arrivals, corpus as corpus_gen, evolution
from nydus_snapshotter_tpu.scenario.orchestrator import (
    ScenarioRunError,
    ScenarioRunner,
)
from nydus_snapshotter_tpu.scenario.sentinel import SentinelSeries
from nydus_snapshotter_tpu.scenario.spec import PhaseSpec, ScenarioSpec

# Phase-index namespace per epoch: epoch e's convert/deploy/remove/gc
# phases run as indices BASE + e*STRIDE + {0,1,2,3}, so snapshot keys,
# pod dirs and read-digest tags never collide across epochs (and a
# replayed epoch lands on identical tags).
EPOCH_IDX_BASE = 100
EPOCH_IDX_STRIDE = 10

# Node admission ceiling for the concurrent soak: every demand read of a
# wave passes one shared per-epoch gate, so a flash crowd queues where a
# real cluster's does — at the serving tier's concurrency limit — and
# the demand-pressure signal (queued_peak / wait EWMA) actually moves.
# Each serve-only member the scale-up policy spawns brings its uplink:
# +SLOTS_PER_MEMBER admission slots for the NEXT wave. That is the
# closed loop the A/B efficacy gate measures.
NODE_SLOTS = 8
SLOTS_PER_MEMBER = 4


class SoakRuntimeConfig:
    __slots__ = ("epochs", "spot_epochs", "report_path")

    def __init__(self, epochs: int, spot_epochs: int, report_path: str):
        self.epochs = epochs
        self.spot_epochs = spot_epochs
        self.report_path = report_path


def _global_soak_config():
    try:
        from nydus_snapshotter_tpu.config import config as _cfg

        return _cfg.get_global_config().soak
    except Exception:
        return None


def resolve_soak_config() -> SoakRuntimeConfig:
    """env (``NTPU_SOAK*``) > ``[soak]`` global config > defaults.

    ``epochs`` 0 means "use the spec's ``[scenario.soak]`` value";
    ``spot_epochs`` is how many epochs the profile replays serially for
    the identity spot-check."""
    from nydus_snapshotter_tpu.daemon.fetch_sched import _env_int

    sc = _global_soak_config()
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    report_path = os.environ.get(
        "NTPU_SOAK_REPORT",
        getattr(sc, "report_path", "") or os.path.join(repo, "SOAK_r01.json"),
    )
    return SoakRuntimeConfig(
        epochs=max(0, _env_int("NTPU_SOAK_EPOCHS", getattr(sc, "epochs", 0))),
        spot_epochs=max(
            1, _env_int("NTPU_SOAK_SPOT_EPOCHS", getattr(sc, "spot_epochs", 2))
        ),
        report_path=report_path,
    )


class SoakRunner(ScenarioRunner):
    """Drive a spec's ``[scenario.soak]`` endurance loop.

    Reuses every phase primitive of :class:`ScenarioRunner`; what the
    soak adds is the epoch loop, the corpus-evolution override of
    :meth:`_corpus_tar`, the leak sentinels and the closed-loop
    capacity policy. ``serial=True`` gives the replay shape (pods
    sequential, peers off, no scale-up) used for identity spot-checks.
    """

    def __init__(
        self,
        spec: ScenarioSpec,
        workdir: str,
        serial: bool = False,
        epochs: Optional[int] = None,
        **kw,
    ):
        if spec.soak is None:
            raise ScenarioRunError(
                f"spec {spec.name!r} has no [scenario.soak] table"
            )
        super().__init__(spec, workdir, serial=serial, **kw)
        self.soak = spec.soak
        self.epochs = epochs if epochs else self.soak.epochs
        self.epoch = 0
        self.waves: list[dict] = []
        self.epoch_reports: list[dict] = []
        # Warm-up exclusion and evidence window scale with run length: a
        # full-size soak spends its first ~4 epochs compiling per-shape
        # convert kernels and filling allocator pools (measured RSS
        # plateaus around epoch 5 at full scale) — ramp, not leak —
        # and the allocator keeps taking one-off ~50 MiB pool steps at
        # arbitrary later epochs. A leak is MONOTONE growth, so the fit
        # only fires once the post-warmup window is wide enough that a
        # single step dilutes below the per-epoch bound (8+ samples:
        # a 54 MiB step reads as <8 MiB/epoch, a real 30 MiB/epoch leak
        # still reads as 30). Short smoke runs keep the tight window so
        # their sentinel gate still fires inside CI walls.
        warmup = 1 if self.epochs <= 4 else 4
        min_samples = 3 if self.epochs <= 4 else 12
        self.sentinel = SentinelSeries({
            "rss_bytes": self.soak.rss_growth_mib_per_epoch * (1 << 20),
            "open_fds": self.soak.fd_growth_per_epoch,
            "metastore_rows": self.soak.row_growth_per_epoch,
        }, warmup=warmup, min_samples=min_samples)
        self.scaleup = None  # built in run_soak (concurrent mode only)

    # -- corpus evolution ----------------------------------------------------

    def _corpus_tar(self, cid: str) -> bytes:
        """Real-tree corpora age with the drift model; synthetic kinds
        stay frozen (their value is the adversarial shape, not realism).
        Epoch 0 is byte-identical to the base runner's corpus."""
        cs = self.spec.corpus_by_id(cid)
        if cs.kind in ("real_tree", "real_tree2") and self.epoch > 0:
            manifest = corpus_gen.load_manifest(
                corpus_gen.MANIFEST_TREE1 if cs.kind == "real_tree"
                else corpus_gen.MANIFEST_TREE2
            )
            return corpus_gen.members_to_tar(
                evolution.evolved_members(
                    manifest, self.spec.seed, self.soak.drift_rate, self.epoch
                )
            )
        return super()._corpus_tar(cid)

    # -- template phases -----------------------------------------------------

    def _template(self, op: str) -> Optional[PhaseSpec]:
        for p in self.spec.phases:
            if p.op == op:
                return p
        return None

    def _epoch_phases(self, wave) -> list[tuple[str, PhaseSpec]]:
        """The four-phase churn program for one wave, derived from the
        spec's template phases (first of each op; convert/deploy are
        synthesized over all corpora when the spec has none)."""
        all_ids = tuple(c.id for c in self.spec.corpus)
        conv = self._template("convert") or PhaseSpec(op="convert", corpus=all_ids)
        dep = self._template("deploy") or PhaseSpec(op="deploy", corpus=all_ids)
        # Default remove fraction is 1.0 (not the storm's 0.5): a soak
        # epoch must return to steady state or the metastore-row growth
        # bound trips on perfectly healthy runs.
        rem = self._template("remove") or PhaseSpec(op="remove", fraction=1.0)
        gc = self._template("gc") or PhaseSpec(op="gc")
        return [
            ("convert", conv),
            ("deploy", _dc_replace(dep, pods=wave.pods)),
            ("remove", rem),
            ("gc", gc),
        ]

    # -- the epoch loop ------------------------------------------------------

    def _node_gate_for(self, e: int):
        """This epoch's node admission ceiling: base slots plus the
        uplink each live serve-only member contributes. Fresh per epoch
        so queued_peak / wait EWMA describe ONE wave, not the year."""
        from nydus_snapshotter_tpu.daemon.fetch_sched import (
            AdmissionGate,
            MemoryBudget,
        )

        slots = NODE_SLOTS + SLOTS_PER_MEMBER * self.extra_serve_pods
        return AdmissionGate(
            budget=MemoryBudget(slots * (1 << 20)),
            max_concurrent=slots,
            demand_reserve=0,
            name=f"soak-node-e{e}",
        )

    def _run_epoch(self, e: int) -> dict:
        failpoint.hit("soak.wave")
        wave = arrivals.wave_for(self.soak, self.spec.seed, e)
        self.epoch = e
        self.waves.append(wave.to_dict())
        if not self.serial and not self.pods_sequential:
            self.node_gate = self._node_gate_for(e)
        base = EPOCH_IDX_BASE + e * EPOCH_IDX_STRIDE
        detail: dict = {"epoch": e, "wave": wave.to_dict()}
        t0 = time.perf_counter()
        for k, (op, phase) in enumerate(self._epoch_phases(wave)):
            if op == "convert":
                failpoint.hit("soak.evolve")
            dispatch = {
                "convert": self._phase_convert,
                "deploy": self._phase_deploy,
                "remove": self._phase_remove,
                "gc": self._phase_gc,
            }
            detail[op] = dispatch[op](base + k, phase)
        detail["wall_s"] = round(time.perf_counter() - t0, 4)
        # Registry GC: drop blob bytes from retired corpus generations
        # (ids stay known for audit accounting) — a year of evolution
        # must not read as an RSS leak in the sim's own origin.
        live = {img["blob_id"] for img in self.images.values()}
        detail["retired_blobs"] = self.registry.retire_except(live)
        detail["demand_pressure"] = dict(self.last_demand_pressure)
        detail["extra_serve_pods"] = self.extra_serve_pods
        if self.scaleup is not None:
            event = self.scaleup.tick()
            if event is not None:
                detail["scaleup_event"] = event
        aud = self.audit()
        detail["audit"] = {
            "clean": aud["clean"],
            "issues": aud["issues"][:8],
            "metastore_rows": aud["metastore_rows"],
            "cache_files": aud["cache_files"],
        }
        self.sentinel.sample({
            "metastore_rows": aud["metastore_rows"],
            "cache_entries": aud["cache_files"],
        })
        if not aud["clean"]:
            raise ScenarioRunError(
                f"epoch {e}: audit drift — {aud['issues'][:4]}"
            )
        leaks = self.sentinel.check()
        if leaks:
            raise ScenarioRunError(f"epoch {e}: {leaks[0]}")
        detail["fingerprint"] = self.epoch_fingerprint(e)
        self.epoch_reports.append(detail)
        return detail

    def epoch_fingerprint(self, e: int) -> dict:
        """One epoch's identity surface: the wave's read digests plus
        the epoch's converted blob ids — everything a standalone serial
        replay of the same epoch must reproduce byte-for-byte."""
        tag = f"ph{EPOCH_IDX_BASE + e * EPOCH_IDX_STRIDE + 1}-"
        return {
            "reads": {
                k: v for k, v in sorted(self.read_digests.items())
                if k.startswith(tag)
            },
            "blobs": {
                cid: img["blob_id"]
                for cid, img in sorted(self.images.items())
                if not str(cid).startswith("soci:")
            },
        }

    def _build_scaleup(self):
        from nydus_snapshotter_tpu.metrics.slo import SloScaleUp

        def spawn(target: int) -> None:
            self.extra_serve_pods = target

        def retire(target: int) -> None:
            self.extra_serve_pods = target

        def demand() -> dict:
            # The queue drains before teardown reads the gate, so the
            # live depth is ~always 0; the epoch's PEAK depth is the
            # load signal the policy should act on.
            p = dict(self.last_demand_pressure)
            p["queued"] = max(
                int(p.get("queued", 0)), int(p.get("queued_peak", 0))
            )
            return p

        return SloScaleUp(
            self._engine,
            demand_fn=demand,
            spawn_fn=spawn,
            retire_fn=retire,
            queue_high=self.soak.queue_high,
            wait_high_ms=self.soak.wait_high_ms,
            quiet_ticks=self.soak.quiet_epochs,
            max_members=self.soak.max_extra_members,
        )

    def run_soak(self) -> dict:
        """The endurance loop; returns the soak report (never raises —
        failure lands in ``ok``/``error`` like :meth:`ScenarioRunner.run`)."""
        from nydus_snapshotter_tpu import scenario as _scn

        report = {
            "scenario": self.spec.name,
            "mode": "soak",
            "serial": self.serial,
            "seed": self.spec.seed,
            "epochs_planned": self.epochs,
            "epochs": [],
            "ok": True,
            "error": "",
        }
        self._open_control_plane()
        self._start_judge()
        if self.soak.scaleup and not self.serial and not self.pods_sequential:
            self.scaleup = self._build_scaleup()
        try:
            for e in range(self.epochs):
                report["epochs"].append(self._run_epoch(e))
        except BaseException as exc:  # noqa: BLE001 — the run fails loudly
            report["ok"] = False
            report["error"] = f"epoch {len(report['epochs'])}: {exc!r}"
        finally:
            self._stop_judge()
        if self._engine is not None:
            status = self._engine.status()
            breaches = status.get("breaches", [])
            report["slo"] = {
                "breaches": len(breaches),
                "demand_p95_ms": self.demand_p95_ms(),
            }
            if breaches and report["ok"]:
                report["ok"] = False
                report["error"] = (
                    f"SLO judge: {len(breaches)} multi-window burn "
                    "breach(es) across the soak"
                )
        report["waves"] = list(self.waves)
        report["sentinel"] = self.sentinel.report()
        if report["sentinel"]["issues"] and report["ok"]:
            report["ok"] = False
            report["error"] = report["sentinel"]["issues"][0]
        if self.scaleup is not None:
            report["scaleup"] = self.scaleup.state()
        report["origin"] = {
            "egress_bytes": self.registry.egress,
            "calls": self.registry.calls,
        }
        _scn.RUNS_TOTAL.labels("pass" if report["ok"] else "fail").inc()
        return report


def replay_epoch(
    spec: ScenarioSpec,
    epoch: int,
    workdir: str,
    serial: bool = True,
    extra_serve_pods: int = 0,
    **kw,
) -> dict:
    """Standalone re-run of ONE soak epoch in a fresh runner; returns
    ``{"fingerprint", "demand_pressure", "demand_p95_ms", "ok"}``.

    With ``serial=True`` this is the identity oracle: the epoch's corpus
    and wave are pure functions of ``(seed, epoch)``, so the replay's
    fingerprint must equal the soak's record for that epoch. With
    ``serial=False`` it is the capacity A/B arm: same epoch, chosen
    ``extra_serve_pods``, compare demand pressure — pass the soak's
    ``origin_latency_s`` so both arms sit on the same analytic latency
    floor the soak measured against."""
    runner = SoakRunner(spec, workdir, serial=serial, epochs=1, **kw)
    runner._open_control_plane()
    runner._start_judge()
    runner.extra_serve_pods = 0 if serial else max(0, int(extra_serve_pods))
    try:
        detail = runner._run_epoch(epoch)
        return {
            "fingerprint": detail["fingerprint"],
            "demand_pressure": detail["demand_pressure"],
            "demand_p95_ms": runner.demand_p95_ms(),
            "ok": True,
        }
    finally:
        runner._stop_judge()
        runner.close()
