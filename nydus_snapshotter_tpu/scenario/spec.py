"""Declarative scenario specs: TOML in, validated phase program out.

A spec is one TOML document::

    [scenario]
    name = "worst-day"
    description = "full-lifecycle churn with hostile inputs"
    seed = 7
    pods = 16

    [[scenario.corpus]]
    id = "ubuntu"
    kind = "real_tree"        # real_tree | real_tree2 | incompressible |
                              # compressible | cdc_resonant | tiny_files |
                              # huge_file
    # mib = 2                 # sized kinds
    # count = 2000            # tiny_files
    # avg_kib = 4             # cdc_resonant (FastCDC average, power of 2)
    # mode = "min"            # cdc_resonant: min | max

    [[scenario.phases]]
    op = "convert"            # convert | deploy | remove | gc | crash_restart
    corpus = ["ubuntu"]
    # adaptive = true         # convert: enable the adaptive codec
    # shard_failover = true   # convert: dict-HA fault arm (primary dies
    #                         # mid-merge; promotion + failover must match
    #                         # the straight-line oracle byte for byte)

    [[scenario.phases]]
    op = "deploy"
    corpus = ["ubuntu"]
    # pods = 8                # default scenario.pods
    # layers = 4              # snapshot chain depth per pod
    # peers = true            # peer chunk tier between pods (default on)
    # corrupt_peer = true     # one hostile peer serves corrupted bytes
    # soci = true             # unconverted gzip layer via the soci index
    # read_mib = 8            # demand-read window per pod (0 = whole blob)
    # crash = "mid"           # crash/restart the control plane mid-phase
    # gc_watermark_mib = 8    # concurrent watermark eviction during the phase
    # deploy_api = "grpc"     # drive the real snapshots.v1 gRPC surface
    # kill_zone = true        # topology fault arm: pods get deterministic
    #                         # rack:zone:region localities and one whole
    #                         # zone is killed mid-deploy

    [[scenario.phases]]
    op = "remove"
    # fraction = 0.5          # deterministic subset of deployed pods

    [[scenario.phases]]
    op = "gc"
    # watermark_mib = 0       # 0 = age-GC only

    [[scenario.faults]]
    site = "blobcache.fetch"  # any failpoint.KNOWN_SITES entry
    action = "error(OSError)*2"
    phase = 1                 # 0-based phase index the fault is armed for

    [scenario.slo]            # the in-run judge (deploy demand reads)
    demand_threshold_ms = 50.0
    demand_p95_factor = 2.0   # vs the unloaded baseline (gate, tools)
    target = 0.9
    window_secs = 0.6
    burn_threshold = 2.0

    [scenario.soak]           # endurance plane (scenario/soak.py; optional)
    epochs = 6                # waves to run
    base_pods = 4             # Poisson mean, diurnal x flash modulated
    drift_rate = 0.08         # corpus-evolution mutation probability
    # full key set (arrivals, scale-up, sentinel growth bounds) in
    # docs/scenarios.md

Validation is strict: unknown keys, unknown ops/kinds, fault sites not
in the failpoint catalog, unparsable fault actions and out-of-range
phase references all raise :class:`ScenarioSpecError` naming the table.
``load`` → ``to_dict`` → ``from_dict`` round-trips exactly.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

from nydus_snapshotter_tpu import failpoint
from nydus_snapshotter_tpu.failpoint.spec import SpecError, parse_action
from nydus_snapshotter_tpu.utils.tomlcompat import tomllib


class ScenarioSpecError(ValueError):
    pass


CORPUS_KINDS = (
    "real_tree",
    "real_tree2",
    "incompressible",
    "compressible",
    "cdc_resonant",
    "tiny_files",
    "huge_file",
)
PHASE_OPS = ("convert", "deploy", "remove", "gc", "crash_restart")
CRASH_MODES = ("", "mid")
DEPLOY_APIS = ("", "snapshotter", "grpc")
# Per-layer lazy formats a soci deploy phase can ship (mirrors the
# FormatRouter's probe classes; "gzip" is the historical default).
SOCI_FORMATS = ("gzip", "zstd-seekable", "zstd-opaque", "zstd-chunked")


def _only_keys(table: dict, allowed: set, where: str) -> None:
    unknown = set(table) - allowed
    if unknown:
        raise ScenarioSpecError(f"{where}: unknown keys {sorted(unknown)}")


@dataclass(frozen=True)
class CorpusSpec:
    id: str
    kind: str
    mib: int = 1
    count: int = 1000
    avg_kib: int = 4
    mode: str = "min"

    @classmethod
    def from_dict(cls, d: dict, idx: int) -> "CorpusSpec":
        where = f"[[scenario.corpus]] #{idx}"
        _only_keys(d, {"id", "kind", "mib", "count", "avg_kib", "mode"}, where)
        if not d.get("id"):
            raise ScenarioSpecError(f"{where}: needs an id")
        kind = d.get("kind", "")
        if kind not in CORPUS_KINDS:
            raise ScenarioSpecError(
                f"{where} ({d['id']!r}): unknown kind {kind!r} "
                f"(one of {', '.join(CORPUS_KINDS)})"
            )
        spec = cls(
            id=d["id"],
            kind=kind,
            mib=int(d.get("mib", 1)),
            count=int(d.get("count", 1000)),
            avg_kib=int(d.get("avg_kib", 4)),
            mode=d.get("mode", "min"),
        )
        if spec.mib < 1 or spec.count < 1:
            raise ScenarioSpecError(f"{where} ({spec.id!r}): mib/count must be >= 1")
        if spec.kind == "cdc_resonant":
            avg = spec.avg_kib << 10
            if avg & (avg - 1) or spec.avg_kib < 4:
                raise ScenarioSpecError(
                    f"{where} ({spec.id!r}): avg_kib must be a power of two >= 4"
                )
            if spec.mode not in ("min", "max"):
                raise ScenarioSpecError(
                    f"{where} ({spec.id!r}): mode must be min|max"
                )
        return spec

    def to_dict(self) -> dict:
        return {
            "id": self.id, "kind": self.kind, "mib": self.mib,
            "count": self.count, "avg_kib": self.avg_kib, "mode": self.mode,
        }


@dataclass(frozen=True)
class PhaseSpec:
    op: str
    corpus: tuple = ()
    pods: int = 0  # 0 = scenario default
    layers: int = 3
    adaptive: bool = False
    peers: bool = True
    corrupt_peer: bool = False
    soci: bool = False
    # deploy + soci: per-corpus lazy format, parallel to ``corpus``
    # (one entry per image; empty = all gzip, the historical shape).
    # Mixed lists put gzip + zstd-seekable + zstd-opaque + TOC layers
    # in the SAME storm; every writer is deterministic so the serial
    # replay keeps blob-id identity.
    soci_formats: tuple = ()
    read_mib: int = 0  # demand-read window per pod (0 = whole blob)
    crash: str = ""
    gc_watermark_mib: int = 0
    watermark_mib: int = 0
    fraction: float = 0.5
    # deploy: "" (default, in-process Snapshotter calls), "snapshotter"
    # (explicit default), or "grpc" — pods drive the REAL snapshots.v1
    # gRPC surface over a UDS (api/service.py), exactly as containerd
    # would (ROADMAP item 5 follow-up).
    deploy_api: str = ""
    # convert: exercise the dict-HA plane end to end — the phase's
    # converted bootstraps merge through a primary+replica dict set, the
    # primary dies mid-sequence, the placement controller promotes, the
    # client fails over, and the reconstructed table must be byte-
    # identical to the straight-line oracle.
    shard_failover: bool = False
    # deploy: topology fault arm — pods get deterministic rack:zone:region
    # localities (two zones), every member of one zone is killed mid-
    # deploy, and the survivors must degrade to shield/origin with
    # serial-replay identity preserved.
    kill_zone: bool = False

    @classmethod
    def from_dict(cls, d: dict, idx: int) -> "PhaseSpec":
        where = f"[[scenario.phases]] #{idx}"
        _only_keys(
            d,
            {"op", "corpus", "pods", "layers", "adaptive", "peers",
             "corrupt_peer", "soci", "soci_formats", "read_mib", "crash",
             "gc_watermark_mib", "watermark_mib", "fraction", "deploy_api",
             "shard_failover", "kill_zone"},
            where,
        )
        op = d.get("op", "")
        if op not in PHASE_OPS:
            raise ScenarioSpecError(
                f"{where}: unknown op {op!r} (one of {', '.join(PHASE_OPS)})"
            )
        spec = cls(
            op=op,
            corpus=tuple(d.get("corpus", ())),
            pods=int(d.get("pods", 0)),
            layers=int(d.get("layers", 3)),
            adaptive=bool(d.get("adaptive", False)),
            peers=bool(d.get("peers", True)),
            corrupt_peer=bool(d.get("corrupt_peer", False)),
            soci=bool(d.get("soci", False)),
            soci_formats=tuple(d.get("soci_formats", ())),
            read_mib=int(d.get("read_mib", 0)),
            crash=d.get("crash", ""),
            gc_watermark_mib=int(d.get("gc_watermark_mib", 0)),
            watermark_mib=int(d.get("watermark_mib", 0)),
            fraction=float(d.get("fraction", 0.5)),
            deploy_api=d.get("deploy_api", ""),
            shard_failover=bool(d.get("shard_failover", False)),
            kill_zone=bool(d.get("kill_zone", False)),
        )
        if op in ("convert", "deploy") and not spec.corpus:
            raise ScenarioSpecError(f"{where}: {op} needs a corpus list")
        if spec.crash not in CRASH_MODES:
            raise ScenarioSpecError(f"{where}: crash must be one of {CRASH_MODES}")
        if spec.pods < 0 or spec.layers < 1:
            raise ScenarioSpecError(f"{where}: pods >= 0 and layers >= 1 required")
        if spec.read_mib < 0:
            raise ScenarioSpecError(f"{where}: read_mib must be >= 0 (0 = whole blob)")
        if not 0.0 < spec.fraction <= 1.0:
            raise ScenarioSpecError(f"{where}: fraction must be in (0, 1]")
        if spec.deploy_api not in DEPLOY_APIS:
            raise ScenarioSpecError(
                f"{where}: deploy_api must be one of {DEPLOY_APIS}"
            )
        if spec.deploy_api and op != "deploy":
            raise ScenarioSpecError(f"{where}: deploy_api only applies to deploy")
        if spec.shard_failover and op != "convert":
            raise ScenarioSpecError(
                f"{where}: shard_failover only applies to convert"
            )
        if spec.kill_zone and op != "deploy":
            raise ScenarioSpecError(f"{where}: kill_zone only applies to deploy")
        if spec.kill_zone and not spec.peers:
            raise ScenarioSpecError(f"{where}: kill_zone needs peers = true")
        if spec.soci_formats:
            if op != "deploy" or not spec.soci:
                raise ScenarioSpecError(
                    f"{where}: soci_formats only applies to deploy with"
                    " soci = true"
                )
            if len(spec.soci_formats) != len(spec.corpus):
                raise ScenarioSpecError(
                    f"{where}: soci_formats must be parallel to corpus"
                    f" ({len(spec.soci_formats)} formats for"
                    f" {len(spec.corpus)} corpora)"
                )
            bad = [f for f in spec.soci_formats if f not in SOCI_FORMATS]
            if bad:
                raise ScenarioSpecError(
                    f"{where}: unknown soci format(s) {bad}"
                    f" (one of {', '.join(SOCI_FORMATS)})"
                )
        return spec

    def to_dict(self) -> dict:
        return {
            "op": self.op, "corpus": list(self.corpus), "pods": self.pods,
            "layers": self.layers, "adaptive": self.adaptive,
            "peers": self.peers, "corrupt_peer": self.corrupt_peer,
            "soci": self.soci, "soci_formats": list(self.soci_formats),
            "read_mib": self.read_mib, "crash": self.crash,
            "gc_watermark_mib": self.gc_watermark_mib,
            "watermark_mib": self.watermark_mib, "fraction": self.fraction,
            "deploy_api": self.deploy_api,
            "shard_failover": self.shard_failover,
            "kill_zone": self.kill_zone,
        }


@dataclass(frozen=True)
class FaultSpec:
    site: str
    action: str
    phase: int

    @classmethod
    def from_dict(cls, d: dict, idx: int, n_phases: int) -> "FaultSpec":
        where = f"[[scenario.faults]] #{idx}"
        _only_keys(d, {"site", "action", "phase"}, where)
        site = d.get("site", "")
        if site not in failpoint.KNOWN_SITES:
            raise ScenarioSpecError(f"{where}: unknown failpoint site {site!r}")
        action = d.get("action", "")
        try:
            parse_action(action)
        except SpecError as e:
            raise ScenarioSpecError(f"{where}: bad action {action!r}: {e}") from e
        phase = int(d.get("phase", -1))
        if not 0 <= phase < n_phases:
            raise ScenarioSpecError(
                f"{where}: phase {phase} out of range (spec has {n_phases})"
            )
        return cls(site=site, action=action, phase=phase)

    def to_dict(self) -> dict:
        return {"site": self.site, "action": self.action, "phase": self.phase}


@dataclass(frozen=True)
class SloBudget:
    demand_threshold_ms: float = 50.0
    demand_p95_factor: float = 2.0
    target: float = 0.9
    window_secs: float = 0.6
    burn_threshold: float = 2.0

    @classmethod
    def from_dict(cls, d: dict) -> "SloBudget":
        _only_keys(
            d,
            {"demand_threshold_ms", "demand_p95_factor", "target",
             "window_secs", "burn_threshold"},
            "[scenario.slo]",
        )
        spec = cls(
            demand_threshold_ms=float(d.get("demand_threshold_ms", 50.0)),
            demand_p95_factor=float(d.get("demand_p95_factor", 2.0)),
            target=float(d.get("target", 0.9)),
            window_secs=float(d.get("window_secs", 0.6)),
            burn_threshold=float(d.get("burn_threshold", 2.0)),
        )
        if spec.demand_threshold_ms <= 0 or spec.window_secs <= 0:
            raise ScenarioSpecError("[scenario.slo]: threshold/window must be positive")
        from nydus_snapshotter_tpu.metrics.registry import DEFAULT_DURATION_BUCKETS

        if spec.demand_threshold_ms not in DEFAULT_DURATION_BUCKETS:
            raise ScenarioSpecError(
                f"[scenario.slo]: demand_threshold_ms must align to a "
                f"histogram bucket boundary {DEFAULT_DURATION_BUCKETS}"
            )
        if not 0.0 < spec.target < 1.0:
            raise ScenarioSpecError("[scenario.slo]: target must be in (0, 1)")
        if spec.demand_p95_factor < 1.0 or spec.burn_threshold <= 0:
            raise ScenarioSpecError(
                "[scenario.slo]: demand_p95_factor >= 1 and burn_threshold > 0"
            )
        return spec

    def to_dict(self) -> dict:
        return {
            "demand_threshold_ms": self.demand_threshold_ms,
            "demand_p95_factor": self.demand_p95_factor,
            "target": self.target,
            "window_secs": self.window_secs,
            "burn_threshold": self.burn_threshold,
        }


@dataclass(frozen=True)
class SoakSpec:
    """``[scenario.soak]`` — the endurance-plane knobs (docs/scenarios.md).

    The soak runs ``epochs`` waves; each wave's pod count is a pure
    function of ``(seed, epoch)``: a Poisson draw around ``base_pods``
    modulated by a cosine diurnal curve (period ``epochs_per_day``,
    amplitude ``diurnal_amplitude``) with a ``flash_prob`` chance of a
    ``flash_factor`` flash crowd. ``drift_rate`` feeds the corpus
    evolution model (per-epoch per-path mutation probability). The
    ``*_growth_per_epoch`` bounds feed the leak sentinels; the scale-up
    trio (``queue_high``/``wait_high_ms``/``quiet_epochs``) feeds the
    closed-loop capacity policy.
    """

    epochs: int = 6
    base_pods: int = 4
    diurnal_amplitude: float = 0.5
    epochs_per_day: int = 8
    flash_prob: float = 0.12
    flash_factor: float = 3.0
    drift_rate: float = 0.08
    reads_per_pod: int = 1
    scaleup: bool = True
    max_extra_members: int = 2
    queue_high: int = 4
    wait_high_ms: float = 25.0
    quiet_epochs: int = 2
    rss_growth_mib_per_epoch: float = 8.0
    fd_growth_per_epoch: float = 4.0
    row_growth_per_epoch: float = 2.0

    @classmethod
    def from_dict(cls, d: dict) -> "SoakSpec":
        where = "[scenario.soak]"
        _only_keys(
            d,
            {"epochs", "base_pods", "diurnal_amplitude", "epochs_per_day",
             "flash_prob", "flash_factor", "drift_rate", "reads_per_pod",
             "scaleup", "max_extra_members", "queue_high", "wait_high_ms",
             "quiet_epochs", "rss_growth_mib_per_epoch",
             "fd_growth_per_epoch", "row_growth_per_epoch"},
            where,
        )
        spec = cls(
            epochs=int(d.get("epochs", 6)),
            base_pods=int(d.get("base_pods", 4)),
            diurnal_amplitude=float(d.get("diurnal_amplitude", 0.5)),
            epochs_per_day=int(d.get("epochs_per_day", 8)),
            flash_prob=float(d.get("flash_prob", 0.12)),
            flash_factor=float(d.get("flash_factor", 3.0)),
            drift_rate=float(d.get("drift_rate", 0.08)),
            reads_per_pod=int(d.get("reads_per_pod", 1)),
            scaleup=bool(d.get("scaleup", True)),
            max_extra_members=int(d.get("max_extra_members", 2)),
            queue_high=int(d.get("queue_high", 4)),
            wait_high_ms=float(d.get("wait_high_ms", 25.0)),
            quiet_epochs=int(d.get("quiet_epochs", 2)),
            rss_growth_mib_per_epoch=float(d.get("rss_growth_mib_per_epoch", 8.0)),
            fd_growth_per_epoch=float(d.get("fd_growth_per_epoch", 4.0)),
            row_growth_per_epoch=float(d.get("row_growth_per_epoch", 2.0)),
        )
        if spec.epochs < 1 or spec.base_pods < 1:
            raise ScenarioSpecError(f"{where}: epochs/base_pods must be >= 1")
        if not 0.0 <= spec.diurnal_amplitude < 1.0:
            raise ScenarioSpecError(f"{where}: diurnal_amplitude must be in [0, 1)")
        if spec.epochs_per_day < 1:
            raise ScenarioSpecError(f"{where}: epochs_per_day must be >= 1")
        if not 0.0 <= spec.flash_prob <= 1.0:
            raise ScenarioSpecError(f"{where}: flash_prob must be in [0, 1]")
        if spec.flash_factor < 1.0:
            raise ScenarioSpecError(f"{where}: flash_factor must be >= 1")
        if not 0.0 <= spec.drift_rate <= 1.0:
            raise ScenarioSpecError(f"{where}: drift_rate must be in [0, 1]")
        if spec.reads_per_pod < 1 or spec.quiet_epochs < 1:
            raise ScenarioSpecError(
                f"{where}: reads_per_pod/quiet_epochs must be >= 1"
            )
        if spec.max_extra_members < 0 or spec.queue_high < 1:
            raise ScenarioSpecError(
                f"{where}: max_extra_members >= 0 and queue_high >= 1 required"
            )
        if spec.wait_high_ms <= 0:
            raise ScenarioSpecError(f"{where}: wait_high_ms must be positive")
        if (spec.rss_growth_mib_per_epoch < 0 or spec.fd_growth_per_epoch < 0
                or spec.row_growth_per_epoch < 0):
            raise ScenarioSpecError(f"{where}: growth bounds must be >= 0")
        return spec

    def to_dict(self) -> dict:
        return {
            "epochs": self.epochs,
            "base_pods": self.base_pods,
            "diurnal_amplitude": self.diurnal_amplitude,
            "epochs_per_day": self.epochs_per_day,
            "flash_prob": self.flash_prob,
            "flash_factor": self.flash_factor,
            "drift_rate": self.drift_rate,
            "reads_per_pod": self.reads_per_pod,
            "scaleup": self.scaleup,
            "max_extra_members": self.max_extra_members,
            "queue_high": self.queue_high,
            "wait_high_ms": self.wait_high_ms,
            "quiet_epochs": self.quiet_epochs,
            "rss_growth_mib_per_epoch": self.rss_growth_mib_per_epoch,
            "fd_growth_per_epoch": self.fd_growth_per_epoch,
            "row_growth_per_epoch": self.row_growth_per_epoch,
        }


@dataclass(frozen=True)
class ScenarioSpec:
    name: str
    description: str = ""
    seed: int = 7
    pods: int = 4
    corpus: tuple = ()
    phases: tuple = ()
    faults: tuple = ()
    slo: SloBudget = field(default_factory=SloBudget)
    soak: Optional[SoakSpec] = None

    @classmethod
    def from_dict(cls, data: dict) -> "ScenarioSpec":
        if "scenario" not in data:
            raise ScenarioSpecError("spec needs a [scenario] table")
        sc = dict(data["scenario"])
        extra = set(data) - {"scenario"}
        if extra:
            raise ScenarioSpecError(f"unknown top-level tables {sorted(extra)}")
        _only_keys(
            sc,
            {"name", "description", "seed", "pods", "corpus", "phases",
             "faults", "slo", "soak"},
            "[scenario]",
        )
        if not sc.get("name"):
            raise ScenarioSpecError("[scenario]: needs a name")
        phases_raw = sc.get("phases", [])
        if not phases_raw:
            raise ScenarioSpecError("[scenario]: needs at least one [[scenario.phases]]")
        corpus = tuple(
            CorpusSpec.from_dict(c, i) for i, c in enumerate(sc.get("corpus", []))
        )
        ids = [c.id for c in corpus]
        if len(set(ids)) != len(ids):
            raise ScenarioSpecError(f"[scenario]: duplicate corpus ids in {ids}")
        phases = tuple(PhaseSpec.from_dict(p, i) for i, p in enumerate(phases_raw))
        for i, p in enumerate(phases):
            missing = set(p.corpus) - set(ids)
            if missing:
                raise ScenarioSpecError(
                    f"[[scenario.phases]] #{i}: corpus refs {sorted(missing)} "
                    "name no [[scenario.corpus]] entry"
                )
        faults = tuple(
            FaultSpec.from_dict(f, i, len(phases))
            for i, f in enumerate(sc.get("faults", []))
        )
        spec = cls(
            name=sc["name"],
            description=sc.get("description", ""),
            seed=int(sc.get("seed", 7)),
            pods=int(sc.get("pods", 4)),
            corpus=corpus,
            phases=phases,
            faults=faults,
            slo=SloBudget.from_dict(sc.get("slo", {})),
            soak=(SoakSpec.from_dict(sc["soak"]) if "soak" in sc else None),
        )
        if spec.pods < 1:
            raise ScenarioSpecError("[scenario]: pods must be >= 1")
        return spec

    def corpus_by_id(self, cid: str) -> CorpusSpec:
        for c in self.corpus:
            if c.id == cid:
                return c
        raise KeyError(cid)

    def to_dict(self) -> dict:
        sc = {
            "name": self.name,
            "description": self.description,
            "seed": self.seed,
            "pods": self.pods,
            "corpus": [c.to_dict() for c in self.corpus],
            "phases": [p.to_dict() for p in self.phases],
            "faults": [f.to_dict() for f in self.faults],
            "slo": self.slo.to_dict(),
        }
        if self.soak is not None:
            sc["soak"] = self.soak.to_dict()
        return {"scenario": sc}


def loads(text: str) -> ScenarioSpec:
    try:
        data = tomllib.loads(text)
    except Exception as e:  # tomllib.TOMLDecodeError (tomli variant differs)
        raise ScenarioSpecError(f"spec is not valid TOML: {e}") from e
    return ScenarioSpec.from_dict(data)


def load_spec(path: str) -> ScenarioSpec:
    with open(path, "r", encoding="utf-8") as f:
        return loads(f.read())


def list_specs(spec_dir: str) -> list[tuple[str, Optional[ScenarioSpec], str]]:
    """``(path, spec-or-None, error)`` for every ``*.toml`` in a spec dir
    (``ntpuctl scenario``'s catalog view; a broken spec lists its error
    instead of disappearing)."""
    out = []
    try:
        names = sorted(os.listdir(spec_dir))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".toml"):
            continue
        path = os.path.join(spec_dir, name)
        try:
            out.append((path, load_spec(path), ""))
        except (ScenarioSpecError, OSError) as e:
            out.append((path, None, str(e)))
    return out
