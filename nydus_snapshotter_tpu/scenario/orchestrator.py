"""Scenario orchestrator: drive a spec through the REAL stack.

One :class:`ScenarioRunner` owns a workdir and executes a
:class:`~nydus_snapshotter_tpu.scenario.spec.ScenarioSpec` phase by
phase against the real subsystems:

- **convert** — ``converter.convert.pack_layer`` (optionally through the
  PR 10 adaptive codec) over the spec's corpora; converted blobs are
  registered with the in-process origin;
- **deploy** — per pod, the real snapshot control plane
  (``Snapshotter`` prepare/commit/mounts/usage over a crash-able
  filesystem facade) plus a real lazy-read data plane: a per-pod
  ``CachedBlob`` behind its own ``AdmissionGate``, wired through the
  peer chunk tier (``PeerChunkServer``/``PeerRouter``/
  ``PeerAwareFetcher``) when the phase enables it — including a
  HOSTILE peer arm (:class:`CorruptPeerServer`: payload corrupted after
  the CRC header is stamped, exactly transit corruption) and a soci arm
  (unconverted gzip layers read through a first-pull checkpoint index);
- **remove** — children-first removal of a deterministic subset of
  deployed pods, then the orphan-dir cleanup sweep;
- **gc** — watermark / age eviction over every pod cache dir
  (``cache.manager.CacheManager``);
- **crash_restart** — close the control plane mid-run and reopen it
  over the same metastore (also available mid-deploy via
  ``crash = "mid"``: in-flight pods quiesce at an op checkpoint, the
  snapshotter restarts, the storm resumes).

Determinism contract: ``ScenarioRunner(spec, serial=True)`` replays the
same spec with pods sequential, control-plane workers serial, peers off
and faults disarmed — the oracle. The concurrent chaos run must match
it byte for byte on :meth:`fingerprint` (id-normalized metastore dump +
per-pod read digests + blob ids), and :meth:`audit` must come back
clean (no leaked snapshot rows, no orphan snapshot dirs, no
unaccounted cache entries).

The SLO engine rides along as the in-run judge: every demand read lands
in the ``scenario_demand`` op histogram, a judge thread ticks a
:class:`~nydus_snapshotter_tpu.metrics.slo.SloEngine` built from
``[scenario.slo]``, and any multi-window burn breach fails the run.
"""

from __future__ import annotations

import gzip as _gzip
import hashlib
import os
import shutil
import threading
import time
from typing import Callable, Optional

from nydus_snapshotter_tpu import constants as C
from nydus_snapshotter_tpu import failpoint, scenario, trace
from nydus_snapshotter_tpu.analysis import runtime as _an
from nydus_snapshotter_tpu.cache.manager import CacheManager
from nydus_snapshotter_tpu.scenario import corpus as corpus_gen
from nydus_snapshotter_tpu.scenario.spec import PhaseSpec, ScenarioSpec
from nydus_snapshotter_tpu.snapshot.metastore import Usage
from nydus_snapshotter_tpu.utils import errdefs

# Demand-read granule (also the peer region size). 256 KiB balances the
# per-read HTTP/bookkeeping overhead against per-request service time:
# bigger granules halve request count but double service time, which
# doubles queue-wait tails at the region owners under a storm.
READ_CHUNK = 256 << 10
POD_BUDGET_BYTES = 8 << 20
SLO_OP = "scenario_demand"


class ScenarioRunError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# Simulated origin + crash-able filesystem facade
# ---------------------------------------------------------------------------


class SimRegistry:
    """In-process origin for every converted/unconverted blob of a run.

    Counts egress per blob so storm arms can bound origin traffic;
    ``latency_s`` models a slow uplink when a scenario wants demand
    latency pressure.
    """

    def __init__(self, latency_s: float = 0.0):
        self.latency_s = latency_s
        self._lock = _an.make_lock("scenario.registry")
        self._blobs: dict[str, bytes] = {}
        self._retired: set = set()
        self.egress = 0
        self.calls = 0

    def register(self, blob_id: str, data: bytes) -> None:
        with self._lock:
            self._blobs[blob_id] = data

    def blob(self, blob_id: str) -> bytes:
        with self._lock:
            return self._blobs[blob_id]

    def blob_ids(self) -> set:
        with self._lock:
            return set(self._blobs) | set(self._retired)

    def retire_except(self, live: set) -> int:
        """Drop blob BYTES for everything outside ``live`` but keep the
        ids known (a real registry GC deletes layer data while the ids
        stay resolvable in catalogs). The soak calls this per epoch so a
        year of corpus evolution doesn't read as an RSS leak; a fetch of
        a retired blob still fails loudly (KeyError), it does not
        silently resurrect."""
        with self._lock:
            stale = [bid for bid in self._blobs if bid not in live]
            for bid in stale:
                del self._blobs[bid]
                self._retired.add(bid)
            return len(stale)

    def fetch(self, blob_id: str, off: int, size: int) -> bytes:
        with self._lock:
            data = self._blobs[blob_id]
            self.egress += size
            self.calls += 1
        if off + size > len(data):
            raise OSError(f"range [{off}, {off + size}) past blob end")
        if self.latency_s:
            time.sleep(self.latency_s)
        return data[off : off + size]

    def fetcher(self, blob_id: str) -> Callable[[int, int], bytes]:
        return lambda off, size: self.fetch(blob_id, off, size)


class SimFs:
    """Thread-safe FilesystemLike facade with daemon latency and a crash
    switch. ``crash()`` drops every mounted instance (the daemons died
    with the process); ``wait_until_ready`` on an unknown snapshot
    REMOUNTS it first — the ``recover_policy = "restart"`` contract, so
    a post-crash join point recovers instead of failing."""

    def __init__(self, mount_ms: float = 1.0, ready_ms: float = 4.0):
        self.mount_ms = mount_ms
        self.ready_ms = ready_ms
        self._lock = _an.make_lock("scenario.simfs")
        self._ready_at: dict[str, float] = {}
        self.mounted: dict[str, dict] = {}
        self.remounts = 0

    def crash(self) -> None:
        with self._lock:
            self.mounted.clear()
            self._ready_at.clear()

    def mount(self, sid, labels, snapshot):
        time.sleep(self.mount_ms / 1000.0)
        with self._lock:
            self.mounted[sid] = dict(labels or {})
            self._ready_at[sid] = time.monotonic() + self.ready_ms / 1000.0

    def umount(self, sid):
        with self._lock:
            self.mounted.pop(sid, None)
            self._ready_at.pop(sid, None)

    def wait_until_ready(self, sid):
        with self._lock:
            at = self._ready_at.get(sid)
        if at is None:
            # Daemon recovery: the restart policy respawns and remounts.
            self.mount(sid, {}, None)
            with self._lock:
                self.remounts += 1
                at = self._ready_at[sid]
        delay = at - time.monotonic()
        if delay > 0:
            time.sleep(delay)

    def mount_point(self, sid):
        with self._lock:
            if sid in self.mounted:
                return f"/mnt/nydus/{sid}"
        raise errdefs.NotFound(sid)

    def bootstrap_file(self, sid):
        return f"/snap/{sid}/fs/image/image.boot"

    def remove_cache(self, digest):
        pass

    def cache_usage(self, digest):
        return Usage()

    def teardown(self):
        pass

    def try_stop_shared_daemon(self):
        pass

    def check_referrer(self, labels):
        return False

    def referrer_detect_enabled(self):
        return False

    def try_fetch_metadata(self, labels, meta_path):
        pass

    def stargz_enabled(self):
        return False

    def is_stargz_data_layer(self, labels):
        return False, None

    def prepare_stargz_meta_layer(self, blob, storage_path, labels):
        pass

    def merge_stargz_meta_layer(self, snapshot):
        pass

    def soci_enabled(self):
        return False

    def is_soci_data_layer(self, labels):
        return False, None

    def prepare_soci_meta_layer(self, blob, storage_path, labels):
        pass

    def merge_soci_meta_layer(self, snapshot):
        pass

    def tarfs_enabled(self):
        return False

    def prepare_tarfs_layer(self, labels, sid, upper):
        pass

    def merge_tarfs_layers(self, snapshot, path_fn):
        pass

    def export_block_data(self, snapshot, per_layer, labels, path_fn):
        return []

    def detach_tarfs_layer(self, sid):
        pass

    def tarfs_export_enabled(self):
        return False

    def get_instance_extra_option(self, sid):
        return None


class _GrpcControlPlane:
    """The ``deploy_api = "grpc"`` driver: pods issue their control-plane
    RPC mix through the REAL snapshots.v1 gRPC surface on a UDS
    (api/service.py), exactly as containerd's proxy plugin would —
    instead of calling the Snapshotter object directly. The server wraps
    the SAME Snapshotter, so the metastore fingerprint stays comparable
    with the in-process driver (and with the serial replay, which runs
    the same deploy_api). gRPC status codes map back onto the errdefs
    the pod logic already handles."""

    def __init__(self, sn, sock: str):
        from nydus_snapshotter_tpu.api.client import SnapshotsClient
        from nydus_snapshotter_tpu.api.service import serve

        self.sock = sock
        self.server = serve(sn, sock)
        self.client = SnapshotsClient(sock, timeout=30.0)

    def close(self) -> None:
        self.client.close()
        self.server.stop(grace=None)

    @staticmethod
    def _map(call):
        import grpc

        try:
            return call()
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.ALREADY_EXISTS:
                raise errdefs.AlreadyExists(e.details()) from e
            if e.code() == grpc.StatusCode.NOT_FOUND:
                raise errdefs.NotFound(e.details()) from e
            raise ScenarioRunError(
                f"grpc control plane: {e.code().name}: {e.details()}"
            ) from e

    def prepare(self, key, parent, labels=None):
        return self._map(lambda: self.client.prepare(key, parent, labels))

    def commit(self, name, key, labels=None):
        return self._map(lambda: self.client.commit(name, key, labels))

    def mounts(self, key):
        return self._map(lambda: self.client.mounts(key))

    def usage(self, key):
        return self._map(lambda: self.client.usage(key))


class CorruptPeerServer:
    """Hostile peer: wraps a real PeerChunkServer and corrupts blob
    payloads AFTER the CRC header is stamped — exactly what transit
    corruption looks like on the wire, so the requester's CRC check MUST
    reject it and fall back to the registry (never caching poisoned
    bytes). Index/stat routes pass through untouched.

    The serve loop dispatches through the INNER server's ``handle``
    attribute (``run()`` closes over ``self``), so the corrupting hook is
    installed as an instance attribute on it.
    """

    def __init__(self, inner, seed: int):
        self._inner = inner
        self._seed = seed
        self.corrupted = 0
        inner_handle = inner.handle

        def handle(method, path, headers):
            status, extra, body = inner_handle(method, path, headers)
            if status == 200 and "/api/v1/peer/blob/" in path and body:
                body = corpus_gen.corrupt_variant(body, self._seed, "flip")
                self.corrupted += 1
            return status, extra, body

        inner.handle = handle
        self.handle = handle

    def __getattr__(self, name):
        return getattr(self._inner, name)


# ---------------------------------------------------------------------------
# The runner
# ---------------------------------------------------------------------------


class _Pod:
    """One simulated node of a deploy phase: CachedBlob + admission gate
    (+ peer server when the tier is on)."""

    def __init__(self, idx, cache_dir, blob_id, blob_len, origin_fetch,
                 addrs, peers_on, health, corrupt_seed=None,
                 localities=None, serve=True):
        from nydus_snapshotter_tpu.daemon import peer
        from nydus_snapshotter_tpu.daemon.blobcache import CachedBlob
        from nydus_snapshotter_tpu.daemon.fetch_sched import (
            AdmissionGate,
            FetchConfig,
            MemoryBudget,
        )

        self.idx = idx
        self.cache_dir = cache_dir
        self.gate = AdmissionGate(
            budget=MemoryBudget(POD_BUDGET_BYTES),
            max_concurrent=8,
            demand_reserve=1,
            name=f"scn-pod{idx}",
        )
        fetch_range = origin_fetch
        self.server = None
        if peers_on:
            locs = localities or {}
            router = peer.PeerRouter(
                addrs,
                self_address=addrs[idx],
                region_bytes=READ_CHUNK,
                health_registry=health,
                locality=locs.get(addrs[idx], ""),
                localities=locs,
            )
            fetch_range = peer.PeerAwareFetcher(
                blob_id, origin_fetch, router, timeout_s=5.0
            ).read_range
        self.cb = CachedBlob(
            cache_dir,
            blob_id,
            fetch_range,
            blob_size=blob_len,
            config=FetchConfig(fetch_workers=2, merge_gap=0, readahead=0),
            gate=self.gate,
            tenant=f"scn-pod{idx}",
        )
        if peers_on and serve:
            export = peer.PeerExport()
            export.register(blob_id, self.cb)
            srv = peer.PeerChunkServer(
                export, gate=self.gate, pull_through=True, router=router
            )
            if corrupt_seed is not None:
                srv = CorruptPeerServer(srv, corrupt_seed)
            srv.run(addrs[idx])
            self.server = srv

    def close(self) -> None:
        if self.server is not None:
            self.server.stop()
            self.server = None
        self.cb.close()


class ScenarioRunner:
    def __init__(
        self,
        spec: ScenarioSpec,
        workdir: str,
        serial: bool = False,
        pods: Optional[int] = None,
        arm_faults: Optional[bool] = None,
        origin_latency_s: float = 0.0,
        pods_sequential: bool = False,
    ):
        self.spec = spec
        self.workdir = workdir
        self.serial = serial
        # Unloaded-baseline shape: pods run one at a time (zero
        # contention) but keep the storm's topology — peer tier on,
        # concurrent control plane — so a p95 comparison isolates LOAD,
        # not the peer hop.
        self.pods_sequential = pods_sequential
        self.pods_default = pods if pods is not None else spec.pods
        self.arm_faults = (not serial) if arm_faults is None else arm_faults
        self.registry = SimRegistry(latency_s=origin_latency_s)
        self.fs = SimFs()
        self.sn = None
        self.images: dict[str, dict] = {}  # corpus id -> blob/blob_id/...
        self.deployed: list[dict] = []  # one entry per deployed pod chain
        self.read_digests: dict[str, str] = {}
        self.demand_ms: list[float] = []
        self.expected_keys: set = set()
        self.corrupt_served = 0
        self.soci_outcomes: list[str] = []
        self.crashes = 0
        self.ha_promotions = 0
        # Serve-only peer members beyond the wave's demand pods: the
        # soak's scale-up actuation raises this between epochs so the
        # rendezvous ring spreads region ownership across more servers.
        # Always 0 for the serial replay (peers are off there), so the
        # identity surface never sees it.
        self.extra_serve_pods = 0
        self.last_demand_pressure: dict = {}
        # Optional node-level admission gate over the DEMAND READ window
        # (not the pods' fetch schedulers — sharing those would let a
        # flash crowd's queued demand waiters starve the strict-priority
        # PEER_SERVE lane into its timeout). The soak installs one per
        # epoch sized to the cluster's serving capacity, so a flash
        # crowd queues HERE and the scale-up loop has a real signal.
        # None = no cluster ceiling (the worst-day storm shape).
        self.node_gate = None
        self._engine = None
        self._engine_stop = threading.Event()
        self._engine_thread = None
        self._demand_mu = _an.make_lock("scenario.demand")
        self._grpc: Optional[_GrpcControlPlane] = None
        self._grpc_mu = _an.make_lock("scenario.grpc")

    # -- control plane lifecycle --------------------------------------------

    def _snap_root(self) -> str:
        return os.path.join(self.workdir, "snapshotter")

    def _open_control_plane(self):
        from nydus_snapshotter_tpu.snapshot.snapshotter import Snapshotter

        os.makedirs(self._snap_root(), exist_ok=True)
        kw = dict(read_pool=1, prepare_fanout=0, usage_workers=0,
                  cleanup_workers=1) if self.serial else dict(
            read_pool=4, prepare_fanout=4, usage_workers=1, cleanup_workers=2)
        self.sn = Snapshotter(root=self._snap_root(), fs=self.fs, **kw)

    def _grpc_plane(self) -> _GrpcControlPlane:
        """The lazily-opened gRPC control-plane driver over the current
        Snapshotter (re-opened on crash/restart with it)."""
        with self._grpc_mu:
            if self._grpc is None:
                self._grpc = _GrpcControlPlane(
                    self.sn, os.path.join(self.workdir, "scn-grpc.sock")
                )
            return self._grpc

    def _crash_restart(self) -> None:
        """Close the control plane mid-run (daemons die with it) and
        reopen it over the same persisted metastore.

        Never called concurrently by construction: a deploy phase's
        crash controller is joined before the phase ends, and standalone
        ``crash_restart`` phases run on the main thread between phases —
        so no lock is held across the close (which joins the usage
        accountant's workers)."""
        with self._grpc_mu:
            grpc_was_open = self._grpc is not None
            plane, self._grpc = self._grpc, None
        if plane is not None:
            plane.close()
        if self.sn is not None:
            self.sn.close()
            self.sn = None
        self.fs.crash()
        self.crashes += 1
        self._open_control_plane()
        if grpc_was_open:
            # The gRPC surface died with the control plane; reopen it on
            # the same socket so parked pods resume over the same API.
            self._grpc_plane()

    # -- corpora -------------------------------------------------------------

    def _corpus_tar(self, cid: str) -> bytes:
        cs = self.spec.corpus_by_id(cid)
        idx = list(self.spec.corpus).index(cs)
        seed = self.spec.seed * 1000 + idx
        if cs.kind == "real_tree":
            return corpus_gen.members_to_tar(corpus_gen.real_tree_members())
        if cs.kind == "real_tree2":
            return corpus_gen.members_to_tar(corpus_gen.real_tree2_members())
        if cs.kind == "incompressible":
            return corpus_gen.incompressible_layer(seed, cs.mib)
        if cs.kind == "compressible":
            return corpus_gen.compressible_layer(seed, cs.mib)
        if cs.kind == "cdc_resonant":
            return corpus_gen.cdc_resonant_layer(
                seed, cs.mib, cs.avg_kib << 10, cs.mode
            )
        if cs.kind == "tiny_files":
            return corpus_gen.tiny_files_layer(seed, cs.count)
        if cs.kind == "huge_file":
            return corpus_gen.single_huge_file_layer(seed, cs.mib)
        raise ScenarioRunError(f"unhandled corpus kind {cs.kind!r}")

    # -- phases --------------------------------------------------------------

    def _phase_convert(self, idx: int, phase: PhaseSpec) -> dict:
        from nydus_snapshotter_tpu.converter.codec import AdaptiveCodec, CodecConfig
        from nydus_snapshotter_tpu.converter.convert import pack_layer
        from nydus_snapshotter_tpu.converter.types import PackOption
        from nydus_snapshotter_tpu.utils import zstd as zstd_native

        adaptive = phase.adaptive and zstd_native.available()
        opt = PackOption(
            backend="numpy",
            chunking="cdc",
            compressor="zstd" if adaptive else "lz4_block",
        )

        def convert_one(cid: str) -> dict:
            tar = self._corpus_tar(cid)
            codec = (
                AdaptiveCodec(CodecConfig(adaptive=True)) if adaptive else None
            )
            blob, res = pack_layer(tar, opt, codec=codec)
            return {
                "cid": cid,
                "tar_len": len(tar),
                "blob": blob,
                "blob_id": res.blob_id,
                "bootstrap": res.bootstrap,
                "digest": hashlib.sha256(blob).hexdigest(),
            }

        results = []
        if self.serial or len(phase.corpus) == 1:
            results = [convert_one(c) for c in phase.corpus]
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=min(4, len(phase.corpus)),
                thread_name_prefix="ntpu-scn-convert",
            ) as ex:
                results = [
                    f.result()
                    for f in [ex.submit(convert_one, c) for c in phase.corpus]
                ]
        out = {}
        for r in results:
            self.images[r["cid"]] = r
            self.registry.register(r["blob_id"], r["blob"])
            out[r["cid"]] = {
                "blob_id": r["blob_id"],
                "tar_mib": round(r["tar_len"] / (1 << 20), 2),
                "blob_mib": round(len(r["blob"]) / (1 << 20), 2),
            }
        detail = {"converted": out}
        if phase.shard_failover and not self.serial:
            detail["shard_failover"] = self._shard_failover_arm(idx, results)
        return detail

    def _shard_failover_arm(self, idx: int, results: list) -> dict:
        """The ``shard_failover`` fault arm: drive the dict-HA plane end
        to end with this phase's real converted bootstraps. A primary +
        replica dict-service pair replicates under a placement
        controller; the PRIMARY DIES mid-merge-sequence, the controller
        promotes the replica (scrape-liveness path), the mirror client
        fails over and replays its un-acked batch — and the surviving
        table must be byte-identical to a straight-line single-service
        oracle fed the same bootstraps in the same order. Skipped in the
        serial replay (like the corrupt-peer probe, it is a fault arm,
        not part of the identity surface)."""
        from nydus_snapshotter_tpu import fleet as fleet_mod
        from nydus_snapshotter_tpu.ha import PlacementController
        from nydus_snapshotter_tpu.ha.replicate import HaAgent
        from nydus_snapshotter_tpu.parallel.dict_service import (
            DictClient,
            DictService,
            ServiceChunkDict,
            ServiceDict,
        )

        # Converted bootstraps in deterministic (corpus) order; the arm
        # needs at least two merges so the kill lands mid-sequence.
        boots = [
            self.images[r["cid"]].get("bootstrap")
            for r in sorted(results, key=lambda r: r["cid"])
        ]
        boots = [b for b in boots if b]
        if len(boots) < 2:
            return {"skipped": "needs >= 2 converted bootstraps"}
        sockdir = os.path.join(self.workdir, f"ph{idx}-ha")
        os.makedirs(sockdir, exist_ok=True)
        services, members = [], []
        liveness = {}
        for i in range(2):
            svc = DictService()
            HaAgent(svc, role="unassigned")
            svc.run(os.path.join(sockdir, f"dict{i}.sock"))
            services.append(svc)
            members.append(
                fleet_mod.Member(
                    name=f"scn-dict-{i}", component="dict",
                    address=svc.sock_path, pid=os.getpid(),
                )
            )
            liveness[f"scn-dict-{i}"] = {"up": True, "stale": False}
        controller = PlacementController(
            lambda: members, lambda: dict(liveness), shards=1, replicas=1
        )
        oracle = ServiceDict("scnha")
        promotions = 0
        try:
            controller.tick()
            primary_name = controller.map()["assignments"][0]["primary"]["name"]
            primary_i = int(primary_name.rsplit("-", 1)[1])
            replica_i = 1 - primary_i
            scd = ServiceChunkDict(
                [DictClient(services[primary_i].sock_path)], "scnha",
                failover=[[services[replica_i].sock_path]],
            )
            for b in boots:
                oracle.merge_bootstrap_bytes(b)
            half = max(1, len(boots) // 2)
            for b in boots[:half]:
                scd.add_bootstrap_bytes(b)
            # Let the replica catch up to the acked half, then kill the
            # primary without ceremony (its threads die unanswered).
            deadline = time.monotonic() + 10.0
            want = len(services[primary_i].dict_for("scnha").records.bootstrap.chunks)
            while time.monotonic() < deadline:
                got = len(
                    services[replica_i].dict_for("scnha").records.bootstrap.chunks
                )
                if got >= want:
                    break
                time.sleep(0.02)
            services[primary_i].stop()
            liveness[primary_name] = {"up": False, "stale": True}
            controller.tick()  # promotes the replica
            promotions = controller.map()["promotions"]
            for b in boots[half:]:
                scd.add_bootstrap_bytes(b)  # mid-merge failover path
            survivor = services[replica_i].dict_for("scnha")
            identical = (
                survivor.records.bootstrap.to_bytes()
                == oracle.records.bootstrap.to_bytes()
            )
            scd.close()
            if not identical:
                raise ScenarioRunError(
                    "shard_failover arm: post-promotion table diverged "
                    "from the straight-line oracle"
                )
            if promotions < 1:
                raise ScenarioRunError(
                    "shard_failover arm: controller performed no promotion"
                )
            self.ha_promotions += promotions
            return {
                "promotions": promotions,
                "chunks": len(survivor.records.bootstrap.chunks),
                "identical": identical,
            }
        finally:
            for svc in services:
                svc.stop()

    def _image_for_deploy(self, cid: str, soci: bool, fmt: str = "gzip") -> dict:
        """Converted image, or (soci arm) the UNCONVERTED layer in one of
        the lazy formats the FormatRouter recognizes — registered lazily
        so a deploy can reference a corpus no convert phase touched."""
        key = f"soci:{fmt}:{cid}" if soci else cid
        if key in self.images:
            return self.images[key]
        if soci:
            tar = self._corpus_tar(cid)
            # Every writer here is deterministic (gzip mtime=0, fixed
            # zstd level): wall-clock in a header would fork the serial
            # replay's blob id from the storm's.
            blob = self._format_blob(tar, fmt)
            blob_id = hashlib.sha256(blob).hexdigest()
            img = {
                "cid": key, "blob": blob, "blob_id": blob_id,
                "digest": hashlib.sha256(blob).hexdigest(),
                "tar": tar, "soci": True, "format": fmt,
            }
            self.images[key] = img
            self.registry.register(blob_id, blob)
            return img
        raise ScenarioRunError(
            f"deploy references corpus {cid!r} with no converted image "
            "(add a convert phase or set soci = true)"
        )

    @staticmethod
    def _format_blob(tar: bytes, fmt: str) -> bytes:
        """The corpus tar in one deployable lazy format. zstd shapes need
        the system libzstd; a spec asking for them on a box without it is
        a hard run error, not silent gzip."""
        if fmt == "gzip":
            return _gzip.compress(tar, compresslevel=6, mtime=0)
        from nydus_snapshotter_tpu.soci import toc as ztoc
        from nydus_snapshotter_tpu.soci import zframe
        from nydus_snapshotter_tpu.utils import zstd as _zstd

        if not (zframe.available() and _zstd.dctx_available()):
            raise ScenarioRunError(
                f"soci format {fmt!r} needs the system libzstd"
            )
        if fmt == "zstd-seekable":
            return zframe.write_seekable(tar, frame_usize=256 << 10)
        if fmt == "zstd-opaque":
            return zframe.write_frames(tar, frame_usize=256 << 10)
        if fmt == "zstd-chunked":
            import io
            import tarfile

            files: dict[str, bytes] = {}
            with tarfile.open(fileobj=io.BytesIO(tar), mode="r:") as tf:
                for m in tf:
                    if m.isreg():
                        files[m.name] = tf.extractfile(m).read()
            return ztoc.write_zstd_chunked(files, chunk_size=256 << 10)
        raise ScenarioRunError(f"unhandled soci format {fmt!r}")

    def _control_plane_pod(self, prefix: str, layers: int, cp=None) -> dict:
        """The containerd cold-start RPC mix for one pod: layer chain +
        meta layer + writable container layer, then usage for every
        name. ``cp`` is the control-plane driver — the Snapshotter
        itself, or the gRPC facade when the phase sets
        ``deploy_api = "grpc"``. Returns the chain record removal
        needs."""
        sn = cp if cp is not None else self.sn
        parent = ""
        names = []
        for j in range(layers - 1):
            key = f"{prefix}-extract-{j}"
            name = f"{prefix}-layer-{j}"
            labels = {
                C.TARGET_SNAPSHOT_REF: name,
                C.NYDUS_DATA_LAYER: "true",
                C.CRI_LAYER_DIGEST: "sha256:" + hashlib.sha256(
                    name.encode()).hexdigest(),
            }
            try:
                sn.prepare(key, parent, labels)
            except errdefs.AlreadyExists:
                pass  # skip handler committed under the target name
            names.append(name)
            parent = name
        meta_key = f"{prefix}-extract-meta"
        meta_name = f"{prefix}-meta"
        meta_labels = {C.NYDUS_META_LAYER: "true", C.CRI_IMAGE_REF: prefix}
        sn.prepare(
            meta_key, parent, {C.TARGET_SNAPSHOT_REF: meta_name, **meta_labels}
        )
        # Upper-dir writes stay process-local (the gRPC surface carries
        # no file I/O, exactly as with containerd).
        sid = self.sn.ms.get_snapshot(meta_key).id
        upper = self.sn.upper_path(sid)
        for i in range(8):
            with open(os.path.join(upper, f"f{i:02d}.bin"), "wb") as f:
                f.write(bytes([(i * 7) % 251]) * (512 + 16 * i))
        sn.commit(meta_name, meta_key, meta_labels)
        names.append(meta_name)
        ctr = f"{prefix}-ctr"
        sn.prepare(ctr, meta_name, {})
        sn.mounts(ctr)
        for name in names:
            sn.usage(name)
        return {"prefix": prefix, "names": names, "ctr": ctr}

    def _demand_read(
        self, cb, off: int, size: int, tenant: str = "scn-demand"
    ) -> bytes:
        from nydus_snapshotter_tpu.daemon.fetch_sched import OP_HIST

        t0 = time.perf_counter()
        gate = self.node_gate
        if gate is not None:
            # Queue wait is part of the demand latency on purpose: the
            # SLO judge and the p95 gates must see what a pod sees.
            gate.acquire(size, tenant=tenant)
        try:
            data = cb.read_at(off, size)
        finally:
            if gate is not None:
                gate.release(size, tenant=tenant)
        ms = (time.perf_counter() - t0) * 1000.0
        OP_HIST.labels(SLO_OP).observe(ms)
        with self._demand_mu:
            self.demand_ms.append(ms)
        return data

    def _phase_deploy(self, idx: int, phase: PhaseSpec) -> dict:
        pods = phase.pods or self.pods_default
        peers_on = phase.peers and not self.serial and pods > 1
        layers = phase.layers
        fmts = phase.soci_formats or ("gzip",) * len(phase.corpus)
        images = [
            self._image_for_deploy(cid, phase.soci, fmt)
            for cid, fmt in zip(phase.corpus, fmts)
        ]
        from nydus_snapshotter_tpu.remote.mirror import HostHealthRegistry

        health = HostHealthRegistry()
        sockdir = os.path.join(self.workdir, f"ph{idx}-sock")
        os.makedirs(sockdir, exist_ok=True)
        extra = self.extra_serve_pods if peers_on else 0
        addrs = [
            os.path.join(sockdir, f"p{i}.sock") for i in range(pods + extra)
        ]
        errors: list[str] = []
        chains: list = [None] * pods
        # Topology fault arm: deterministic rack:zone:region localities
        # (zone by pod-index parity, racks alternating in pairs) so the
        # kill controller can SIGKILL-equivalent one whole zone's peer
        # servers mid-deploy. Survivors must degrade to shield/origin;
        # the serial replay (peers off) proves read identity.
        kill_zone_on = phase.kill_zone and peers_on
        localities = (
            {
                a: f"r{(i // 2) % 2}:z{i % 2}:reg0"
                for i, a in enumerate(addrs)
            }
            if kill_zone_on
            else None
        )
        zone_dead = threading.Event()
        kill_done = threading.Event()
        killed: list[int] = []
        suppressed: list[int] = []
        crash_done = threading.Event()
        pause = threading.Event()
        resume = threading.Event()
        quiesced = _an.make_condition("scenario.quiesce")
        state = {"completed": 0, "cp_active": 0}

        def enter_cp():
            """Gate into the control-plane window. While a restart is
            pending, pods park HERE — so the metastore only ever closes
            with zero control-plane RPCs in flight (a restart between
            requests, not data loss mid-transaction)."""
            while True:
                if pause.is_set():
                    resume.wait()
                with quiesced:
                    if not pause.is_set():
                        state["cp_active"] += 1
                        return

        def exit_cp():
            with quiesced:
                state["cp_active"] -= 1
                state["completed"] += 1
                quiesced.notify_all()

        def crash_controller():
            # Fire once half the pods completed their control-plane ops.
            while not crash_done.is_set():
                with quiesced:
                    if state["completed"] >= max(1, pods // 2):
                        break
                time.sleep(0.005)
            if crash_done.is_set():
                return
            pause.set()
            try:
                with quiesced:
                    while state["cp_active"] > 0:
                        quiesced.wait(timeout=0.05)
                self._crash_restart()
            finally:
                # Always release parked pods, even if the restart itself
                # blew up — their next op will surface the broken plane.
                crash_done.set()
                resume.set()
                pause.clear()

        open_pods: list = []
        pods_mu = _an.make_lock("scenario.pods")

        def kill_zone_controller():
            # Fire once half the pods completed their control-plane ops
            # (the crash_controller trigger shape), then sweep until the
            # phase ends: every registered zone-1 peer server goes down,
            # including any that raced past the creation guard.
            # Late-arriving zone-1 pods see zone_dead and never serve.
            while not kill_done.is_set():
                with quiesced:
                    if state["completed"] >= max(1, pods // 2):
                        break
                time.sleep(0.005)
            if kill_done.is_set():
                return
            zone_dead.set()
            while True:
                with pods_mu:
                    targets = [
                        (i, pod) for i, pod in open_pods
                        if i % 2 == 1 and pod.server is not None
                    ]
                for i, pod in targets:
                    srv, pod.server = pod.server, None
                    srv.stop()
                    killed.append(i)
                if kill_done.is_set():
                    return
                time.sleep(0.005)
        # Pod threads open trace spans (prepare/commit/blobcache): carry
        # the phase's trace context so their spans don't detach.
        phase_ctx = trace.capture()

        def run_pod(i: int) -> None:
            img = images[i % len(images)]
            try:
                with trace.with_context(phase_ctx):
                    _run_pod_traced(i, img)
            except BaseException as e:  # noqa: BLE001 — surfaced as run failure
                errors.append(f"pod{i}: {e!r}")

        def _run_pod_traced(i: int, img: dict) -> None:
            enter_cp()
            try:
                # Resolve the control-plane driver INSIDE the cp window:
                # a crash/restart replaces both the Snapshotter and the
                # gRPC plane, and enter_cp guarantees neither happens
                # while this pod's RPC mix is in flight.
                cp = (
                    self._grpc_plane() if phase.deploy_api == "grpc" else None
                )
                chains[i] = self._control_plane_pod(
                    f"ph{idx}-{img['cid'].replace(':', '_')}-pod{i}", layers,
                    cp=cp,
                )
            finally:
                exit_cp()
            # Data plane: cold-read the image through the waterfall.
            corrupt_seed = (
                self.spec.seed if (phase.corrupt_peer and i == 0) else None
            )
            serve = not (
                kill_zone_on and zone_dead.is_set() and i % 2 == 1
            )
            if kill_zone_on and not serve:
                with pods_mu:
                    suppressed.append(i)
            pod = _Pod(
                i,
                os.path.join(self.workdir, f"ph{idx}-pod{i}"),
                img["blob_id"],
                len(img["blob"]),
                self.registry.fetcher(img["blob_id"]),
                addrs,
                peers_on,
                health,
                corrupt_seed=corrupt_seed,
                localities=localities,
                serve=serve,
            )
            with pods_mu:
                open_pods.append((i, pod))
            # Demand-read window: read_mib bounds per-pod volume so a
            # big image's storm stays latency-dominated on a small
            # box (blob-id equality with the serial replay still
            # proves full-content identity).
            total = len(img["blob"])
            if phase.read_mib:
                total = min(total, phase.read_mib << 20)
            h = hashlib.sha256()
            for off in range(0, total, READ_CHUNK):
                n = min(READ_CHUNK, total - off)
                h.update(
                    self._demand_read(pod.cb, off, n, tenant=f"scn-pod{i}")
                )
            self.read_digests[f"ph{idx}-pod{i}"] = h.hexdigest()
            if phase.corrupt_peer and peers_on and i == 1:
                self._corrupt_probe(img, addrs[0])
            if img.get("soci"):
                self._soci_reads(pod, img, f"ph{idx}-pod{i}")

        # Serve-only members (scale-up capacity): open BEFORE the demand
        # pods so their peer servers are listening when the rendezvous
        # ring routes regions at them. They issue no control-plane ops
        # and no demand reads — pure extra serving capacity, pulled
        # through from the origin on first touch.
        for j in range(pods, pods + extra):
            img = images[j % len(images)]
            pod = _Pod(
                j,
                os.path.join(self.workdir, f"ph{idx}-pod{j}"),
                img["blob_id"],
                len(img["blob"]),
                self.registry.fetcher(img["blob_id"]),
                addrs,
                True,
                health,
            )
            with pods_mu:
                open_pods.append((j, pod))

        gc_stop = threading.Event()
        gc_thread = None
        if phase.gc_watermark_mib and not self.serial:
            def gc_tick():
                while not gc_stop.wait(0.05):
                    self._gc_all(phase.gc_watermark_mib << 20)
            gc_thread = threading.Thread(
                target=gc_tick, name="ntpu-scn-gc", daemon=True
            )
            gc_thread.start()

        kill_t = None
        if kill_zone_on:
            kill_t = threading.Thread(
                target=kill_zone_controller, name="ntpu-scn-killzone"
            )
            kill_t.start()

        crash_t = None
        if phase.crash == "mid":
            if self.serial:
                # Serial replay: the restart happens at the same logical
                # point — between pods, after half of them.
                pass
            else:
                crash_t = threading.Thread(
                    target=crash_controller, name="ntpu-scn-crash"
                )
                crash_t.start()

        if self.serial or self.pods_sequential:
            for i in range(pods):
                if phase.crash == "mid" and i == max(1, pods // 2):
                    self._crash_restart()
                run_pod(i)
        else:
            threads = [
                threading.Thread(
                    target=run_pod, args=(i,), name=f"ntpu-scn-pod{i}"
                )
                for i in range(pods)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        if kill_t is not None:
            kill_done.set()
            kill_t.join()
        if crash_t is not None:
            crash_done.set()
            crash_t.join()
        if gc_thread is not None:
            gc_stop.set()
            gc_thread.join()
        if phase.gc_watermark_mib and self.serial:
            self._gc_all(phase.gc_watermark_mib << 20)
        # Pods stay open (serving peers) until the whole phase is done —
        # exactly the deployed shape; teardown collects the hostile
        # peer's corruption count before closing it.
        with pods_mu:
            teardown = list(open_pods)
            open_pods.clear()
        # Aggregate the demand-lane pressure signal (queue depth + wait
        # EWMA) across the wave's gates before they close — the soak's
        # scale-up policy reads this to decide spawn/retire.
        press = [pod.gate.demand_pressure() for i, pod in teardown if i < pods]
        samples = sum(p["samples"] for p in press)
        self.last_demand_pressure = {
            "queued": sum(p["queued"] for p in press),
            "queued_peak": max(
                (p.get("queued_peak", 0) for p in press), default=0
            ),
            "wait_ms": (
                sum(p["wait_ms"] * p["samples"] for p in press) / samples
                if samples else 0.0
            ),
            "samples": samples,
            "gates": len(press),
            "extra_serve_pods": extra,
        }
        if self.node_gate is not None:
            # The node ceiling is where a crowd actually queues; its
            # signal supersedes the per-pod schedulers' (whose 8-wide
            # gates a 2-worker fetch pool can never saturate).
            node = self.node_gate.demand_pressure()
            self.last_demand_pressure.update({
                "queued": node["queued"],
                "queued_peak": node["queued_peak"],
                "wait_ms": node["wait_ms"],
                "node_samples": node["samples"],
            })
        for i, pod in teardown:
            if phase.corrupt_peer and i == 0 and pod.server is not None:
                self.corrupt_served += getattr(pod.server, "corrupted", 0)
            pod.close()
        if errors:
            raise ScenarioRunError(f"deploy pod failures: {errors[:4]}")
        for ch in chains:
            if ch is not None:
                self.deployed.append(ch)
                self.expected_keys.update(ch["names"])
                self.expected_keys.add(ch["ctr"])
        # Analytic demand volume of the wave (the capacity model's
        # numerator): each pod cold-reads its image's window.
        window = (phase.read_mib << 20) if phase.read_mib else (1 << 62)
        demand_bytes = sum(
            min(len(images[i % len(images)]["blob"]), window)
            for i in range(pods)
        )
        out = {
            "pods": pods,
            "peers": peers_on,
            "extra_serve_pods": extra,
            "demand_bytes": demand_bytes,
            "corrupt_served": self.corrupt_served if phase.corrupt_peer else 0,
            "crashes": self.crashes,
        }
        if kill_zone_on:
            out["kill_zone"] = {
                "zone": "z1",
                "killed": sorted(killed),
                "suppressed": sorted(suppressed),
            }
        return out

    def _corrupt_probe(self, img: dict, hostile_addr: str) -> None:
        """Deterministically engage the hostile-peer arm: rendezvous
        ownership hashes over this run's socket paths, so a bounded read
        window may never land on the hostile peer's regions by luck.
        Pod 1 contacts the hostile peer DIRECTLY for one region — the
        poisoned payload must fail the CRC check (a clean payload from a
        corrupting peer would mean the corruption hook is dead)."""
        from nydus_snapshotter_tpu.daemon.peer import PeerClient, PeerError, PeerMiss

        n = min(READ_CHUNK, len(img["blob"]))
        deadline = time.monotonic() + 10.0
        while True:
            try:
                PeerClient(hostile_addr, timeout_s=2.0).read_range(
                    img["blob_id"], 0, n
                )
            except PeerError as e:
                if "CRC32" in str(e):
                    return  # poisoned payload detected and rejected
                # Server not listening yet (pod 0 may still be in its
                # control-plane phase) — retry until the deadline.
            except PeerMiss:
                pass
            else:
                raise ScenarioRunError(
                    "hostile peer served a payload that passed the CRC check"
                )
            if time.monotonic() > deadline:
                raise ScenarioRunError(
                    "hostile-peer probe never got a corrupt response"
                )
            time.sleep(0.05)

    def _soci_reads(self, pod, img, tag: str) -> None:
        """The unconverted arm: lazy per-file reads over the pod's
        CachedBlob, verified against the original tar — the read path the
        soci backend deploys for whichever format the image ships.
        gzip → checkpoint index, zstd-seekable/opaque → frame index,
        zstd-chunked → TOC adoption (zero index-build bytes)."""
        fmt = img.get("format", "gzip")
        if fmt == "zstd-chunked":
            self._soci_reads_toc(pod, img, tag)
            return
        from nydus_snapshotter_tpu.soci import blob as soci_blob

        if fmt == "gzip":
            index, outcome = soci_blob.load_or_build_index(
                [pod.cache_dir],
                img["blob_id"],
                csize=len(img["blob"]),
                builder=lambda: pod.cb.read_at(0, len(img["blob"])),
                stride=64 << 10,
            )
        else:  # zstd-seekable / zstd-opaque: the frame-index twin
            from nydus_snapshotter_tpu.soci import zblob as soci_zblob

            index, outcome = soci_zblob.load_or_build_zindex(
                [pod.cache_dir],
                img["blob_id"],
                csize=len(img["blob"]),
                builder=lambda: pod.cb.read_at(0, len(img["blob"])),
            )
        self.soci_outcomes.append(outcome)
        if index is None:
            raise ScenarioRunError(f"{tag}: soci index unavailable ({outcome})")
        if fmt == "gzip":
            reader = soci_blob.SociStreamReader(index, pod.cb.read_at, name=tag)
        else:
            from nydus_snapshotter_tpu.soci.zblob import ZstdStreamReader

            reader = ZstdStreamReader(index, pod.cb.read_at, name=tag)
        tar = img["tar"]
        extents = sorted(soci_blob.file_extents(tar).items())
        h = hashlib.sha256()
        want = hashlib.sha256()
        for path, (off, size) in extents[:: max(1, len(extents) // 8)]:
            h.update(reader.read_range(off, min(size, READ_CHUNK)))
            want.update(tar[off : off + min(size, READ_CHUNK)])
        if h.hexdigest() != want.hexdigest():
            raise ScenarioRunError(f"{tag}: soci reads diverge from the tar")
        self.read_digests[f"{tag}-soci"] = h.hexdigest()

    def _soci_reads_toc(self, pod, img, tag: str) -> None:
        """The toc-adopt arm: the shipped zstd:chunked TOC IS the
        file→extent map — adopt it into a bootstrap, read files through
        per-chunk ranged fetches of the ORIGINAL blob, verify against the
        tar. No index artifact exists for this format, by design."""
        from nydus_snapshotter_tpu.converter.convert import BlobReader
        from nydus_snapshotter_tpu.soci import blob as soci_blob
        from nydus_snapshotter_tpu.soci import toc as ztoc
        from nydus_snapshotter_tpu.constants import COMPRESSOR_ZSTD
        from nydus_snapshotter_tpu.stargz.index import bootstrap_from_toc

        failpoint.hit("soci.index")
        size = len(img["blob"])
        toc = ztoc.read_toc(pod.cb.read_at, size)
        loc = ztoc.parse_footer(
            pod.cb.read_at(size - ztoc.FOOTER_SIZE, ztoc.FOOTER_SIZE)
        )
        if toc is None or loc is None:
            raise ScenarioRunError(f"{tag}: zstd:chunked TOC unreadable")
        bs = bootstrap_from_toc(
            toc,
            img["blob_id"],
            chunk_size=256 << 10,
            blob_compressed_size=loc[0],
            compressor=COMPRESSOR_ZSTD,
        )
        self.soci_outcomes.append("toc-adopt")
        br = BlobReader(bs, 0, pod.cb.read_at)
        tar = img["tar"]
        contents = {
            p.lstrip("/"): tar[off : off + sz]
            for p, (off, sz) in soci_blob.file_extents(tar).items()
        }
        import stat as statmod

        inodes = sorted(
            (i for i in bs.inodes if statmod.S_ISREG(i.mode)),
            key=lambda i: i.path,
        )
        h = hashlib.sha256()
        want = hashlib.sha256()
        for ino in inodes[:: max(1, len(inodes) // 8)]:
            recs = bs.chunks[ino.chunk_index : ino.chunk_index + ino.chunk_count]
            got = b"".join(br.chunk_data(r) for r in recs)
            h.update(got[:READ_CHUNK])
            want.update(contents[ino.path.lstrip("/")][:READ_CHUNK])
        if h.hexdigest() != want.hexdigest():
            raise ScenarioRunError(f"{tag}: toc-adopt reads diverge from the tar")
        self.read_digests[f"{tag}-soci"] = h.hexdigest()

    def _phase_remove(self, idx: int, phase: PhaseSpec) -> dict:
        count = max(1, int(len(self.deployed) * phase.fraction)) if self.deployed else 0
        victims, keep = self.deployed[:count], self.deployed[count:]
        removed = 0
        for ch in victims:
            # Children first: the writable layer, then the chain top-down
            # refusal order (metastore refuses while children exist).
            for key in [ch["ctr"], *reversed(ch["names"])]:
                self.sn.remove(key)
                self.expected_keys.discard(key)
                removed += 1
        self.deployed = keep
        self.sn.cleanup()
        return {"removed_snapshots": removed, "removed_pods": count}

    def _gc_all(self, watermark_bytes: int) -> list:
        removed = []
        for name in sorted(os.listdir(self.workdir)):
            if "-pod" not in name:
                continue
            mgr = CacheManager(os.path.join(self.workdir, name))
            if watermark_bytes > 0:
                removed += mgr.gc_watermark(watermark_bytes)
            else:
                removed += mgr.gc_once(0.0)
        return removed

    def _phase_gc(self, idx: int, phase: PhaseSpec) -> dict:
        removed = self._gc_all(phase.watermark_mib << 20)
        return {"evicted_files": len(removed)}

    # -- the run -------------------------------------------------------------

    def _start_judge(self) -> None:
        from nydus_snapshotter_tpu.metrics.slo import SloEngine, SloObjective

        budget = self.spec.slo
        self._engine = SloEngine([
            SloObjective(
                name=f"{self.spec.name}-demand",
                metric="ntpu_blobcache_op_duration_milliseconds",
                labels={"op": SLO_OP},
                threshold_ms=budget.demand_threshold_ms,
                target=budget.target,
                window_secs=budget.window_secs,
                long_window_factor=2.0,
                burn_threshold=budget.burn_threshold,
            )
        ])

        def judge():
            while not self._engine_stop.wait(0.05):
                self._engine.tick()

        self._engine_thread = threading.Thread(
            target=judge, name="ntpu-scn-judge", daemon=True
        )
        self._engine_thread.start()

    def _stop_judge(self) -> None:
        if self._engine_thread is not None:
            self._engine_stop.set()
            self._engine_thread.join()
            self._engine_thread = None
            self._engine.tick()

    def run(self) -> dict:
        report = {
            "scenario": self.spec.name,
            "serial": self.serial,
            "seed": self.spec.seed,
            "phases": [],
            "ok": True,
            "error": "",
        }
        self._open_control_plane()
        if any(p.op == "deploy" for p in self.spec.phases):
            self._start_judge()
        dispatch = {
            "convert": self._phase_convert,
            "deploy": self._phase_deploy,
            "remove": self._phase_remove,
            "gc": self._phase_gc,
            "crash_restart": lambda i, p: (self._crash_restart() or
                                           {"crashes": self.crashes}),
        }
        try:
            for i, phase in enumerate(self.spec.phases):
                armed = []
                if self.arm_faults:
                    for f in self.spec.faults:
                        if f.phase == i:
                            failpoint.inject(f.site, f.action)
                            scenario.FAULTS_ARMED.inc()
                            armed.append(f.site)
                t0 = time.perf_counter()
                try:
                    failpoint.hit("scenario.phase")
                    detail = dispatch[phase.op](i, phase)
                finally:
                    for site in armed:
                        failpoint.clear(site)
                scenario.PHASES_TOTAL.labels(phase.op).inc()
                report["phases"].append({
                    "op": phase.op,
                    "wall_s": round(time.perf_counter() - t0, 4),
                    "faults": armed,
                    **detail,
                })
        except BaseException as e:  # noqa: BLE001 — the run fails loudly
            report["ok"] = False
            report["error"] = (
                f"phase {len(report['phases'])} "
                f"({self.spec.phases[len(report['phases'])].op}): {e!r}"
            )
        finally:
            self._stop_judge()
        if self._engine is not None:
            status = self._engine.status()
            breaches = status.get("breaches", [])
            report["slo"] = {
                "breaches": len(breaches),
                "objectives": [
                    {k: o.get(k) for k in
                     ("objective", "compliance_short", "burn_short",
                      "burn_long", "breached")}
                    for o in status.get("objectives", [])
                ],
                "demand_p95_ms": self.demand_p95_ms(),
            }
            if breaches and report["ok"]:
                report["ok"] = False
                report["error"] = (
                    f"SLO judge: {len(breaches)} multi-window burn breach(es) "
                    "— demand latency out of budget"
                )
        report["origin"] = {
            "egress_bytes": self.registry.egress,
            "calls": self.registry.calls,
        }
        report["soci_outcomes"] = self.soci_outcomes
        scenario.RUNS_TOTAL.labels("pass" if report["ok"] else "fail").inc()
        return report

    def demand_p95_ms(self) -> float:
        with self._demand_mu:
            xs = sorted(self.demand_ms)
        return round(xs[int(len(xs) * 0.95)], 3) if xs else 0.0

    # -- identity + audit ----------------------------------------------------

    def fingerprint(self) -> dict:
        """The serial-replay identity surface: id-normalized metastore
        dump, per-pod demand-read digests, per-corpus blob ids."""
        return {
            "metastore": self.sn.ms.dump() if self.sn is not None else "",
            "reads": dict(sorted(self.read_digests.items())),
            "blobs": {
                cid: img["blob_id"] for cid, img in sorted(self.images.items())
            },
        }

    def audit(self) -> dict:
        """End-state audit: no leaked snapshot rows, no orphan snapshot
        dirs, no unaccounted cache entries (blob + companions must map to
        a registered blob id), no staging leftovers."""
        issues = []
        rows = []
        if self.sn is not None:
            self.sn.walk(lambda sid, info: rows.append(info.name))
            leaked = set(rows) - self.expected_keys
            missing = self.expected_keys - set(rows)
            for k in sorted(leaked):
                issues.append(f"leaked snapshot row {k!r}")
            for k in sorted(missing):
                issues.append(f"expected snapshot row {k!r} missing")
            snap_dir = os.path.join(self._snap_root(), "snapshots")
            ids = set(self.sn.ms.id_map())
            try:
                names = sorted(os.listdir(snap_dir))
            except OSError:
                names = []
            for name in names:
                if name == "metadata.db" or name.startswith("metadata.db"):
                    continue
                if name.startswith("new-") or name.startswith("rm-"):
                    issues.append(f"staging leftover {name!r} in snapshots dir")
                elif name not in ids:
                    issues.append(f"orphan snapshot dir {name!r}")
        known = self.registry.blob_ids()
        cache_files = 0
        for name in sorted(os.listdir(self.workdir)):
            if "-pod" not in name:
                continue
            pod_dir = os.path.join(self.workdir, name)
            for fn in sorted(os.listdir(pod_dir)):
                cache_files += 1
                bid = CacheManager._entry_id(fn)
                if bid not in known:
                    issues.append(f"unaccounted cache entry {name}/{fn}")
        return {
            "clean": not issues,
            "issues": issues,
            "metastore_rows": len(rows),
            "cache_files": cache_files,
        }

    def close(self) -> None:
        if self._grpc is not None:
            self._grpc.close()
            self._grpc = None
        if self.sn is not None:
            self.sn.close()
            self.sn = None


def run_scenario(
    spec: ScenarioSpec,
    workdir: Optional[str] = None,
    serial: bool = False,
    pods: Optional[int] = None,
) -> tuple[dict, dict, dict]:
    """One-shot convenience: run a spec in a (temp) workdir; returns
    ``(report, fingerprint, audit)``."""
    import tempfile

    own = workdir is None
    if own:
        workdir = tempfile.mkdtemp(prefix="ntpu-scenario-")
    runner = ScenarioRunner(spec, workdir, serial=serial, pods=pods)
    try:
        report = runner.run()
        return report, runner.fingerprint(), runner.audit()
    finally:
        runner.close()
        if own:
            shutil.rmtree(workdir, ignore_errors=True)
