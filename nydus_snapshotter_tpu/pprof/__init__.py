from nydus_snapshotter_tpu.pprof.listener import new_pprof_http_listener

__all__ = ["new_pprof_http_listener"]
