"""Profiling HTTP endpoint (reference pkg/pprof/listener.go:18-44).

Python-runtime equivalents of the Go pprof handlers:

    /debug/pprof/threads   — all thread stacks (goroutine-profile analogue)
    /debug/pprof/profile   — cProfile sample for ?seconds=N, pstats text
    /debug/pprof/heap      — per-type object counts + gc stats
    /debug/pprof/trace     — the request-trace ring buffer as text
                             (span trees per trace; see docs/observability.md)

Concurrent /debug/pprof/profile requests are serialized behind one lock:
two overlapping cProfile sessions race the interpreter's global profiler
hook, and the second would silently corrupt (or steal) the first's
sample. Serialized, each requester gets a full, clean window.

Gated by the system-controller config exactly like the reference
(snapshot.go:254-261).
"""

from __future__ import annotations

import cProfile
import gc
import io
import pstats
import sys
import threading
import traceback
from collections import Counter
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit


def _thread_dump() -> str:
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        out.append(f"thread {ident} [{names.get(ident, '?')}]:")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out)


def _heap_dump(limit: int = 50) -> str:
    counts = Counter(type(o).__name__ for o in gc.get_objects())
    lines = [f"{n} {c}" for n, c in counts.most_common(limit)]
    lines.append("")
    lines.append(f"gc_counts {gc.get_count()}")
    return "\n".join(lines)


_profile_lock = threading.Lock()


def _cpu_profile(seconds: float) -> str:
    with _profile_lock:
        prof = cProfile.Profile()
        done = threading.Event()
        prof.enable()
        done.wait(seconds)
        prof.disable()
    buf = io.StringIO()
    pstats.Stats(prof, stream=buf).sort_stats("cumulative").print_stats(60)
    return buf.getvalue()


def new_pprof_http_listener(addr: str) -> ThreadingHTTPServer:
    """Start the profiling server on ``host:port``; returns it (caller owns
    shutdown)."""
    if not addr:
        raise ValueError("the address for pprof HTTP server is invalid")
    host, _, port = addr.rpartition(":")

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            parsed = urlsplit(self.path)
            if parsed.path in ("/debug/pprof/threads", "/debug/pprof/goroutine"):
                body = _thread_dump()
            elif parsed.path == "/debug/pprof/heap":
                body = _heap_dump()
            elif parsed.path == "/debug/pprof/profile":
                secs = float(parse_qs(parsed.query).get("seconds", ["1"])[0])
                body = _cpu_profile(min(secs, 60.0))
            elif parsed.path == "/debug/pprof/trace":
                from nydus_snapshotter_tpu import trace

                body = trace.dump_text()
            else:
                self.send_response(404)
                self.end_headers()
                return
            data = body.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port)), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd
