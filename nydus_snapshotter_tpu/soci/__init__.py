"""Seekable-OCI backend: lazy-load plain OCI layers, convert nothing.

Every other lazy path in this tree (RAFS, eStargz, tarfs) needs the image
rewritten or annotated first. This package is the backend for the
registry's millions of images that never will be: on FIRST PULL the layer
is indexed — a zran/gzip checkpoint index (inflate resume points at a
configurable stride) or a zstd frame index (one entry per independent
frame, free when the blob ships a seekable-format seek table), plus a
per-layer file→decompressed-extent map — and from then on file reads
resolve to compressed byte ranges of the ORIGINAL registry blob, fetched
through the ordinary lazy-read data plane (daemon/fetch_sched.py:
singleflight, coalescing, readahead, watermark eviction, peer tier, QoS
admission lanes). Layers that ship their own TOC (eStargz, zstd:chunked)
skip even the index build: the TOC is adopted as the extent map for zero
build-pass bytes. The index is the only new artifact; no RAFS blob is
ever written.

Modules:

- :mod:`~nydus_snapshotter_tpu.soci.zran` — ctypes binding of the SYSTEM
  libz (the same discipline as utils/zstd.py): checkpoint capture with
  ``Z_BLOCK`` during one sequential inflate, bit-exact mid-stream resume
  via ``inflatePrime`` + ``inflateSetDictionary``;
- :mod:`~nydus_snapshotter_tpu.soci.zframe` — the zstd counterpart on
  the SYSTEM libzstd: frame walking via ``ZSTD_findFrameCompressedSize``
  and the seekable-format seek-table parser (frames decode independently,
  so the frame table IS the random-access index — no window captures);
- :mod:`~nydus_snapshotter_tpu.soci.index` — the persisted, checksummed
  ``<blob_id>.soci.idx`` artifact (tail-first/header-last torn-write
  hardening like the v5 dict format) and the read→compressed-range
  resolve geometry;
- :mod:`~nydus_snapshotter_tpu.soci.zindex` — the sibling
  ``<blob_id>.soci.zidx`` zstd frame-index artifact, same torn-write and
  checksum discipline;
- :mod:`~nydus_snapshotter_tpu.soci.toc` — zstd:chunked footer/manifest
  parsing (and a deterministic writer for tests and benches);
- :mod:`~nydus_snapshotter_tpu.soci.router` — the per-layer
  :class:`FormatRouter`: two ranged probe reads classify the blob and a
  closed-form cold-read cost model picks {toc-adopt, seekable-index,
  zran-index, rafs-convert}, surfaced as ``ntpu_soci_route_total``;
- :mod:`~nydus_snapshotter_tpu.soci.blob` — :class:`SociStreamReader`
  (the concurrent decompressed-domain reader the daemon's BlobReader
  mounts) and the index store: local load → peer-tier replication →
  rebuild-once, never poisoning reads;
- :mod:`~nydus_snapshotter_tpu.soci.zblob` — the zstd twin:
  :class:`ZstdStreamReader` plus the same store waterfall for the frame
  index (peer kind ``zsoci``);
- :mod:`~nydus_snapshotter_tpu.soci.adaptor` — the snapshotter-side
  driver (resolver probe + routed prepare + layer merge), routed by
  ``filesystem/fs.py`` exactly like the stargz adaptor.

Failpoint sites ``soci.{index,resolve,fetch}`` (docs/robustness.md),
metrics ``ntpu_soci_*`` (docs/observability.md), config ``[soci]`` with
``NTPU_SOCI*`` env overrides (docs/configure.md).
"""

from nydus_snapshotter_tpu.soci.adaptor import SociAdaptor, SociResolver  # noqa: F401
from nydus_snapshotter_tpu.soci.blob import (  # noqa: F401
    SociStreamReader,
    load_or_build_index,
    resolve_soci_config,
)
from nydus_snapshotter_tpu.soci.index import SociIndex, SociIndexError  # noqa: F401
from nydus_snapshotter_tpu.soci.router import FormatRouter, RouteDecision  # noqa: F401
from nydus_snapshotter_tpu.soci.zblob import (  # noqa: F401
    ZstdStreamReader,
    load_or_build_zindex,
)
from nydus_snapshotter_tpu.soci.zindex import ZstdFrameIndex, ZstdIndexError  # noqa: F401
