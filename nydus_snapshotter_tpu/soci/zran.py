"""zran: random access into foreign gzip streams via the SYSTEM libz.

CPython's ``zlib`` cannot build a *persistable* gzip index: resuming an
inflate mid-stream needs the bit-level offset of the deflate block
boundary (``inflatePrime``) and the preceding 32 KiB of output
(``inflateSetDictionary``), neither of which the module exposes — which
is why ``converter/zran.py``'s ``GzipStreamReader`` keeps live
``decompressobj.copy()`` checkpoints that die with the process. This
module binds the system ``libz`` with ctypes (the same system-library
discipline as utils/zstd.py) and implements the classic zran scheme
(madler/zlib examples/zran.c, the technique behind AWS SOCI's zTOC):

- **build**: one sequential inflate with ``Z_BLOCK`` stops at every
  deflate block boundary; whenever ``stride`` decompressed bytes have
  passed since the last checkpoint, record ``(uout, cin, bits, window)``
  — output offset, input byte offset, unconsumed bits of the byte at
  ``cin-1``, and the trailing 32 KiB of output;
- **extract**: raw-init (``wbits=-15``), ``inflatePrime`` the partial
  byte, ``inflateSetDictionary`` the window, then inflate forward from
  ``cin`` — so a read at decompressed offset O costs O(stride) inflate
  work instead of O(O), from a *persisted* checkpoint in any process.

Multi-member gzip (pigz, eStargz, concatenated members) is handled in
both directions: the build pass restarts header parsing at member
boundaries and records member-start checkpoints as ``fresh`` (no window,
``wbits=47`` resume), and extraction re-inits across ``Z_STREAM_END``.

``available()`` gates everything: without a loadable libz the soci
backend falls back to the in-process ``GzipStreamReader`` (correct,
sequential-cost cold reads — documented degraded mode, never wrong
bytes).
"""

from __future__ import annotations

import ctypes
import ctypes.util
from dataclasses import dataclass
from typing import Callable, Optional

from nydus_snapshotter_tpu.analysis import runtime as _an
from nydus_snapshotter_tpu.utils import errdefs

WINDOW_SIZE = 32768  # deflate's maximum back-reference distance
DEFAULT_STRIDE = 1 << 20

_Z_OK = 0
_Z_STREAM_END = 1
_Z_BUF_ERROR = -5
_Z_BLOCK = 5

_IN_STEP = 1 << 20
_OUT_STEP = 256 << 10


class ZranError(errdefs.NydusError):
    pass


class _ZStream(ctypes.Structure):
    # zlib.h z_stream — layout stable since zlib 1.0.
    _fields_ = [
        ("next_in", ctypes.POINTER(ctypes.c_ubyte)),
        ("avail_in", ctypes.c_uint),
        ("total_in", ctypes.c_ulong),
        ("next_out", ctypes.POINTER(ctypes.c_ubyte)),
        ("avail_out", ctypes.c_uint),
        ("total_out", ctypes.c_ulong),
        ("msg", ctypes.c_char_p),
        ("state", ctypes.c_void_p),
        ("zalloc", ctypes.c_void_p),
        ("zfree", ctypes.c_void_p),
        ("opaque", ctypes.c_void_p),
        ("data_type", ctypes.c_int),
        ("adler", ctypes.c_ulong),
        ("reserved", ctypes.c_ulong),
    ]


class _Api:
    def __init__(self, lib: ctypes.CDLL):
        lib.zlibVersion.restype = ctypes.c_char_p
        self.version = lib.zlibVersion()
        for name in ("inflateInit2_", "inflate", "inflateEnd",
                     "inflatePrime", "inflateSetDictionary", "inflateReset2"):
            getattr(lib, name).restype = ctypes.c_int
        self.lib = lib

    def init(self, strm: _ZStream, wbits: int) -> None:
        rc = self.lib.inflateInit2_(
            ctypes.byref(strm), wbits, self.version, ctypes.sizeof(_ZStream)
        )
        if rc != _Z_OK:
            raise ZranError(f"inflateInit2({wbits}) -> {rc}")

    def reset(self, strm: _ZStream, wbits: int) -> None:
        rc = self.lib.inflateReset2(ctypes.byref(strm), wbits)
        if rc != _Z_OK:
            raise ZranError(f"inflateReset2({wbits}) -> {rc}")

    def prime(self, strm: _ZStream, bits: int, value: int) -> None:
        rc = self.lib.inflatePrime(ctypes.byref(strm), bits, value)
        if rc != _Z_OK:
            raise ZranError(f"inflatePrime -> {rc}")

    def set_dictionary(self, strm: _ZStream, window: bytes) -> None:
        buf = (ctypes.c_ubyte * len(window)).from_buffer_copy(window)
        rc = self.lib.inflateSetDictionary(ctypes.byref(strm), buf, len(window))
        if rc != _Z_OK:
            raise ZranError(f"inflateSetDictionary -> {rc}")

    def end(self, strm: _ZStream) -> None:
        self.lib.inflateEnd(ctypes.byref(strm))


_api: Optional[_Api] = None
_api_failed = False
_api_lock = _an.make_lock("soci.zran.api")

_LIB_CANDIDATES = ("libz.so.1", "libz.so", "libz.dylib")


def _load_api() -> Optional[_Api]:
    global _api, _api_failed
    with _api_lock:
        if _api is not None or _api_failed:
            return _api
        names = list(_LIB_CANDIDATES)
        found = ctypes.util.find_library("z")
        if found:
            names.insert(0, found)
        for name in names:
            try:
                lib = ctypes.CDLL(name)
                # inflatePrime landed in zlib 1.2.2.4; probe for it so a
                # prehistoric libz degrades instead of AttributeError-ing
                # mid-read.
                lib.inflatePrime
                _api = _Api(lib)
                return _api
            except (OSError, AttributeError):
                continue
        _api_failed = True
        return None


def available() -> bool:
    """Whether checkpointed random access is usable on this host."""
    return _load_api() is not None


@dataclass
class Checkpoint:
    """One inflate resume point.

    ``uout``/``cin`` are the decompressed/compressed offsets; ``bits`` is
    how many bits of the byte at ``cin - 1`` belong to the next block;
    ``window`` is the preceding (up to) 32 KiB of decompressed output.
    ``fresh`` marks a gzip member start: resume parses a fresh header
    (``wbits=47``) and needs no prime/window.
    """

    uout: int
    cin: int
    bits: int
    window: bytes
    fresh: bool = False


def build(
    raw: bytes, stride: int = DEFAULT_STRIDE
) -> tuple[list[Checkpoint], bytes]:
    """One sequential inflate of a whole gzip blob, capturing resume
    checkpoints roughly every ``stride`` decompressed bytes.

    Returns ``(checkpoints, decompressed bytes)`` — the build pass IS a
    full decompression, so index-on-first-pull reuses its output for the
    bootstrap build instead of inflating twice. The implicit stream-start
    checkpoint is not stored (extraction from offset 0 just inits fresh).
    """
    api = _load_api()
    if api is None:
        raise ZranError("system libz with inflatePrime is not available")
    stride = max(WINDOW_SIZE, int(stride))
    strm = _ZStream()
    api.init(strm, 47)
    inbuf = (ctypes.c_ubyte * len(raw)).from_buffer_copy(raw)
    strm.next_in = ctypes.cast(inbuf, ctypes.POINTER(ctypes.c_ubyte))
    strm.avail_in = len(raw)
    outchunk = (ctypes.c_ubyte * _OUT_STEP)()
    out = bytearray()
    points: list[Checkpoint] = []
    last = 0
    try:
        while True:
            strm.next_out = ctypes.cast(outchunk, ctypes.POINTER(ctypes.c_ubyte))
            strm.avail_out = _OUT_STEP
            # Z_BLOCK (stop at every deflate block boundary) costs ~5x
            # the bare inflate rate in call overhead; only pay it while
            # hunting the next checkpointable boundary — plain inflate
            # covers the stretch between checkpoints at full speed.
            flush = _Z_BLOCK if len(out) - last >= stride else 0
            rc = api.lib.inflate(ctypes.byref(strm), flush)
            produced = _OUT_STEP - strm.avail_out
            if produced:
                out += ctypes.string_at(outchunk, produced)
            if rc == _Z_STREAM_END:
                if strm.avail_in == 0:
                    break
                # Multi-member blob: restart header parsing; the member
                # boundary itself is a natural (windowless) checkpoint.
                api.reset(strm, 47)
                if len(out) - last >= stride:
                    points.append(
                        Checkpoint(len(out), len(raw) - strm.avail_in, 0, b"",
                                   fresh=True)
                    )
                    last = len(out)
                continue
            if rc not in (_Z_OK, _Z_BUF_ERROR):
                msg = strm.msg.decode() if strm.msg else f"rc={rc}"
                raise ZranError(f"corrupt gzip stream at byte "
                                f"{len(raw) - strm.avail_in}: {msg}")
            if rc == _Z_BUF_ERROR and strm.avail_in == 0 and produced == 0:
                raise ZranError("gzip stream truncated")
            # Block boundary (data_type bit 7, not at end of stream):
            # the only place bit-exact resume is possible.
            if (strm.data_type & 0xC0) == 0x80 and len(out) - last >= stride:
                points.append(
                    Checkpoint(
                        len(out),
                        len(raw) - strm.avail_in,
                        strm.data_type & 7,
                        bytes(out[-WINDOW_SIZE:]),
                    )
                )
                last = len(out)
    finally:
        api.end(strm)
    return points, bytes(out)


def extract(
    read_comp: Callable[[int, int], bytes],
    csize: int,
    checkpoint: Optional[Checkpoint],
    offset: int,
    size: int,
    comp_end: Optional[int] = None,
) -> bytes:
    """Decompressed ``[offset, offset + size)`` resumed at ``checkpoint``
    (None = stream start). ``read_comp(pos, n)`` supplies compressed
    bytes on demand — extraction pulls only what inflate consumes, in
    ``_IN_STEP`` steps, never past ``comp_end`` (the resolve geometry's
    upper bound, default: the whole blob).

    Each call owns a private z_stream: concurrent extracts are safe.
    """
    if size <= 0:
        return b""
    api = _load_api()
    if api is None:
        raise ZranError("system libz with inflatePrime is not available")
    if comp_end is None or comp_end > csize:
        comp_end = csize
    strm = _ZStream()
    raw_mode = checkpoint is not None and not checkpoint.fresh
    if not raw_mode:
        api.init(strm, 47)
        upos = 0 if checkpoint is None else checkpoint.uout
        cpos = 0 if checkpoint is None else checkpoint.cin
    else:
        api.init(strm, -15)
        upos = checkpoint.uout
        cpos = checkpoint.cin
        try:
            if checkpoint.bits:
                ch = read_comp(checkpoint.cin - 1, 1)
                if len(ch) != 1:
                    raise ZranError("short read priming checkpoint byte")
                api.prime(strm, checkpoint.bits, ch[0] >> (8 - checkpoint.bits))
            if checkpoint.window:
                api.set_dictionary(strm, checkpoint.window)
        except ZranError:
            api.end(strm)
            raise
    out = bytearray()
    skip = offset - upos
    if skip < 0:
        api.end(strm)
        raise ZranError(f"checkpoint at {upos} is past read offset {offset}")
    buf = (ctypes.c_ubyte * _OUT_STEP)()
    pending = b""
    skip_in = 0  # gzip member trailer bytes a raw-mode inflate leaves behind
    try:
        while len(out) < size:
            if not pending:
                if cpos >= comp_end:
                    break
                pending = read_comp(cpos, min(_IN_STEP, comp_end - cpos))
                if not pending:
                    break
                cpos += len(pending)
            if skip_in:
                drop = min(skip_in, len(pending))
                pending = pending[drop:]
                skip_in -= drop
                continue
            inbuf = (ctypes.c_ubyte * len(pending)).from_buffer_copy(pending)
            strm.next_in = ctypes.cast(inbuf, ctypes.POINTER(ctypes.c_ubyte))
            strm.avail_in = len(pending)
            while len(out) < size:
                strm.next_out = ctypes.cast(buf, ctypes.POINTER(ctypes.c_ubyte))
                strm.avail_out = _OUT_STEP
                rc = api.lib.inflate(ctypes.byref(strm), 0)
                produced = _OUT_STEP - strm.avail_out
                if produced:
                    if skip >= produced:
                        skip -= produced
                    else:
                        want = size - len(out)
                        out += ctypes.string_at(
                            ctypes.addressof(buf) + skip,
                            min(produced - skip, want),
                        )
                        skip = 0
                if rc == _Z_STREAM_END:
                    # Member boundary. A raw (-15) resume stops at the
                    # final deflate block and never consumes the 8-byte
                    # gzip trailer (CRC32 + ISIZE) — drop it before the
                    # next member's header parse; a 47-mode inflate ate
                    # it already.
                    pending = pending[len(pending) - strm.avail_in :]
                    if raw_mode:
                        skip_in = 8
                        raw_mode = False
                    api.reset(strm, 47)
                    break
                if rc not in (_Z_OK, _Z_BUF_ERROR):
                    msg = strm.msg.decode() if strm.msg else f"rc={rc}"
                    raise ZranError(
                        f"inflate failed resuming at {upos}: {msg}"
                    )
                if strm.avail_in == 0:
                    pending = b""
                    break
                if rc == _Z_BUF_ERROR and produced == 0:
                    pending = b""
                    break
    finally:
        api.end(strm)
    if len(out) != size:
        raise ZranError(
            f"range [{offset}, +{size}) yielded {len(out)} bytes "
            f"(checkpoint at {upos}, compressed [{cpos}, {comp_end}))"
        )
    return bytes(out)
