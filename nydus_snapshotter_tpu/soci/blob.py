"""Runtime half of the soci backend: checkpoint-indexed lazy reads.

:class:`SociStreamReader` is what the daemon's
:class:`~nydus_snapshotter_tpu.converter.convert.BlobReader` mounts for a
gzip-stream blob when a persisted index exists: ``read_range`` resolves a
decompressed extent to its compressed byte range through the index
geometry, pulls exactly those bytes through the caller-supplied
compressed-domain reader — a registry-backed
:class:`~nydus_snapshotter_tpu.daemon.blobcache.CachedBlob`'s ``read_at``
in the deployed stack, so singleflight, coalescing, readahead, watermark
eviction, the peer tier and QoS admission all apply untouched — and
inflates from the nearest checkpoint. Unlike the in-process
``GzipStreamReader`` it replaces, every call owns its own inflate state:
concurrent chunk reads proceed without a shared lock, and cold cost is
O(stride), not O(offset), in ANY process.

The index store (:func:`load_or_build_index`) implements the
first-pull amortization contract: local load (checksummed — a corrupt,
torn or stale artifact fails loudly and is deleted) → peer-tier
replication (one pod's first-pull build serves the fleet; replicated
bytes revalidate through the same checksum) → rebuild-once from the
original blob. A bad index can cost one rebuild; it can never poison
reads.

Failpoints: ``soci.index`` (store boundary), ``soci.resolve``
(read→range geometry), ``soci.fetch`` (compressed-range pull for a lazy
read). Metrics: ``ntpu_soci_*``. Config: ``[soci]`` with ``NTPU_SOCI*``
env overrides (the env is also how the section reaches spawned daemon
processes, like every blobcache knob).
"""

from __future__ import annotations

import io
import logging
import os
import tarfile
from time import perf_counter
from typing import Callable, Optional, Sequence

from nydus_snapshotter_tpu import failpoint, trace
from nydus_snapshotter_tpu.metrics import registry as _metrics
from nydus_snapshotter_tpu.soci import zran
from nydus_snapshotter_tpu.soci.index import (
    SociIndex,
    SociIndexError,
    index_path,
)

logger = logging.getLogger(__name__)

DEFAULT_STRIDE_KIB = 1024
MIN_STRIDE_KIB = 64

_reg = _metrics.default_registry
INDEX_EVENTS = _reg.register(
    _metrics.Counter(
        "ntpu_soci_index_events_total",
        "Seekable-OCI index store outcomes (loaded / built / rebuilt /"
        " replicated / error)",
        ("outcome",),
    )
)
INDEX_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_soci_index_bytes_total",
        "Bytes of persisted seekable-OCI index artifacts written",
    )
)
INDEX_CHECKPOINTS = _reg.register(
    _metrics.Counter(
        "ntpu_soci_index_checkpoints_total",
        "zran inflate checkpoints captured by index builds",
    )
)
READ_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_soci_read_bytes_total",
        "Decompressed bytes served by checkpoint-indexed lazy reads",
    )
)
FETCH_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_soci_compressed_fetch_bytes_total",
        "Compressed bytes pulled to satisfy checkpoint-indexed reads"
        " (amplification numerator vs ntpu_soci_read_bytes_total)",
    )
)
OP_MS = _reg.register(
    _metrics.Histogram(
        "ntpu_soci_op_duration_milliseconds",
        "Latency of seekable-OCI operations (index build / lazy read)",
        ("op",),
    )
)


def snapshot_counters() -> dict:
    """Cumulative ``ntpu_soci_*`` values (tools delta these around runs)."""
    return {
        "index_loaded": INDEX_EVENTS.value("loaded"),
        "index_built": INDEX_EVENTS.value("built"),
        "index_rebuilt": INDEX_EVENTS.value("rebuilt"),
        "index_replicated": INDEX_EVENTS.value("replicated"),
        "index_errors": INDEX_EVENTS.value("error"),
        "index_bytes": INDEX_BYTES.value(),
        "index_checkpoints": INDEX_CHECKPOINTS.value(),
        "read_bytes": READ_BYTES.value(),
        "compressed_fetch_bytes": FETCH_BYTES.value(),
    }


# ---------------------------------------------------------------------------
# Config resolution (env > [soci] config > defaults)
# ---------------------------------------------------------------------------


class SociRuntimeConfig:
    __slots__ = ("enable", "stride_bytes", "replicate", "zstd", "toc_adopt")

    def __init__(
        self,
        enable: bool,
        stride_bytes: int,
        replicate: bool,
        zstd: bool = True,
        toc_adopt: bool = True,
    ):
        self.enable = enable
        self.stride_bytes = stride_bytes
        self.replicate = replicate
        self.zstd = zstd
        self.toc_adopt = toc_adopt


def _global_soci_config():
    try:
        from nydus_snapshotter_tpu.config import config as _cfg

        return _cfg.get_global_config().soci
    except Exception:
        return None


def resolve_soci_config() -> SociRuntimeConfig:
    """env (``NTPU_SOCI*``) > ``[soci]`` global config > defaults."""
    from nydus_snapshotter_tpu.daemon.fetch_sched import _env_int

    sc = _global_soci_config()

    def _bool(name: str, default: bool) -> bool:
        v = os.environ.get(name, "")
        if not v:
            return default
        return v not in ("0", "off", "false")

    stride_kib = _env_int(
        "NTPU_SOCI_STRIDE_KIB",
        getattr(sc, "stride_kib", 0) or DEFAULT_STRIDE_KIB,
    )
    return SociRuntimeConfig(
        enable=_bool("NTPU_SOCI_ENABLE", bool(getattr(sc, "enable", False))),
        stride_bytes=max(MIN_STRIDE_KIB, stride_kib) << 10,
        replicate=_bool(
            "NTPU_SOCI_REPLICATE", bool(getattr(sc, "replicate", True))
        ),
        zstd=_bool("NTPU_SOCI_ZSTD", bool(getattr(sc, "zstd", True))),
        toc_adopt=_bool(
            "NTPU_SOCI_TOC_ADOPT", bool(getattr(sc, "toc_adopt", True))
        ),
    )


# ---------------------------------------------------------------------------
# Index building
# ---------------------------------------------------------------------------


def _norm_path(name: str) -> str:
    p = "/" + name.strip("/")
    return "/" if p == "/" else p


def file_extents(tar_bytes: bytes) -> dict[str, tuple[int, int]]:
    """path → (decompressed offset, size) for every regular file's
    content region — tar semantics applied (a repeated path replaces the
    earlier entry; whiteouts carry no data and are skipped)."""
    files: dict[str, tuple[int, int]] = {}
    try:
        tf = tarfile.open(fileobj=io.BytesIO(tar_bytes), mode="r:")
        for info in tf:
            if info.isreg() and info.size > 0:
                files[_norm_path(info.name)] = (info.offset_data, info.size)
    except tarfile.TarError:
        # A gzip blob that isn't a tar: the checkpoint index still gives
        # random access to the byte stream; only the file map is empty.
        logger.warning("soci file map skipped: decompressed stream is not "
                       "a tar", exc_info=True)
    return files


def build_index_from_gzip(
    blob_id: str,
    raw_gzip: bytes,
    stride: Optional[int] = None,
) -> tuple[SociIndex, bytes]:
    """One inflate pass over the original layer → ``(index, tar bytes)``.

    The decompressed output is returned so index-on-first-pull builds the
    layer bootstrap from the same pass instead of inflating twice.
    """
    failpoint.hit("soci.index")
    stride = stride or resolve_soci_config().stride_bytes
    t0 = perf_counter()
    with trace.span("soci.index.build", blob=blob_id[:8], bytes=len(raw_gzip)):
        checkpoints, tar_bytes = zran.build(raw_gzip, stride=stride)
        index = SociIndex(
            blob_id=blob_id,
            compressed_size=len(raw_gzip),
            uncompressed_size=len(tar_bytes),
            stride=stride,
            checkpoints=checkpoints,
            files=file_extents(tar_bytes),
        )
    INDEX_CHECKPOINTS.inc(len(checkpoints))
    OP_MS.labels("build").observe((perf_counter() - t0) * 1000.0)
    return index, tar_bytes


# ---------------------------------------------------------------------------
# Index store: local → peer → rebuild-once
# ---------------------------------------------------------------------------


def find_index(
    dirs: Sequence[str], blob_id: str, csize: int = 0
) -> tuple[Optional[SociIndex], int]:
    """``(first loadable index for blob_id in dirs, discarded count)``.
    A corrupt or stale artifact fails loudly (warning + error metric),
    is deleted so it cannot fail twice, and the search continues."""
    discarded = 0
    for d in dirs:
        if not d:
            continue
        path = index_path(d, blob_id)
        if not os.path.exists(path):
            continue
        try:
            return SociIndex.load(path, blob_id=blob_id, csize=csize), discarded
        except SociIndexError as e:
            INDEX_EVENTS.labels("error").inc()
            logger.warning("discarding bad soci index %s: %s", path, e)
            discarded += 1
            try:
                os.unlink(path)
            except OSError:
                pass
    return None, discarded


def load_or_build_index(
    dirs: Sequence[str],
    blob_id: str,
    csize: int = 0,
    builder: Optional[Callable[[], bytes]] = None,
    fetch_remote: Optional[Callable[[], bytes]] = None,
    stride: Optional[int] = None,
    persist: bool = True,
) -> tuple[Optional[SociIndex], str]:
    """The store waterfall: local cache dirs → peer replication → one
    local rebuild. Returns ``(index, outcome)``; ``(None, ...)`` means
    the caller must fall back to the sequential in-process reader —
    NEVER to wrong bytes.

    ``builder()`` returns the original compressed layer (the rebuild
    source); ``fetch_remote()`` returns serialized index bytes from the
    peer tier, revalidated by checksum before adoption. A (re)build or
    adopted replica persists into ``dirs[0]``.
    """
    failpoint.hit("soci.index")
    try:
        idx, discarded = find_index(dirs, blob_id, csize=csize)
    except Exception:  # noqa: BLE001 — the store degrades, reads survive
        logger.warning("soci index search failed for %s", blob_id[:12],
                       exc_info=True)
        idx, discarded = None, 1
    if idx is not None:
        INDEX_EVENTS.labels("loaded").inc()
        return idx, "loaded"

    if fetch_remote is not None:
        try:
            raw = fetch_remote()
            idx = SociIndex.from_bytes(raw, blob_id=blob_id, csize=csize)
        except Exception as e:  # noqa: BLE001 — peer replication is an
            # optimization; any failure (dead peer, corrupt bytes) walks
            # on to the local build
            logger.warning("soci index replication for %s failed: %s",
                           blob_id[:12], e)
            idx = None
        if idx is not None:
            INDEX_EVENTS.labels("replicated").inc()
            if persist and dirs and dirs[0]:
                try:
                    INDEX_BYTES.inc(idx.save(index_path(dirs[0], blob_id)))
                except OSError:
                    logger.warning("cannot persist replicated soci index",
                                   exc_info=True)
            return idx, "replicated"

    if builder is None:
        return None, "missing"
    try:
        raw_gzip = builder()
        idx, _ = build_index_from_gzip(blob_id, raw_gzip, stride=stride)
    except Exception as e:  # noqa: BLE001 — a failed build degrades to
        # the sequential reader, never to a broken one
        INDEX_EVENTS.labels("error").inc()
        logger.warning("soci index build for %s failed: %s", blob_id[:12], e)
        return None, "error"
    outcome = "rebuilt" if discarded else "built"
    INDEX_EVENTS.labels(outcome).inc()
    if persist and dirs and dirs[0]:
        try:
            INDEX_BYTES.inc(idx.save(index_path(dirs[0], blob_id)))
        except OSError:
            logger.warning("cannot persist soci index", exc_info=True)
    return idx, outcome


# ---------------------------------------------------------------------------
# The reader BlobReader mounts
# ---------------------------------------------------------------------------


class SociStreamReader:
    """Decompressed-domain random access over an indexed gzip blob.

    Interface-compatible with ``converter/zran.GzipStreamReader``
    (``read_range(offset, size)``), but stateless per call —
    ``concurrent = True`` tells BlobReader it needs no serializing lock —
    and cold cost is bounded by the index stride. ``read_comp`` is the
    compressed-domain reader (CachedBlob.read_at in the daemon, a plain
    pread for local blobs); all caching stays in the compressed domain,
    where the fetch scheduler and eviction already manage it.
    """

    concurrent = True

    def __init__(
        self,
        index: SociIndex,
        read_comp: Callable[[int, int], bytes],
        name: str = "",
    ):
        self.index = index
        self._read_comp = read_comp
        self.name = name or index.blob_id[:8]

    def read_range(self, offset: int, size: int) -> bytes:
        if size <= 0:
            return b""
        if offset + size > self.index.uncompressed_size:
            raise SociIndexError(
                f"read [{offset}, +{size}) beyond decompressed end "
                f"{self.index.uncompressed_size}"
            )
        t0 = perf_counter()
        failpoint.hit("soci.resolve")
        cp, comp_start, comp_end = self.index.resolve(offset, size)
        with trace.span(
            "soci.read",
            blob=self.name,
            offset=offset,
            bytes=size,
            checkpoint=0 if cp is None else cp.uout,
        ) as sp:
            fetched = 0

            def pull(pos: int, n: int) -> bytes:
                nonlocal fetched
                failpoint.hit("soci.fetch")
                data = self._read_comp(pos, n)
                fetched += len(data)
                return data

            out = zran.extract(
                pull, self.index.compressed_size, cp, offset, size,
                comp_end=comp_end,
            )
            sp.annotate(compressed_bytes=fetched)
        READ_BYTES.inc(size)
        FETCH_BYTES.inc(fetched)
        OP_MS.labels("read").observe((perf_counter() - t0) * 1000.0)
        return out

    def resolve_compressed(self, offset: int, size: int) -> tuple[int, int]:
        """Compressed ``[start, end)`` a decompressed extent needs —
        the prefetch replayer warms THIS range (warming the decompressed
        offsets against a compressed blob would warm garbage)."""
        _, comp_start, comp_end = self.index.resolve(offset, size)
        return comp_start, comp_end


def warm_list_from_index(index, paths: list[str]) -> tuple[list, list[str]]:
    """The soci index as a prefetch-trace source: translate an ordered
    path list through the index's self-contained file → decompressed-
    extent map into ``(path, comp_start, comp_end)`` compressed warm
    ranges, one per file (vs one per bootstrap chunk record — the replay
    issues whole-file ranges the fetch scheduler then coalesces).
    Returns the warm list plus the paths the index doesn't map (the
    caller replays those through the bootstrap as before). The ranges
    are warmed at PREFETCH lane priority by the caller; order is the
    trace's access order, which IS the replay priority."""
    warms = []
    missing: list[str] = []
    for path in paths:
        ext = index.file_extent(_norm_path(path))
        if ext is None:
            missing.append(path)
            continue
        uoff, usize = ext
        _, comp_start, comp_end = index.resolve(uoff, usize)
        warms.append((path, comp_start, comp_end))
    return warms, missing
