"""The persisted zstd frame-index artifact (``<blob_id>.soci.zidx``).

The zstd sibling of :mod:`~nydus_snapshotter_tpu.soci.index`: one file
per lazily-read zstd layer, living in the blob cache dir as a
cache-entry companion (watermark eviction and GC remove it with the
blob), peer-replicated through the generic artifact plane under kind
``"zsoci"``. It carries:

- the **frame table** (:class:`~nydus_snapshotter_tpu.soci.zframe.FrameEntry`
  rows — zstd frames decode independently, so unlike gzip checkpoints
  there are no windows to compress and no bit offsets: 32 bytes/frame);
- the **file → decompressed-extent map** (same shape as the gzip index);
- blob geometry plus the index ``source`` (parsed seek table vs
  sequential frame walk), surfaced on ``ntpuctl soci``.

Persistence discipline is byte-for-byte the same as ``.soci.idx``:
payload written first, the fixed header (magic, counts, payload SHA-256)
written last, fsync + atomic rename — a crashed writer leaves the old
index or none. Validation failures raise :class:`ZstdIndexError`, a
:class:`~nydus_snapshotter_tpu.soci.index.SociIndexError` subclass, so
the load→replicate→rebuild-once waterfall in :mod:`soci.zblob` handles
torn, stale and foreign files identically: delete, rebuild once, never
poison reads.
"""

from __future__ import annotations

import hashlib
import io
import os
import struct
import tempfile
from bisect import bisect_right
from dataclasses import dataclass, field
from typing import Optional

from nydus_snapshotter_tpu.soci.index import SociIndexError, _FILE_HEAD
from nydus_snapshotter_tpu.soci.zframe import FrameEntry

ZINDEX_SUFFIX = ".soci.zidx"

_MAGIC = b"NTPUZSTD"
_VERSION = 1
# magic, version, source, csize, usize, n_frames, n_files, payload_len,
# payload sha256, blob_id (64 hex, space-padded), reserved.
_HEADER = struct.Struct("<8sIQQQIIQ32s64s16s")
_FRAME = struct.Struct("<QQQQ")

# How the frame table was obtained — a seek table costs two ranged tail
# reads, a frame walk costs the one sequential first-pull pass.
SOURCE_FRAME_WALK = 0
SOURCE_SEEK_TABLE = 1
_SOURCE_NAMES = {SOURCE_FRAME_WALK: "frame_walk", SOURCE_SEEK_TABLE: "seek_table"}


class ZstdIndexError(SociIndexError):
    """The zstd index artifact is corrupt, torn, or stale for its blob."""


def zindex_path(cache_dir: str, blob_id: str) -> str:
    return os.path.join(cache_dir, blob_id + ZINDEX_SUFFIX)


@dataclass
class ZstdFrameIndex:
    blob_id: str
    compressed_size: int
    uncompressed_size: int
    source: int = SOURCE_FRAME_WALK
    frames: list[FrameEntry] = field(default_factory=list)
    # path -> (decompressed offset, size) of every regular file's content.
    files: dict[str, tuple[int, int]] = field(default_factory=dict)

    def __post_init__(self):
        self.frames.sort(key=lambda e: e.uout)
        self._uouts = [e.uout for e in self.frames]

    @property
    def source_name(self) -> str:
        return _SOURCE_NAMES.get(self.source, f"source_{self.source}")

    # -- resolve geometry ----------------------------------------------------

    def resolve(
        self, offset: int, size: int
    ) -> tuple[list[FrameEntry], int, int]:
        """Frames covering decompressed ``[offset, offset+size)``.

        Returns ``(frames, comp_start, comp_end)``: the ascending slice
        of frame entries the read overlaps, and the compressed byte span
        ``[comp_start, comp_end)`` that feeds them — contiguous by frame
        adjacency, so one ranged fetch (or the CachedBlob waterfall's
        coalesced chunk reads) covers every needed frame.
        """
        end = offset + max(0, size)
        i = bisect_right(self._uouts, offset) - 1
        if i < 0:
            i = 0
        j = bisect_right(self._uouts, max(offset, end - 1))
        covering = self.frames[i:j]
        if not covering:
            return [], 0, 0
        return (
            covering,
            covering[0].cin,
            covering[-1].cin + covering[-1].csize,
        )

    def file_extent(self, path: str) -> Optional[tuple[int, int]]:
        return self.files.get(path)

    # -- (de)serialization ---------------------------------------------------

    def _payload(self) -> bytes:
        out = io.BytesIO()
        for e in self.frames:
            out.write(_FRAME.pack(e.uout, e.cin, e.usize, e.csize))
        for path, (uoff, usize) in sorted(self.files.items()):
            p = path.encode()
            out.write(_FILE_HEAD.pack(len(p), uoff, usize))
            out.write(p)
        return out.getvalue()

    def to_bytes(self) -> bytes:
        payload = self._payload()
        header = _HEADER.pack(
            _MAGIC,
            _VERSION,
            self.source,
            self.compressed_size,
            self.uncompressed_size,
            len(self.frames),
            len(self.files),
            len(payload),
            hashlib.sha256(payload).digest(),
            self.blob_id.encode().ljust(64),
            b"\0" * 16,
        )
        return header + payload

    def save(self, path: str) -> int:
        """Persist atomically, payload-first/header-last (the discipline
        of ``SociIndex.save``): the header that makes the bytes loadable
        lands after the payload is fsynced, then an atomic rename.
        Returns bytes written."""
        payload = self._payload()
        blob = self.to_bytes()
        d = os.path.dirname(path) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=".soci-zidx-", dir=d)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(b"\0" * _HEADER.size)  # placeholder until payload lands
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
                f.seek(0)
                f.write(blob[: _HEADER.size])
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return len(blob)

    @classmethod
    def from_bytes(
        cls, raw: bytes, blob_id: str = "", csize: int = 0
    ) -> "ZstdFrameIndex":
        """Parse + validate; ``blob_id``/``csize`` (when given) pin the
        index to the blob it is about to serve — a stale index for a
        different or re-pushed blob fails here, loudly."""
        if len(raw) < _HEADER.size:
            raise ZstdIndexError("zstd index truncated before header")
        (magic, version, source, hcsize, usize, n_frames, n_files,
         payload_len, digest, hblob, _reserved) = _HEADER.unpack_from(raw, 0)
        if magic != _MAGIC:
            raise ZstdIndexError("bad zstd index magic (torn or foreign file)")
        if version != _VERSION:
            raise ZstdIndexError(f"unsupported zstd index version {version}")
        payload = raw[_HEADER.size : _HEADER.size + payload_len]
        if len(payload) != payload_len:
            raise ZstdIndexError("zstd index payload truncated")
        if hashlib.sha256(payload).digest() != digest:
            raise ZstdIndexError("zstd index payload checksum mismatch")
        hblob_id = hblob.rstrip(b" \0").decode()
        if blob_id and hblob_id != blob_id:
            raise ZstdIndexError(
                f"zstd index is for blob {hblob_id[:12]}…, not {blob_id[:12]}…"
            )
        if csize and hcsize != csize:
            raise ZstdIndexError(
                f"zstd index is stale: built for {hcsize}-byte blob, "
                f"blob is {csize} bytes"
            )
        pos = 0
        frames: list[FrameEntry] = []
        for _ in range(n_frames):
            if pos + _FRAME.size > len(payload):
                raise ZstdIndexError("zstd index frame table truncated")
            uout, cin, fusize, fcsize = _FRAME.unpack_from(payload, pos)
            pos += _FRAME.size
            frames.append(FrameEntry(uout, cin, fusize, fcsize))
        files: dict[str, tuple[int, int]] = {}
        for _ in range(n_files):
            if pos + _FILE_HEAD.size > len(payload):
                raise ZstdIndexError("zstd index file map truncated")
            plen, uoff, fsize = _FILE_HEAD.unpack_from(payload, pos)
            pos += _FILE_HEAD.size
            p = payload[pos : pos + plen]
            if len(p) != plen:
                raise ZstdIndexError("zstd index file map truncated")
            pos += plen
            files[p.decode()] = (uoff, fsize)
        return cls(
            blob_id=hblob_id,
            compressed_size=hcsize,
            uncompressed_size=usize,
            source=source,
            frames=frames,
            files=files,
        )

    @classmethod
    def load(cls, path: str, blob_id: str = "", csize: int = 0) -> "ZstdFrameIndex":
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise ZstdIndexError(f"cannot read zstd index {path}: {e}") from e
        return cls.from_bytes(raw, blob_id=blob_id, csize=csize)
