"""The persisted seekable-OCI index artifact (``<blob_id>.soci.idx``).

One file per indexed layer, living in the blob cache dir next to the
chunk map (cache/manager.py treats it as a cache-entry companion, so
watermark eviction and GC remove it with the blob it describes). It
carries everything a fresh process needs to read the unconverted layer
lazily:

- the zran **checkpoint table** (:mod:`~nydus_snapshotter_tpu.soci.zran`
  resume points at the build stride, windows zlib-compressed);
- the **file → decompressed-extent map** (path, offset, size per regular
  file) — self-contained resolve geometry for tooling and peers, without
  needing the layer bootstrap;
- blob geometry (id, compressed/uncompressed size, stride).

Torn-write hardening follows the v5 dict format's tail-first/header-last
discipline, belt and braces: the payload is written first and the fixed
header — whose magic, counts and payload SHA-256 are what ``load``
validates — is written last (then fsync + atomic rename, so a crashed
writer leaves either the old index or none). A corrupt, truncated or
stale index NEVER poisons reads: ``load`` fails loudly with
:class:`SociIndexError` and the store rebuilds once
(:mod:`~nydus_snapshotter_tpu.soci.blob`).
"""

from __future__ import annotations

import hashlib
import io
import os
import struct
import tempfile
import zlib
from bisect import bisect_right
from dataclasses import dataclass, field
from typing import Optional

from nydus_snapshotter_tpu.soci.zran import DEFAULT_STRIDE, Checkpoint
from nydus_snapshotter_tpu.utils import errdefs

INDEX_SUFFIX = ".soci.idx"

_MAGIC = b"NTPUSOCI"
_VERSION = 1
# magic, version, stride, csize, usize, n_checkpoints, n_files,
# payload_len, payload sha256, blob_id (64 hex, space-padded), reserved.
_HEADER = struct.Struct("<8sIQQQIIQ32s64s16s")
_CP_HEAD = struct.Struct("<QQBBI")
_FILE_HEAD = struct.Struct("<IQQ")


class SociIndexError(errdefs.NydusError):
    """The index artifact is corrupt, torn, or stale for its blob."""


def index_path(cache_dir: str, blob_id: str) -> str:
    return os.path.join(cache_dir, blob_id + INDEX_SUFFIX)


@dataclass
class SociIndex:
    blob_id: str
    compressed_size: int
    uncompressed_size: int
    stride: int = DEFAULT_STRIDE
    checkpoints: list[Checkpoint] = field(default_factory=list)
    # path -> (decompressed offset, size) of every regular file's content.
    files: dict[str, tuple[int, int]] = field(default_factory=dict)

    def __post_init__(self):
        self.checkpoints.sort(key=lambda c: c.uout)
        self._uouts = [c.uout for c in self.checkpoints]

    # -- resolve geometry ----------------------------------------------------

    def resolve(
        self, offset: int, size: int
    ) -> tuple[Optional[Checkpoint], int, int]:
        """Compressed bytes needed for decompressed ``[offset, offset+size)``.

        Returns ``(checkpoint, comp_start, comp_end)``: resume at
        ``checkpoint`` (None = stream start), feeding compressed bytes
        from ``comp_start`` (includes the checkpoint's shared partial
        byte) up to at most ``comp_end`` — the input position of the
        first checkpoint at or past the read's end, which has by
        construction consumed enough input to produce it.
        """
        end = offset + max(0, size)
        i = bisect_right(self._uouts, offset) - 1
        cp = self.checkpoints[i] if i >= 0 else None
        comp_start = 0 if cp is None else cp.cin - (1 if cp.bits else 0)
        # First checkpoint with uout >= end has consumed enough input to
        # produce the whole read; its cin bounds the compressed range.
        j = bisect_right(self._uouts, max(offset, end - 1))
        comp_end = (
            self.checkpoints[j].cin
            if j < len(self.checkpoints)
            else self.compressed_size
        )
        return cp, comp_start, comp_end

    def file_extent(self, path: str) -> Optional[tuple[int, int]]:
        return self.files.get(path)

    # -- (de)serialization ---------------------------------------------------

    def _payload(self) -> bytes:
        out = io.BytesIO()
        for cp in self.checkpoints:
            win = zlib.compress(cp.window, 1) if cp.window else b""
            out.write(
                _CP_HEAD.pack(cp.uout, cp.cin, cp.bits, int(cp.fresh), len(win))
            )
            out.write(win)
        for path, (uoff, usize) in sorted(self.files.items()):
            p = path.encode()
            out.write(_FILE_HEAD.pack(len(p), uoff, usize))
            out.write(p)
        return out.getvalue()

    def to_bytes(self) -> bytes:
        payload = self._payload()
        header = _HEADER.pack(
            _MAGIC,
            _VERSION,
            self.stride,
            self.compressed_size,
            self.uncompressed_size,
            len(self.checkpoints),
            len(self.files),
            len(payload),
            hashlib.sha256(payload).digest(),
            self.blob_id.encode().ljust(64),
            b"\0" * 16,
        )
        return header + payload

    def save(self, path: str) -> int:
        """Persist atomically, payload-first/header-last: the header that
        makes the bytes loadable is the final write before fsync+rename,
        so no crash window leaves a half-index under the real name.
        Returns bytes written."""
        payload = self._payload()
        blob = self.to_bytes()
        d = os.path.dirname(path) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=".soci-idx-", dir=d)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(b"\0" * _HEADER.size)  # placeholder until payload lands
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
                f.seek(0)
                f.write(blob[: _HEADER.size])
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return len(blob)

    @classmethod
    def from_bytes(cls, raw: bytes, blob_id: str = "", csize: int = 0) -> "SociIndex":
        """Parse + validate; ``blob_id``/``csize`` (when given) pin the
        index to the blob it is about to serve — a stale index for a
        different or re-pushed blob fails here, loudly."""
        if len(raw) < _HEADER.size:
            raise SociIndexError("soci index truncated before header")
        (magic, version, stride, hcsize, usize, n_cp, n_files, payload_len,
         digest, hblob, _reserved) = _HEADER.unpack_from(raw, 0)
        if magic != _MAGIC:
            raise SociIndexError("bad soci index magic (torn or foreign file)")
        if version != _VERSION:
            raise SociIndexError(f"unsupported soci index version {version}")
        payload = raw[_HEADER.size : _HEADER.size + payload_len]
        if len(payload) != payload_len:
            raise SociIndexError("soci index payload truncated")
        if hashlib.sha256(payload).digest() != digest:
            raise SociIndexError("soci index payload checksum mismatch")
        hblob_id = hblob.rstrip(b" \0").decode()
        if blob_id and hblob_id != blob_id:
            raise SociIndexError(
                f"soci index is for blob {hblob_id[:12]}…, not {blob_id[:12]}…"
            )
        if csize and hcsize != csize:
            raise SociIndexError(
                f"soci index is stale: built for {hcsize}-byte blob, "
                f"blob is {csize} bytes"
            )
        pos = 0
        checkpoints: list[Checkpoint] = []
        for _ in range(n_cp):
            uout, cin, bits, fresh, wlen = _CP_HEAD.unpack_from(payload, pos)
            pos += _CP_HEAD.size
            win = payload[pos : pos + wlen]
            if len(win) != wlen:
                raise SociIndexError("soci index checkpoint window truncated")
            pos += wlen
            try:
                window = zlib.decompress(win) if win else b""
            except zlib.error as e:
                raise SociIndexError(f"corrupt checkpoint window: {e}") from e
            checkpoints.append(Checkpoint(uout, cin, bits, window, bool(fresh)))
        files: dict[str, tuple[int, int]] = {}
        for _ in range(n_files):
            plen, uoff, fsize = _FILE_HEAD.unpack_from(payload, pos)
            pos += _FILE_HEAD.size
            p = payload[pos : pos + plen]
            if len(p) != plen:
                raise SociIndexError("soci index file map truncated")
            pos += plen
            files[p.decode()] = (uoff, fsize)
        return cls(
            blob_id=hblob_id,
            compressed_size=hcsize,
            uncompressed_size=usize,
            stride=stride,
            checkpoints=checkpoints,
            files=files,
        )

    @classmethod
    def load(cls, path: str, blob_id: str = "", csize: int = 0) -> "SociIndex":
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise SociIndexError(f"cannot read soci index {path}: {e}") from e
        return cls.from_bytes(raw, blob_id=blob_id, csize=csize)
