"""Snapshotter-side soci driver: probe, route, index-on-first-pull, merge.

The exact shape of the stargz adaptor pair (stargz/{resolver,adaptor}.py),
for layers that carry NO cooperation from the image builder — and, since
the universal-formats work, for cooperating zstd:chunked / eStargz /
seekable-zstd layers too:

- :class:`SociResolver` probes a claimable layer the cheapest possible
  way — two ranged reads (4 head bytes + one ≤56-byte tail) through the
  per-layer :class:`~nydus_snapshotter_tpu.soci.router.FormatRouter`,
  which picks {toc-adopt, seekable-index, zran-index} by modeled
  cold-read cost. A layer the model routes to ``rafs-convert`` (unknown
  compression, missing decoder surface) raises :class:`SociError` here,
  cheaply, so the snapshotter falls through to ordinary conversion. The
  decision rides the returned blob as ``blob.route``.
- :class:`SociAdaptor.prepare_meta_layer` executes the routed backend:

  * ``toc-adopt`` — fetch the shipped TOC (eStargz tar member or
    zstd:chunked manifest) with ranged reads and emit the bootstrap
    straight from it (``stargz/index.bootstrap_from_toc``): ZERO
    build-pass bytes, no index artifact — the TOC is the index.
  * ``seekable-index`` — the one full pull, one sequential frame pass
    (:func:`~nydus_snapshotter_tpu.soci.zblob.build_zindex_from_zstd`
    — free when a seek table is shipped), bootstrap via
    ``pack_zstd_layer`` from the same pass, ``.soci.zidx`` persisted.
    A degenerate single-frame blob demotes to ``rafs-convert`` (no
    random access exists to index) by raising — the layer converts
    normally.
  * ``zran-index`` — the PR-12 gzip path, unchanged: one full pull, one
    inflate pass, ``.soci.idx`` persisted.

- ``merge_meta_layer`` is byte-for-byte the stargz merge (per-layer
  bootstraps named by digest hex → ``image.boot``), reused by
  composition: zran, zstd-frame and TOC bootstraps merge identically.

When the needed decoder surface is missing (no libz zran, no libzstd
frame API) the router's cost table simply lacks those candidates and
the layer routes to what remains — degraded modes are routing outcomes,
not special cases.
"""

from __future__ import annotations

import logging
import os
import tempfile
from typing import Callable, Mapping, Optional

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu.converter.types import PackOption
from nydus_snapshotter_tpu.converter.zran import pack_gzip_layer
from nydus_snapshotter_tpu.converter.zstd_ref import pack_zstd_layer
from nydus_snapshotter_tpu.soci import blob as soci_blob
from nydus_snapshotter_tpu.soci import router as soci_router
from nydus_snapshotter_tpu.soci import toc as ztoc
from nydus_snapshotter_tpu.soci import zblob, zran
from nydus_snapshotter_tpu.soci.index import index_path
from nydus_snapshotter_tpu.soci.router import (
    BACKEND_RAFS,
    BACKEND_SEEKABLE,
    BACKEND_TOC_ADOPT,
    FORMAT_ESTARGZ,
    FormatRouter,
)
from nydus_snapshotter_tpu.soci.zindex import zindex_path
from nydus_snapshotter_tpu.stargz.adaptor import StargzAdaptor
from nydus_snapshotter_tpu.stargz.index import bootstrap_from_toc
from nydus_snapshotter_tpu.stargz.resolver import Blob, Resolver, _blob_size
from nydus_snapshotter_tpu.utils import errdefs

logger = logging.getLogger(__name__)


class SociError(errdefs.NydusError):
    pass


def _config_router() -> FormatRouter:
    cfg = soci_blob.resolve_soci_config()
    return FormatRouter(enable_zstd=cfg.zstd, enable_toc=cfg.toc_adopt)


class SociResolver(Resolver):
    """Ranged-blob resolver accepting any layer the FormatRouter can
    route to a lazy backend (gzip, eStargz, seekable/opaque/chunked
    zstd — no footer or annotation required)."""

    def get_blob(
        self, ref: str, digest: str, labels: Optional[Mapping[str, str]] = None
    ) -> Blob:
        from nydus_snapshotter_tpu.auth import keychain as authmod
        from nydus_snapshotter_tpu.remote.reference import parse_docker_ref

        parsed = parse_docker_ref(ref)
        kc = authmod.get_keychain_by_ref(ref, dict(labels or {}))
        _, client = self.pool.resolve(parsed, digest, keychain=kc)
        repo = parsed.path
        size = _blob_size(client, repo, digest)

        def read_at(offset: int, length: int) -> bytes:
            if length <= 0:
                return b""
            r = client.fetch_blob(
                repo, digest, byte_range=(offset, offset + length - 1)
            )
            try:
                return r.read()
            finally:
                r.close()

        # Routing IS the detection: an unroutable layer (unknown magic,
        # or every lazy candidate infeasible) must fail here, cheaply,
        # not later in the prepare path.
        decision = _config_router().route(read_at, size)
        if decision.backend == BACKEND_RAFS:
            raise SociError(
                f"blob {digest} routed to rafs-convert "
                f"({decision.format}: {decision.reason})"
            )
        blob = Blob(ref, digest, read_at, size)
        blob.route = decision
        return blob


class SociAdaptor:
    def __init__(
        self,
        upper_path_fn: Callable[[str], str],
        cache_dir: str = "",
        fs_driver: str = constants.FS_DRIVER_FUSEDEV,
        chunk_size: int = constants.CHUNK_SIZE_DEFAULT,
        stride: int = 0,
    ):
        self.upper_path = upper_path_fn
        self.cache_dir = cache_dir
        self.fs_driver = fs_driver
        self.chunk_size = chunk_size
        self.stride = stride  # 0 = resolve from [soci]/env at build time
        # The merge half is format-agnostic bootstrap plumbing — reuse it.
        self._merge = StargzAdaptor(
            upper_path_fn, cache_dir=cache_dir, fs_driver=fs_driver,
            chunk_size=chunk_size,
        )

    # -- prepare (route → adopt or index on first pull) ----------------------

    def prepare_meta_layer(
        self, blob: Blob, storage_path: str,
        _labels: Optional[Mapping[str, str]] = None,
    ) -> None:
        blob_id = blob.get_digest().split(":", 1)[-1]
        os.makedirs(storage_path, exist_ok=True)
        converted = os.path.join(storage_path, blob_id)
        if os.path.exists(converted):
            return

        route = getattr(blob, "route", None)
        if route is None:
            # Direct callers (tests, tools) that skipped the resolver:
            # route now, with the same counters.
            route = _config_router().route(blob.read_at, blob.size)
            if route.backend == BACKEND_RAFS:
                raise SociError(
                    f"blob {blob_id[:12]} routed to rafs-convert "
                    f"({route.format}: {route.reason})"
                )

        if route.backend == BACKEND_TOC_ADOPT:
            bootstrap = self._adopt_toc(blob, blob_id, route)
        elif route.backend == BACKEND_SEEKABLE:
            bootstrap = self._index_zstd(blob, blob_id, route)
        else:
            bootstrap = self._index_gzip(blob, blob_id)

        fd, tmp = tempfile.mkstemp(prefix="converting-soci", dir=storage_path)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(bootstrap.to_bytes())
            os.rename(tmp, converted)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        os.chmod(converted, 0o440)

    # -- backend arms --------------------------------------------------------

    def _adopt_toc(self, blob: Blob, blob_id: str, route) -> "object":
        """TOC adoption: the shipped file→extent map becomes the
        bootstrap. Ranged reads only — zero build-pass bytes."""
        if route.format == FORMAT_ESTARGZ:
            toc = blob.toc()
            data_end = blob.get_toc_offset()
            compressor = constants.COMPRESSOR_GZIP
        else:
            toc = ztoc.read_toc(blob.read_at, blob.size)
            if toc is None:
                raise SociError(
                    f"blob {blob_id[:12]} routed toc-adopt but carries no TOC"
                )
            loc = route.toc_location or ztoc.parse_footer(
                blob.read_at(blob.size - ztoc.FOOTER_SIZE, ztoc.FOOTER_SIZE)
            )
            data_end = loc[0]
            compressor = constants.COMPRESSOR_ZSTD
        logger.info("soci toc-adopt for %s (%s): bootstrap from shipped TOC",
                    blob_id[:12], route.format)
        return bootstrap_from_toc(
            toc,
            blob_id,
            chunk_size=self.chunk_size,
            blob_compressed_size=data_end,
            compressor=compressor,
        )

    def _index_zstd(self, blob: Blob, blob_id: str, route) -> "object":
        """seekable-index: the one full pull, one frame pass (seek table
        trusted when shipped), bootstrap + persisted ``.soci.zidx``."""
        raw = self._full_pull(blob, blob_id)
        index, tar_bytes = zblob.build_zindex_from_zstd(blob_id, raw)
        if len(index.frames) <= 1 and index.uncompressed_size > self.chunk_size:
            # One frame = no random access to index: every cold read
            # would decode from byte zero. The cost model's answer for
            # that shape is conversion; re-route and decline.
            soci_router.ROUTE_TOTAL.labels(BACKEND_RAFS).inc()
            raise SociError(
                f"blob {blob_id[:12]} is single-frame zstd "
                f"({index.uncompressed_size} bytes): re-routed to rafs-convert"
            )

        opt = PackOption(chunk_size=self.chunk_size, oci_ref=True)
        bootstrap = pack_zstd_layer(raw, opt, tar_bytes=tar_bytes)

        if self.cache_dir:
            os.makedirs(self.cache_dir, exist_ok=True)
            soci_blob.INDEX_BYTES.inc(
                index.save(zindex_path(self.cache_dir, blob_id))
            )
            soci_blob.INDEX_EVENTS.labels("built").inc()
        return bootstrap

    def _index_gzip(self, blob: Blob, blob_id: str) -> "object":
        """zran-index: the PR-12 gzip arm, unchanged."""
        raw = self._full_pull(blob, blob_id)
        index = None
        tar_bytes = None
        stride = self.stride or soci_blob.resolve_soci_config().stride_bytes
        if zran.available():
            index, tar_bytes = soci_blob.build_index_from_gzip(
                blob_id, raw, stride=stride
            )

        opt = PackOption(chunk_size=self.chunk_size, oci_ref=True)
        bootstrap = pack_gzip_layer(raw, opt, tar_bytes=tar_bytes)

        if index is not None and self.cache_dir:
            os.makedirs(self.cache_dir, exist_ok=True)
            soci_blob.INDEX_BYTES.inc(
                index.save(index_path(self.cache_dir, blob_id))
            )
            soci_blob.INDEX_EVENTS.labels("built").inc()
        elif index is None:
            logger.warning(
                "libz zran unavailable: soci layer %s gets no checkpoint "
                "index (sequential cold reads)", blob_id[:12],
            )
        return bootstrap

    @staticmethod
    def _full_pull(blob: Blob, blob_id: str) -> bytes:
        # The one full pull. Everything after this is ranged.
        raw = blob.read_at(0, blob.size)
        if len(raw) != blob.size:
            raise SociError(
                f"blob {blob_id[:12]} short pull: {len(raw)} of {blob.size}"
            )
        return raw

    # -- merge ---------------------------------------------------------------

    def merge_meta_layer(self, snapshot) -> None:
        self._merge.merge_meta_layer(snapshot)
