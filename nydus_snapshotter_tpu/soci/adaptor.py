"""Snapshotter-side soci driver: probe, index-on-first-pull, merge.

The exact shape of the stargz adaptor pair (stargz/{resolver,adaptor}.py),
for layers that carry NO cooperation from the image builder:

- :class:`SociResolver` detects a claimable layer the cheapest possible
  way — one 2-byte ranged read proving the blob is gzip. Any plain OCI
  ``.tar.gz`` layer qualifies; there is nothing to parse because the
  whole point is that the image was never rewritten.
- :class:`SociAdaptor.prepare_meta_layer` is the **one** full pull the
  backend ever performs: stream the original blob, run the single zran
  build pass (checkpoints + decompressed bytes in one inflate), emit the
  layer bootstrap from that same pass via
  :func:`~nydus_snapshotter_tpu.converter.zran.pack_gzip_layer` — the
  blob referenced is the ORIGINAL registry layer, nothing is converted
  or re-stored — and persist the checkpoint index into the cache dir
  next to where the blob's chunk map will live. Subsequent pods skip
  even this: the index replicates through the peer tier
  (:func:`~nydus_snapshotter_tpu.soci.blob.load_or_build_index`).
- ``merge_meta_layer`` is byte-for-byte the stargz merge (per-layer
  bootstraps named by digest hex → ``image.boot``), reused by
  composition: zran bootstraps and TOC bootstraps merge identically
  (pinned since the ``test_merge_mixes_zran_and_packed_layers`` days).

When the system libz lacks zran support the adaptor still claims the
layer — the bootstrap alone makes it lazily readable via the sequential
in-process reader — it just cannot persist checkpoints (documented
degraded mode).
"""

from __future__ import annotations

import logging
import os
import tempfile
from typing import Callable, Mapping, Optional

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu.converter.types import PackOption
from nydus_snapshotter_tpu.converter.zran import pack_gzip_layer
from nydus_snapshotter_tpu.soci import blob as soci_blob
from nydus_snapshotter_tpu.soci import zran
from nydus_snapshotter_tpu.soci.index import index_path
from nydus_snapshotter_tpu.stargz.adaptor import StargzAdaptor
from nydus_snapshotter_tpu.stargz.resolver import Blob, Resolver, _blob_size
from nydus_snapshotter_tpu.utils import errdefs

logger = logging.getLogger(__name__)

_GZIP_MAGIC = b"\x1f\x8b"


class SociError(errdefs.NydusError):
    pass


class SociResolver(Resolver):
    """Ranged-blob resolver accepting ANY gzip layer (no footer needed)."""

    def get_blob(
        self, ref: str, digest: str, labels: Optional[Mapping[str, str]] = None
    ) -> Blob:
        from nydus_snapshotter_tpu.auth import keychain as authmod
        from nydus_snapshotter_tpu.remote.reference import parse_docker_ref

        parsed = parse_docker_ref(ref)
        kc = authmod.get_keychain_by_ref(ref, dict(labels or {}))
        _, client = self.pool.resolve(parsed, digest, keychain=kc)
        repo = parsed.path
        size = _blob_size(client, repo, digest)

        def read_at(offset: int, length: int) -> bytes:
            if length <= 0:
                return b""
            r = client.fetch_blob(
                repo, digest, byte_range=(offset, offset + length - 1)
            )
            try:
                return r.read()
            finally:
                r.close()

        # Detection is two bytes: a non-gzip layer (zstd, uncompressed
        # tar, foreign media type) must fail here, cheaply, not later in
        # the prepare path.
        head = read_at(0, 2)
        if head != _GZIP_MAGIC:
            raise SociError(f"blob {digest} is not a gzip layer")
        return Blob(ref, digest, read_at, size)


class SociAdaptor:
    def __init__(
        self,
        upper_path_fn: Callable[[str], str],
        cache_dir: str = "",
        fs_driver: str = constants.FS_DRIVER_FUSEDEV,
        chunk_size: int = constants.CHUNK_SIZE_DEFAULT,
        stride: int = 0,
    ):
        self.upper_path = upper_path_fn
        self.cache_dir = cache_dir
        self.fs_driver = fs_driver
        self.chunk_size = chunk_size
        self.stride = stride  # 0 = resolve from [soci]/env at build time
        # The merge half is format-agnostic bootstrap plumbing — reuse it.
        self._merge = StargzAdaptor(
            upper_path_fn, cache_dir=cache_dir, fs_driver=fs_driver,
            chunk_size=chunk_size,
        )

    # -- prepare (index on first pull) ---------------------------------------

    def prepare_meta_layer(
        self, blob: Blob, storage_path: str,
        _labels: Optional[Mapping[str, str]] = None,
    ) -> None:
        blob_id = blob.get_digest().split(":", 1)[-1]
        os.makedirs(storage_path, exist_ok=True)
        converted = os.path.join(storage_path, blob_id)
        if os.path.exists(converted):
            return

        # The one full pull. Everything after this is ranged.
        raw = blob.read_at(0, blob.size)
        if len(raw) != blob.size:
            raise SociError(
                f"blob {blob_id[:12]} short pull: {len(raw)} of {blob.size}"
            )

        index = None
        tar_bytes = None
        stride = self.stride or soci_blob.resolve_soci_config().stride_bytes
        if zran.available():
            index, tar_bytes = soci_blob.build_index_from_gzip(
                blob_id, raw, stride=stride
            )

        opt = PackOption(chunk_size=self.chunk_size, oci_ref=True)
        bootstrap = pack_gzip_layer(raw, opt, tar_bytes=tar_bytes)

        if index is not None and self.cache_dir:
            os.makedirs(self.cache_dir, exist_ok=True)
            soci_blob.INDEX_BYTES.inc(
                index.save(index_path(self.cache_dir, blob_id))
            )
            soci_blob.INDEX_EVENTS.labels("built").inc()
        elif index is None:
            logger.warning(
                "libz zran unavailable: soci layer %s gets no checkpoint "
                "index (sequential cold reads)", blob_id[:12],
            )

        fd, tmp = tempfile.mkstemp(prefix="converting-soci", dir=storage_path)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(bootstrap.to_bytes())
            os.rename(tmp, converted)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        os.chmod(converted, 0o440)

    # -- merge ---------------------------------------------------------------

    def merge_meta_layer(self, snapshot) -> None:
        self._merge.merge_meta_layer(snapshot)
