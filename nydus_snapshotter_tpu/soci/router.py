"""Per-layer format routing for the lazy-read plane.

The resolver path (snapshot/snapshotter.py → soci/adaptor.py) asks one
question per layer: *cheapest way to make this blob lazily readable?*
:class:`FormatRouter` answers it from two ranged probe reads — 4 head
bytes (compression magic) and one tail read (eStargz footer /
zstd:chunked footer / seekable-zstd seek-table footer all live in the
last ≤56 bytes) — then picks among

- ``toc-adopt``     — the layer ships a TOC (eStargz or zstd:chunked):
                      adopt it as the file→extent map, zero build pass;
- ``seekable-index`` — zstd layer, frame-indexable (seek table parsed
                      for free, or frame-walked during the one
                      first-pull pass);
- ``zran-index``    — plain gzip, checkpoint-indexed (the PR-12 path);
- ``rafs-convert``  — nothing lazy applies (unknown compression, or the
                      needed decoder surface is missing): full pull +
                      conversion, the pre-soci behavior.

by **modeled cold-read cost**: origin bytes to first file read =
build-pass bytes (full blob for index builds, ~nothing for TOC
adoption) + first lazy read's fetch span. The model is closed-form and
deliberately coarse — its job is ordering, not prediction, and the
ordering is stable: a shipped TOC always beats an index build, which
always beats paying conversion on top of the same full pull.

Decisions are counted on ``ntpu_soci_route_total{backend}`` and carried
on the resolved blob (``Blob.route``) so ``ntpuctl soci`` can show why
each layer took the path it did.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Callable, Optional

from nydus_snapshotter_tpu.metrics import registry as _metrics
from nydus_snapshotter_tpu.soci import toc as ztoc
from nydus_snapshotter_tpu.soci import zframe, zran
from nydus_snapshotter_tpu.stargz import resolver as stargz_resolver
from nydus_snapshotter_tpu.utils import zstd as _zstd

logger = logging.getLogger(__name__)

BACKEND_TOC_ADOPT = "toc-adopt"
BACKEND_SEEKABLE = "seekable-index"
BACKEND_ZRAN = "zran-index"
BACKEND_RAFS = "rafs-convert"

FORMAT_GZIP = "gzip"
FORMAT_ESTARGZ = "estargz"
FORMAT_ZSTD_SEEKABLE = "zstd-seekable"
FORMAT_ZSTD_CHUNKED = "zstd-chunked"
FORMAT_ZSTD_OPAQUE = "zstd-opaque"
FORMAT_UNKNOWN = "unknown"

_GZIP_MAGIC = b"\x1f\x8b"
_ZSTD_MAGIC = b"\x28\xb5\x2f\xfd"

# Modeled first lazy read span when the real geometry is unknown: one
# default soci stride / one default frame (both 1 MiB by convention).
_EST_READ_SPAN = 1 << 20

_reg = _metrics.default_registry
ROUTE_TOTAL = _reg.register(
    _metrics.Counter(
        "ntpu_soci_route_total",
        "FormatRouter layer routing decisions by chosen backend"
        " (toc-adopt / seekable-index / zran-index / rafs-convert)",
        ("backend",),
    )
)


def route_counts() -> dict:
    """Cumulative routing decisions per backend (the ``ntpuctl soci``
    surface)."""
    return {
        b: ROUTE_TOTAL.value(b)
        for b in (BACKEND_TOC_ADOPT, BACKEND_SEEKABLE, BACKEND_ZRAN,
                  BACKEND_RAFS)
        if ROUTE_TOTAL.value(b)
    }


@dataclass
class RouteDecision:
    backend: str
    format: str
    reason: str
    probe_bytes: int = 0
    # backend -> modeled origin bytes to first cold file read; only the
    # feasible candidates appear.
    costs: dict[str, int] = field(default_factory=dict)
    # Tail geometry the adaptor reuses so prepare never re-probes:
    # parsed seek-table entries (zstd-seekable) or the TOC manifest
    # location (zstd-chunked).
    seek_entries: Optional[list] = None
    toc_location: Optional[tuple[int, int, int]] = None

    def describe(self) -> str:
        return f"{self.backend} ({self.format}: {self.reason})"


class FormatRouter:
    """Probe a layer blob's head/tail and route it to the cheapest lazy
    backend. ``enable_zstd`` / ``enable_toc`` mirror the ``[soci]``
    config keys; switching either off removes those candidates and the
    cost model picks among what remains."""

    def __init__(self, enable_zstd: bool = True, enable_toc: bool = True):
        self.enable_zstd = enable_zstd
        self.enable_toc = enable_toc

    def route(
        self, read_at: Callable[[int, int], bytes], size: int,
        record: bool = True,
    ) -> RouteDecision:
        probe = 0

        def _read(off: int, n: int) -> bytes:
            nonlocal probe
            off = max(0, off)
            n = min(n, size - off)
            if n <= 0:
                return b""
            probe += n
            return read_at(off, n)

        head = _read(0, 4)
        tail_span = max(
            ztoc.FOOTER_SIZE, stargz_resolver.ESTARGZ_FOOTER_SIZE, 9
        )
        tail = _read(size - tail_span, tail_span)

        decision = self._decide(head, tail, size)
        decision.probe_bytes = probe
        if record:
            ROUTE_TOTAL.labels(decision.backend).inc()
        logger.debug("soci route: %s", decision.describe())
        return decision

    # -- the model -----------------------------------------------------------

    def _decide(self, head: bytes, tail: bytes, size: int) -> RouteDecision:
        # Modeled first lazy read: one stride/frame, clamped to the blob
        # (a flat 1 MiB would dwarf 2*size on small layers and invert
        # the ordering). Every candidate pays it — including conversion,
        # whose first cold read comes only after pull + full re-store —
        # so the span cancels in comparisons and the ordering is stable
        # at every blob size: shipped TOC < index build < conversion.
        span = min(_EST_READ_SPAN, max(1, size))
        costs: dict[str, int] = {BACKEND_RAFS: 2 * size + span}

        if head[:2] == _GZIP_MAGIC:
            fmt = FORMAT_GZIP
            reason = "gzip magic"
            toc_off = 0
            for fsize in (stargz_resolver.ESTARGZ_FOOTER_SIZE,
                          stargz_resolver.FOOTER_SIZE):
                if fsize > len(tail):
                    continue
                off, ok = stargz_resolver.parse_footer(tail[len(tail) - fsize:])
                if ok and 0 < off < size:
                    fmt, toc_off = FORMAT_ESTARGZ, off
                    reason = "estargz footer"
                    break
            if fmt == FORMAT_ESTARGZ and self.enable_toc:
                costs[BACKEND_TOC_ADOPT] = (size - toc_off) + span
            if zran.available():
                costs[BACKEND_ZRAN] = size + span
            return self._pick(fmt, reason, costs)

        if head[:4] == _ZSTD_MAGIC or _zstd.is_skippable_frame(head):
            loc = ztoc.parse_footer(tail) if len(tail) >= ztoc.FOOTER_SIZE else None
            if loc is not None:
                fmt, reason = FORMAT_ZSTD_CHUNKED, "GnUlInUx footer"
                if self.enable_toc and _zstd.dctx_available():
                    costs[BACKEND_TOC_ADOPT] = loc[1] + span
                if self.enable_zstd and zframe.available():
                    # Frame-walking a chunked blob works too; it just
                    # pays the full pull the TOC makes unnecessary.
                    costs[BACKEND_SEEKABLE] = size + span
                return self._pick(fmt, reason, costs, toc_location=loc)

            table_size = zframe.seek_table_frame_size(tail[-9:])
            entries: Optional[list] = None
            if table_size is not None and table_size <= size:
                fmt, reason = FORMAT_ZSTD_SEEKABLE, "seek-table footer"
                if self.enable_zstd and zframe.available():
                    n = max(1, (table_size - 17) // 8)
                    frame_est = max(1, (size - table_size) // n)
                    # The table is free geometry, but the bootstrap's
                    # file map still costs the one first-pull pass.
                    costs[BACKEND_SEEKABLE] = size + min(frame_est, span)
                return self._pick(fmt, reason, costs, seek_entries=entries)

            fmt, reason = FORMAT_ZSTD_OPAQUE, "zstd magic, no TOC or seek table"
            if self.enable_zstd and zframe.available():
                costs[BACKEND_SEEKABLE] = size + span
            return self._pick(fmt, reason, costs)

        return self._pick(FORMAT_UNKNOWN, "unrecognized magic", costs)

    @staticmethod
    def _pick(
        fmt: str, reason: str, costs: dict[str, int],
        seek_entries: Optional[list] = None,
        toc_location: Optional[tuple[int, int, int]] = None,
    ) -> RouteDecision:
        backend = min(costs, key=lambda b: costs[b])
        return RouteDecision(
            backend=backend, format=fmt, reason=reason, costs=dict(costs),
            seek_entries=seek_entries, toc_location=toc_location,
        )
