"""zstd frame geometry: random access into foreign zstd streams.

The zstd analog of :mod:`~nydus_snapshotter_tpu.soci.zran`. Where gzip
needs bit-level inflate checkpoints, zstd's native unit of independent
decode is the FRAME: every frame starts clean (no cross-frame window),
so a frame-boundary table ``(uout, cin, usize, csize)`` is a complete,
persistable random-access index — no window bytes, no bit offsets. Three
sources, cheapest first:

- **seek table** (facebook/zstd ``contrib/seekable_format``): a trailing
  skippable frame listing every frame's compressed/decompressed size.
  Parsing it is a pure struct walk over the blob TAIL — zero
  decompression, zero extra origin bytes beyond one ranged tail read.
- **frame walk**: ``ZSTD_findFrameCompressedSize`` measures each frame
  without decoding it; one sequential pass decodes each frame once to
  learn its decompressed size when the header omits it (and
  index-on-first-pull wants the decompressed bytes anyway, for the
  bootstrap build — same single-pass discipline as ``zran.build``).
- the degenerate case: a single-frame blob yields a 1-entry table, which
  makes every cold read a decompress-from-zero — the FormatRouter's cost
  model routes those layers to rafs-convert instead.

``extract`` resumes at a frame boundary and decodes only the frames the
read overlaps: cold cost is O(frame size), not O(offset), from a
persisted table in any process. Skippable frames (metadata, seek tables,
zstd:chunked manifests) are measured in the walk but never become
entries — reads never decode them.

``available()`` gates on the system libzstd's frame surface
(utils/zstd.py); without it the soci backend's router refuses zstd
layers and they fall back to full pull + RAFS convert, never to wrong
bytes.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Callable, Optional

from nydus_snapshotter_tpu.utils import errdefs
from nydus_snapshotter_tpu.utils import zstd as _zstd

# facebook/zstd seekable format constants.
SEEK_TABLE_SKIPPABLE_MAGIC = 0x184D2A5E
SEEKABLE_MAGIC = 0x8F92EAB1
_FOOTER = struct.Struct("<IBI")  # n_frames, descriptor, seekable magic
_DESC_CHECKSUM = 0x80
_DESC_RESERVED = 0x7C  # reserved bits must be zero per the spec

# A writer bound: the seekable spec caps frame decompressed size at 1 GiB.
MAX_FRAME_USIZE = 1 << 30
DEFAULT_FRAME_USIZE = 1 << 20


class ZstdFrameError(errdefs.NydusError):
    pass


def available() -> bool:
    """Whether frame-table random access is usable on this host."""
    return _zstd.frames_available()


@dataclass
class FrameEntry:
    """One zstd frame's span: decompressed offset/size, compressed
    offset/size. Frames decode independently, so the entry IS the resume
    point — no window, no bit offset."""

    uout: int  # decompressed offset of the frame's first byte
    cin: int  # compressed offset of the frame header
    usize: int  # decompressed size of the frame
    csize: int  # on-wire size of the frame (header + blocks + checksum)


# ---------------------------------------------------------------------------
# Seek-table parse (pure struct walk, no decompression)
# ---------------------------------------------------------------------------


def seek_table_frame_size(tail: bytes) -> Optional[int]:
    """On-wire size of the trailing seek-table skippable frame, derived
    from the blob's last 9 bytes — or ``None`` when the tail carries no
    seekable footer. Callers use this to size the one ranged tail read
    that fetches the whole table."""
    if len(tail) < _FOOTER.size:
        return None
    n_frames, desc, magic = _FOOTER.unpack(tail[-_FOOTER.size:])
    if magic != SEEKABLE_MAGIC or desc & _DESC_RESERVED:
        return None
    entry_size = 12 if desc & _DESC_CHECKSUM else 8
    return 8 + n_frames * entry_size + _FOOTER.size


def parse_seek_table(table: bytes, blob_size: int) -> list[FrameEntry]:
    """Decode a complete seek-table frame (header through footer) into
    the frame-entry table. Validates the skippable magic, the declared
    content length, and that the listed compressed sizes tile the blob
    exactly up to the table itself — a stale or foreign table fails
    loudly here, never at read time."""
    if len(table) < 8 + _FOOTER.size:
        raise ZstdFrameError("seek table truncated")
    skip_magic, content_len = struct.unpack_from("<II", table, 0)
    if skip_magic != SEEK_TABLE_SKIPPABLE_MAGIC:
        raise ZstdFrameError(
            f"seek table skippable magic {skip_magic:#x} != "
            f"{SEEK_TABLE_SKIPPABLE_MAGIC:#x}"
        )
    if content_len != len(table) - 8:
        raise ZstdFrameError(
            f"seek table declares {content_len} content bytes, "
            f"frame carries {len(table) - 8}"
        )
    n_frames, desc, magic = _FOOTER.unpack(table[-_FOOTER.size:])
    if magic != SEEKABLE_MAGIC:
        raise ZstdFrameError("seekable footer magic missing")
    if desc & _DESC_RESERVED:
        raise ZstdFrameError(f"seekable descriptor reserved bits set: {desc:#x}")
    entry_size = 12 if desc & _DESC_CHECKSUM else 8
    want = 8 + n_frames * entry_size + _FOOTER.size
    if want != len(table):
        raise ZstdFrameError(
            f"seek table size {len(table)} != {want} for {n_frames} frames"
        )
    entries: list[FrameEntry] = []
    upos = cpos = 0
    pos = 8
    for _ in range(n_frames):
        csize, usize = struct.unpack_from("<II", table, pos)
        pos += entry_size  # checksum (when present) is skipped, not verified
        if csize == 0:
            raise ZstdFrameError("seek table lists a zero-byte frame")
        # Skippable frames appear in the table with usize 0; they are
        # walked over, never decoded, so they produce no entry.
        if usize:
            entries.append(FrameEntry(upos, cpos, usize, csize))
        upos += usize
        cpos += csize
    if blob_size and cpos + len(table) != blob_size:
        raise ZstdFrameError(
            f"seek table covers {cpos} compressed bytes + {len(table)} table "
            f"bytes, blob is {blob_size}"
        )
    return entries


def read_seek_table(
    read_at: Callable[[int, int], bytes], blob_size: int
) -> Optional[list[FrameEntry]]:
    """Fetch + parse the seek table with two ranged reads (9-byte footer,
    then the exact table frame). Returns ``None`` when the blob has no
    seekable footer; raises on a footer that promises a table the blob
    cannot hold."""
    if blob_size < _FOOTER.size:
        return None
    tail = read_at(blob_size - _FOOTER.size, _FOOTER.size)
    size = seek_table_frame_size(tail)
    if size is None:
        return None
    if size > blob_size:
        raise ZstdFrameError(
            f"seekable footer promises a {size}-byte table in a "
            f"{blob_size}-byte blob"
        )
    table = read_at(blob_size - size, size)
    if len(table) != size:
        raise ZstdFrameError("short read fetching seek table")
    return parse_seek_table(table, blob_size)


# ---------------------------------------------------------------------------
# Frame walk + one-pass build
# ---------------------------------------------------------------------------


def build(
    raw: bytes, entries: Optional[list[FrameEntry]] = None
) -> tuple[list[FrameEntry], bytes]:
    """One sequential pass over a whole zstd blob → ``(frame table,
    decompressed bytes)`` — the zstd mirror of ``zran.build``.

    Without ``entries`` the pass walks frame boundaries with
    ``ZSTD_findFrameCompressedSize`` and decodes each data frame once
    (headers that omit the content size take the streaming decoder).
    With ``entries`` (a parsed seek table) the boundaries are trusted as
    geometry but every decoded size is still verified against the table
    — a lying table fails the build, it cannot mis-index reads.
    """
    if not available():
        raise ZstdFrameError("system libzstd lacks the frame surface")
    out = bytearray()
    table: list[FrameEntry] = []
    if entries is not None:
        for e in entries:
            frame = raw[e.cin : e.cin + e.csize]
            if len(frame) != e.csize:
                raise ZstdFrameError(
                    f"frame at {e.cin} (+{e.csize}) past blob end {len(raw)}"
                )
            data = _decode_frame(frame, e.usize)
            if len(data) != e.usize or len(out) != e.uout:
                raise ZstdFrameError(
                    f"seek table lies: frame at {e.cin} decodes to "
                    f"{len(data)} bytes, table says {e.usize} at {e.uout}"
                )
            table.append(FrameEntry(len(out), e.cin, len(data), e.csize))
            out += data
        return table, bytes(out)

    pos = 0
    while pos < len(raw):
        csize = _zstd.find_frame_compressed_size(raw, pos)
        if csize <= 0 or pos + csize > len(raw):
            raise ZstdFrameError(f"corrupt zstd frame at byte {pos}")
        if not _zstd.is_skippable_frame(raw, pos):
            frame = raw[pos : pos + csize]
            data = _decode_frame(frame, _zstd.frame_content_size(raw, pos))
            if data:
                table.append(FrameEntry(len(out), pos, len(data), csize))
                out += data
        pos += csize
    return table, bytes(out)


def _decode_frame(frame: bytes, usize_hint: Optional[int]) -> bytes:
    """One data frame → bytes: exact one-shot decode when the header (or
    table) declares the content size, streaming decode when it doesn't."""
    try:
        if usize_hint:
            return _zstd.decompress_block(frame, max_output_size=usize_hint)
        return _zstd.stream_decompress(frame)
    except _zstd.ZstdError as e:
        raise ZstdFrameError(str(e)) from e


# ---------------------------------------------------------------------------
# Extraction (decompress-from-frame-boundary)
# ---------------------------------------------------------------------------


def extract(
    read_comp: Callable[[int, int], bytes],
    csize: int,
    entries: list[FrameEntry],
    offset: int,
    size: int,
) -> bytes:
    """Decompressed ``[offset, offset + size)`` from the frames in
    ``entries`` (the resolve geometry's covering slice, ascending).
    ``read_comp(pos, n)`` supplies compressed bytes on demand —
    extraction pulls exactly the overlapped frames' on-wire bytes, never
    the blob. Each frame decodes on its own pooled context: concurrent
    extracts are safe."""
    if size <= 0:
        return b""
    if not entries:
        raise ZstdFrameError(f"no frame covers [{offset}, +{size})")
    out = bytearray()
    end = offset + size
    for e in entries:
        if e.uout >= end:
            break
        if e.uout + e.usize <= offset:
            continue
        if e.cin + e.csize > csize:
            raise ZstdFrameError(
                f"frame at {e.cin} (+{e.csize}) past compressed end {csize}"
            )
        frame = read_comp(e.cin, e.csize)
        if len(frame) != e.csize:
            raise ZstdFrameError(
                f"short compressed read at {e.cin}: {len(frame)} of {e.csize}"
            )
        data = _decode_frame(frame, e.usize)
        if len(data) != e.usize:
            raise ZstdFrameError(
                f"frame at {e.cin} decoded to {len(data)} bytes, "
                f"table says {e.usize}"
            )
        lo = max(0, offset - e.uout)
        hi = min(e.usize, end - e.uout)
        out += data[lo:hi]
    if len(out) != size:
        raise ZstdFrameError(
            f"range [{offset}, +{size}) yielded {len(out)} bytes from "
            f"{len(entries)} frames"
        )
    return bytes(out)


# ---------------------------------------------------------------------------
# Writers (tests, profiles, scenario corpora — no seekable writer ships
# with the system library, so synthesize spec-shaped blobs here)
# ---------------------------------------------------------------------------


def write_frames(
    raw: bytes, frame_usize: int = DEFAULT_FRAME_USIZE, level: int = 3
) -> bytes:
    """Compress ``raw`` as independent fixed-stride zstd frames with NO
    seek table — the "opaque multi-frame" shape (what a chunked encoder
    emits when it drops the index). Deterministic for a given input and
    level, so scenario serial replays keep blob-id identity."""
    if not 0 < frame_usize <= MAX_FRAME_USIZE:
        raise ZstdFrameError(f"frame_usize {frame_usize} out of range")
    parts = []
    for pos in range(0, len(raw), frame_usize):
        parts.append(_zstd.compress_block(raw[pos : pos + frame_usize], level))
    return b"".join(parts)


def write_seekable(
    raw: bytes, frame_usize: int = DEFAULT_FRAME_USIZE, level: int = 3
) -> bytes:
    """Compress ``raw`` into the facebook/zstd seekable format:
    independent frames of ``frame_usize`` decompressed bytes each, plus
    the trailing seek-table skippable frame (no per-frame checksums).
    Any seekable-format reader — including :func:`read_seek_table` —
    can random-access the result."""
    if not 0 < frame_usize <= MAX_FRAME_USIZE:
        raise ZstdFrameError(f"frame_usize {frame_usize} out of range")
    parts = []
    sizes: list[tuple[int, int]] = []
    for pos in range(0, len(raw), frame_usize):
        chunk = raw[pos : pos + frame_usize]
        frame = _zstd.compress_block(chunk, level)
        parts.append(frame)
        sizes.append((len(frame), len(chunk)))
    table = bytearray()
    for fcsize, fusize in sizes:
        table += struct.pack("<II", fcsize, fusize)
    table += _FOOTER.pack(len(sizes), 0, SEEKABLE_MAGIC)
    parts.append(
        struct.pack("<II", SEEK_TABLE_SKIPPABLE_MAGIC, len(table)) + table
    )
    return b"".join(parts)
