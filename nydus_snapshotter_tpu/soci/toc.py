"""zstd:chunked TOC support: footer probe, ranged manifest read, writer.

zstd:chunked (containers/storage) is the zstd ecosystem's eStargz: the
layer is a sequence of independent per-chunk zstd frames, a
zstd-compressed TOC manifest near the tail, and a fixed-size skippable
FOOTER frame ending in the ``GnUlInUx`` magic that locates the manifest
without any out-of-band annotation. A cooperating layer therefore needs
NO build pass at all — the TOC *is* the file→extent map, and chunks
decode through the ordinary per-chunk ``COMPRESSOR_ZSTD`` arm of
``converter/convert._decompress_chunk`` over the original blob
(index adoption, zero extra origin bytes).

The manifest this module reads and writes is the eStargz jtoc shape
(``{"version": 1, "entries": [...]}`` — ``stargz/index.py`` parses it),
so adoption is one call: ``bootstrap_from_toc(toc, ...,
compressor=COMPRESSOR_ZSTD)``. The real zstd:chunked manifest differs
in field spelling but not in content; this repo's writer exists to
exercise the adoption path end-to-end, not to interoperate with
containers/storage blobs byte-for-byte.
"""

from __future__ import annotations

import hashlib
import json
import struct
from typing import Callable, Optional

from nydus_snapshotter_tpu.utils import errdefs
from nydus_snapshotter_tpu.utils import zstd as _zstd

ZSTD_CHUNKED_MAGIC = b"GnUlInUx"
_FOOTER_SKIPPABLE_MAGIC = 0x184D2A50
# manifest offset, compressed length, uncompressed length, manifest type,
# trailing magic — the footer payload of the zstd:chunked format.
_FOOTER_PAYLOAD = struct.Struct("<QQQQ8s")
FOOTER_SIZE = 8 + _FOOTER_PAYLOAD.size  # skippable header + payload
_MANIFEST_TYPE_TOC = 1

DEFAULT_CHUNK_SIZE = 0x400000


class ZstdChunkedError(errdefs.NydusError):
    pass


def parse_footer(tail: bytes) -> Optional[tuple[int, int, int]]:
    """``(manifest_offset, manifest_csize, manifest_usize)`` from the
    blob's last ``FOOTER_SIZE`` bytes, or ``None`` when the tail is not
    a zstd:chunked footer (the probe path — absence is routing, not an
    error)."""
    if len(tail) < FOOTER_SIZE:
        return None
    frame = tail[-FOOTER_SIZE:]
    magic, content_len = struct.unpack_from("<II", frame, 0)
    if magic != _FOOTER_SKIPPABLE_MAGIC or content_len != _FOOTER_PAYLOAD.size:
        return None
    off, csize, usize, mtype, tag = _FOOTER_PAYLOAD.unpack_from(frame, 8)
    if tag != ZSTD_CHUNKED_MAGIC or mtype != _MANIFEST_TYPE_TOC:
        return None
    return off, csize, usize


def read_toc(
    read_at: Callable[[int, int], bytes], blob_size: int
) -> Optional[dict]:
    """Fetch + decode the TOC manifest with two ranged reads (footer,
    then the exact manifest frame). Returns ``None`` when the blob has
    no zstd:chunked footer; raises on a footer that promises a manifest
    the blob cannot hold or a manifest that fails to decode."""
    if blob_size < FOOTER_SIZE:
        return None
    loc = parse_footer(read_at(blob_size - FOOTER_SIZE, FOOTER_SIZE))
    if loc is None:
        return None
    off, csize, usize = loc
    if off + csize > blob_size or csize <= 0:
        raise ZstdChunkedError(
            f"zstd:chunked footer promises manifest [{off}, +{csize}) in a "
            f"{blob_size}-byte blob"
        )
    raw = read_at(off, csize)
    if len(raw) != csize:
        raise ZstdChunkedError("short read fetching zstd:chunked manifest")
    try:
        plain = _zstd.decompress_block(raw, max_output_size=max(usize, 1))
    except _zstd.ZstdError as e:
        raise ZstdChunkedError(f"corrupt zstd:chunked manifest: {e}") from e
    if len(plain) != usize:
        raise ZstdChunkedError(
            f"zstd:chunked manifest decoded to {len(plain)} bytes, "
            f"footer says {usize}"
        )
    try:
        return json.loads(plain)
    except ValueError as e:
        raise ZstdChunkedError(f"zstd:chunked manifest is not JSON: {e}") from e


def write_zstd_chunked(
    files: dict[str, bytes],
    chunk_size: int = DEFAULT_CHUNK_SIZE,
    level: int = 3,
) -> bytes:
    """Synthesize a zstd:chunked-shaped layer blob from ``files``
    (path → content): one independent zstd frame per chunk, a
    zstd-compressed version-1 TOC, and the ``GnUlInUx`` footer.
    Deterministic for fixed input/level, so scenario serial replays keep
    blob-id identity. Used by tests, the profile tool and the scenario
    corpus — production blobs arrive pre-chunked from the registry."""
    parts: list[bytes] = []
    entries: list[dict] = []
    pos = 0
    for name, data in sorted(files.items()):
        clean = name.strip("/")
        first = True
        coff = 0
        while first or coff < len(data):
            piece = data[coff : coff + chunk_size]
            frame = _zstd.compress_block(piece, level) if piece else b""
            digest = "sha256:" + hashlib.sha256(piece).hexdigest()
            if first:
                entries.append({
                    "name": clean,
                    "type": "reg",
                    "size": len(data),
                    "mode": 0o644,
                    "offset": pos,
                    "chunkOffset": 0,
                    "chunkSize": len(piece),
                    "chunkDigest": digest,
                })
            else:
                entries.append({
                    "name": clean,
                    "type": "chunk",
                    "offset": pos,
                    "chunkOffset": coff,
                    "chunkSize": len(piece),
                    "chunkDigest": digest,
                })
            parts.append(frame)
            pos += len(frame)
            coff += len(piece)
            first = False
    toc = json.dumps(
        {"version": 1, "entries": entries}, sort_keys=True
    ).encode()
    manifest = _zstd.compress_block(toc, level)
    footer = struct.pack(
        "<II", _FOOTER_SKIPPABLE_MAGIC, _FOOTER_PAYLOAD.size
    ) + _FOOTER_PAYLOAD.pack(
        pos, len(manifest), len(toc), _MANIFEST_TYPE_TOC, ZSTD_CHUNKED_MAGIC
    )
    return b"".join(parts) + manifest + footer
