"""Runtime half of the zstd lazy-read plane: frame-indexed reads.

The zstd mirror of :mod:`~nydus_snapshotter_tpu.soci.blob`, sharing its
metrics, failpoints and store discipline so operators see ONE soci plane
regardless of layer compression:

- :func:`build_zindex_from_zstd` is index-on-first-pull: one sequential
  pass (``zframe.build``) over the original layer yields the frame table
  AND the decompressed tar, so the layer bootstrap builds from the same
  pass. When the blob ships a seekable-format seek table the pass trusts
  its geometry (verifying every decoded size) and records the cheaper
  provenance.
- :func:`load_or_build_zindex` is the same waterfall as the gzip index:
  local cache dirs → peer replication (kind ``"zsoci"`` on the generic
  artifact plane) → rebuild once. A corrupt ``.soci.zidx`` is deleted
  and rebuilt; it can never poison reads.
- :class:`ZstdStreamReader` is what ``BlobReader`` mounts for a
  zstd-stream blob: ``read_range`` resolves a decompressed extent to its
  covering frames and pulls exactly those frames' compressed bytes
  through the caller-supplied compressed-domain reader (a
  ``CachedBlob.read_at`` in the deployed stack — singleflight,
  coalescing, readahead, peer tier and QoS all apply untouched). Frames
  decode on pooled contexts: concurrent reads need no shared lock.

Failpoints are the soci set (``soci.index`` / ``soci.resolve`` /
``soci.fetch``) — chaos drills that degrade the gzip path degrade this
one identically.
"""

from __future__ import annotations

import logging
import os
from time import perf_counter
from typing import Callable, Optional, Sequence

from nydus_snapshotter_tpu import failpoint, trace
from nydus_snapshotter_tpu.metrics import registry as _metrics
from nydus_snapshotter_tpu.soci import zframe
from nydus_snapshotter_tpu.soci.blob import (
    FETCH_BYTES,
    INDEX_BYTES,
    INDEX_EVENTS,
    OP_MS,
    READ_BYTES,
    file_extents,
)
from nydus_snapshotter_tpu.soci.index import SociIndexError
from nydus_snapshotter_tpu.soci.zindex import (
    SOURCE_FRAME_WALK,
    SOURCE_SEEK_TABLE,
    ZstdFrameIndex,
    ZstdIndexError,
    zindex_path,
)

logger = logging.getLogger(__name__)

# Peer artifact kind for replicated zstd frame indexes (the generic
# artifact plane's analog of the first-class soci route).
ZSOCI_ARTIFACT_KIND = "zsoci"

_reg = _metrics.default_registry
ZINDEX_FRAMES = _reg.register(
    _metrics.Counter(
        "ntpu_soci_zindex_frames_total",
        "zstd frame-table entries captured by zstd index builds",
    )
)


# ---------------------------------------------------------------------------
# Index building
# ---------------------------------------------------------------------------


def build_zindex_from_zstd(
    blob_id: str,
    raw: bytes,
    entries: Optional[list[zframe.FrameEntry]] = None,
) -> tuple[ZstdFrameIndex, bytes]:
    """One sequential pass over the original zstd layer → ``(index, tar
    bytes)``. ``entries`` — a parsed seek table — upgrades the pass from
    frame-walking to table-verified decode and stamps the cheaper
    provenance; either way the decompressed output feeds the bootstrap
    build so the layer is inflated exactly once."""
    failpoint.hit("soci.index")
    t0 = perf_counter()
    source = SOURCE_FRAME_WALK
    with trace.span("soci.zindex.build", blob=blob_id[:8], bytes=len(raw)):
        if entries is None:
            try:
                entries = zframe.read_seek_table(
                    lambda o, n: raw[o : o + n], len(raw)
                )
            except zframe.ZstdFrameError as e:
                # A broken seek table demotes to the walk, never to failure.
                logger.warning("ignoring bad zstd seek table for %s: %s",
                               blob_id[:12], e)
                entries = None
        if entries is not None:
            source = SOURCE_SEEK_TABLE
        frames, tar_bytes = zframe.build(raw, entries)
        index = ZstdFrameIndex(
            blob_id=blob_id,
            compressed_size=len(raw),
            uncompressed_size=len(tar_bytes),
            source=source,
            frames=frames,
            files=file_extents(tar_bytes),
        )
    ZINDEX_FRAMES.inc(len(frames))
    OP_MS.labels("build").observe((perf_counter() - t0) * 1000.0)
    return index, tar_bytes


# ---------------------------------------------------------------------------
# Index store: local → peer → rebuild-once (the gzip waterfall, verbatim)
# ---------------------------------------------------------------------------


def find_zindex(
    dirs: Sequence[str], blob_id: str, csize: int = 0
) -> tuple[Optional[ZstdFrameIndex], int]:
    """``(first loadable zstd index for blob_id in dirs, discarded
    count)``; corrupt or stale artifacts warn, count an error, are
    unlinked, and the search continues."""
    discarded = 0
    for d in dirs:
        if not d:
            continue
        path = zindex_path(d, blob_id)
        if not os.path.exists(path):
            continue
        try:
            return (
                ZstdFrameIndex.load(path, blob_id=blob_id, csize=csize),
                discarded,
            )
        except SociIndexError as e:
            INDEX_EVENTS.labels("error").inc()
            logger.warning("discarding bad zstd index %s: %s", path, e)
            discarded += 1
            try:
                os.unlink(path)
            except OSError:
                pass
    return None, discarded


def load_or_build_zindex(
    dirs: Sequence[str],
    blob_id: str,
    csize: int = 0,
    builder: Optional[Callable[[], bytes]] = None,
    fetch_remote: Optional[Callable[[], bytes]] = None,
    persist: bool = True,
) -> tuple[Optional[ZstdFrameIndex], str]:
    """Local cache dirs → peer replication → one local rebuild. Returns
    ``(index, outcome)``; ``(None, ...)`` means the caller falls back to
    full pull + convert — NEVER to wrong bytes. ``builder()`` returns
    the original compressed layer; ``fetch_remote()`` returns serialized
    index bytes from a peer, revalidated by checksum before adoption."""
    failpoint.hit("soci.index")
    try:
        idx, discarded = find_zindex(dirs, blob_id, csize=csize)
    except Exception:  # noqa: BLE001 — the store degrades, reads survive
        logger.warning("zstd index search failed for %s", blob_id[:12],
                       exc_info=True)
        idx, discarded = None, 1
    if idx is not None:
        INDEX_EVENTS.labels("loaded").inc()
        return idx, "loaded"

    if fetch_remote is not None:
        try:
            raw = fetch_remote()
            idx = ZstdFrameIndex.from_bytes(raw, blob_id=blob_id, csize=csize)
        except Exception as e:  # noqa: BLE001 — replication is an
            # optimization; any failure walks on to the local build
            logger.warning("zstd index replication for %s failed: %s",
                           blob_id[:12], e)
            idx = None
        if idx is not None:
            INDEX_EVENTS.labels("replicated").inc()
            if persist and dirs and dirs[0]:
                try:
                    INDEX_BYTES.inc(idx.save(zindex_path(dirs[0], blob_id)))
                except OSError:
                    logger.warning("cannot persist replicated zstd index",
                                   exc_info=True)
            return idx, "replicated"

    if builder is None:
        return None, "missing"
    try:
        raw_zstd = builder()
        idx, _ = build_zindex_from_zstd(blob_id, raw_zstd)
    except Exception as e:  # noqa: BLE001 — a failed build degrades to
        # full pull + convert, never to a broken reader
        INDEX_EVENTS.labels("error").inc()
        logger.warning("zstd index build for %s failed: %s", blob_id[:12], e)
        return None, "error"
    outcome = "rebuilt" if discarded else "built"
    INDEX_EVENTS.labels(outcome).inc()
    if persist and dirs and dirs[0]:
        try:
            INDEX_BYTES.inc(idx.save(zindex_path(dirs[0], blob_id)))
        except OSError:
            logger.warning("cannot persist zstd index", exc_info=True)
    return idx, outcome


# ---------------------------------------------------------------------------
# The reader BlobReader mounts
# ---------------------------------------------------------------------------


class ZstdStreamReader:
    """Decompressed-domain random access over a frame-indexed zstd blob.

    Interface-compatible with :class:`~nydus_snapshotter_tpu.soci.blob.
    SociStreamReader` (``read_range`` / ``resolve_compressed`` /
    ``concurrent``); cold cost is bounded by the largest covering frame,
    and every read decodes on its own pooled context — no shared lock.
    """

    concurrent = True

    def __init__(
        self,
        index: ZstdFrameIndex,
        read_comp: Callable[[int, int], bytes],
        name: str = "",
    ):
        self.index = index
        self._read_comp = read_comp
        self.name = name or index.blob_id[:8]

    def read_range(self, offset: int, size: int) -> bytes:
        if size <= 0:
            return b""
        if offset + size > self.index.uncompressed_size:
            raise ZstdIndexError(
                f"read [{offset}, +{size}) beyond decompressed end "
                f"{self.index.uncompressed_size}"
            )
        t0 = perf_counter()
        failpoint.hit("soci.resolve")
        frames, comp_start, _comp_end = self.index.resolve(offset, size)
        with trace.span(
            "soci.read",
            blob=self.name,
            offset=offset,
            bytes=size,
            checkpoint=frames[0].uout if frames else 0,
        ) as sp:
            fetched = 0

            def pull(pos: int, n: int) -> bytes:
                nonlocal fetched
                failpoint.hit("soci.fetch")
                data = self._read_comp(pos, n)
                fetched += len(data)
                return data

            try:
                out = zframe.extract(
                    pull, self.index.compressed_size, frames, offset, size
                )
            except zframe.ZstdFrameError as e:
                raise ZstdIndexError(str(e)) from e
            sp.annotate(compressed_bytes=fetched)
        READ_BYTES.inc(size)
        FETCH_BYTES.inc(fetched)
        OP_MS.labels("read").observe((perf_counter() - t0) * 1000.0)
        return out

    def resolve_compressed(self, offset: int, size: int) -> tuple[int, int]:
        """Compressed ``[start, end)`` a decompressed extent needs — what
        the prefetch replayer warms (see ``SociStreamReader``)."""
        _, comp_start, comp_end = self.index.resolve(offset, size)
        return comp_start, comp_end
