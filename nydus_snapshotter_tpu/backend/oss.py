"""Aliyun OSS blob backend with header signing.

Reference pkg/backend/oss.go:25-192 (aliyun SDK there). Config keys:
endpoint, bucket_name, object_prefix, access_key_id, access_key_secret.
Signing follows the OSS "Authorization: OSS AccessKeyId:Signature" header
scheme (HMAC-SHA1 over verb/md5/type/date/canonicalized resource).
"""

from __future__ import annotations

import base64
import email.utils
import hashlib
import hmac
import http.client
import urllib.parse
from typing import Optional

from nydus_snapshotter_tpu.backend.backend import (
    MULTIPART_CHUNK_SIZE,
    Backend,
    BlobSource,
    _read_source,
    _source_size,
    digest_hex,
    multipart_upload,
)
from nydus_snapshotter_tpu.utils import errdefs


class OSSBackend(Backend):
    def __init__(self, config: dict, force_push: bool = False, part_size: int = MULTIPART_CHUNK_SIZE):
        endpoint = config.get("endpoint", "")
        self.bucket = config.get("bucket_name", "")
        if not endpoint or not self.bucket:
            raise errdefs.InvalidArgument("invalid OSS configuration: missing 'endpoint' or 'bucket_name'")
        self.scheme = "https"
        if "://" in endpoint:
            self.scheme, endpoint = endpoint.split("://", 1)
        self.endpoint = endpoint
        self.object_prefix = config.get("object_prefix", "")
        self.access_key = config.get("access_key_id", "")
        self.secret_key = config.get("access_key_secret", "")
        self.force_push = force_push
        self.part_size = part_size

    def _sign(self, verb: str, key: str, date: str, content_type: str = "", subresource: str = "") -> str:
        resource = f"/{self.bucket}/{key}{subresource}"
        to_sign = f"{verb}\n\n{content_type}\n{date}\n{resource}"
        mac = hmac.new(self.secret_key.encode(), to_sign.encode(), hashlib.sha1)
        return base64.b64encode(mac.digest()).decode()

    def _request(self, method: str, key: str, query: Optional[dict] = None, body: bytes = b"",
                 content_type: str = ""):
        query = query or {}
        date = email.utils.formatdate(usegmt=True)
        # Subresources (uploads, uploadId, partNumber) join the signed resource.
        signed_q = {k: v for k, v in query.items() if k in ("uploads", "uploadId", "partNumber")}
        subresource = ""
        if signed_q:
            parts = [k if v == "" else f"{k}={v}" for k, v in sorted(signed_q.items())]
            subresource = "?" + "&".join(parts)
        sig = self._sign(method, key, date, content_type, subresource)
        hdrs = {
            "Host": f"{self.bucket}.{self.endpoint}",
            "Date": date,
            "Authorization": f"OSS {self.access_key}:{sig}",
        }
        if content_type:
            hdrs["Content-Type"] = content_type
        if body:
            hdrs["Content-Length"] = str(len(body))
        conn_cls = http.client.HTTPSConnection if self.scheme == "https" else http.client.HTTPConnection
        conn = conn_cls(f"{self.bucket}.{self.endpoint}", timeout=60)
        qs = "?" + urllib.parse.urlencode(query) if query else ""
        try:
            conn.request(method, f"/{urllib.parse.quote(key)}{qs}", body=body or None, headers=hdrs)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        finally:
            conn.close()

    def _object_key(self, digest: str) -> str:
        return self.object_prefix + digest_hex(digest)

    def _exists(self, key: str) -> bool:
        status, _, _ = self._request("HEAD", key)
        if status == 200:
            return True
        if status in (403, 404):
            return False
        raise errdefs.Unavailable(f"OSS HEAD {key}: HTTP {status}")

    def push(self, data: BlobSource, digest: str) -> None:
        key = self._object_key(digest)
        if self._exists(key) and not self.force_push:
            return
        # The reference multipart-splits large blobs (oss.go:99-157); same
        # threshold here, via the shared streaming multipart driver.
        if _source_size(data) <= self.part_size:
            blob = _read_source(data)
            status, _, body = self._request("PUT", key, body=blob)
            if status // 100 != 2:
                raise errdefs.Unavailable(f"OSS PUT {key}: HTTP {status} {body[:200]!r}")
            return
        multipart_upload(self._request, key, data, self.part_size, ("UploadId",), "OSS")

    def check(self, digest: str) -> str:
        key = self._object_key(digest)
        if self._exists(key):
            return key
        raise errdefs.NotFound(f"blob {digest} not in oss backend")

    def type(self) -> str:
        return "oss"
