"""Backend interface + factory (reference pkg/backend/backend.go:31-57)."""

from __future__ import annotations

import json
from abc import ABC, abstractmethod
from typing import Union

from nydus_snapshotter_tpu.utils import errdefs

BACKEND_TYPE_OSS = "oss"
BACKEND_TYPE_S3 = "s3"
BACKEND_TYPE_LOCALFS = "localfs"

# Default multipart part size (backend.go:24-28).
MULTIPART_CHUNK_SIZE = 500 * 1024 * 1024

BlobSource = Union[bytes, bytearray, str]  # raw bytes or a file path


def _read_source(data: BlobSource) -> bytes:
    if isinstance(data, (bytes, bytearray)):
        return bytes(data)
    with open(data, "rb") as f:
        return f.read()


def _source_size(data: BlobSource) -> int:
    if isinstance(data, (bytes, bytearray)):
        return len(data)
    import os

    return os.path.getsize(data)


def _iter_parts(data: BlobSource, part_size: int):
    """Yield part-sized byte chunks without loading file sources whole."""
    if isinstance(data, (bytes, bytearray)):
        for off in range(0, len(data), part_size):
            yield bytes(data[off : off + part_size])
        return
    with open(data, "rb") as f:
        while True:
            part = f.read(part_size)
            if not part:
                return
            yield part


def digest_hex(digest: str) -> str:
    return digest.split(":", 1)[-1]


def multipart_upload(request, key: str, data: BlobSource, part_size: int,
                     upload_id_tags: tuple[str, ...], service: str) -> None:
    """Generic multipart-upload driver shared by S3 and OSS (both speak the
    same initiate / per-part PUT / complete-XML / abort protocol).

    ``request(method, key, query=None, body=b"")`` returns
    ``(status, headers, body)``. Parts are streamed one at a time; the
    session is aborted on failure so no orphaned parts accrue storage.
    """
    import xml.etree.ElementTree as ET

    from nydus_snapshotter_tpu.utils import errdefs as _errdefs

    status, _, body = request("POST", key, query={"uploads": ""})
    if status // 100 != 2:
        raise _errdefs.Unavailable(f"{service} InitiateMultipartUpload: HTTP {status}")
    root = ET.fromstring(body)
    upload_id = ""
    for tag in upload_id_tags:
        upload_id = root.findtext(tag) or upload_id
    try:
        etags: list[tuple[int, str]] = []
        for idx, part in enumerate(_iter_parts(data, part_size), start=1):
            status, hdrs, _ = request(
                "PUT", key, query={"partNumber": str(idx), "uploadId": upload_id}, body=part
            )
            if status // 100 != 2:
                raise _errdefs.Unavailable(f"{service} UploadPart {idx}: HTTP {status}")
            etags.append((idx, {k.lower(): v for k, v in hdrs.items()}.get("etag", "")))
        parts_xml = "".join(
            f"<Part><PartNumber>{n}</PartNumber><ETag>{e}</ETag></Part>" for n, e in etags
        )
        complete = f"<CompleteMultipartUpload>{parts_xml}</CompleteMultipartUpload>".encode()
        status, _, _ = request("POST", key, query={"uploadId": upload_id}, body=complete)
        if status // 100 != 2:
            raise _errdefs.Unavailable(f"{service} CompleteMultipartUpload: HTTP {status}")
    except BaseException:
        try:
            request("DELETE", key, query={"uploadId": upload_id})
        except Exception:
            pass
        raise


class Backend(ABC):
    """Uploads conversion blobs to remote storage (backend.go:31-40)."""

    @abstractmethod
    def push(self, data: BlobSource, digest: str) -> None:
        """Push blob content for ``digest`` (skip if present, unless
        force_push)."""

    @abstractmethod
    def check(self, digest: str) -> str:
        """Return the backend path/key if the blob exists; raise NotFound
        otherwise."""

    @abstractmethod
    def type(self) -> str:
        ...


def new_backend(backend_type: str, config: bytes | str | dict, force_push: bool = False) -> Backend:
    from nydus_snapshotter_tpu.backend.localfs import LocalFSBackend
    from nydus_snapshotter_tpu.backend.oss import OSSBackend
    from nydus_snapshotter_tpu.backend.s3 import S3Backend

    if isinstance(config, (bytes, str)):
        config = json.loads(config)
    if backend_type == BACKEND_TYPE_OSS:
        return OSSBackend(config, force_push)
    if backend_type == BACKEND_TYPE_S3:
        return S3Backend(config, force_push)
    if backend_type == BACKEND_TYPE_LOCALFS:
        return LocalFSBackend(config, force_push)
    raise errdefs.InvalidArgument(f"unsupported backend type {backend_type}")
