"""Backend interface + factory (reference pkg/backend/backend.go:31-57)."""

from __future__ import annotations

import json
from abc import ABC, abstractmethod
from typing import Union

from nydus_snapshotter_tpu.utils import errdefs

BACKEND_TYPE_OSS = "oss"
BACKEND_TYPE_S3 = "s3"
BACKEND_TYPE_LOCALFS = "localfs"

# Default multipart part size (backend.go:24-28).
MULTIPART_CHUNK_SIZE = 500 * 1024 * 1024

BlobSource = Union[bytes, bytearray, str]  # raw bytes or a file path


def _read_source(data: BlobSource) -> bytes:
    if isinstance(data, (bytes, bytearray)):
        return bytes(data)
    with open(data, "rb") as f:
        return f.read()


def _source_size(data: BlobSource) -> int:
    if isinstance(data, (bytes, bytearray)):
        return len(data)
    import os

    return os.path.getsize(data)


def _iter_parts(data: BlobSource, part_size: int):
    """Yield part-sized byte chunks without loading file sources whole."""
    if isinstance(data, (bytes, bytearray)):
        for off in range(0, len(data), part_size):
            yield bytes(data[off : off + part_size])
        return
    with open(data, "rb") as f:
        while True:
            part = f.read(part_size)
            if not part:
                return
            yield part


def digest_hex(digest: str) -> str:
    return digest.split(":", 1)[-1]


class Backend(ABC):
    """Uploads conversion blobs to remote storage (backend.go:31-40)."""

    @abstractmethod
    def push(self, data: BlobSource, digest: str) -> None:
        """Push blob content for ``digest`` (skip if present, unless
        force_push)."""

    @abstractmethod
    def check(self, digest: str) -> str:
        """Return the backend path/key if the blob exists; raise NotFound
        otherwise."""

    @abstractmethod
    def type(self) -> str:
        ...


def new_backend(backend_type: str, config: bytes | str | dict, force_push: bool = False) -> Backend:
    from nydus_snapshotter_tpu.backend.localfs import LocalFSBackend
    from nydus_snapshotter_tpu.backend.oss import OSSBackend
    from nydus_snapshotter_tpu.backend.s3 import S3Backend

    if isinstance(config, (bytes, str)):
        config = json.loads(config)
    if backend_type == BACKEND_TYPE_OSS:
        return OSSBackend(config, force_push)
    if backend_type == BACKEND_TYPE_S3:
        return S3Backend(config, force_push)
    if backend_type == BACKEND_TYPE_LOCALFS:
        return LocalFSBackend(config, force_push)
    raise errdefs.InvalidArgument(f"unsupported backend type {backend_type}")
