"""Blob storage backends for conversion push (reference pkg/backend).

``new_backend(type, config, force_push)`` mirrors backend.go:46-57 with the
same three types: ``oss``, ``s3``, ``localfs``. The cloud backends are
stdlib HTTP clients (OSS header signing, AWS SigV4) instead of vendored
SDKs; multipart uploads use the same 500 MiB default part size
(backend.go:24-28).
"""

from nydus_snapshotter_tpu.backend.backend import (
    MULTIPART_CHUNK_SIZE,
    Backend,
    new_backend,
)
from nydus_snapshotter_tpu.backend.localfs import LocalFSBackend
from nydus_snapshotter_tpu.backend.oss import OSSBackend
from nydus_snapshotter_tpu.backend.s3 import S3Backend

__all__ = [
    "Backend",
    "new_backend",
    "MULTIPART_CHUNK_SIZE",
    "LocalFSBackend",
    "OSSBackend",
    "S3Backend",
]
