"""Local-filesystem blob backend (reference pkg/backend/localfs.go:24-99)."""

from __future__ import annotations

import os

from nydus_snapshotter_tpu.backend.backend import Backend, BlobSource, _read_source, digest_hex
from nydus_snapshotter_tpu.utils import errdefs


class LocalFSBackend(Backend):
    def __init__(self, config: dict, force_push: bool = False):
        dir_ = config.get("dir")
        if not dir_:
            raise errdefs.InvalidArgument("no `dir` option is specified")
        self.dir = dir_
        self.force_push = force_push

    def _dst_path(self, blob_id: str) -> str:
        return os.path.join(self.dir, blob_id)

    def push(self, data: BlobSource, digest: str) -> None:
        try:
            self.check(digest)
            if not self.force_push:
                return
        except errdefs.NotFound:
            pass
        os.makedirs(self.dir, exist_ok=True)
        path = self._dst_path(digest_hex(digest))
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_read_source(data))
        os.replace(tmp, path)

    def check(self, digest: str) -> str:
        path = self._dst_path(digest_hex(digest))
        st = None
        try:
            st = os.stat(path)
        except FileNotFoundError:
            raise errdefs.NotFound(f"blob {digest} not in localfs backend") from None
        if not os.path.isfile(path) or st is None:
            raise errdefs.NotFound(f"{path} is not a regular file")
        return path

    def type(self) -> str:
        return "localfs"
