"""S3 blob backend with stdlib AWS SigV4 signing.

Reference pkg/backend/s3.go:29-187 (aws-sdk-go-v2 there). Same config
schema (access_key_id/secret, endpoint, scheme, bucket_name, region,
object_prefix), same existence-check-then-upload flow, multipart upload
for blobs over the part size.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import http.client
import urllib.parse
from typing import Mapping, Optional

from nydus_snapshotter_tpu.backend.backend import (
    MULTIPART_CHUNK_SIZE,
    Backend,
    BlobSource,
    _read_source,
    _source_size,
    digest_hex,
    multipart_upload,
)
from nydus_snapshotter_tpu.utils import errdefs


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sigv4_headers(
    method: str,
    host: str,
    path: str,
    query: Mapping[str, str],
    region: str,
    access_key: str,
    secret_key: str,
    payload_sha256: str,
    now: Optional[datetime.datetime] = None,
) -> dict[str, str]:
    """AWS Signature V4 for the s3 service; returns headers to attach."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")

    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
        for k, v in sorted(query.items())
    )
    headers = {
        "host": host,
        "x-amz-content-sha256": payload_sha256,
        "x-amz-date": amz_date,
    }
    signed_headers = ";".join(sorted(headers))
    canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in sorted(headers))
    canonical_request = "\n".join(
        [method, urllib.parse.quote(path), canonical_query, canonical_headers, signed_headers, payload_sha256]
    )
    scope = f"{datestamp}/{region}/s3/aws4_request"
    string_to_sign = "\n".join(
        ["AWS4-HMAC-SHA256", amz_date, scope, hashlib.sha256(canonical_request.encode()).hexdigest()]
    )
    k = _sign(_sign(_sign(_sign(b"AWS4" + secret_key.encode(), datestamp), region), "s3"), "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()
    return {
        "Host": host,
        "x-amz-content-sha256": payload_sha256,
        "x-amz-date": amz_date,
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"
        ),
    }


class S3Backend(Backend):
    def __init__(self, config: dict, force_push: bool = False, part_size: int = MULTIPART_CHUNK_SIZE):
        endpoint = config.get("endpoint") or "s3.amazonaws.com"
        scheme = config.get("scheme") or "https"
        self.bucket = config.get("bucket_name", "")
        self.region = config.get("region", "")
        if not self.bucket or not self.region:
            raise errdefs.InvalidArgument("invalid S3 configuration: missing 'bucket_name' or 'region'")
        self.endpoint = endpoint
        self.scheme = scheme
        self.object_prefix = config.get("object_prefix", "")
        self.access_key = config.get("access_key_id", "")
        self.secret_key = config.get("access_key_secret", "")
        self.force_push = force_push
        self.part_size = part_size

    # -- raw signed request ---------------------------------------------------

    def _request(self, method: str, key: str, query: Optional[dict] = None, body: bytes = b""):
        query = query or {}
        path = f"/{self.bucket}/{urllib.parse.quote(key)}"
        payload_hash = hashlib.sha256(body).hexdigest()
        hdrs = sigv4_headers(
            method, self.endpoint, f"/{self.bucket}/{key}", query,
            self.region, self.access_key, self.secret_key, payload_hash,
        )
        if body:
            hdrs["Content-Length"] = str(len(body))
        conn_cls = http.client.HTTPSConnection if self.scheme == "https" else http.client.HTTPConnection
        conn = conn_cls(self.endpoint, timeout=60)
        qs = "?" + urllib.parse.urlencode(query) if query else ""
        try:
            conn.request(method, path + qs, body=body or None, headers=hdrs)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        finally:
            conn.close()

    def _object_key(self, digest: str) -> str:
        return self.object_prefix + digest_hex(digest)

    def _exists(self, key: str) -> bool:
        status, _, _ = self._request("HEAD", key)
        if status == 200:
            return True
        if status in (403, 404):
            return False
        raise errdefs.Unavailable(f"S3 HEAD {key}: HTTP {status}")

    # -- Backend --------------------------------------------------------------

    def push(self, data: BlobSource, digest: str) -> None:
        key = self._object_key(digest)
        if self._exists(key) and not self.force_push:
            return
        if _source_size(data) <= self.part_size:
            blob = _read_source(data)
            status, _, body = self._request("PUT", key, body=blob)
            if status // 100 != 2:
                raise errdefs.Unavailable(f"S3 PUT {key}: HTTP {status} {body[:200]!r}")
            return
        multipart_upload(
            self._request, key, data, self.part_size,
            ("{http://s3.amazonaws.com/doc/2006-03-01/}UploadId", "UploadId"), "S3",
        )

    def check(self, digest: str) -> str:
        key = self._object_key(digest)
        if self._exists(key):
            return key
        raise errdefs.NotFound(f"blob {digest} not in s3 backend")

    def type(self) -> str:
        return "s3"
