"""RAFS instance records + global cache.

Reference pkg/rafs/rafs.go:37-205: one ``Rafs`` per mounted snapshot
(snapshot id, image id, owning daemon, mountpoint, annotations, persisted
sequence for replay ordering), plus a process-global instance cache.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Optional

from nydus_snapshotter_tpu.models import layout


@dataclass
class Rafs:
    snapshot_id: str
    image_id: str = ""
    daemon_id: str = ""
    fs_driver: str = ""
    snapshot_dir: str = ""
    mountpoint: str = ""
    annotations: dict[str, str] = field(default_factory=dict)
    seq: int = 0  # replay order (reference rafs.go:112-117)

    def bootstrap_file(self) -> str:
        """Path of the bootstrap within the snapshot dir, with the legacy
        fallback (reference rafs.go:187-205: fs/image/image.boot, else
        fs/image.boot)."""
        primary = os.path.join(self.snapshot_dir, "fs", layout.BOOTSTRAP_FILE)
        if os.path.exists(primary):
            return primary
        legacy = os.path.join(self.snapshot_dir, "fs", layout.LEGACY_BOOTSTRAP_FILE)
        if os.path.exists(legacy):
            return legacy
        return primary

    def fscache_work_dir(self) -> str:
        return os.path.join(self.snapshot_dir, "fs")

    def relative_mountpoint(self) -> str:
        """Mountpoint inside the daemon's FUSE namespace."""
        return f"/{self.snapshot_id}"

    def to_dict(self) -> dict[str, Any]:
        return {
            "snapshot_id": self.snapshot_id,
            "image_id": self.image_id,
            "daemon_id": self.daemon_id,
            "fs_driver": self.fs_driver,
            "snapshot_dir": self.snapshot_dir,
            "mountpoint": self.mountpoint,
            "annotations": dict(self.annotations),
            "seq": self.seq,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Rafs":
        return cls(**d)


class RafsCache:
    """Thread-safe snapshot-id → Rafs map (reference RafsGlobalCache)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._by_snapshot: dict[str, Rafs] = {}

    def add(self, rafs: Rafs) -> None:
        with self._lock:
            self._by_snapshot[rafs.snapshot_id] = rafs

    def get(self, snapshot_id: str) -> Optional[Rafs]:
        with self._lock:
            return self._by_snapshot.get(snapshot_id)

    def remove(self, snapshot_id: str) -> Optional[Rafs]:
        with self._lock:
            return self._by_snapshot.pop(snapshot_id, None)

    def list(self) -> list[Rafs]:
        with self._lock:
            return sorted(self._by_snapshot.values(), key=lambda r: r.seq)

    def by_daemon(self, daemon_id: str) -> list[Rafs]:
        with self._lock:
            return sorted(
                (r for r in self._by_snapshot.values() if r.daemon_id == daemon_id),
                key=lambda r: r.seq,
            )

    def head(self) -> Optional[Rafs]:
        with self._lock:
            vals = list(self._by_snapshot.values())
            return min(vals, key=lambda r: r.seq) if vals else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_snapshot)


rafs_global_cache = RafsCache()
