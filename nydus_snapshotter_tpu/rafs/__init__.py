"""RAFS instance registry (reference pkg/rafs/rafs.go)."""

from nydus_snapshotter_tpu.rafs.rafs import Rafs, RafsCache, rafs_global_cache  # noqa: F401
