"""nydus_snapshotter_tpu — a TPU-native re-design of the Nydus snapshotter stack.

A brand-new framework with the capabilities of containerd/nydus-snapshotter
(reference surveyed in /root/repo/SURVEY.md): a containerd remote-snapshotter
control plane plus the OCI→RAFS image conversion surface, with the conversion
hot path (content-defined chunking, chunk digesting, cross-image dedup) running
as a JAX/XLA data plane on TPU instead of the reference's external Rust
``nydus-image`` binary.

Layout (tpu-first, not a port of the reference's Go package tree):

- ``models/``    on-disk/on-wire data models: RAFS bootstraps, nydus-tar
                 framing, TOC entries, eStargz TOC, OCI media types.
- ``ops/``       JAX/Pallas compute kernels: gear rolling hash, CDC cut-point
                 resolution, SHA-256 lanes, dict probes.
- ``parallel/``  mesh construction, sharded HBM chunk-dict, host<->device
                 streaming pipeline, multi-host coordination.
- ``converter/`` the Pack/Merge/Unpack public surface (reference
                 pkg/converter) backed by the TPU engine.
- ``snapshot/``  containerd-snapshotter control plane (reference snapshot/).
- ``daemon/`` ``manager/`` ``supervisor/``  daemon lifecycle, liveness
                 monitoring, fd-passing failover (reference pkg/{daemon,
                 manager,supervisor}).
- ``store/``     persistence (reference pkg/store bbolt database).
- ``config/``    layered TOML config + daemon config templates.
- ``utils/``     retry, transport, mount/erofs helpers, signals.
- ``failpoint/`` process-wide fault-injection registry threaded through
                 every I/O and process boundary (docs/robustness.md).
"""

__version__ = "0.1.0"

from nydus_snapshotter_tpu import constants  # noqa: F401
