"""Re-export shim for embedding the snapshotter in other programs
(reference export/snapshotter/snapshotter.go)."""

from nydus_snapshotter_tpu.cmd.snapshotter import build_stack
from nydus_snapshotter_tpu.config.config import SnapshotterConfig, load_config
from nydus_snapshotter_tpu.snapshot.snapshotter import Snapshotter

__all__ = ["SnapshotterConfig", "Snapshotter", "build_stack", "load_config"]
