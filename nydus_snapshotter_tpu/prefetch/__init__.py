from nydus_snapshotter_tpu.prefetch.prefetch import Pm, PrefetchManager

__all__ = ["Pm", "PrefetchManager"]
