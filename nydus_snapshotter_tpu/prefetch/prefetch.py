"""Global image -> prefetch-file-list map (reference pkg/prefetch/prefetch.go).

Fed by the prefetchfiles NRI plugin through the system controller's
PUT /api/v1/prefetch; consumed as ``--prefetch-files`` when a daemon starts
(daemon_adaptor.go:179-185).
"""

from __future__ import annotations

import json
import threading


class PrefetchManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._map: dict[str, str] = {}

    def set_prefetch_files(self, body: bytes | str) -> None:
        """Parse ``[{"image": ..., "prefetch": ...}, ...]`` (prefetch.go:23-43)."""
        if isinstance(body, (bytes, bytearray)):
            body = body.decode()
        msg = json.loads(body)
        if not isinstance(msg, list):
            raise ValueError("prefetch list must be a JSON array")
        with self._lock:
            for item in msg:
                self._map[item["image"]] = item.get("prefetch", "")

    def get_prefetch_info(self, image: str) -> str:
        with self._lock:
            return self._map.get(image, "")

    def delete(self, image: str) -> None:
        with self._lock:
            self._map.pop(image, None)

    def reset(self) -> None:
        with self._lock:
            self._map.clear()


Pm = PrefetchManager()
