"""Global image -> prefetch-file-list map (reference pkg/prefetch/prefetch.go).

Fed by the prefetchfiles NRI plugin through the system controller's
PUT /api/v1/prefetch; consumed as ``--prefetch-files`` when a daemon starts
(daemon_adaptor.go:179-185).
"""

from __future__ import annotations

import json
import threading


class PrefetchManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._map: dict[str, str] = {}

    def set_prefetch_files(self, body: bytes | str) -> None:
        """Parse ``[{"image": ..., "prefetch": ...}, ...]`` (prefetch.go:23-43)."""
        if isinstance(body, (bytes, bytearray)):
            body = body.decode()
        msg = json.loads(body)
        if not isinstance(msg, list):
            raise ValueError("prefetch list must be a JSON array")
        with self._lock:
            for item in msg:
                self._map[item["image"]] = item.get("prefetch", "")

    def get_prefetch_info(self, image: str) -> str:
        with self._lock:
            return self._map.get(image, "")

    def paths_for(self, image: str) -> list[str]:
        """The image's prefetch hint as an ordered replay list for
        :class:`~nydus_snapshotter_tpu.daemon.fetch_sched.PrefetchReplayer`
        (newline- or comma-separated paths, duplicates dropped, order —
        i.e. replay priority — preserved)."""
        info = self.get_prefetch_info(image)
        seen: set[str] = set()
        out: list[str] = []
        for p in info.replace(",", "\n").split("\n"):
            p = p.strip()
            if p and p not in seen:
                seen.add(p)
                out.append(p)
        return out

    def delete(self, image: str) -> None:
        with self._lock:
            self._map.pop(image, None)

    def reset(self) -> None:
        with self._lock:
            self._map.clear()


Pm = PrefetchManager()


def patterns_from_trace(trace_path: str, strip_prefix: str = "") -> str:
    """Turn an optimizer access trace (one accessed path per line, the
    fanotify receiver's persist file) into converter prefetch patterns.

    This closes the reference's optimization loop (optimizer-nri-plugin →
    accessed-file list → ``nydus-image --prefetch-files``,
    docs/optimize_nydus_image.md): feed the result to
    ``PackOption.prefetch_patterns`` / ``MergeOption.prefetch_patterns``.
    Order is preserved (first access first — that IS the prefetch
    priority), duplicates dropped, ``strip_prefix`` removes a container
    rootfs mount prefix so paths are image-relative.
    """
    seen: set[str] = set()
    out: list[str] = []
    with open(trace_path) as f:
        for line in f:
            path = line.strip()
            if not path:
                continue
            if strip_prefix:
                # Path-boundary-aware: "/rootfs" must not mangle "/rootfs2".
                if path == strip_prefix:
                    path = "/"
                elif path.startswith(strip_prefix + "/"):
                    path = path[len(strip_prefix):]
            if not path.startswith("/"):
                path = "/" + path
            if path not in seen:
                seen.add(path)
                out.append(path)
    return "\n".join(out)
