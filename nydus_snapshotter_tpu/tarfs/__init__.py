"""Tarfs mode: plain-tar layers indexed in place and served as
EROFS-over-loop block devices (reference pkg/tarfs)."""

from nydus_snapshotter_tpu.tarfs.bootstrap import (
    DEFAULT_CHUNK_SIZE,
    tarfs_bootstrap_from_tar,
)
from nydus_snapshotter_tpu.tarfs.tarfs import (
    IMAGE_BOOTSTRAP_NAME,
    IMAGE_DISK_NAME,
    LAYER_BOOTSTRAP_NAME,
    LAYER_DISK_NAME,
    TARFS_STATUS_FAILED,
    TARFS_STATUS_INIT,
    TARFS_STATUS_PREPARE,
    TARFS_STATUS_READY,
    ExportFlags,
    Manager,
)
from nydus_snapshotter_tpu.tarfs.verity import (
    VerityInfo,
    build_tree,
    parse_block_info_label,
    verify,
)

__all__ = [
    "DEFAULT_CHUNK_SIZE",
    "ExportFlags",
    "IMAGE_BOOTSTRAP_NAME",
    "IMAGE_DISK_NAME",
    "LAYER_BOOTSTRAP_NAME",
    "LAYER_DISK_NAME",
    "Manager",
    "TARFS_STATUS_FAILED",
    "TARFS_STATUS_INIT",
    "TARFS_STATUS_PREPARE",
    "TARFS_STATUS_READY",
    "VerityInfo",
    "build_tree",
    "parse_block_info_label",
    "tarfs_bootstrap_from_tar",
    "verify",
]
