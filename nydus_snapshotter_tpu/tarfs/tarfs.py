"""Tarfs mode: download OCI layers as plain tars, index them in place, and
serve them as EROFS-over-loop block devices.

Reference pkg/tarfs/tarfs.go. Capabilities reproduced:

- async per-layer blob process with per-ref concurrency limits
  (tarfs.go:309-389, :799-812): download, decompress, tee to the layer tar
  file while validating the diffID against the image config, then build the
  layer bootstrap in-process (bootstrap.tarfs_bootstrap_from_tar replaces
  ``nydus-image create --type tar-tarfs``, tarfs.go:253-270);
- merge layer bootstraps bottom-up into ``image.boot`` via converter.Merge
  (tarfs.go:411-464);
- export block images with an optional dm-verity tree + the
  ``<blocks>,<offset>,sha256:<root>`` label contract (tarfs.go:466-571);
- loop-attach tars/bootstraps and mount EROFS with a ``device=`` list
  (tarfs.go:573-662), both behind injectable OS backends;
- status lifecycle INIT/PREPARE/READY/FAILED with waiters
  (tarfs.go:44-49, :739-752).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import tempfile
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Optional

from nydus_snapshotter_tpu import constants as C
from nydus_snapshotter_tpu.auth import keychain as authmod
from nydus_snapshotter_tpu.converter.convert import Merge
from nydus_snapshotter_tpu.converter.types import MergeOption
from nydus_snapshotter_tpu.models.bootstrap import Bootstrap
from nydus_snapshotter_tpu.remote.reference import parse_docker_ref
from nydus_snapshotter_tpu.remote.remote import Remote
from nydus_snapshotter_tpu.remote.unpack import decompress_stream
from nydus_snapshotter_tpu.tarfs import verity
from nydus_snapshotter_tpu.tarfs.bootstrap import tarfs_bootstrap_from_tar
from nydus_snapshotter_tpu.utils import errdefs, losetup
from nydus_snapshotter_tpu.utils import mount as mount_utils
from nydus_snapshotter_tpu.utils import singleflight

logger = logging.getLogger(__name__)

TARFS_STATUS_INIT = 0
TARFS_STATUS_PREPARE = 1
TARFS_STATUS_READY = 2
TARFS_STATUS_FAILED = 3

MAX_MANIFEST_CONFIG_SIZE = 0x100000
LAYER_BOOTSTRAP_NAME = "layer.boot"
IMAGE_BOOTSTRAP_NAME = "image.boot"
LAYER_DISK_NAME = "layer.disk"
IMAGE_DISK_NAME = "image.disk"



@dataclass
class ExportFlags:
    """config.GetTarfsExportFlags() equivalent (config.go:151-168)."""

    whole_image: bool = False
    export_disk: bool = False
    with_verity: bool = False

    @classmethod
    def from_mode(cls, mode: str) -> "ExportFlags":
        table = {
            "": cls(),
            "layer_verity_only": cls(False, False, True),
            "image_verity_only": cls(True, False, True),
            "layer_block": cls(False, True, False),
            "image_block": cls(True, True, False),
            "layer_block_with_verity": cls(False, True, True),
            "image_block_with_verity": cls(True, True, True),
        }
        if mode not in table:
            raise errdefs.InvalidArgument(f"unknown tarfs export mode {mode!r}")
        return table[mode]


class _SnapshotStatus:
    def __init__(self):
        self.lock = threading.Lock()
        self.status = TARFS_STATUS_INIT
        self.blob_id = ""
        self.blob_tar_file_path = ""
        self.erofs_mountpoint = ""
        self.data_loopdev: Optional[losetup.LoopDevice] = None
        self.meta_loopdev: Optional[losetup.LoopDevice] = None
        self.meta_image_path = ""  # the EROFS meta the meta loop backs
        self.done = threading.Event()


class _LRU:
    def __init__(self, cap: int):
        self.cap = cap
        self._d: OrderedDict = OrderedDict()
        self._mu = threading.Lock()

    def get(self, key):
        with self._mu:
            if key in self._d:
                self._d.move_to_end(key)
                return self._d[key]
            return None

    def add(self, key, value):
        with self._mu:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.cap:
                self._d.popitem(last=False)


class Manager:
    def __init__(
        self,
        cache_dir_path: str,
        insecure: bool = False,
        check_tarfs_hint: bool = False,
        max_concurrent_process: int = 4,
        validate_diff_id: bool = True,
        mount_on_host: bool = False,
        export_mode: str = "",
        engine=None,
    ):
        self.cache_dir_path = cache_dir_path
        os.makedirs(cache_dir_path, exist_ok=True)
        self.insecure = insecure
        self.check_tarfs_hint = check_tarfs_hint
        self.validate_diff_id = validate_diff_id
        self.mount_on_host = mount_on_host
        self.export_flags = ExportFlags.from_mode(export_mode)
        self.max_concurrent_process = max_concurrent_process
        self.engine = engine  # optional TPU digest engine for index builds
        self.snapshot_map: dict[str, _SnapshotStatus] = {}
        self._mu = threading.Lock()
        self._loop_mu = threading.Lock()
        self.tarfs_hint_cache = _LRU(50)
        self.process_limiter_cache = _LRU(50)
        self.diff_id_cache = _LRU(1000)
        self._sg = singleflight.Group()

    # -- image metadata (tarfs.go:104-199) -----------------------------------

    def _remote(self, ref: str) -> Remote:
        keychain = authmod.get_keychain_by_ref(ref, {})
        return Remote(keychain=keychain, insecure=self.insecure)

    def _fetch_image_info(self, remote: Remote, ref: str, manifest_digest: str) -> None:
        parsed = parse_docker_ref(ref)
        client = remote.client(ref)
        body = client.fetch_by_digest(parsed.path, manifest_digest)
        if len(body) > MAX_MANIFEST_CONFIG_SIZE:
            raise errdefs.InvalidArgument("image manifest content too big")
        manifest = json.loads(body)
        layers = manifest.get("layers") or []
        if not layers:
            raise errdefs.InvalidArgument("OCI image manifest without any layer")
        config_digest = (manifest.get("config") or {}).get("digest", "")
        cfg_body = client.fetch_by_digest(parsed.path, config_digest)
        if len(cfg_body) > MAX_MANIFEST_CONFIG_SIZE:
            raise errdefs.InvalidArgument("image config content too big")
        config = json.loads(cfg_body)
        diff_ids = (config.get("rootfs") or {}).get("diff_ids") or []
        if len(diff_ids) != len(layers):
            raise errdefs.InvalidArgument("number of diffIDs does not match layers")
        if self.check_tarfs_hint:
            annotations = manifest.get("annotations") or {}
            self.tarfs_hint_cache.add(ref, C.TARFS_HINT in annotations and
                                      annotations[C.TARFS_HINT].lower() == "true")
        if self.validate_diff_id:
            for layer, diff_id in zip(layers, diff_ids):
                self.diff_id_cache.add(layer["digest"], diff_id)

    def _get_blob_diff_id(
        self, remote: Remote, ref: str, manifest_digest: str, layer_digest: str
    ) -> str:
        cached = self.diff_id_cache.get(layer_digest)
        if cached is not None:
            return cached
        self._sg.do(ref, lambda: self._fetch_image_info(remote, ref, manifest_digest))
        cached = self.diff_id_cache.get(layer_digest)
        if cached is None:
            raise errdefs.NotFound(f"no diffID for layer {layer_digest}")
        return cached

    def check_tarfs_hint_annotation(self, ref: str, manifest_digest: str) -> bool:
        """tarfs.go:762-797: manifest annotation gate, LRU + singleflight."""
        if not self.check_tarfs_hint:
            return True
        remote = self._remote(ref)

        def handle() -> bool:
            hint = self.tarfs_hint_cache.get(ref)
            if hint is not None:
                return hint
            self._sg.do(ref, lambda: self._fetch_image_info(remote, ref, manifest_digest))
            hint = self.tarfs_hint_cache.get(ref)
            if hint is None:
                raise errdefs.NotFound("get tarfs hint annotation failed")
            return hint

        try:
            return handle()
        except Exception as e:
            if remote.retry_with_plain_http(ref, e):
                return handle()
            raise

    def get_concurrent_limiter(self, ref: str) -> Optional[threading.Semaphore]:
        """Per-ref bounded parallelism (tarfs.go:799-812)."""
        if self.max_concurrent_process <= 0:
            return None
        limiter = self.process_limiter_cache.get(ref)
        if limiter is None:
            limiter = threading.Semaphore(self.max_concurrent_process)
            self.process_limiter_cache.add(ref, limiter)
        return limiter

    # -- layer prepare (tarfs.go:215-389) ------------------------------------

    def prepare_layer(
        self, snap_labels: dict, snapshot_id: str, upper_dir_path: str
    ) -> None:
        """Async download + index of one layer (PrepareLayer :391-410)."""
        ref = snap_labels.get(C.CRI_IMAGE_REF, "")
        layer_digest = snap_labels.get(C.CRI_LAYER_DIGEST, "")
        manifest_digest = snap_labels.get(C.CRI_MANIFEST_DIGEST, "")
        if not ref or not layer_digest:
            raise errdefs.InvalidArgument("missing image ref / layer digest labels")
        with self._mu:
            if snapshot_id in self.snapshot_map:
                raise errdefs.AlreadyExists(
                    f"snapshot {snapshot_id} has already been prepared"
                )
            st = _SnapshotStatus()
            st.status = TARFS_STATUS_PREPARE
            self.snapshot_map[snapshot_id] = st

        t = threading.Thread(
            target=self._blob_process,
            args=(snapshot_id, ref, manifest_digest, layer_digest, upper_dir_path),
            daemon=True,
            name=f"tarfs-blob-{snapshot_id}",
        )
        t.start()

    def _epilog(self, snapshot_id: str, blob_id: str, err: Optional[BaseException], msg: str):
        st = self.snapshot_map.get(snapshot_id)
        if st is None:
            logger.error("no status object for snapshot %s after prepare", snapshot_id)
            return
        with st.lock:
            st.blob_id = blob_id
            st.blob_tar_file_path = self.layer_tar_file_path(blob_id)
            if err is not None:
                logger.error("%s: %s", msg, err)
                st.status = TARFS_STATUS_FAILED
            else:
                logger.info(msg)
                st.status = TARFS_STATUS_READY
        st.done.set()

    def _blob_process(
        self, snapshot_id: str, ref: str, manifest_digest: str,
        layer_digest: str, upper_dir_path: str,
    ) -> None:
        blob_id = layer_digest.split(":", 1)[-1]
        limiter = self.get_concurrent_limiter(ref)
        if limiter is not None:
            limiter.acquire()
        try:
            remote = self._remote(ref)
            parsed = parse_docker_ref(ref)

            def fetch() -> bytes:
                client = remote.client(ref)
                r = client.fetch_blob(parsed.path, layer_digest)
                try:
                    return r.read()
                finally:
                    r.close()

            try:
                raw = fetch()
            except Exception as e:
                if remote.retry_with_plain_http(ref, e):
                    raw = fetch()
                else:
                    raise
            tar_bytes = decompress_stream(raw)
            if self.validate_diff_id:
                diff_id = self._get_blob_diff_id(remote, ref, manifest_digest, layer_digest)
                actual = "sha256:" + hashlib.sha256(tar_bytes).hexdigest()
                if actual != diff_id:
                    raise errdefs.InvalidArgument(
                        f"layer diffID mismatch: {actual} != {diff_id}"
                    )
            self._generate_bootstrap(tar_bytes, snapshot_id, blob_id, upper_dir_path)
            self._epilog(snapshot_id, blob_id, None,
                         f"nydus tarfs for snapshot {snapshot_id} is ready")
        except errdefs.AlreadyExists:
            self._epilog(snapshot_id, blob_id, None,
                         f"nydus tarfs for snapshot {snapshot_id} already exists")
        except BaseException as e:
            self._epilog(snapshot_id, blob_id, e,
                         f"prepare tarfs layer for snapshot {snapshot_id}")
        finally:
            # Missing this release deadlocked every ref after
            # max_concurrent_process layers (caught by
            # tests/test_concurrency_stress.py; reference releases via
            # defer, tarfs.go:309-333).
            if limiter is not None:
                limiter.release()

    def _generate_bootstrap(
        self, tar_bytes: bytes, snapshot_id: str, layer_blob_id: str, upper_dir_path: str
    ) -> None:
        """generateBootstrap (tarfs.go:215-284): persist the tar into the
        blob cache and emit the layer bootstrap next to the snapshot."""
        image_dir = os.path.join(upper_dir_path, "image")
        os.makedirs(image_dir, exist_ok=True)
        layer_meta = self.layer_meta_file_path(upper_dir_path)
        if os.path.exists(layer_meta):
            raise errdefs.AlreadyExists(f"layer bootstrap {layer_meta} exists")

        layer_tar = self.layer_tar_file_path(layer_blob_id)
        # Unique per-call temp names: two snapshots of the same layer digest
        # (different images sharing a base layer) may prepare concurrently.
        suffix = f".{snapshot_id}.{os.getpid()}.tarfs.tmp"
        tar_tmp = layer_tar + suffix
        meta_tmp = layer_meta + suffix
        try:
            with open(tar_tmp, "wb") as f:
                f.write(tar_bytes)
            with open(tar_tmp, "rb") as f:
                bootstrap = tarfs_bootstrap_from_tar(
                    f, layer_blob_id, engine=self.engine
                )
            with open(meta_tmp, "wb") as f:
                f.write(bootstrap.to_bytes())
            os.rename(tar_tmp, layer_tar)
            os.rename(meta_tmp, layer_meta)
        finally:
            for tmp in (tar_tmp, meta_tmp):
                if os.path.exists(tmp):
                    os.unlink(tmp)

    # -- status (tarfs.go:727-752) -------------------------------------------

    def _get_status(self, snapshot_id: str) -> _SnapshotStatus:
        with self._mu:
            st = self.snapshot_map.get(snapshot_id)
        if st is None:
            raise errdefs.NotFound(f"not found snapshot {snapshot_id}")
        return st

    def wait_layer_ready(self, snapshot_id: str, timeout: float = 120.0) -> None:
        st = self._get_status(snapshot_id)
        if not st.done.wait(timeout):
            raise errdefs.Unavailable(
                f"tarfs conversion for snapshot {snapshot_id} timed out"
            )
        if st.status != TARFS_STATUS_READY:
            raise errdefs.Unavailable(
                f"snapshot {snapshot_id} is in state {st.status} instead of ready"
            )

    # -- merge (tarfs.go:411-464) --------------------------------------------

    def merge_layers(self, snapshot, storage_locator: Callable[[str], str]) -> None:
        if not snapshot.parent_ids:
            raise errdefs.InvalidArgument("tarfs merge needs parent layers")
        merged = self.image_meta_file_path(storage_locator(snapshot.parent_ids[0]))
        if os.path.exists(merged):
            return
        boots: list[Bootstrap] = []
        for snapshot_id in reversed(snapshot.parent_ids):  # low to high
            self.wait_layer_ready(snapshot_id)
            meta = self.layer_meta_file_path(storage_locator(snapshot_id))
            with open(meta, "rb") as f:
                boots.append(Bootstrap.from_bytes(f.read()))
        result = Merge(boots, MergeOption())
        tmp = merged + ".tarfs.tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(result.bootstrap)
            os.rename(tmp, merged)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    # -- block export (tarfs.go:466-571) -------------------------------------

    def export_block_data(
        self, snapshot, per_layer: bool, snap_labels: dict,
        storage_locator: Callable[[str], str],
    ) -> list[str]:
        update_fields: list[str] = []
        flags = self.export_flags
        if not flags.export_disk and not flags.with_verity:
            return update_fields
        if (not flags.whole_image) != per_layer:
            # `layer_block` special case (tarfs.go:478-487)
            if flags.export_disk and not flags.with_verity and not per_layer:
                snap_labels[C.NYDUS_LAYER_BLOCK_INFO] = ""
                update_fields.append("labels." + C.NYDUS_LAYER_BLOCK_INFO)
            return update_fields

        if per_layer:
            snapshot_id = snapshot.id
        else:
            if not snapshot.parent_ids:
                raise errdefs.InvalidArgument(f"snapshot {snapshot.id} has no parent")
            snapshot_id = snapshot.parent_ids[0]
        self.wait_layer_ready(snapshot_id)

        blob_id = snap_labels.get(C.NYDUS_TARFS_LAYER)
        if not blob_id:
            raise errdefs.InvalidArgument(
                f"missing nydus tarfs layer annotation for snapshot {snapshot.id}"
            )

        if flags.whole_image:
            meta_file = self.image_meta_file_path(storage_locator(snapshot_id))
            disk_file = self.image_disk_file_path(blob_id)
        else:
            meta_file = self.layer_meta_file_path(storage_locator(snapshot_id))
            disk_file = self.layer_disk_file_path(blob_id)

        if not os.path.exists(disk_file):
            info = self._export_disk(meta_file, disk_file, flags.with_verity)
        elif flags.with_verity:
            # Disk already exported (another snapshot of the same image):
            # reuse its persisted verity info instead of dropping it.
            with open(disk_file + ".verity.json") as f:
                info = verity.VerityInfo(**json.load(f))
        else:
            info = None
        block_info = info.block_info_label() if flags.with_verity and info else ""
        if flags.whole_image:
            snap_labels[C.NYDUS_IMAGE_BLOCK_INFO] = block_info
            update_fields.append("labels." + C.NYDUS_IMAGE_BLOCK_INFO)
        else:
            snap_labels[C.NYDUS_LAYER_BLOCK_INFO] = block_info
            update_fields.append("labels." + C.NYDUS_LAYER_BLOCK_INFO)
        return update_fields

    def _export_disk(
        self, meta_file: str, disk_file: str, with_verity: bool
    ) -> Optional[verity.VerityInfo]:
        """``nydus-image export --block [--verity]`` equivalent: one
        self-contained, kernel-mountable EROFS image — metadata plus the
        referenced tar blobs, chunks addressing the primary device
        (models/erofs_image.write_erofs_disk) — then the dm-verity tree."""
        from nydus_snapshotter_tpu.models.erofs_image import write_erofs_disk

        with open(meta_file, "rb") as f:
            bootstrap = Bootstrap.from_bytes(f.read())
        tmp = disk_file + ".tarfs.tmp"
        try:
            with open(tmp, "w+b") as img:
                data_size = write_erofs_disk(
                    bootstrap, self.layer_tar_file_path, img
                )
                info = verity.append_tree(img, data_size) if with_verity else None
            if info is not None:
                with open(disk_file + ".verity.json", "w") as f:
                    json.dump(
                        {
                            "data_blocks": info.data_blocks,
                            "hash_offset": info.hash_offset,
                            "root_hash": info.root_hash,
                        },
                        f,
                    )
            os.rename(tmp, disk_file)
            return info
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    # -- mount (tarfs.go:573-662) --------------------------------------------

    def mount_tar_erofs(self, snapshot_id: str, snapshot, snap_labels: dict, rafs) -> None:
        if snapshot is None:
            raise errdefs.InvalidArgument("snapshot object for mount_tar_erofs is nil")
        self._copy_tarfs_annotations(snap_labels, rafs)
        upper_dir = os.path.join(rafs.snapshot_dir, "fs")
        if not self.mount_on_host:
            rafs.mountpoint = upper_dir
            return

        merged_bootstrap = self.image_meta_file_path(upper_dir)
        with open(merged_bootstrap, "rb") as f:
            merged = Bootstrap.from_bytes(f.read())

        # The kernel maps the -o device= list POSITIONALLY onto the meta
        # image's device table, which erofs_from_rafs emits in blob-table
        # order — so the loop devices must be collected per blob-table
        # entry, not per parent-chain order.
        status_by_blob: dict[str, _SnapshotStatus] = {}
        for sid in snapshot.parent_ids:
            self.wait_layer_ready(sid)
            lst = self._get_status(sid)
            with lst.lock:
                status_by_blob[lst.blob_id] = lst
        devices = []
        # Pin each validated device with an open fd until the mount holds
        # it: autoclear fires when the LAST reference drops, so without a
        # pin a concurrent remove of a sharing image could reap + re-bind
        # the index between validation and mount(2) — the mount would then
        # read another snapshot's bytes. An open fd is a reference, so the
        # kernel cannot reap the loop inside the window.
        pin_fds: list[int] = []
        try:
            for blob in merged.blobs:
                lst = status_by_blob.get(blob.blob_id)
                if lst is None:
                    raise errdefs.NotFound(
                        f"no prepared layer tar for blob {blob.blob_id}"
                    )
                with lst.lock:
                    dev = lst.data_loopdev
                    # AUTOCLEAR hands loop lifetime to the kernel: a cached
                    # handle may be unbound (reaped with a previous mount)
                    # or re-bound to an unrelated file — validate before
                    # reuse, and re-validate after pinning (the reap could
                    # land between the check and the open).
                    dev = self._pin_validated(
                        dev, lst.blob_tar_file_path, pin_fds
                    )
                    if dev is None:
                        with self._loop_mu:
                            dev = losetup.attach(lst.blob_tar_file_path)
                        self._pin(dev, pin_fds)
                        lst.data_loopdev = dev
                    devices.append("device=" + dev.path)
            mount_opts = ",".join(devices)
            self._mount_meta(
                snapshot_id, snapshot, rafs, merged_bootstrap, merged,
                mount_opts, status_by_blob, pin_fds,
            )
        finally:
            for fd in pin_fds:
                try:
                    os.close(fd)
                except OSError:
                    pass

    def _pin(self, dev, pin_fds: list) -> None:
        try:
            pin_fds.append(os.open(dev.path, os.O_RDONLY))
        except OSError:
            pass  # fake/test backends have no real device nodes

    def _pin_validated(self, dev, path: str, pin_fds: list):
        """Pin dev if (still) backed by path; None if it must be re-made."""
        if dev is None or not losetup.still_backed_by(dev, path):
            return None
        self._pin(dev, pin_fds)
        # re-check under the pin: a reap between validate and open would
        # have let the index re-bind; pinned-and-matching cannot change.
        if not losetup.still_backed_by(dev, path):
            return None
        return dev

    def _mount_meta(
        self, snapshot_id: str, snapshot, rafs, merged_bootstrap: str,
        merged, mount_opts: str, status_by_blob: dict, pin_fds: list,
    ) -> None:

        # The kernel mounts an EROFS meta image, not the internal merged
        # bootstrap: export it next to the bootstrap on first mount
        # (reference: `nydus-image export --block` produces the block image;
        # here models/erofs_image writes it in-process).
        meta_image = merged_bootstrap + ".erofs"
        if not os.path.exists(meta_image):
            from nydus_snapshotter_tpu.models.erofs_image import erofs_from_rafs

            # Unique temp per writer: two concurrent first-mounts must not
            # share (and truncate) one tmp file; whoever renames first wins
            # and the loser's identical image is discarded.
            fd, tmp = tempfile.mkstemp(
                prefix=os.path.basename(meta_image) + ".",
                dir=os.path.dirname(meta_image),
            )
            try:
                os.fchmod(fd, 0o644)  # mkstemp's 0600 would hide the image from non-root readers
                with os.fdopen(fd, "wb") as f:
                    f.write(erofs_from_rafs(merged))
                os.rename(tmp, meta_image)
            except BaseException:
                with contextlib.suppress(OSError):
                    os.unlink(tmp)
                raise

        st = self._get_status(snapshot_id)
        mountpoint = os.path.join(rafs.snapshot_dir, "mnt")
        with st.lock:
            if st.erofs_mountpoint:
                if st.erofs_mountpoint == mountpoint:
                    rafs.mountpoint = mountpoint
                    return
                raise errdefs.AlreadyExists(
                    f"tarfs for snapshot {snapshot_id} already mounted at {st.erofs_mountpoint}"
                )
            meta_dev = self._pin_validated(st.meta_loopdev, meta_image, pin_fds)
            if meta_dev is None:
                with self._loop_mu:
                    meta_dev = losetup.attach(meta_image)
                self._pin(meta_dev, pin_fds)
                st.meta_loopdev = meta_dev
                st.meta_image_path = meta_image
            mount_utils.mount(meta_dev.path, mountpoint, "erofs", mount_opts)
            st.erofs_mountpoint = mountpoint
        # Now that the mount holds every device, flag AUTOCLEAR so the
        # kernel reaps the loops when the mount goes away — a crash-
        # restarted snapshotter that can only unmount by path (its
        # in-memory loop handles are gone) then strands nothing. Outside
        # st.lock: snapshot_id is usually its own topmost parent, so
        # re-locking parent statuses here would self-deadlock. meta_dev is
        # the locally-captured handle (st.meta_loopdev may be nulled by a
        # concurrent detach); the data handles are re-read under their
        # locks with None guards.
        losetup.set_autoclear(meta_dev)
        for lst in status_by_blob.values():
            with lst.lock:
                if lst.data_loopdev is not None:
                    losetup.set_autoclear(lst.data_loopdev)
        rafs.mountpoint = mountpoint

    def umount_tar_erofs(self, snapshot_id: str, mountpoint: str = "") -> None:
        """Unmount a tarfs EROFS mount. The in-memory status survives only
        one snapshotter process, but the KERNEL mount survives restarts —
        after a crash-restart the caller supplies the persisted instance's
        mountpoint (rafs.mountpoint, replayed from the db) so the mount
        never leaks (the reference recovers the same way: instance records
        are the durable truth, tarfs.go vestige handling)."""
        with self._mu:
            st = self.snapshot_map.get(snapshot_id)
        if st is not None:
            with st.lock:
                if st.erofs_mountpoint:
                    mount_utils.umount(st.erofs_mountpoint)
                    st.erofs_mountpoint = ""
            return
        if mountpoint and os.path.ismount(mountpoint):
            mount_utils.umount(mountpoint)

    def detach_layer(self, snapshot_id: str) -> None:
        with self._mu:
            st = self.snapshot_map.get(snapshot_id)
        if st is not None:
            with st.lock:
                if st.erofs_mountpoint:
                    mount_utils.umount(st.erofs_mountpoint)
                    st.erofs_mountpoint = ""
                # AUTOCLEAR may have reaped these handles with the mount —
                # and LOOP_CTL_GET_FREE may have re-bound the same index to
                # an unrelated snapshot. Only detach a loop that is still
                # OURS; a stale handle is just dropped.
                if st.meta_loopdev is not None:
                    if losetup.still_backed_by(
                        st.meta_loopdev, st.meta_image_path
                    ):
                        st.meta_loopdev.detach()
                    st.meta_loopdev = None
                if st.data_loopdev is not None:
                    if losetup.still_backed_by(
                        st.data_loopdev, st.blob_tar_file_path
                    ):
                        st.data_loopdev.detach()
                    st.data_loopdev = None
        with self._mu:
            self.snapshot_map.pop(snapshot_id, None)

    # -- annotations + paths (tarfs.go:814-845) ------------------------------

    def _copy_tarfs_annotations(self, snap_labels: dict, rafs) -> None:
        for key in (C.NYDUS_TARFS_LAYER, C.NYDUS_IMAGE_BLOCK_INFO, C.NYDUS_LAYER_BLOCK_INFO):
            if key in snap_labels:
                rafs.annotations[key] = snap_labels[key]

    def layer_tar_file_path(self, blob_id: str) -> str:
        return os.path.join(self.cache_dir_path, blob_id)

    def layer_disk_file_path(self, blob_id: str) -> str:
        return os.path.join(self.cache_dir_path, f"{blob_id}.{LAYER_DISK_NAME}")

    def image_disk_file_path(self, blob_id: str) -> str:
        return os.path.join(self.cache_dir_path, f"{blob_id}.{IMAGE_DISK_NAME}")

    def layer_meta_file_path(self, upper_dir_path: str) -> str:
        return os.path.join(upper_dir_path, "image", LAYER_BOOTSTRAP_NAME)

    def image_meta_file_path(self, upper_dir_path: str) -> str:
        return os.path.join(upper_dir_path, "image", IMAGE_BOOTSTRAP_NAME)
