"""dm-verity hash-tree builder for exported block images.

Reference: ``nydus-image export --block --verity`` emits the line parsed at
pkg/tarfs/tarfs.go:547-554 — ``dm-verity options: --no-superblock
--format=1 -s "" --hash=sha256 --data-block-size=512
--hash-block-size=4096 --data-blocks N --hash-offset H <root>``. This
module computes that tree in-process: a standard dm-verity Merkle tree
(sha256, empty salt, no superblock) with the hash area appended to the
data area, levels stored top-down.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import BinaryIO

DATA_BLOCK_SIZE = 512
HASH_BLOCK_SIZE = 4096
DIGEST_SIZE = 32
_PER_BLOCK = HASH_BLOCK_SIZE // DIGEST_SIZE  # 128 digests per hash block


@dataclass
class VerityInfo:
    data_blocks: int
    hash_offset: int  # byte offset of the hash area within the image file
    root_hash: str  # hex sha256

    def block_info_label(self) -> str:
        """`<data_blocks>,<hash_offset>,sha256:<root>` — the label format
        stored under nydus-image-block / nydus-layer-block
        (tarfs.go:555-562)."""
        return f"{self.data_blocks},{self.hash_offset},sha256:{self.root_hash}"


def parse_block_info_label(value: str) -> VerityInfo:
    data_blocks, hash_offset, root = value.split(",")
    if not root.startswith("sha256:"):
        raise ValueError(f"bad verity root in block info {value!r}")
    return VerityInfo(int(data_blocks), int(hash_offset), root[len("sha256:") :])


def _level_digests(blocks: list[bytes]) -> bytes:
    return b"".join(hashlib.sha256(b).digest() for b in blocks)


def _pack_hash_blocks(digests: bytes) -> list[bytes]:
    """Pack concatenated digests into zero-padded hash blocks."""
    blocks = []
    for off in range(0, len(digests), _PER_BLOCK * DIGEST_SIZE):
        chunk = digests[off : off + _PER_BLOCK * DIGEST_SIZE]
        blocks.append(chunk.ljust(HASH_BLOCK_SIZE, b"\x00"))
    return blocks


def build_tree(data: bytes) -> tuple[bytes, VerityInfo]:
    """(hash_area_bytes, info) for ``data``.

    ``data`` must be 512-aligned (the exporter pads). Levels are laid out
    top-down (root level first) as dm-verity expects with --no-superblock;
    hash_offset is filled in by the caller once the data-area size is known
    (the returned info carries hash_offset == len(data), i.e. the tree is
    appended immediately after the data area).
    """
    if len(data) % DATA_BLOCK_SIZE:
        raise ValueError("verity data area must be a multiple of 512 bytes")
    data_blocks = len(data) // DATA_BLOCK_SIZE

    if data_blocks == 0:
        empty_root = hashlib.sha256(b"\x00" * HASH_BLOCK_SIZE).hexdigest()
        return b"", VerityInfo(0, len(data), empty_root)

    level = _pack_hash_blocks(
        _level_digests(
            [data[i : i + DATA_BLOCK_SIZE] for i in range(0, len(data), DATA_BLOCK_SIZE)]
        )
    )
    levels: list[list[bytes]] = [level]
    while len(levels[-1]) > 1:
        levels.append(_pack_hash_blocks(_level_digests(levels[-1])))

    root_hash = hashlib.sha256(levels[-1][0]).hexdigest()
    # Store top-down: root level first, widest (level 0) last.
    tree = b"".join(b for lvl in reversed(levels) for b in lvl)
    return tree, VerityInfo(data_blocks, len(data), root_hash)


def verify(data: bytes, info: VerityInfo, tree: bytes) -> bool:
    """Recompute the tree and compare the root — the integrity check a
    dm-verity target performs block-by-block, done wholesale."""
    rebuilt, rebuilt_info = build_tree(data)
    return (
        rebuilt == tree
        and rebuilt_info.data_blocks == info.data_blocks
        and rebuilt_info.root_hash == info.root_hash
    )


def append_tree(image: BinaryIO, data_size: int) -> VerityInfo:
    """Build the tree over the first ``data_size`` bytes of ``image`` and
    append it; returns the final info with hash_offset set."""
    image.seek(0)
    data = image.read(data_size)
    tree, info = build_tree(data)
    image.seek(0, 2)
    pad = (-image.tell()) % HASH_BLOCK_SIZE
    if pad:
        image.write(b"\x00" * pad)
    info.hash_offset = image.tell()
    image.write(tree)
    return info
