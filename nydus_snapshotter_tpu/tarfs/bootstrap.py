"""tar-tarfs bootstrap: index a plain tar file as a RAFS layer in place.

Replaces the reference's ``nydus-image create --type tar-tarfs``
(pkg/tarfs/tarfs.go:253-270): the uncompressed layer tar itself is the data
blob; the bootstrap's chunks point straight at each file's data region
inside the tar (offset = tar data offset), so the kernel can read file
contents from a loop-attached tar with zero copies.

Chunk digests are computed over the indexed regions with the same batched
SHA-256 engine the converter uses, so this build source exercises the TPU
digest path exactly like Pack does (SURVEY §7 stage 5).
"""

from __future__ import annotations

import hashlib
import stat as statmod
import tarfile
from typing import BinaryIO, Optional

from nydus_snapshotter_tpu.models import fstree, layout
from nydus_snapshotter_tpu.models.bootstrap import (
    INODE_FLAG_OPAQUE,
    INODE_FLAG_WHITEOUT,
    BlobRecord,
    Bootstrap,
    ChunkRecord,
    Inode,
)
from nydus_snapshotter_tpu.models.fstree import (
    OPAQUE_MARKER,
    OPAQUE_XATTR,
    WHITEOUT_PREFIX,
    FileEntry,
)

DEFAULT_CHUNK_SIZE = 0x400000


def _digest_regions(
    blob: BinaryIO, regions: list[tuple[int, int]], engine=None
) -> list[bytes]:
    """sha256 per (offset, size) region; routed through the converter's
    batched engine when one is supplied, host hashlib otherwise."""
    datas = []
    for off, size in regions:
        blob.seek(off)
        datas.append(blob.read(size))
    if engine is not None:
        return engine.digest_many(datas)
    return [hashlib.sha256(d).digest() for d in datas]


def tarfs_bootstrap_from_tar(
    tar_file: BinaryIO,
    blob_id: str,
    chunk_size: int = DEFAULT_CHUNK_SIZE,
    fs_version: str = layout.RAFS_V6,
    engine=None,
) -> Bootstrap:
    """Index ``tar_file`` (seekable, uncompressed) into a layer bootstrap.

    Whiteout markers get the same RAFS normalization as the converter path
    (fstree.tree_from_tar) so converter.Merge overlays tarfs layers
    identically.
    """
    entries: dict[str, FileEntry] = {}
    opaque_dirs: list[str] = []
    # path -> list of (tar data offset, size) chunk regions
    regions: dict[str, list[tuple[int, int]]] = {}

    tar_file.seek(0)
    tf = tarfile.open(fileobj=tar_file, mode="r:")
    for info in tf:
        path = fstree._norm(info.name)
        base = path.rsplit("/", 1)[1] if path != "/" else "/"
        if base == OPAQUE_MARKER:
            opaque_dirs.append(path.rsplit("/", 1)[0] or "/")
            continue
        if base.startswith(WHITEOUT_PREFIX):
            target = fstree._norm(
                path.rsplit("/", 1)[0] + "/" + base[len(WHITEOUT_PREFIX) :]
            )
            entries[target] = FileEntry(
                path=target, mode=statmod.S_IFCHR, flags=INODE_FLAG_WHITEOUT
            )
            continue
        entry = fstree.entry_from_tarinfo(tf, info, path, with_data=False)
        entries[path] = entry
        # last member wins: a replacement entry must not inherit a prior
        # regular file's data regions
        regions.pop(path, None)
        if info.isreg() and info.size > 0:
            file_regions = []
            off = info.offset_data
            remaining = info.size
            while remaining > 0:
                step = min(chunk_size, remaining)
                file_regions.append((off, step))
                off += step
                remaining -= step
            regions[path] = file_regions

    for d in opaque_dirs:
        if d not in entries:
            entries[d] = FileEntry(path=d, mode=statmod.S_IFDIR | 0o755)
        entries[d].flags |= INODE_FLAG_OPAQUE
        entries[d].xattrs[OPAQUE_XATTR] = b"y"

    ordered = fstree.ensure_parents(sorted(entries.values(), key=lambda e: e.path))

    # Flatten all regions (stable path order) for one batched digest pass.
    flat: list[tuple[int, int]] = []
    spans: dict[str, tuple[int, int]] = {}  # path -> (start, count) in flat
    for e in ordered:
        rs = regions.get(e.path)
        if rs:
            spans[e.path] = (len(flat), len(rs))
            flat.extend(rs)
    digests = _digest_regions(tar_file, flat, engine=engine)

    tar_file.seek(0, 2)
    tar_size = tar_file.tell()

    inodes: list[Inode] = []
    chunks: list[ChunkRecord] = []
    for e in ordered:
        inode = fstree.entry_to_inode(e)
        span = spans.get(e.path)
        if span is not None:
            start, count = span
            inode.chunk_index = len(chunks)
            inode.chunk_count = count
            # regular-file size is not derivable from e.data (not loaded)
            inode.size = sum(size for _, size in flat[start : start + count])
            for (off, size), digest in zip(
                flat[start : start + count], digests[start : start + count]
            ):
                chunks.append(
                    ChunkRecord(
                        digest=digest,
                        blob_index=0,
                        # the tar IS the uncompressed blob: both offsets
                        # are tar offsets, compression is identity
                        uncompressed_offset=off,
                        compressed_offset=off,
                        uncompressed_size=size,
                        compressed_size=size,
                    )
                )
        inodes.append(inode)

    blob = BlobRecord(
        blob_id=blob_id,
        compressed_size=tar_size,
        uncompressed_size=tar_size,
        chunk_count=len(chunks),
    )
    return Bootstrap(
        version=fs_version,
        chunk_size=chunk_size,
        inodes=inodes,
        chunks=chunks,
        blobs=[blob],
    )
