"""Minimal read-only bbolt (boltdb) file reader.

The reference snapshotter persists daemon/instance state in a bbolt
database (``/root/reference/pkg/store/database.go``: bucket hierarchy
v1 → daemons/instances, JSON values). This framework's store is sqlite
(store/database.py), so migrating a live deployment off the reference
needs to READ its old ``nydus.db`` — that, plus consuming the reference's
committed binary fixtures (``pkg/store/testdata/*.db``,
``pkg/stargz/testdata/db/nydus.db``), is exactly what this module covers.
Read-only on purpose: nothing here ever writes the bolt format.

Format (bbolt on-disk):
  page header (16 B): id u64 | flags u16 | count u16 | overflow u32
  flags: 0x01 branch, 0x02 leaf, 0x04 meta, 0x10 freelist
  meta payload: magic u32 (0xED0CDAED) | version u32 (2) | pageSize u32 |
    flags u32 | root bucket {root pgid u64, sequence u64} | freelist u64 |
    pgid u64 | txid u64 | checksum u64 (FNV-1a over the first 64 B)
  leaf element (16 B): flags u32 | pos u32 | ksize u32 | vsize u32
    (pos is relative to the element's own offset)
  branch element (16 B): pos u32 | ksize u32 | pgid u64
  bucket value: {root pgid u64, sequence u64}; root == 0 ⇒ the bucket is
    inline and the page follows those 16 bytes.
"""

from __future__ import annotations

import struct
from typing import Iterator, Optional

MAGIC = 0xED0CDAED
VERSION = 2

_PAGE_HDR = struct.Struct("<QHHI")
_META = struct.Struct("<IIII QQ Q Q Q Q")
_LEAF_ELEM = struct.Struct("<IIII")
_BRANCH_ELEM = struct.Struct("<IIQ")

FLAG_BRANCH = 0x01
FLAG_LEAF = 0x02
FLAG_META = 0x04
LEAF_FLAG_BUCKET = 0x01


class BoltError(ValueError):
    pass


def _fnv1a(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


class Bucket:
    """A bucket positioned at a root page (or an inline page buffer)."""

    def __init__(self, db: "BoltDB", root: int, inline: Optional[bytes] = None):
        self._db = db
        self._root = root
        self._inline = inline

    def _walk(
        self,
        page: Optional[bytes] = None,
        depth: int = 0,
        budget: Optional[list[int]] = None,
    ) -> Iterator[tuple[int, bytes, bytes]]:
        """Yield (elem_flags, key, value) across the bucket's B+tree.

        Defensive against corrupt/crafted files (this reader ingests
        untrusted legacy databases): element tables must fit the page,
        branch depth is capped, and total pages visited per walk is
        bounded by the file's page count — a legitimate tree visits each
        page at most once, so a cycle (even a wide one whose path count
        would explode combinatorially under a depth cap alone) raises
        instead of hanging.
        """
        if depth > 64:  # bolt trees are a few levels; a cycle is corruption
            raise BoltError("branch chain exceeds max depth (page cycle?)")
        if budget is None:
            budget = [len(self._db._buf) // max(1, self._db.page_size) + 2]
        budget[0] -= 1
        if budget[0] < 0:
            raise BoltError("walk visited more pages than the file holds (cycle?)")
        if page is None:
            page = self._inline if self._inline is not None else self._db._page(self._root)
        if len(page) < 16:
            raise BoltError("page shorter than its header")
        pid, flags, count, overflow = _PAGE_HDR.unpack_from(page, 0)
        if flags & FLAG_LEAF:
            if 16 + count * _LEAF_ELEM.size > len(page):
                raise BoltError(f"leaf page {pid}: element table beyond page")
            for i in range(count):
                off = 16 + i * _LEAF_ELEM.size
                eflags, pos, ksize, vsize = _LEAF_ELEM.unpack_from(page, off)
                k0 = off + pos
                if k0 + ksize + vsize > len(page):
                    raise BoltError(f"leaf page {pid}: element data beyond page")
                yield eflags, bytes(page[k0 : k0 + ksize]), bytes(
                    page[k0 + ksize : k0 + ksize + vsize]
                )
        elif flags & FLAG_BRANCH:
            if 16 + count * _BRANCH_ELEM.size > len(page):
                raise BoltError(f"branch page {pid}: element table beyond page")
            for i in range(count):
                off = 16 + i * _BRANCH_ELEM.size
                _pos, _ksize, child = _BRANCH_ELEM.unpack_from(page, off)
                yield from self._walk(self._db._page(child), depth + 1, budget)
        else:
            raise BoltError(f"page {pid} has unexpected flags {flags:#x}")

    def items(self) -> Iterator[tuple[bytes, bytes]]:
        """(key, value) pairs for plain entries (nested buckets excluded)."""
        for eflags, k, v in self._walk():
            if not eflags & LEAF_FLAG_BUCKET:
                yield k, v

    def buckets(self) -> Iterator[tuple[bytes, "Bucket"]]:
        for eflags, k, v in self._walk():
            if eflags & LEAF_FLAG_BUCKET:
                yield k, self._db._open_bucket_value(v)

    def bucket(self, name: bytes) -> Optional["Bucket"]:
        for k, b in self.buckets():
            if k == name:
                return b
        return None


class BoltDB:
    """Read-only view over a bbolt file (fully loaded into memory —
    reference state databases are tens of KiB)."""

    def __init__(self, path: str):
        with open(path, "rb") as f:
            self._buf = f.read()
        if len(self._buf) < 2 * 4096:
            raise BoltError("file too small for a bolt database")
        # Meta 0 sits at offset 0; meta 1 sits at offset pageSize, which
        # bolt takes from os.Getpagesize() at creation (4 KiB on x86, but
        # 16/64 KiB on some arm64/ppc64le hosts) — so meta 0's declared
        # page size locates meta 1, with a scan over common sizes as the
        # fallback when meta 0 itself is torn.
        metas = []
        m0 = self._meta_at(0)
        if m0 is not None:
            metas.append(m0)
            m1 = self._meta_at(m0["page_size"])
            if m1 is not None:
                metas.append(m1)
        else:
            for ps in (4096, 8192, 16384, 32768, 65536):
                m1 = self._meta_at(ps)
                if m1 is not None:
                    metas.append(m1)
                    break
        if not metas:
            raise BoltError("no valid bolt meta page (bad magic/version/checksum)")
        # bolt keeps two meta pages and uses the valid one with max txid
        meta = max(metas, key=lambda m: m["txid"])
        self.page_size = meta["page_size"]
        self._root = meta["root"]

    def _meta_at(self, base: int):
        hdr = self._buf[base : base + 16]
        if len(hdr) < 16:
            return None
        _pid, flags, _count, _ovf = _PAGE_HDR.unpack_from(hdr, 0)
        if not flags & FLAG_META:
            return None
        body = self._buf[base + 16 : base + 16 + _META.size]
        if len(body) < _META.size:
            return None
        (magic, version, page_size, _flags, root, _seq, _freelist, _pgid,
         txid, checksum) = _META.unpack_from(body, 0)
        if magic != MAGIC or version != VERSION:
            return None
        if checksum and checksum != _fnv1a(body[: _META.size - 8]):
            return None
        return {"page_size": page_size, "root": root, "txid": txid}

    def _page(self, pgid: int) -> bytes:
        base = pgid * self.page_size
        if base + 16 > len(self._buf):
            raise BoltError(f"page {pgid} beyond end of file")
        _pid, _flags, _count, overflow = _PAGE_HDR.unpack_from(self._buf, base)
        end = base + (1 + overflow) * self.page_size
        return self._buf[base:end]

    def _open_bucket_value(self, value: bytes) -> Bucket:
        if len(value) < 16:
            raise BoltError("bucket value shorter than bucket header")
        root, _seq = struct.unpack_from("<QQ", value, 0)
        if root == 0:  # inline bucket: page follows the header
            return Bucket(self, 0, inline=value[16:])
        return Bucket(self, root)

    def root(self) -> Bucket:
        return Bucket(self, self._root)

    def bucket(self, *names: bytes) -> Optional[Bucket]:
        b: Optional[Bucket] = self.root()
        for name in names:
            if b is None:
                return None
            b = b.bucket(name)
        return b
