"""Crash-consistent state database.

Reference pkg/store/database.go:36-331 keeps two bbolt buckets
(``v1/daemons``, ``v1/instances``) of JSON values plus a monotonic instance
sequence used to replay mounts in creation order after a restart
(rafs.go:112-117), with schema-version migration (database_compat.go).

Re-implemented on sqlite3 (stdlib, transactional): same record semantics,
same JSON value encoding, same monotonic-seq guarantee (survives deletes),
same versioned-schema migration hook.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
from typing import Any, Callable, Iterator, Optional

from nydus_snapshotter_tpu.utils import errdefs

SCHEMA_VERSION = 1


class StoreError(errdefs.NydusError):
    pass


class Database:
    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.RLock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._init_schema()

    def _init_schema(self) -> None:
        with self._lock, self._conn:
            c = self._conn
            c.execute("CREATE TABLE IF NOT EXISTS meta (key TEXT PRIMARY KEY, value TEXT)")
            c.execute(
                "CREATE TABLE IF NOT EXISTS daemons (id TEXT PRIMARY KEY, value TEXT NOT NULL)"
            )
            c.execute(
                "CREATE TABLE IF NOT EXISTS instances ("
                "snapshot_id TEXT PRIMARY KEY, value TEXT NOT NULL, seq INTEGER NOT NULL)"
            )
            c.execute("CREATE TABLE IF NOT EXISTS seqs (name TEXT PRIMARY KEY, next INTEGER)")
            row = c.execute("SELECT value FROM meta WHERE key='schema_version'").fetchone()
            if row is None:
                c.execute(
                    "INSERT INTO meta (key, value) VALUES ('schema_version', ?)",
                    (str(SCHEMA_VERSION),),
                )
            else:
                self._migrate(int(row[0]))

    def _migrate(self, from_version: int) -> None:
        """Versioned migration (reference database_compat.go). v1 is current."""
        if from_version == SCHEMA_VERSION:
            return
        if from_version > SCHEMA_VERSION:
            raise StoreError(
                f"database schema {from_version} is newer than supported {SCHEMA_VERSION}"
            )
        # Future upgrades: apply stepwise migrations here, then bump.
        self._conn.execute(
            "UPDATE meta SET value=? WHERE key='schema_version'", (str(SCHEMA_VERSION),)
        )

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    # -- daemons ------------------------------------------------------------

    def save_daemon(self, daemon_id: str, state: dict[str, Any]) -> None:
        with self._lock, self._conn:
            try:
                self._conn.execute(
                    "INSERT INTO daemons (id, value) VALUES (?, ?)",
                    (daemon_id, json.dumps(state, sort_keys=True)),
                )
            except sqlite3.IntegrityError as e:
                raise errdefs.AlreadyExists(f"daemon {daemon_id} already saved") from e

    def update_daemon(self, daemon_id: str, state: dict[str, Any]) -> None:
        with self._lock, self._conn:
            cur = self._conn.execute(
                "UPDATE daemons SET value=? WHERE id=?",
                (json.dumps(state, sort_keys=True), daemon_id),
            )
            if cur.rowcount == 0:
                raise errdefs.NotFound(f"daemon {daemon_id} not in store")

    def get_daemon(self, daemon_id: str) -> dict[str, Any]:
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM daemons WHERE id=?", (daemon_id,)
            ).fetchone()
        if row is None:
            raise errdefs.NotFound(f"daemon {daemon_id} not in store")
        return json.loads(row[0])

    def delete_daemon(self, daemon_id: str) -> None:
        with self._lock, self._conn:
            self._conn.execute("DELETE FROM daemons WHERE id=?", (daemon_id,))

    def walk_daemons(self) -> Iterator[dict[str, Any]]:
        with self._lock:
            rows = self._conn.execute("SELECT value FROM daemons ORDER BY id").fetchall()
        for (value,) in rows:
            yield json.loads(value)

    def cleanup_daemons(self) -> int:
        with self._lock, self._conn:
            return self._conn.execute("DELETE FROM daemons").rowcount

    # -- instances (RAFS) ---------------------------------------------------

    def next_instance_seq(self) -> int:
        """Monotonic sequence — survives deletes, mirrors bbolt's
        NextSequence (database.go:302)."""
        with self._lock, self._conn:
            self._conn.execute(
                "INSERT INTO seqs (name, next) VALUES ('instance', 1) "
                "ON CONFLICT(name) DO UPDATE SET next = next + 1"
            )
            (seq,) = self._conn.execute(
                "SELECT next FROM seqs WHERE name='instance'"
            ).fetchone()
            return int(seq)

    def save_instance(self, snapshot_id: str, state: dict[str, Any], seq: int) -> None:
        with self._lock, self._conn:
            try:
                self._conn.execute(
                    "INSERT INTO instances (snapshot_id, value, seq) VALUES (?, ?, ?)",
                    (snapshot_id, json.dumps(state, sort_keys=True), seq),
                )
            except sqlite3.IntegrityError as e:
                raise errdefs.AlreadyExists(f"instance {snapshot_id} already saved") from e

    def get_instance(self, snapshot_id: str) -> dict[str, Any]:
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM instances WHERE snapshot_id=?", (snapshot_id,)
            ).fetchone()
        if row is None:
            raise errdefs.NotFound(f"instance {snapshot_id} not in store")
        return json.loads(row[0])

    def delete_instance(self, snapshot_id: str) -> None:
        with self._lock, self._conn:
            self._conn.execute("DELETE FROM instances WHERE snapshot_id=?", (snapshot_id,))

    def walk_instances(self) -> Iterator[tuple[dict[str, Any], int]]:
        """Yield (state, seq) in seq order — the mount replay order."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT value, seq FROM instances ORDER BY seq"
            ).fetchall()
        for value, seq in rows:
            yield json.loads(value), int(seq)

    def import_legacy_bolt(self, path: str) -> tuple[int, int]:
        """Import a reference-snapshotter bbolt database (nydus.db).

        Handles both on-disk generations the reference migrates between
        (database.go:147-188): the legacy top-level ``daemons`` bucket and
        the ``v1`` hierarchy (v1/daemons + v1/instances). Values are the
        reference's JSON records, stored verbatim so the daemon manager's
        recovery can interpret them. Returns (daemons, instances) counts.
        """
        daemons, instances = load_legacy_bolt(path)
        n_daemons = n_instances = 0
        for rec in daemons:
            did = rec.get("ID") or rec.get("id")
            if not did:
                continue
            try:
                self.save_daemon(did, rec)
            except errdefs.AlreadyExists:
                self.update_daemon(did, rec)
            n_daemons += 1
        # Preserve the reference's recorded mount-replay order: its seq
        # field (rafs.go:112-117), not bbolt's lexical key order, decides
        # recovery order.
        instances = sorted(instances, key=lambda r: r.get("Seq", r.get("seq", 0)))
        for rec in instances:
            sid = rec.get("SnapshotID") or rec.get("snapshot_id")
            if not sid:
                continue
            try:
                self.save_instance(sid, rec, self.next_instance_seq())
                n_instances += 1
            except errdefs.AlreadyExists:
                pass  # idempotent re-import: the existing record wins
        return n_daemons, n_instances


def load_legacy_bolt(path: str) -> tuple[list[dict], list[dict]]:
    """(daemon records, instance records) from a reference bbolt file."""
    from nydus_snapshotter_tpu.store.boltdb import BoltDB

    db = BoltDB(path)
    daemons_bucket = db.bucket(b"v1", b"daemons") or db.bucket(b"daemons")
    instances_bucket = db.bucket(b"v1", b"instances")
    daemons = (
        [json.loads(v) for _k, v in daemons_bucket.items()]
        if daemons_bucket is not None
        else []
    )
    instances = (
        [json.loads(v) for _k, v in instances_bucket.items()]
        if instances_bucket is not None
        else []
    )
    return daemons, instances
