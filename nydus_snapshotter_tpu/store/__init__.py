"""Persistence for daemon + RAFS instance states (reference pkg/store)."""

from nydus_snapshotter_tpu.store.database import Database, StoreError  # noqa: F401
