"""Kubernetes dockerconfigjson secret store.

Reference pkg/auth/kubesecret.go:33-175 runs a client-go informer over
`kubernetes.io/dockerconfigjson` secrets and indexes their auth entries by
registry host. No kubernetes API client is baked into this environment, so
the TPU-era equivalent watches a secrets *directory* (the standard
projected-secret mount shape: one file per secret containing a
.dockerconfigjson document) and keeps the same host-indexed lookup; the
in-memory feed path (`add_dockerconfigjson`) is what an informer would
call on Add/Update events.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

from nydus_snapshotter_tpu.auth.keychain import PassKeyChain, entry_keychain

_lock = threading.Lock()
_by_host: dict[str, PassKeyChain] = {}


def add_dockerconfigjson(doc: bytes | str) -> None:
    """Index one .dockerconfigjson document (informer Add/Update path)."""
    if isinstance(doc, (bytes, bytearray)):
        doc = doc.decode()
    try:
        cfg = json.loads(doc)
    except ValueError:
        return
    with _lock:
        for key, entry in (cfg.get("auths") or {}).items():
            host = key.split("://", 1)[-1].rstrip("/").split("/")[0]
            kc = entry_keychain(entry)
            if kc is not None:
                _by_host[host] = kc


def load_secrets_dir(path: str) -> int:
    """Scan a projected-secrets directory; returns entries indexed."""
    count = 0
    try:
        names = os.listdir(path)
    except OSError:
        return 0
    for name in names:
        full = os.path.join(path, name)
        if not os.path.isfile(full):
            continue
        try:
            with open(full, "rb") as f:
                add_dockerconfigjson(f.read())
                count += 1
        except OSError:
            continue
    return count


def from_kube_secret(host: str) -> Optional[PassKeyChain]:
    if host == "docker.io":
        host = "index.docker.io"
    with _lock:
        return _by_host.get(host)


def reset() -> None:
    with _lock:
        _by_host.clear()
