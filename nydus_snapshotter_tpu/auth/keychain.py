"""User/password keychain + the layered credential lookup.

Reference pkg/auth/keychain.go:30-140.
"""

from __future__ import annotations

import base64
from dataclasses import dataclass
from typing import Mapping, Optional

from nydus_snapshotter_tpu import constants as C
from nydus_snapshotter_tpu.remote.reference import parse_docker_ref


@dataclass(frozen=True)
class PassKeyChain:
    username: str = ""
    password: str = ""

    def empty(self) -> bool:
        return not self.username and not self.password

    def token_base(self) -> bool:
        """Token-based when only a password (= registry token) is present
        (keychain.go:57-60)."""
        return self.username == "" and self.password != ""

    def to_base64(self) -> str:
        if self.empty():
            return ""
        return base64.b64encode(f"{self.username}:{self.password}".encode()).decode()


def from_base64(value: str) -> PassKeyChain:
    decoded = base64.b64decode(value).decode()
    user, sep, password = decoded.partition(":")
    # partition, not split: GCR-style passwords (JSON service-account keys)
    # legitimately contain colons.
    if not sep:
        raise ValueError("invalid registry auth token")
    return PassKeyChain(user, password)


def entry_keychain(entry: Mapping) -> Optional[PassKeyChain]:
    """Decode one dockerconfig ``auths`` entry (base64 ``auth`` field with
    username/password fallback); shared by the docker-config and
    kube-secret lookups."""
    auth_b64 = entry.get("auth", "")
    if auth_b64:
        try:
            kc = from_base64(auth_b64)
        except Exception:
            kc = None
        if kc is not None and kc.username and kc.password:
            return kc
    user, pw = entry.get("username", ""), entry.get("password", "")
    if user and pw:
        return PassKeyChain(user, pw)
    return None


def from_labels(labels: Mapping[str, str]) -> Optional[PassKeyChain]:
    """Image pull username/secret from snapshot labels
    (keychain.go:63-80); None means nothing usable was passed."""
    username = labels.get(C.NYDUS_IMAGE_PULL_USERNAME, "")
    secret = labels.get(C.NYDUS_IMAGE_PULL_SECRET, "")
    if not username or not secret:
        return None
    return PassKeyChain(username, secret)


def get_registry_keychain(host: str, ref: str, labels: Mapping[str, str]) -> Optional[PassKeyChain]:
    """Ordered lookup: labels, CRI proxy captures, docker config, k8s
    secret store (keychain.go:85-105)."""
    from nydus_snapshotter_tpu.auth import docker as docker_cfg
    from nydus_snapshotter_tpu.auth import image_proxy, kubesecret

    kc = from_labels(labels)
    if kc is not None:
        return kc
    kc = image_proxy.from_cri(host, ref)
    if kc is not None:
        return kc
    kc = docker_cfg.from_docker_config(host)
    if kc is not None:
        return kc
    return kubesecret.from_kube_secret(host)


def get_keychain_by_ref(ref: str, labels: Mapping[str, str]) -> Optional[PassKeyChain]:
    parsed = parse_docker_ref(ref)
    return get_registry_keychain(parsed.domain, ref, labels)
