"""CRI image-proxy credential capture.

Reference pkg/auth/image_proxy.go:52-130 proxies containerd's CRI
ImageService over a UDS and records the auth carried by each PullImage
request, so later snapshot mounts can reuse the kubelet-supplied
credentials. The TPU-era framework keeps the same capture surface as an
in-process store fed by the gRPC layer (the CRI wire hookup lives in
cmd/snapshotter when an image service address is configured); lookup
semantics mirror the reference: most-recent credential whose image ref
matches the requested ref/host wins.
"""

from __future__ import annotations

import threading
from typing import Optional

from nydus_snapshotter_tpu.auth.keychain import PassKeyChain
from nydus_snapshotter_tpu.remote.reference import parse_docker_ref

_lock = threading.Lock()
# ref -> keychain, insertion-ordered; newest matching entry wins.
_captured: dict[str, PassKeyChain] = {}
_MAX_ENTRIES = 512


def capture(ref: str, keychain: PassKeyChain) -> None:
    """Record credentials observed on a PullImage request."""
    with _lock:
        _captured.pop(ref, None)
        _captured[ref] = keychain
        while len(_captured) > _MAX_ENTRIES:
            _captured.pop(next(iter(_captured)))


def from_cri(host: str, ref: str) -> Optional[PassKeyChain]:
    """Credential for ref (exact match first, then same-registry match)."""
    with _lock:
        kc = _captured.get(ref)
        if kc is not None:
            return kc
        for seen_ref, kc in reversed(list(_captured.items())):
            try:
                if parse_docker_ref(seen_ref).domain == host:
                    return kc
            except ValueError:
                continue
    return None


def reset() -> None:
    with _lock:
        _captured.clear()
