"""Registry credential chain (reference pkg/auth).

Order of precedence (keychain.go:85-105): snapshot labels -> CRI
image-proxy captured creds -> docker config file -> kubernetes
dockerconfigjson secrets.
"""

from nydus_snapshotter_tpu.auth.keychain import (
    PassKeyChain,
    from_base64,
    from_labels,
    get_keychain_by_ref,
    get_registry_keychain,
)

__all__ = [
    "PassKeyChain",
    "from_base64",
    "from_labels",
    "get_keychain_by_ref",
    "get_registry_keychain",
]
