"""Docker config.json credential lookup.

Reference pkg/auth/docker.go:22-50: read `auths` from the default docker
config file, mapping the docker-hub endpoint `registry-1.docker.io` back to
its config key `https://index.docker.io/v1/`.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from nydus_snapshotter_tpu.auth.keychain import PassKeyChain, entry_keychain

DOCKER_HUB_KEY = "https://index.docker.io/v1/"
CONVERTED_DOCKER_HOST = "registry-1.docker.io"


def default_config_path() -> str:
    base = os.environ.get("DOCKER_CONFIG") or os.path.join(os.path.expanduser("~"), ".docker")
    return os.path.join(base, "config.json")


def from_docker_config(host: str, config_path: Optional[str] = None) -> Optional[PassKeyChain]:
    if not host:
        return None
    if host in (CONVERTED_DOCKER_HOST, "docker.io"):
        host = DOCKER_HUB_KEY
    path = config_path or default_config_path()
    try:
        with open(path, "rb") as f:
            cfg = json.load(f)
    except (OSError, ValueError):
        return None
    auths = cfg.get("auths") or {}
    for key, entry in auths.items():
        # Keys may be bare hosts or full URLs; match on the host part.
        key_host = key
        if "://" in key_host:
            key_host = key_host.split("://", 1)[1]
        key_host = key_host.rstrip("/")
        if key == host or key_host == host or key_host.split("/")[0] == host:
            return entry_keychain(entry)
    return None
