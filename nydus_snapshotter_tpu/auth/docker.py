"""Docker config.json credential lookup.

Reference pkg/auth/docker.go:22-50: read `auths` from the default docker
config file, mapping the docker-hub endpoint `registry-1.docker.io` back to
its config key `https://index.docker.io/v1/`.
"""

from __future__ import annotations

import base64
import json
import os
from typing import Optional

from nydus_snapshotter_tpu.auth.keychain import PassKeyChain

DOCKER_HUB_KEY = "https://index.docker.io/v1/"
CONVERTED_DOCKER_HOST = "registry-1.docker.io"


def default_config_path() -> str:
    base = os.environ.get("DOCKER_CONFIG") or os.path.join(os.path.expanduser("~"), ".docker")
    return os.path.join(base, "config.json")


def _entry_keychain(entry: dict) -> Optional[PassKeyChain]:
    auth_b64 = entry.get("auth", "")
    if auth_b64:
        try:
            user, _, pw = base64.b64decode(auth_b64).decode().partition(":")
        except Exception:
            return None
        if user and pw:
            return PassKeyChain(user, pw)
    user, pw = entry.get("username", ""), entry.get("password", "")
    if user and pw:
        return PassKeyChain(user, pw)
    return None


def from_docker_config(host: str, config_path: Optional[str] = None) -> Optional[PassKeyChain]:
    if not host:
        return None
    if host in (CONVERTED_DOCKER_HOST, "docker.io"):
        host = DOCKER_HUB_KEY
    path = config_path or default_config_path()
    try:
        with open(path, "rb") as f:
            cfg = json.load(f)
    except (OSError, ValueError):
        return None
    auths = cfg.get("auths") or {}
    for key, entry in auths.items():
        # Keys may be bare hosts or full URLs; match on the host part.
        key_host = key
        if "://" in key_host:
            key_host = key_host.split("://", 1)[1]
        key_host = key_host.rstrip("/")
        if key == host or key_host == host or key_host.split("/")[0] == host:
            return _entry_keychain(entry)
    return None
