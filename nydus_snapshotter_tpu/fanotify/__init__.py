"""Container file-access tracing via the native fanotify server
(reference pkg/fanotify + tools/optimizer-server)."""

from nydus_snapshotter_tpu.fanotify.server import EventInfo, Server, default_binary_path

__all__ = ["EventInfo", "Server", "default_binary_path"]
