"""Drive the native fanotify tracer and persist its access log.

Reference pkg/fanotify/fanotify.go:38-163 + conn/conn.go: fork the
optimizer-server binary with ``_MNTNS_PID``/``_TARGET`` env (it joins the
container's namespaces itself), read JSON events from its stdout, and write
two artifacts next to each other: the newline-separated accessed-path list
(``PersistFile``) and a ``<PersistFile>.csv`` with path,size,elapsed — the
exact inputs the prefetch table builder consumes.
"""

from __future__ import annotations

import csv
import json
import logging
import os
import signal
import subprocess
import threading
from dataclasses import dataclass
from typing import Optional

from nydus_snapshotter_tpu.utils import display

logger = logging.getLogger(__name__)


@dataclass
class EventInfo:
    path: str
    size: int
    elapsed: int

    @classmethod
    def from_json_line(cls, line: bytes) -> "EventInfo":
        obj = json.loads(line)
        if not isinstance(obj, dict):
            raise ValueError(f"event line is not a JSON object: {obj!r}")
        return cls(path=obj["path"], size=int(obj["size"]), elapsed=int(obj["elapsed"]))


def default_binary_path() -> str:
    """The in-tree native build output, built on demand when missing or
    stale (build artifacts are git-ignored, so a fresh checkout has
    none). utils.native_build gives the atomic-rename + failure-memo
    discipline, so concurrent NRI events never exec a half-written
    binary and a doomed compile is paid once per source state."""
    from nydus_snapshotter_tpu.utils import native_build

    native_build.ensure_built("optimizer-server", "optimizer_server")
    return native_build.target_path("optimizer-server")


class Server:
    def __init__(
        self,
        binary_path: str,
        container_pid: int,
        image_name: str,
        persist_file: str,
        readable: bool = False,
        overwrite: bool = True,
        timeout: float = 0.0,
        target: str = "/",
    ):
        self.binary_path = binary_path or default_binary_path()
        self.container_pid = container_pid
        self.image_name = image_name
        self.persist_file = persist_file
        self.readable = readable
        self.overwrite = overwrite
        self.timeout = timeout
        self.target = target
        self.proc: Optional[subprocess.Popen] = None
        self._receiver: Optional[threading.Thread] = None
        self._timer: Optional[threading.Timer] = None

    def run_server(self) -> None:
        """fanotify.go RunServer :52-101."""
        if not self.overwrite and os.path.isfile(self.persist_file):
            return
        env = {
            "_MNTNS_PID": str(self.container_pid) if self.container_pid else "",
            "_TARGET": self.target,
        }
        self.proc = subprocess.Popen(
            [self.binary_path],
            env=env,
            stdout=subprocess.PIPE,
            stderr=None if logger.isEnabledFor(logging.DEBUG) else subprocess.DEVNULL,
            start_new_session=True,  # Setpgid: SIGTERM the whole group
        )
        self._receiver = threading.Thread(
            target=self._run_receiver, daemon=True,
            name=f"fanotify-recv-{self.image_name}",
        )
        self._receiver.start()
        if self.timeout > 0:
            self._timer = threading.Timer(self.timeout, self.stop_server)
            self._timer.start()

    def _run_receiver(self) -> None:
        """fanotify.go RunReceiver :103-150: path list + CSV side by side."""
        assert self.proc is not None and self.proc.stdout is not None
        os.makedirs(os.path.dirname(self.persist_file) or ".", exist_ok=True)
        with open(self.persist_file, "w") as f, open(
            f"{self.persist_file}.csv", "w", newline=""
        ) as fcsv:
            writer = csv.writer(fcsv)
            writer.writerow(["path", "size", "elapsed"])
            fcsv.flush()
            for line in self.proc.stdout:
                try:
                    info = EventInfo.from_json_line(line)
                except (ValueError, KeyError, TypeError) as e:
                    logger.warning("bad event line %r: %s", line, e)
                    continue
                print(info.path, file=f)
                f.flush()
                if self.readable:
                    row = [
                        info.path,
                        display.byte_to_readable_iec(info.size),
                        display.microsecond_to_readable(info.elapsed),
                    ]
                else:
                    row = [info.path, str(info.size), str(info.elapsed)]
                writer.writerow(row)
                fcsv.flush()
        logger.info("fanotify receiver for %s done", self.image_name)

    def stop_server(self) -> None:
        """SIGTERM the process group, reap (fanotify.go :152-163)."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self.proc is None:
            return
        try:
            os.killpg(self.proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            logger.error("fanotify server %d did not exit, killing", self.proc.pid)
            try:
                os.killpg(self.proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        if self._receiver is not None:
            self._receiver.join(timeout=5)
        self.proc = None  # a recycled pid must never be re-signalled
