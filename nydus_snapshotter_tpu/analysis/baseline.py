"""Reviewed finding suppressions (analysis/baseline.toml).

The analyzers are heuristic and the tree contains *intentional*
blocking-under-lock (failpoint delay injection inside a planning
critical section is the point of the site) — so CI gates on **new**
findings only. Every suppression carries a human justification; an
entry without one fails the load, and entries that stop matching
anything are reported as stale so the file cannot rot.

Format::

    [[suppress]]
    id = "blocking-under-lock:pkg.mod:Class.fn:kind:desc"
    justification = "why this is intentional / safe"
"""

from __future__ import annotations

import os

from nydus_snapshotter_tpu.utils.tomlcompat import tomllib

DEFAULT_PATH = os.path.join(os.path.dirname(__file__), "baseline.toml")


class BaselineError(ValueError):
    pass


def load_baseline(path: str = DEFAULT_PATH) -> dict[str, str]:
    """{fingerprint: justification}; missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, "rb") as f:
        data = tomllib.load(f)
    out: dict[str, str] = {}
    for i, entry in enumerate(data.get("suppress", [])):
        fid = entry.get("id", "")
        just = entry.get("justification", "").strip()
        if not fid:
            raise BaselineError(f"suppress[{i}]: missing id")
        if not just:
            raise BaselineError(
                f"suppress[{i}] ({fid}): a suppression requires a written "
                "justification"
            )
        if fid in out:
            raise BaselineError(f"duplicate suppression {fid}")
        out[fid] = just
    return out


def render_baseline(entries: dict[str, str]) -> str:
    lines = [
        "# Reviewed analyzer suppressions — tools/analyze.py --fail-on-new",
        "# gates CI on findings NOT in this file. Every entry needs a",
        "# justification; stale entries are reported so this cannot rot.",
        "",
    ]
    for fid in sorted(entries):
        lines.append("[[suppress]]")
        lines.append(f'id = "{fid}"')
        just = entries[fid].replace("\\", "\\\\").replace('"', '\\"')
        lines.append(f'justification = "{just}"')
        lines.append("")
    return "\n".join(lines)
