"""Concurrency invariant analysis: static lock/blocking/drift detectors
plus an opt-in runtime lockset race detector.

PRs 2-6 made every plane of this snapshotter concurrent — the convert
pipeline, the fetch scheduler's flight table, the WAL metastore writer,
the lock-striped trace ring, the lock-free dict probes. All of it is
verified *dynamically*, by storms that cannot explore every interleaving
on a 1-core box. This package is the static correctness layer that runs
on every commit in milliseconds:

- :mod:`.package` — whole-package AST model: modules, classes, resolved
  lock objects (``with self._lock`` / ``Condition(lock)`` aliasing /
  ``acquire()``), per-function held-set walks and a best-effort call
  graph;
- :mod:`.locks` — the **lock-order analyzer** (inter-procedural lock
  acquisition graph; cycles and order inversions are potential
  deadlocks) and the **blocking-under-lock lint** (locks held across
  ``queue.put/get``, socket I/O, ``subprocess``, ``Future.result``,
  ``Thread.join``, sleeps, semaphore waits and failpoint-injectable
  sites);
- :mod:`.drift` — **drift gates** keeping the four hand-maintained
  catalogs honest: emitted ``ntpu_*`` metrics vs docs, ``[section]``
  config keys vs ``docs/configure.md`` / ``misc/snapshotter/config.toml``
  / their ``NTPU_*`` env overrides, failpoint sites fired vs
  ``failpoint.KNOWN_SITES`` vs ``docs/robustness.md`` vs chaos-test
  coverage, and thread-pool submissions of traced work vs explicit
  trace-context carry;
- :mod:`.baseline` — reviewed suppression list (every entry carries a
  justification); ``tools/analyze.py --fail-on-new`` gates CI on *new*
  findings only;
- :mod:`.runtime` — the opt-in (``NTPU_ANALYZE=1``) Eraser-style
  lockset race detector: instrumented lock wrappers + ``shared()``
  annotations on the hot shared structures, run under the existing
  stress/storm suites.

Entry point: ``tools/analyze.py`` (docs/static_analysis.md).
"""

from nydus_snapshotter_tpu.analysis.model import Finding  # noqa: F401
