"""Opt-in runtime concurrency detector (``NTPU_ANALYZE=1``).

Two detectors, both fed by instrumented lock wrappers the concurrent
modules create through :func:`make_lock` / :func:`make_rlock` /
:func:`make_condition`:

- **runtime lock order**: every *blocking* acquisition while other
  instrumented locks are held adds an edge to a global order graph; an
  edge that closes a cycle is recorded as an order violation with both
  directions' provenance. This catches orders the static analyzer cannot
  resolve (locks passed between objects, data-dependent paths);
- **lockset (Eraser-style) races**: hot shared structures are annotated
  with :func:`note_read` / :func:`note_write` (or a :func:`shared`
  handle). Each variable keeps the classic state machine — virgin ->
  exclusive(owner) -> shared / shared-modified — and a candidate lockset
  intersected with the accessing thread's held instrumented locks; an
  empty lockset in shared-modified state is a race candidate, reported
  once per variable with both access points.

Disabled (the default) this module costs one global ``ENABLED`` load
per annotation and ``make_lock`` returns plain ``threading`` primitives
— the hot paths stay exactly as fast as before. The stress/storm suites
run under ``NTPU_ANALYZE=1`` in the CI ``analyze`` job and fail on any
recorded race or order violation (tests/conftest.py session hook).

Deliberately excluded: the dict probe tables (lock-free by design,
key-before-value release stores — verified under ThreadSanitizer in
tests/test_native_sanitizers.py, not by this detector) and the
trace-ring stripe locks (per-span hot path inside the kernel-FUSE serve
loop; pinned by tests/test_trace.py's exactness suite instead — see
trace/ring.py).
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Optional

ENABLED = os.environ.get("NTPU_ANALYZE", "") not in ("", "0", "off", "false")

_meta = threading.Lock()  # guards the graphs/reports; strictly leaf
_tls = threading.local()

# order graph: name -> set of successor names; edge provenance kept for
# the first sighting of each edge.
_edges: dict[str, set] = {}
_edge_where: dict[tuple, str] = {}
_order_violations: list[dict] = []
_seen_cycles: set = set()

# Eraser state per annotated variable name.
_vars: dict[str, dict] = {}
_races: list[dict] = []


def _held() -> list:
    try:
        return _tls.held
    except AttributeError:
        h = _tls.held = []
        return h


def _caller(depth: int = 2) -> str:
    try:
        f = sys._getframe(depth)
        return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
    except Exception:
        return "?"


def _reaches(src: str, dst: str) -> bool:
    """dst reachable from src in the order graph (callers hold _meta)."""
    seen = {src}
    work = [src]
    while work:
        n = work.pop()
        if n == dst:
            return True
        for s in _edges.get(n, ()):
            if s not in seen:
                seen.add(s)
                work.append(s)
    return False


def _record_order(acquiring: str, where: str) -> None:
    held = _held()
    if not held:
        return
    with _meta:
        for h in held:
            if h.name == acquiring:
                continue
            edge = (h.name, acquiring)
            if edge in _edge_where:
                continue
            # Adding h -> acquiring closes a cycle iff h is already
            # reachable from acquiring.
            if _reaches(acquiring, h.name):
                key = tuple(sorted((h.name, acquiring)))
                if key not in _seen_cycles:
                    _seen_cycles.add(key)
                    back = next(
                        (w for (a, b), w in _edge_where.items()
                         if a == acquiring and b == h.name),
                        "(transitive)",
                    )
                    _order_violations.append(
                        {
                            "locks": [h.name, acquiring],
                            "forward": where,
                            "reverse": back,
                        }
                    )
            _edges.setdefault(h.name, set()).add(acquiring)
            _edge_where[edge] = where


class LocksetLock:
    """threading.Lock / RLock wrapper feeding the detectors. Duck-typed
    for ``threading.Condition``'s fallback protocol (acquire / release /
    context manager), so ``make_condition(name, lock)`` composes."""

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._reentrant = reentrant
        self._depth = 0  # this-thread reentry depth (tracked per-thread below)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            _record_order(self.name, _caller())
        got = (
            self._inner.acquire(blocking, timeout)
            if timeout != -1
            else self._inner.acquire(blocking)
        )
        if got:
            held = _held()
            if not (self._reentrant and any(h is self for h in held)):
                held.append(self)
            else:
                self._bump(+1)
        return got

    def release(self) -> None:
        held = _held()
        if self._reentrant and self._depth_of() > 0:
            self._bump(-1)
        else:
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break
        self._inner.release()

    # per-thread reentry depth for RLocks
    def _depth_of(self) -> int:
        return getattr(_tls, "depth_" + str(id(self)), 0)

    def _bump(self, d: int) -> None:
        setattr(_tls, "depth_" + str(id(self)), self._depth_of() + d)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        inner = getattr(self._inner, "locked", None)
        return inner() if inner else False


def make_lock(name: str):
    """A threading.Lock, instrumented when NTPU_ANALYZE is on."""
    return LocksetLock(name) if ENABLED else threading.Lock()


def make_rlock(name: str):
    return LocksetLock(name, reentrant=True) if ENABLED else threading.RLock()


def make_condition(name: str, lock=None):
    """A threading.Condition over an (instrumented) lock. With no lock,
    the condition's internal lock is instrumented under ``name``."""
    if not ENABLED:
        return threading.Condition(lock)
    return threading.Condition(lock if lock is not None else LocksetLock(name))


# ---------------------------------------------------------------------------
# Eraser-style lockset race detection on annotated shared state
# ---------------------------------------------------------------------------


def note(name: str, write: bool = True) -> None:
    """Record one access to the shared variable ``name`` from the current
    thread under its current instrumented lockset. Call sites guard on
    ``ENABLED`` so the disabled path costs one global load."""
    if not ENABLED:
        return
    tid = threading.get_ident()
    lockset = frozenset(h.name for h in _held())
    where = _caller()
    with _meta:
        v = _vars.get(name)
        if v is None:
            _vars[name] = {
                "state": "exclusive",
                "owner": tid,
                "lockset": None,
                "first": where,
                "raced": False,
            }
            return
        if v["state"] == "exclusive":
            if v["owner"] == tid:
                return
            v["lockset"] = lockset
            v["state"] = "shared-modified" if write else "shared"
        else:
            v["lockset"] = v["lockset"] & lockset
            if write:
                v["state"] = "shared-modified"
        if v["state"] == "shared-modified" and not v["lockset"] and not v["raced"]:
            v["raced"] = True
            _races.append(
                {
                    "var": name,
                    "first": v["first"],
                    "second": where,
                    "kind": "write" if write else "read",
                }
            )


def note_read(name: str) -> None:
    note(name, write=False)


def note_write(name: str) -> None:
    note(name, write=True)


class shared:
    """Annotation handle for a hot shared structure::

        self._flights_shared = runtime.shared(f"fetch.flights[{name}]")
        ...
        self._flights_shared.write()   # at mutation sites
        self._flights_shared.read()    # at lock-free / read sites
    """

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def read(self) -> None:
        if ENABLED:
            note(self.name, write=False)

    def write(self) -> None:
        if ENABLED:
            note(self.name, write=True)


# ---------------------------------------------------------------------------
# Reporting
# ---------------------------------------------------------------------------


def races() -> list[dict]:
    with _meta:
        return list(_races)


def order_violations() -> list[dict]:
    with _meta:
        return list(_order_violations)


def report() -> str:
    lines = []
    for r in races():
        lines.append(
            f"lockset race on {r['var']}: {r['kind']} at {r['second']} with "
            f"empty candidate lockset (first access {r['first']})"
        )
    for v in order_violations():
        lines.append(
            f"runtime lock-order cycle {v['locks'][0]} <-> {v['locks'][1]}: "
            f"{v['forward']} vs {v['reverse']}"
        )
    return "\n".join(lines)


def reset() -> None:
    with _meta:
        _edges.clear()
        _edge_where.clear()
        _order_violations.clear()
        _seen_cycles.clear()
        _vars.clear()
        _races.clear()


def enable(on: bool = True) -> None:
    """Flip the detector for tests. Only affects locks created after the
    flip (creation-time choice keeps the disabled path free)."""
    global ENABLED
    ENABLED = on
