"""Drift gates: code vs the four hand-maintained catalogs.

Each gate cross-checks something the code *does* against something a
human *wrote down*, in both directions where that makes sense:

- **metrics**: every ``ntpu_*`` metric registered in code must be
  documented (docs/*.md; ``ntpu_foo_*`` prefix wildcards allowed), and
  every exactly-named documented metric must exist in code;
- **config**: every ``[section] key`` declared in ``config/config.py``
  must appear in ``docs/configure.md`` AND in the commented example
  ``misc/snapshotter/config.toml``; every ``NTPU_*`` environment
  override read anywhere in the package must be documented, and every
  exactly-named documented override must be read somewhere;
- **failpoints**: every ``failpoint.hit("site")`` literal must be in
  ``failpoint.KNOWN_SITES``; every known site must be fired somewhere in
  the tree, documented in ``docs/robustness.md``, and referenced by at
  least one test (chaos coverage);
- **trace carry**: every ``Thread(target=...)`` / ``executor.submit``
  whose target transitively opens trace spans must either capture the
  submitting context (``trace.capture``) or adopt one on the worker
  (``trace.with_context``) — otherwise the worker's spans silently
  detach into parentless roots.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Optional

from nydus_snapshotter_tpu.analysis.model import Finding
from nydus_snapshotter_tpu.analysis.package import PackageModel

METRIC_CTORS = {"Counter", "Gauge", "TTLGauge", "Histogram", "LazyCounter"}
_METRIC_RE = re.compile(r"ntpu_[a-z0-9_]+\*?")
_ENV_RE = re.compile(r"NTPU_[A-Z0-9_*{},]+")
_ENV_CODE_RE = re.compile(r"^NTPU_[A-Z0-9_]+$")


def _read_docs(root: str, names=None) -> str:
    out = []
    docdir = os.path.join(root, "docs")
    for fn in sorted(os.listdir(docdir)):
        if not fn.endswith(".md"):
            continue
        if names is not None and fn not in names:
            continue
        with open(os.path.join(docdir, fn), "r", encoding="utf-8") as f:
            out.append(f.read())
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------


def _declared_metrics(model: PackageModel):
    """{name: (module, lineno)} for every registered ntpu_* metric."""
    found = {}
    for mm in model.modules.values():
        for node in ast.walk(mm.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None
            )
            if name not in METRIC_CTORS or not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if arg.value.startswith("ntpu_"):
                    found.setdefault(arg.value, (mm.name, node.lineno))
    return found


def _native_symbols(root: str) -> set[str]:
    """``ntpu_*`` C symbol names exported by the native engine — they
    share the metric prefix in docs but are not metrics."""
    out: set[str] = set()
    ndir = os.path.join(root, "nydus_snapshotter_tpu", "native", "chunk_engine")
    if not os.path.isdir(ndir):
        return out
    for fn in os.listdir(ndir):
        if fn.endswith((".cpp", ".h")):
            with open(os.path.join(ndir, fn), "r", encoding="utf-8") as f:
                out.update(re.findall(r"\b(ntpu_[a-z0-9_]+)\s*\(", f.read()))
    return out


def _expand_braces(tok: str) -> list[str]:
    m = re.match(r"^(.*)\{([a-z0-9_,]+)\}(.*)$", tok)
    if not m:
        return [tok]
    return [m.group(1) + part + m.group(3) for part in m.group(2).split(",")]


def find_metric_drift(model: PackageModel, root: str) -> list[Finding]:
    findings: list[Finding] = []
    declared = _declared_metrics(model)
    native = _native_symbols(root)
    text = _read_docs(root)
    exact: set[str] = set()
    prefixes: set[str] = set()
    for raw in re.findall(r"ntpu_[a-z0-9_{},]*\*?", text):
        if "{" in raw and "," not in raw:
            # ``metric{label}`` — the brace group is a label set, not an
            # alternation; the metric name is everything before it.
            raw = raw.split("{", 1)[0]
        for tok in _expand_braces(raw):
            if tok.endswith("*"):
                p = tok[:-1]
                if len(p) > len("ntpu_"):  # a bare ntpu_* covers nothing
                    prefixes.add(p)
            elif re.fullmatch(r"ntpu_[a-z0-9_]+[a-z0-9]", tok):
                # (a trailing underscore is a truncated prose prefix, not
                # a metric name)
                exact.add(tok)

    def documented(name: str) -> bool:
        return name in exact or any(name.startswith(p) for p in prefixes)

    for name, (mod, lineno) in sorted(declared.items()):
        if not documented(name):
            findings.append(
                Finding(
                    detector="drift-metrics",
                    module=mod,
                    qualname=name,
                    detail=f"undocumented:{name}",
                    message=f"metric {name} is emitted but not documented in docs/",
                    lineno=lineno,
                )
            )
    # Reverse: exactly-named doc claims must exist (prefix wildcards and
    # sub-series names a Histogram renders, _bucket/_sum/_count, excused).
    emitted = set(declared)
    series_suffixes = ("_bucket", "_sum", "_count")
    for name in sorted(exact):
        if name in emitted or any(name.startswith(p) for p in prefixes):
            continue
        if name in native or name.rstrip("_") in native:
            continue  # native engine symbol, not a metric
        if any(
            name == base + sfx for base in emitted for sfx in series_suffixes
        ):
            continue
        findings.append(
            Finding(
                detector="drift-metrics",
                module="docs",
                qualname=name,
                detail=f"stale-doc:{name}",
                message=f"docs reference metric {name}, which no code registers",
            )
        )
    return findings


# ---------------------------------------------------------------------------
# Config sections / keys / env overrides
# ---------------------------------------------------------------------------


def _config_schema(model: PackageModel):
    """{section: [keys]} + top-level keys from the SnapshotterConfig
    dataclass tree in config/config.py."""
    mm = model.modules.get(f"{model.package}.config.config")
    if mm is None:
        return {}, []
    class_fields: dict[str, list[str]] = {}
    for node in mm.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        fields = []
        for sub in node.body:
            if isinstance(sub, ast.AnnAssign) and isinstance(sub.target, ast.Name):
                fields.append((sub.target.id, sub))
        class_fields[node.name] = fields
    sections: dict[str, list[str]] = {}
    top: list[str] = []
    for fname, node in class_fields.get("SnapshotterConfig", []):
        factory = None
        if isinstance(node.value, ast.Call):
            for kw in node.value.keywords:
                if kw.arg == "default_factory" and isinstance(kw.value, ast.Name):
                    factory = kw.value.id
        if factory and factory in class_fields:
            sections[fname] = [k for k, _ in class_fields[factory]]
        else:
            top.append(fname)
    return sections, top


def _env_vars_in_code(model: PackageModel) -> dict[str, str]:
    found: dict[str, str] = {}
    for mm in model.modules.values():
        for node in ast.walk(mm.tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _ENV_CODE_RE.match(node.value)
            ):
                found.setdefault(node.value, mm.name)
    return found


def _expand_env_tokens(text: str):
    """Doc-side NTPU_* mentions -> (exact names, prefix wildcards).
    Handles ``NTPU_PIPELINE_{QUEUE,BUDGET,WINDOW}_MIB`` brace groups and
    ``NTPU_TRACE*`` trailing wildcards."""
    exact: set[str] = set()
    prefixes: set[str] = set()
    for tok in _ENV_RE.findall(text):
        toks = [tok]
        m = re.match(r"^(.*)\{([A-Z0-9_,]+)\}(.*)$", tok)
        if m:
            toks = [m.group(1) + part + m.group(3) for part in m.group(2).split(",")]
        for t in toks:
            t = t.rstrip(",")
            if t.endswith("*"):
                prefixes.add(t[:-1])
            elif _ENV_CODE_RE.match(t):
                exact.add(t)
    return exact, prefixes


def find_config_drift(model: PackageModel, root: str) -> list[Finding]:
    findings: list[Finding] = []
    sections, _top = _config_schema(model)
    configure_md = _read_docs(root, names={"configure.md"})
    toml_path = os.path.join(root, "misc", "snapshotter", "config.toml")
    toml_text = ""
    if os.path.exists(toml_path):
        with open(toml_path, "r", encoding="utf-8") as f:
            toml_text = f.read()

    for section, keys in sorted(sections.items()):
        if f"[{section}]" not in configure_md:
            findings.append(
                Finding(
                    detector="drift-config",
                    module="docs/configure.md",
                    qualname=f"[{section}]",
                    detail=f"section-undocumented:{section}",
                    message=f"config section [{section}] is not documented in "
                    "docs/configure.md",
                )
            )
        if f"[{section}]" not in toml_text:
            findings.append(
                Finding(
                    detector="drift-config",
                    module="misc/snapshotter/config.toml",
                    qualname=f"[{section}]",
                    detail=f"section-missing-example:{section}",
                    message=f"config section [{section}] has no example in "
                    "misc/snapshotter/config.toml",
                )
            )
        for key in keys:
            if f"`{key}`" not in configure_md and f"{key} " not in configure_md:
                findings.append(
                    Finding(
                        detector="drift-config",
                        module="docs/configure.md",
                        qualname=f"{section}.{key}",
                        detail=f"key-undocumented:{section}.{key}",
                        message=f"config key [{section}] {key} is not documented "
                        "in docs/configure.md",
                    )
                )
            if not re.search(rf"(?m)^\s*#?\s*{re.escape(key)}\s*=", toml_text):
                findings.append(
                    Finding(
                        detector="drift-config",
                        module="misc/snapshotter/config.toml",
                        qualname=f"{section}.{key}",
                        detail=f"key-missing-example:{section}.{key}",
                        message=f"config key [{section}] {key} has no (commented) "
                        "example in misc/snapshotter/config.toml",
                    )
                )

    # NTPU_* environment overrides, both directions, against all docs.
    alldocs = _read_docs(root)
    exact, prefixes = _expand_env_tokens(alldocs)
    in_code = _env_vars_in_code(model)
    for var, mod in sorted(in_code.items()):
        if var in exact or any(var.startswith(p) for p in prefixes):
            continue
        findings.append(
            Finding(
                detector="drift-config",
                module=mod,
                qualname=var,
                detail=f"env-undocumented:{var}",
                message=f"environment override {var} is read in code but "
                "documented in no docs/*.md",
            )
        )
    for var in sorted(exact):
        if var not in in_code:
            findings.append(
                Finding(
                    detector="drift-config",
                    module="docs",
                    qualname=var,
                    detail=f"env-stale-doc:{var}",
                    message=f"docs reference environment override {var}, "
                    "which no code reads",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# Failpoints
# ---------------------------------------------------------------------------


def _known_sites(model: PackageModel):
    mm = model.modules.get(f"{model.package}.failpoint")
    if mm is None:
        return []
    for node in mm.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "KNOWN_SITES"
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            return [
                e.value
                for e in node.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
    return []


def _hit_sites(model: PackageModel):
    """{site: (module, lineno)} for every failpoint.hit("...") literal."""
    found: dict[str, tuple] = {}
    for mm in model.modules.values():
        if mm.name == f"{model.package}.failpoint":
            continue
        for node in ast.walk(mm.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (
                isinstance(f, ast.Attribute)
                and f.attr == "hit"
                and isinstance(f.value, ast.Name)
                and f.value.id == "failpoint"
            ):
                continue
            if node.args and isinstance(node.args[0], ast.Constant):
                found.setdefault(str(node.args[0].value), (mm.name, node.lineno))
    return found


def _tests_text(root: str) -> str:
    out = []
    tdir = os.path.join(root, "tests")
    if os.path.isdir(tdir):
        for fn in sorted(os.listdir(tdir)):
            if fn.endswith(".py"):
                with open(os.path.join(tdir, fn), "r", encoding="utf-8") as f:
                    out.append(f.read())
    # The exhaustive chaos sweep lives in tools/ and is also reachable as
    # a slow-marked test; it counts as chaos coverage.
    cm = os.path.join(root, "tools", "chaos_matrix.py")
    if os.path.exists(cm):
        with open(cm, "r", encoding="utf-8") as f:
            out.append(f.read())
    return "\n".join(out)


def find_failpoint_drift(model: PackageModel, root: str) -> list[Finding]:
    findings: list[Finding] = []
    known = _known_sites(model)
    hits = _hit_sites(model)
    robustness = _read_docs(root, names={"robustness.md"})
    tests = _tests_text(root)

    for site, (mod, lineno) in sorted(hits.items()):
        if site not in known:
            findings.append(
                Finding(
                    detector="drift-failpoints",
                    module=mod,
                    qualname=site,
                    detail=f"unregistered:{site}",
                    message=f"failpoint.hit({site!r}) fires a site missing from "
                    "failpoint.KNOWN_SITES",
                    lineno=lineno,
                )
            )
    for site in known:
        if site not in hits:
            findings.append(
                Finding(
                    detector="drift-failpoints",
                    module=f"{model.package}.failpoint",
                    qualname=site,
                    detail=f"unfired:{site}",
                    message=f"KNOWN_SITES entry {site!r} is never fired by any "
                    "failpoint.hit in the tree",
                )
            )
        if site not in robustness:
            findings.append(
                Finding(
                    detector="drift-failpoints",
                    module="docs/robustness.md",
                    qualname=site,
                    detail=f"undocumented:{site}",
                    message=f"failpoint site {site!r} is not documented in "
                    "docs/robustness.md",
                )
            )
        if site not in tests:
            findings.append(
                Finding(
                    detector="drift-failpoints",
                    module="tests",
                    qualname=site,
                    detail=f"untested:{site}",
                    message=f"failpoint site {site!r} is exercised by no test "
                    "(tests/*.py, tools/chaos_matrix.py)",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# Trace-context carry across pool boundaries
# ---------------------------------------------------------------------------


def _callee_closure(model: PackageModel, start_key: str) -> set[str]:
    seen = {start_key}
    work = [start_key]
    while work:
        k = work.pop()
        fi = model.functions.get(k)
        if fi is None:
            continue
        for ref, _held, _ln in fi.calls:
            tgt = model.resolve_ref(fi, ref)
            if tgt is not None and tgt.key not in seen:
                seen.add(tgt.key)
                work.append(tgt.key)
        for name, key in fi.nested.items():
            if key not in seen:
                seen.add(key)
                work.append(key)
    return seen


def find_trace_carry_drift(model: PackageModel) -> list[Finding]:
    findings: list[Finding] = []
    opens = {"span", "start_span", "traced"}
    carries = {"capture", "with_context", "remote_context"}
    for key, fi in sorted(model.functions.items()):
        for ref, kind, lineno in fi.spawns:
            tgt = model.resolve_ref(fi, ref)
            if tgt is None:
                continue
            reach = _callee_closure(model, tgt.key)
            opens_span = any(
                model.functions[k].trace_refs & opens
                for k in reach
                if k in model.functions
            )
            if not opens_span:
                continue  # worker never touches tracing: nothing to carry
            carried = bool(fi.trace_refs & carries) or any(
                model.functions[k].trace_refs & carries
                for k in reach
                if k in model.functions
            )
            if carried:
                continue
            tname = ref[-1] if ref else "?"
            findings.append(
                Finding(
                    detector="drift-trace-carry",
                    module=fi.module,
                    qualname=fi.qualname,
                    detail=f"uncarried:{kind}:{tname}",
                    message=(
                        f"{kind} target {tname} transitively opens trace spans "
                        "but neither the submitter captures a context "
                        "(trace.capture) nor the worker adopts one "
                        "(trace.with_context) — its spans detach into new roots"
                    ),
                    lineno=lineno,
                )
            )
    return findings


def find_all_drift(model: PackageModel, root: str) -> list[Finding]:
    out = []
    out += find_metric_drift(model, root)
    out += find_config_drift(model, root)
    out += find_failpoint_drift(model, root)
    out += find_trace_carry_drift(model)
    return out
