"""Lock-order analyzer + blocking-under-lock lint.

Built on the held-set walks in :mod:`.package`:

- **lock order**: every acquisition of lock B while holding lock A adds
  the edge A -> B to the inter-procedural acquisition graph (calls made
  under A contribute edges to every lock the callee can transitively
  acquire). A cycle in that graph is a potential deadlock; a 2-cycle is
  the classic lock-order inversion. Self-edges on non-reentrant locks
  (re-acquiring a plain ``Lock`` you already hold) are reported too —
  that one is not "potential", it deadlocks deterministically.
- **blocking under lock**: calls that can block indefinitely (or for an
  injected failpoint delay) while a lock is held serialize everything
  behind that lock on an external event — the exact shape of stall the
  PrepareBoard joins / flight promotion / dict-service reconcile paths
  can hide. ``Condition.wait()`` on the *held* condition is excused
  (wait releases it); any OTHER lock held across the wait is flagged.

Both detectors are heuristic: they over-approximate reachability and
under-approximate aliasing, so every finding is a candidate to either
fix or suppress **with a written justification** in
``analysis/baseline.toml``.
"""

from __future__ import annotations

import ast
from typing import Optional

from nydus_snapshotter_tpu.analysis.model import Finding
from nydus_snapshotter_tpu.analysis.package import FunctionInfo, LockDef, PackageModel

# Attribute-call names that block on an external event. ``wait`` covers
# Event.wait / Condition.wait / Future/process wait; ``result`` is the
# Future join; ``acquire`` on semaphore/budget-ish receivers is a
# capacity wait (real lock acquires are modeled separately, as locks).
_SOCKETISH = {"recv", "recv_into", "accept", "connect", "sendall"}
_SUBPROCESS = {"run", "check_call", "check_output", "call", "communicate"}
_SEMAPHORISH = ("sem", "budget", "window", "limiter", "slots")


def classify_blocking(walker, call: ast.Call, func, held):
    """(kind, desc, held, lineno, excused_locks) or None. Called from the
    package walker for every Call node; cheap name-shape checks only."""
    if not isinstance(func, ast.Attribute):
        if isinstance(func, ast.Name) and func.id in ("sleep", "_sleep", "urlopen"):
            kind = "sleep" if "sleep" in func.id else func.id
            return (kind, func.id, tuple(held), call.lineno, ())
        return None
    attr = func.attr
    recv = func.value
    recv_name = _recv_name(recv)

    if attr == "sleep":
        return ("sleep", f"{recv_name}.sleep", tuple(held), call.lineno, ())

    if attr == "join":
        # str.join takes one non-numeric positional; thread/process join
        # takes none or a numeric/keyword timeout.
        if call.args and not _is_numeric(call.args[0]):
            return None
        return ("join", f"{recv_name}.join", tuple(held), call.lineno, ())

    if attr == "result":
        return ("future.result", f"{recv_name}.result", tuple(held), call.lineno, ())

    if attr == "wait":
        excused = ()
        ld = walker.lock_of(recv)
        if ld is not None:
            # Condition.wait releases its own lock while waiting.
            excused = (ld,)
        return ("wait", f"{recv_name}.wait", tuple(held), call.lineno, excused)

    if attr == "get":
        # queue.get() blocks with no positional args; dict.get(k) never
        # has zero args, so the arity IS the discriminator.
        if call.args:
            return None
        if any(kw.arg == "block" and _is_false(kw.value) for kw in call.keywords):
            return None
        if not (_queueish(walker, recv, recv_name)):
            return None
        return ("queue.get", f"{recv_name}.get", tuple(held), call.lineno, ())

    if attr == "put":
        if any(kw.arg == "block" and _is_false(kw.value) for kw in call.keywords):
            return None
        if not _queueish(walker, recv, recv_name):
            return None
        return ("queue.put", f"{recv_name}.put", tuple(held), call.lineno, ())

    if attr == "acquire":
        # Real locks are modeled as acquisitions; semaphore/budget-like
        # receivers are capacity waits.
        if walker.lock_of(recv) is not None:
            return None
        if any(s in recv_name.lower() for s in _SEMAPHORISH):
            return (
                "semaphore.acquire",
                f"{recv_name}.acquire",
                tuple(held),
                call.lineno,
                (),
            )
        return None

    if attr in _SOCKETISH:
        return ("socket", f"{recv_name}.{attr}", tuple(held), call.lineno, ())

    if attr in _SUBPROCESS and recv_name == "subprocess":
        return ("subprocess", f"subprocess.{attr}", tuple(held), call.lineno, ())

    if attr == "hit" and recv_name == "failpoint":
        site = ""
        if call.args and isinstance(call.args[0], ast.Constant):
            site = str(call.args[0].value)
        return ("failpoint", f"failpoint.hit({site})", tuple(held), call.lineno, ())

    return None


def _recv_name(recv) -> str:
    if isinstance(recv, ast.Name):
        return recv.id
    if isinstance(recv, ast.Attribute):
        base = _recv_name(recv.value)
        return f"{base}.{recv.attr}" if base else recv.attr
    return ""


def _is_numeric(node) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value, (int, float))


def _is_false(node) -> bool:
    return isinstance(node, ast.Constant) and node.value is False


def _queueish(walker, recv, recv_name: str) -> bool:
    tail = recv_name.rsplit(".", 1)[-1].lower()
    if (
        isinstance(recv, ast.Attribute)
        and isinstance(recv.value, ast.Name)
        and recv.value.id == "self"
        and walker.cm is not None
        and recv.attr in walker.cm.queue_attrs
    ):
        return True
    return "queue" in tail or tail in ("q", "_q") or tail.endswith("_q")


# ---------------------------------------------------------------------------
# Lock-order analysis
# ---------------------------------------------------------------------------


class LockGraph:
    """Directed acquisition graph over LockDef identities with edge
    provenance (who acquired what while holding what, and via which call
    chain)."""

    def __init__(self):
        self.edges: dict = {}  # (a_id, b_id) -> list[str] provenance

    def add(self, a: LockDef, b: LockDef, why: str) -> None:
        key = (a.id, b.id)
        prov = self.edges.setdefault(key, [])
        if len(prov) < 4 and why not in prov:
            prov.append(why)

    def successors(self, a_id):
        return {b for (x, b) in self.edges if x == a_id}


def _transitive_acquisitions(model: PackageModel) -> dict:
    """fn key -> set[LockDef] of locks the function may acquire,
    including via (resolvable) callees — a bounded fixpoint."""
    direct: dict[str, set] = {}
    callees: dict[str, set] = {}
    for key, fi in model.functions.items():
        direct[key] = {ld for (ld, _held, _ln) in fi.acquisitions}
        outs = set()
        for ref, _held, _ln in fi.calls:
            tgt = model.resolve_ref(fi, ref)
            if tgt is not None:
                outs.add(tgt.key)
        for ref, _kind, _ln in fi.spawns:
            # Work handed to a thread does not run under the caller's
            # locks — spawned targets are excluded on purpose.
            pass
        callees[key] = outs
    acq = {k: set(v) for k, v in direct.items()}
    for _ in range(len(model.functions)):
        changed = False
        for k, outs in callees.items():
            before = len(acq[k])
            for o in outs:
                acq[k] |= acq.get(o, set())
            if len(acq[k]) != before:
                changed = True
        if not changed:
            break
    return acq


def build_lock_graph(model: PackageModel) -> LockGraph:
    g = LockGraph()
    acq = _transitive_acquisitions(model)
    for key, fi in model.functions.items():
        for ld, held, lineno in fi.acquisitions:
            for h in held:
                if h.id != ld.id:
                    g.add(h, ld, f"{fi.module}.{fi.qualname}:{lineno}")
                elif ld.kind == "lock":
                    g.add(h, ld, f"{fi.module}.{fi.qualname}:{lineno} (re-acquire)")
        for ref, held, lineno in fi.calls:
            if not held:
                continue
            tgt = model.resolve_ref(fi, ref)
            if tgt is None:
                continue
            for inner in acq.get(tgt.key, ()):
                for h in held:
                    if h.id != inner.id:
                        g.add(
                            h,
                            inner,
                            f"{fi.module}.{fi.qualname}:{lineno}"
                            f" -> {tgt.module}.{tgt.qualname}",
                        )
                    elif inner.kind == "lock":
                        g.add(
                            h,
                            inner,
                            f"{fi.module}.{fi.qualname}:{lineno}"
                            f" -> {tgt.module}.{tgt.qualname} (re-acquire)",
                        )
    return g


def _lock_name(model: PackageModel, lid) -> str:
    ld = model.lock_defs.get(lid)
    return ld.name if ld is not None else ".".join(str(x) for x in lid if x)


def find_lock_order_findings(model: PackageModel) -> list[Finding]:
    g = build_lock_graph(model)
    findings: list[Finding] = []

    # Self-deadlock: A -> A on a non-reentrant lock. (Module-level locks
    # carry an empty class slot in their id — kept a string so sorting a
    # module that mixes them with class locks stays well-defined.)
    for (a, b), prov in sorted(g.edges.items()):
        if a == b:
            name = _lock_name(model, a)
            findings.append(
                Finding(
                    detector="lock-order",
                    module=a[0],
                    qualname=name,
                    detail=f"self:{name}",
                    message=(
                        f"non-reentrant lock {name} may be re-acquired while "
                        f"held (guaranteed deadlock): {'; '.join(prov)}"
                    ),
                )
            )

    # Inversions: both A -> B and B -> A (reported pairwise, once).
    seen_pairs = set()
    for (a, b) in sorted(g.edges):
        if a == b or (b, a) not in g.edges:
            continue
        pair = tuple(sorted((a, b)))
        if pair in seen_pairs:
            continue
        seen_pairs.add(pair)
        na, nb = _lock_name(model, pair[0]), _lock_name(model, pair[1])
        prov = g.edges[(pair[0], pair[1])] + g.edges[(pair[1], pair[0])]
        findings.append(
            Finding(
                detector="lock-order",
                module=pair[0][0],
                qualname=f"{na} <-> {nb}",
                detail=f"inversion:{na}<->{nb}",
                message=(
                    f"lock-order inversion between {na} and {nb} "
                    f"(potential deadlock): {'; '.join(prov[:4])}"
                ),
            )
        )

    # Longer cycles: SCCs of size > 2 (pairs already reported above).
    for scc in _sccs(g):
        if len(scc) < 3:
            continue
        names = sorted(_lock_name(model, lid) for lid in scc)
        findings.append(
            Finding(
                detector="lock-order",
                module=sorted(scc)[0][0],
                qualname=" -> ".join(names),
                detail="cycle:" + "|".join(names),
                message=f"lock acquisition cycle across {len(names)} locks "
                f"(potential deadlock): {' -> '.join(names)}",
            )
        )
    return findings


def _sccs(g: LockGraph):
    """Tarjan over the lock graph (iterative; the graph is tiny)."""
    nodes = sorted({a for a, _ in g.edges} | {b for _, b in g.edges})
    succ = {n: sorted(g.successors(n)) for n in nodes}
    index: dict = {}
    low: dict = {}
    onstack: set = set()
    stack: list = []
    out = []
    counter = [0]

    def strongconnect(v):
        work = [(v, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                onstack.add(node)
            recurse = False
            for i in range(pi, len(succ[node])):
                w = succ[node][i]
                if w not in index:
                    work[-1] = (node, i + 1)
                    work.append((w, 0))
                    recurse = True
                    break
                if w in onstack:
                    low[node] = min(low[node], index[w])
            if recurse:
                continue
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                out.append(scc)
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])

    for n in nodes:
        if n not in index:
            strongconnect(n)
    return out


# ---------------------------------------------------------------------------
# Blocking-under-lock lint
# ---------------------------------------------------------------------------


def _blocking_summaries(model: PackageModel) -> dict:
    """fn key -> set[(kind, origin, desc)] of blocking calls reachable
    from the function (itself or via resolvable callees), ignoring what
    the *caller* holds — the caller's held set is applied at the call
    site. Condition.wait excused against its own lock does not summarize
    (the callee releases it; a caller's other locks are caught by the
    caller's own call-under-lock edge to the *enclosing* wait kind)."""
    summaries: dict[str, set] = {}
    callees: dict[str, set] = {}
    for key, fi in model.functions.items():
        s = set()
        for kind, desc, _held, _ln, excused in fi.blocking:
            if kind == "wait" and excused:
                # cv.wait on its own condition: releases that lock; as a
                # summary it still blocks the caller, so keep it.
                s.add((kind, f"{fi.module}.{fi.qualname}", desc))
            else:
                s.add((kind, f"{fi.module}.{fi.qualname}", desc))
        summaries[key] = s
        outs = set()
        for ref, _held, _ln in fi.calls:
            tgt = model.resolve_ref(fi, ref)
            if tgt is not None:
                outs.add(tgt.key)
        callees[key] = outs
    for _ in range(len(model.functions)):
        changed = False
        for k, outs in callees.items():
            before = len(summaries[k])
            for o in outs:
                for item in summaries.get(o, ()):
                    if len(summaries[k]) >= 12:
                        break
                    summaries[k].add(item)
            if len(summaries[k]) != before:
                changed = True
        if not changed:
            break
    return summaries


def find_blocking_findings(model: PackageModel) -> list[Finding]:
    findings: list[Finding] = []
    seen: set = set()

    def emit(fi, kind, desc, locks, lineno, via=""):
        names = ", ".join(h.name for h in locks)
        detail = f"{kind}:{desc}" + (f"@{via}" if via else "")
        key = (fi.key, detail)
        if key in seen:
            return
        seen.add(key)
        where = f" (via {via})" if via else ""
        findings.append(
            Finding(
                detector="blocking-under-lock",
                module=fi.module,
                qualname=fi.qualname,
                detail=detail,
                message=f"{desc} ({kind}){where} can block while holding {names}",
                lineno=lineno,
                severity="warn" if kind == "failpoint" else "error",
            )
        )

    summaries = _blocking_summaries(model)
    for key, fi in sorted(model.functions.items()):
        # Direct blocking calls under a held lock.
        for kind, desc, held, lineno, excused in fi.blocking:
            blocked = [h for h in held if h not in excused]
            if blocked:
                emit(fi, kind, desc, blocked, lineno)
        # Calls made under a lock that transitively reach a blocking call.
        for ref, held, lineno in fi.calls:
            if not held:
                continue
            tgt = model.resolve_ref(fi, ref)
            if tgt is None:
                continue
            for kind, origin, desc in sorted(summaries.get(tgt.key, ())):
                if origin == f"{fi.module}.{fi.qualname}":
                    continue  # already reported as direct
                emit(fi, kind, desc, held, lineno, via=origin)
    return findings
