"""Whole-package AST model for the concurrency analyzers.

Parses every module under a package root once and resolves the facts the
detectors need:

- **lock objects**: per-class ``self._lock = threading.Lock()`` (and
  ``RLock`` / ``Condition`` / the instrumented
  ``analysis.runtime.make_lock`` wrappers) plus module-level locks.
  ``threading.Condition(self._lock)`` *aliases* the condition attribute
  to the underlying lock, and ``self._lock = lock`` from an ``__init__``
  parameter named like a lock registers the attribute as a lock in its
  own right (the fetch scheduler shares its caller's lock this way);
- **held-set walks**: for every function, which locks are held at every
  lock acquisition, call and blocking-call site.  ``with lock:`` scopes
  exactly; bare ``lock.acquire()`` statements hold until a matching
  ``release()`` at the same nesting level or the end of the function
  (the ``try/finally`` idiom this codebase uses);
- **call graph**: best-effort resolution of ``self.m()``, same-module
  ``f()``, imported ``mod.f()`` and ``self._attr.m()`` where the
  attribute's class is inferred from its constructor assignment — enough
  to see that ``Snapshotter.commit`` reaches ``MetaStore.commit_active``
  while holding the in-flight lock;
- **thread spawns**: every ``threading.Thread(target=...)`` and
  ``executor.submit(...)`` with its resolved target, plus which trace
  primitives (``span`` / ``capture`` / ``with_context``) each function
  references — the trace-carry drift gate's raw material.

Everything here is approximate by design: Python cannot be soundly
analyzed statically, so detectors built on this model report *candidate*
invariant violations, and the reviewed baseline (analysis/baseline.toml)
records the ones that are intentional.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Optional

# (module, class-or-None, attr) — stable identity of one lock object.
LockId = tuple

LOCK_CTORS = {
    "Lock": "lock",
    "RLock": "rlock",
    "make_lock": "lock",
    "make_rlock": "rlock",
}
COND_CTORS = {"Condition", "make_condition"}
QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "ByteBoundedQueue"}
# Parameter names that mark a lock handed in by the owner (the
# FetchScheduler pattern: the CachedBlob lock IS the scheduler lock).
LOCKISH_PARAMS = {"lock", "mutex", "mu"}
TRACE_ATTRS = {
    "span",
    "start_span",
    "traced",
    "capture",
    "with_context",
    "remote_context",
}


@dataclass(eq=False)
class LockDef:
    """Identity-hashed: aliases (a Condition over a lock) share one
    instance, so set/dict membership IS lock identity."""

    id: LockId
    kind: str  # lock | rlock | condition
    lineno: int = 0

    @property
    def name(self) -> str:
        mod, cls, attr = self.id
        return f"{mod}.{cls}.{attr}" if cls else f"{mod}.{attr}"


@dataclass
class ClassModel:
    module: str
    name: str
    locks: dict = field(default_factory=dict)  # attr -> LockDef (aliases share)
    attr_types: dict = field(default_factory=dict)  # attr -> (module, ClassName)
    queue_attrs: set = field(default_factory=set)


@dataclass
class FunctionInfo:
    module: str
    qualname: str  # Class.method, func, or outer.<locals>.inner
    node: object
    cls: Optional[str] = None
    acquisitions: list = field(default_factory=list)  # (LockDef, held, lineno)
    calls: list = field(default_factory=list)  # (ref, held, lineno)
    blocking: list = field(default_factory=list)  # (kind, desc, held, lineno, excused)
    spawns: list = field(default_factory=list)  # (ref, kind, lineno)
    trace_refs: set = field(default_factory=set)
    nested: dict = field(default_factory=dict)  # name -> qualkey

    @property
    def key(self) -> str:
        return f"{self.module}:{self.qualname}"


@dataclass
class ModuleModel:
    name: str
    path: str
    tree: object
    imports: dict = field(default_factory=dict)  # local name -> module
    from_imports: dict = field(default_factory=dict)  # local -> (module, name)
    locks: dict = field(default_factory=dict)  # global name -> LockDef
    classes: dict = field(default_factory=dict)  # name -> ClassModel


class PackageModel:
    def __init__(self, root: str, package: str):
        self.root = root
        self.package = package
        self.modules: dict[str, ModuleModel] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.lock_defs: dict[LockId, LockDef] = {}
        # fn key -> set[LockDef] held at a ``yield`` — ``with self.write_txn():``
        # bodies run under whatever the contextmanager holds at its yield.
        self.yield_held: dict[str, set] = {}
        self._load()
        self._index()

    # -- loading -------------------------------------------------------------

    def _load(self) -> None:
        pkg_dir = os.path.join(self.root, *self.package.split("."))
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = [d for d in dirnames if d not in ("__pycache__", "bin")]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, self.root)
                modname = rel[:-3].replace(os.sep, ".")
                if modname.endswith(".__init__"):
                    modname = modname[: -len(".__init__")]
                with open(path, "r", encoding="utf-8") as f:
                    src = f.read()
                try:
                    tree = ast.parse(src, filename=path)
                except SyntaxError:
                    continue
                mm = ModuleModel(name=modname, path=path, tree=tree)
                self._collect_imports(mm)
                self.modules[modname] = mm

    def _collect_imports(self, mm: ModuleModel) -> None:
        for node in ast.walk(mm.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mm.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    mm.from_imports[a.asname or a.name] = (node.module, a.name)
                    # `from nydus_snapshotter_tpu import trace` style: the
                    # bound name is itself a module.
                    cand = f"{node.module}.{a.name}"
                    mm.imports.setdefault(a.asname or a.name, cand)

    # -- indexing ------------------------------------------------------------

    def _index(self) -> None:
        for mm in self.modules.values():
            self._index_module_locks(mm)
            for node in mm.tree.body:
                if isinstance(node, ast.ClassDef):
                    mm.classes[node.name] = self._index_class(mm, node)
        # Function infos come after lock/class indexing so held-set walks
        # can resolve everything. Two passes: the first records which
        # locks each contextmanager holds at its yield; the second
        # re-walks with that knowledge so ``with self.write_txn():``
        # bodies count as running under the writer lock.
        for _pass in (1, 2):
            for mm in self.modules.values():
                for node in mm.tree.body:
                    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._index_function(mm, node, None, node.name)
                    elif isinstance(node, ast.ClassDef):
                        for sub in node.body:
                            if isinstance(
                                sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                            ):
                                self._index_function(
                                    mm, sub, node.name, f"{node.name}.{sub.name}"
                                )

    def _ctor_name(self, mm: ModuleModel, call: ast.Call) -> Optional[str]:
        """Terminal name of a constructor call: ``threading.Lock`` ->
        ``Lock``, ``runtime.make_lock`` -> ``make_lock``, ``Lock`` -> itself
        when imported from threading."""
        f = call.func
        if isinstance(f, ast.Attribute):
            return f.attr
        if isinstance(f, ast.Name):
            return f.id
        return None

    def _index_module_locks(self, mm: ModuleModel) -> None:
        for node in mm.tree.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name) or not isinstance(node.value, ast.Call):
                continue
            ctor = self._ctor_name(mm, node.value)
            if ctor in LOCK_CTORS:
                lid = (mm.name, "", tgt.id)
                mm.locks[tgt.id] = self.lock_defs.setdefault(
                    lid, LockDef(lid, LOCK_CTORS[ctor], node.lineno)
                )
            elif ctor in COND_CTORS:
                lid = (mm.name, "", tgt.id)
                mm.locks[tgt.id] = self.lock_defs.setdefault(
                    lid, LockDef(lid, "condition", node.lineno)
                )

    def _index_class(self, mm: ModuleModel, cnode: ast.ClassDef) -> ClassModel:
        cm = ClassModel(module=mm.name, name=cnode.name)
        param_attr: dict[str, str] = {}  # param name -> first attr assigned from it
        for meth in cnode.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # class-level lock: ``_MOUNT_LOCK = threading.Lock()``
                if (
                    isinstance(meth, ast.Assign)
                    and len(meth.targets) == 1
                    and isinstance(meth.targets[0], ast.Name)
                    and isinstance(meth.value, ast.Call)
                ):
                    ctor = self._ctor_name(mm, meth.value)
                    if ctor in LOCK_CTORS or ctor in COND_CTORS:
                        attr = meth.targets[0].id
                        lid = (mm.name, cnode.name, attr)
                        kind = LOCK_CTORS.get(ctor, "condition")
                        cm.locks[attr] = self.lock_defs.setdefault(
                            lid, LockDef(lid, kind, meth.lineno)
                        )
                continue
            params = {a.arg for a in meth.args.args}
            for node in ast.walk(meth):
                if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                    continue
                tgt = node.targets[0]
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                attr = tgt.attr
                val = node.value
                if isinstance(val, ast.Name) and val.id in params:
                    if val.id in LOCKISH_PARAMS:
                        lid = (mm.name, cnode.name, attr)
                        cm.locks.setdefault(
                            attr,
                            self.lock_defs.setdefault(
                                lid, LockDef(lid, "lock", node.lineno)
                            ),
                        )
                        param_attr.setdefault(val.id, attr)
                    continue
                if not isinstance(val, ast.Call):
                    continue
                ctor = self._ctor_name(mm, val)
                if ctor in LOCK_CTORS:
                    lid = (mm.name, cnode.name, attr)
                    cm.locks[attr] = self.lock_defs.setdefault(
                        lid, LockDef(lid, LOCK_CTORS[ctor], node.lineno)
                    )
                elif ctor in COND_CTORS:
                    # Condition over an explicit lock aliases to it.
                    alias = None
                    args = [
                        a
                        for a in val.args
                        if not isinstance(a, ast.Constant)  # make_condition(name)
                    ]
                    for a in args:
                        if (
                            isinstance(a, ast.Attribute)
                            and isinstance(a.value, ast.Name)
                            and a.value.id == "self"
                            and a.attr in cm.locks
                        ):
                            alias = cm.locks[a.attr]
                        elif isinstance(a, ast.Name) and a.id in param_attr:
                            alias = cm.locks.get(param_attr[a.id])
                        elif isinstance(a, ast.Name) and a.id in params:
                            # Condition(lock) where the param was not (yet)
                            # stored: register the attr as the lock itself.
                            lid = (mm.name, cnode.name, attr)
                            alias = self.lock_defs.setdefault(
                                lid, LockDef(lid, "lock", node.lineno)
                            )
                    if alias is not None:
                        cm.locks[attr] = alias
                    else:
                        lid = (mm.name, cnode.name, attr)
                        cm.locks[attr] = self.lock_defs.setdefault(
                            lid, LockDef(lid, "condition", node.lineno)
                        )
                elif ctor in QUEUE_CTORS:
                    cm.queue_attrs.add(attr)
                elif ctor:
                    t = self._resolve_class(mm, val.func)
                    if t is not None:
                        cm.attr_types[attr] = t
        return cm

    def _resolve_class(self, mm: ModuleModel, func: ast.expr):
        """(module, ClassName) when the constructor resolves to a class
        defined in this package."""
        if isinstance(func, ast.Name):
            name = func.id
            if name in mm.from_imports:
                srcmod, srcname = mm.from_imports[name]
                if srcmod in self.modules:
                    return (srcmod, srcname)
            for node in mm.tree.body:
                if isinstance(node, ast.ClassDef) and node.name == name:
                    return (mm.name, name)
        elif isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            modname = mm.imports.get(func.value.id)
            if modname in self.modules:
                return (modname, func.attr)
        return None

    # -- per-function walk ---------------------------------------------------

    def _index_function(self, mm, node, cls, qualname) -> FunctionInfo:
        fi = FunctionInfo(module=mm.name, qualname=qualname, node=node, cls=cls)
        self.functions[fi.key] = fi
        _FunctionWalker(self, mm, fi).run()
        return fi

    # -- resolution helpers used by detectors --------------------------------

    def resolve_ref(self, fi: FunctionInfo, ref) -> Optional[FunctionInfo]:
        """Symbolic callee ref -> FunctionInfo, or None."""
        if ref is None:
            return None
        kind = ref[0]
        mm = self.modules.get(fi.module)
        if kind == "self" and fi.cls:
            return self.functions.get(f"{fi.module}:{fi.cls}.{ref[1]}")
        if kind == "local":
            name = ref[1]
            if name in fi.nested:
                return self.functions.get(fi.nested[name])
            got = self.functions.get(f"{fi.module}:{name}")
            if got is not None:
                return got
            if mm and name in mm.from_imports:
                srcmod, srcname = mm.from_imports[name]
                return self.functions.get(f"{srcmod}:{srcname}")
            return None
        if kind == "mod":
            modname = mm.imports.get(ref[1]) if mm else None
            if modname is None:
                return None
            return self.functions.get(f"{modname}:{ref[2]}")
        if kind == "attrcall" and fi.cls and mm:
            cm = mm.classes.get(fi.cls)
            t = cm.attr_types.get(ref[1]) if cm else None
            if t is None:
                return None
            return self.functions.get(f"{t[0]}:{t[1]}.{ref[2]}")
        return None


class _FunctionWalker:
    """Held-set walk of one function body (nested defs walk separately)."""

    def __init__(self, model: PackageModel, mm: ModuleModel, fi: FunctionInfo):
        self.model = model
        self.mm = mm
        self.fi = fi
        self.cm = mm.classes.get(fi.cls) if fi.cls else None

    def run(self) -> None:
        self.walk_body(self.fi.node.body, ())

    # -- lock resolution ----------------------------------------------------

    def lock_of(self, expr) -> Optional[LockDef]:
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and self.cm is not None
        ):
            return self.cm.locks.get(expr.attr)
        if isinstance(expr, ast.Name):
            ld = self.mm.locks.get(expr.id)
            if ld is not None:
                return ld
            # lock received as a function parameter named like a lock
            if expr.id in LOCKISH_PARAMS:
                lid = (self.fi.module, "", f"<param:{expr.id}>")
                return self.model.lock_defs.setdefault(lid, LockDef(lid, "lock"))
            return None
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            modname = self.mm.imports.get(expr.value.id)
            mm2 = self.model.modules.get(modname) if modname else None
            if mm2 is not None:
                return mm2.locks.get(expr.attr)
            # st.lock — a local whose attr is a known lock attr of some
            # class in this module (the trace-ring stripe pattern).
            for cm in self.mm.classes.values():
                if expr.attr in cm.locks and cm.locks[expr.attr].kind != "condition":
                    return cm.locks[expr.attr]
        return None

    # -- body walking -------------------------------------------------------

    def walk_body(self, stmts, held) -> None:
        held = tuple(held)
        for stmt in stmts:
            # bare ``x.acquire()`` / ``x.release()`` statements scope to
            # the rest of this body (the try/finally idiom).
            got = self._bare_acquire_release(stmt)
            if got is not None:
                op, ld = got
                if op == "acquire":
                    self._record_acquisition(ld, held, stmt.lineno)
                    if ld not in held:
                        held = held + (ld,)
                else:
                    held = tuple(x for x in held if x is not ld)
                continue
            self.walk_stmt(stmt, held)

    def _bare_acquire_release(self, stmt):
        if not (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)):
            return None
        call = stmt.value
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr in ("acquire", "release")):
            return None
        ld = self.lock_of(f.value)
        if ld is None:
            return None
        if f.attr == "acquire" and self._is_trylock(call):
            return None
        return (f.attr, ld)

    @staticmethod
    def _is_trylock(call: ast.Call) -> bool:
        for a in call.args:
            if isinstance(a, ast.Constant) and a.value is False:
                return True
        for kw in call.keywords:
            if (
                kw.arg == "blocking"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is False
            ):
                return True
        return False

    def walk_stmt(self, stmt, held) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = f"{self.fi.qualname}.<locals>.{stmt.name}"
            sub = self.model._index_function(self.mm, stmt, self.fi.cls, qual)
            self.fi.nested[stmt.name] = sub.key
            return
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_held = tuple(held)
            for item in stmt.items:
                ld = self.lock_of(item.context_expr)
                if ld is None and isinstance(item.context_expr, ast.Call):
                    # A contextmanager method that holds locks at its
                    # yield (``with self.write_txn():``) extends the
                    # held set for the body.
                    self.scan_expr(item.context_expr, held)
                    for cl in self._ctx_manager_locks(item.context_expr):
                        self._record_acquisition(cl, new_held, stmt.lineno)
                        if cl not in new_held:
                            new_held = new_held + (cl,)
                    continue
                if ld is not None:
                    self._record_acquisition(ld, new_held, stmt.lineno)
                    if ld not in new_held:
                        new_held = new_held + (ld,)
                else:
                    self.scan_expr(item.context_expr, held)
            self.walk_body(stmt.body, new_held)
            return
        for fname, value in ast.iter_fields(stmt):
            if (
                isinstance(value, list)
                and value
                and isinstance(value[0], ast.stmt)
            ):
                self.walk_body(value, held)
            elif isinstance(value, list) and value and isinstance(
                value[0], ast.excepthandler
            ):
                for h in value:
                    if h.type is not None:
                        self.scan_expr(h.type, held)
                    self.walk_body(h.body, held)
            else:
                self.scan_expr(value, held)

    # -- expression scanning ------------------------------------------------

    def scan_expr(self, node, held) -> None:
        if node is None or isinstance(node, (str, int, float, bytes, bool)):
            return
        if isinstance(node, list):
            for x in node:
                self.scan_expr(x, held)
            return
        if not isinstance(node, ast.AST):
            return
        if isinstance(node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            self.model.yield_held.setdefault(self.fi.key, set()).update(held)
        if isinstance(node, ast.Call):
            self.classify_call(node, held)
        if isinstance(node, ast.Attribute):
            self._note_trace_ref(node)
        for child in ast.iter_child_nodes(node):
            self.scan_expr(child, held)

    def _ctx_manager_locks(self, call: ast.Call):
        """Locks a ``with <call>():`` body runs under, when the callee is
        a resolvable generator contextmanager that yields while holding
        them (populated in pass 1, consumed in pass 2)."""
        ref = self._callee_ref(call.func)
        tgt = self.model.resolve_ref(self.fi, ref)
        if tgt is None:
            return ()
        return tuple(self.model.yield_held.get(tgt.key, ()))

    def _note_trace_ref(self, node: ast.Attribute) -> None:
        if (
            node.attr in TRACE_ATTRS
            and isinstance(node.value, ast.Name)
            and self.mm.imports.get(node.value.id, "").endswith("trace")
        ):
            self.fi.trace_refs.add(node.attr)

    def _record_acquisition(self, ld: LockDef, held, lineno) -> None:
        self.fi.acquisitions.append((ld, tuple(held), lineno))

    def _callee_ref(self, func):
        if isinstance(func, ast.Name):
            return ("local", func.id)
        if isinstance(func, ast.Attribute):
            v = func.value
            if isinstance(v, ast.Name):
                if v.id == "self":
                    return ("self", func.attr)
                if v.id in self.mm.imports:
                    return ("mod", v.id, func.attr)
                return ("obj", v.id, func.attr)
            if (
                isinstance(v, ast.Attribute)
                and isinstance(v.value, ast.Name)
                and v.value.id == "self"
            ):
                return ("attrcall", v.attr, func.attr)
        return None

    def classify_call(self, call: ast.Call, held) -> None:
        from nydus_snapshotter_tpu.analysis.locks import classify_blocking

        func = call.func
        ref = self._callee_ref(func)
        lineno = call.lineno

        # lock acquire in expression position (e.g. ``if l.acquire(0):``)
        if isinstance(func, ast.Attribute) and func.attr == "acquire":
            ld = self.lock_of(func.value)
            if ld is not None:
                if not self._is_trylock(call):
                    self._record_acquisition(ld, held, lineno)
                return

        # thread spawns — Thread(target=...), executor.submit(fn, ...)
        spawn = self._spawn_target(call, func)
        if spawn is not None:
            self.fi.spawns.append((spawn[0], spawn[1], lineno))

        # blocking-call classification (only interesting under a lock,
        # but recorded unconditionally so callers can reuse it)
        blocked = classify_blocking(self, call, func, held)
        if blocked is not None:
            self.fi.blocking.append(blocked)

        if ref is not None:
            self.fi.calls.append((ref, tuple(held), lineno))

    def _spawn_target(self, call: ast.Call, func):
        name = None
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
        if name == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    return (self._callee_ref_of_value(kw.value), "Thread")
            return (None, "Thread")
        if name == "submit" and isinstance(func, ast.Attribute):
            if call.args:
                return (self._callee_ref_of_value(call.args[0]), "submit")
            return (None, "submit")
        return None

    def _callee_ref_of_value(self, value):
        """A function *reference* (not call) passed as target=fn."""
        if isinstance(value, ast.Name):
            return ("local", value.id)
        if isinstance(value, ast.Attribute) and isinstance(value.value, ast.Name):
            if value.value.id == "self":
                return ("self", value.attr)
            if value.value.id in self.mm.imports:
                return ("mod", value.value.id, value.attr)
            return ("obj", value.value.id, value.attr)
        return None
