"""Finding model shared by every detector.

A finding's **fingerprint** deliberately excludes line numbers: the
baseline (analysis/baseline.toml) must survive unrelated edits to the
same file, so identity is ``detector:module:qualname:detail`` — the
detail key is chosen by each detector to be stable (lock names, metric
names, callee names), never positions.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Finding:
    detector: str  # lock-order | blocking-under-lock | drift-* | lockset
    module: str  # dotted module (or catalog file) the finding lives in
    qualname: str  # enclosing function/class, or catalog entry
    detail: str  # stable identity tail (lock pair, metric name, ...)
    message: str  # human-readable explanation
    lineno: int = 0
    severity: str = "error"  # error | warn

    @property
    def fingerprint(self) -> str:
        return f"{self.detector}:{self.module}:{self.qualname}:{self.detail}"

    def render(self) -> str:
        loc = f"{self.module}:{self.lineno}" if self.lineno else self.module
        return f"[{self.detector}] {loc} {self.qualname}: {self.message}"


@dataclass
class Report:
    """All findings from one analyzer run + baseline partition."""

    findings: list[Finding] = field(default_factory=list)
    suppressed: list[Finding] = field(default_factory=list)
    stale_suppressions: list[str] = field(default_factory=list)

    def extend(self, fs: list[Finding]) -> None:
        self.findings.extend(fs)

    def apply_baseline(self, baseline: dict[str, str]) -> None:
        """Partition findings into new vs suppressed; record baseline
        entries that no longer match anything (stale)."""
        matched: set[str] = set()
        new: list[Finding] = []
        for f in self.findings:
            if f.fingerprint in baseline:
                matched.add(f.fingerprint)
                self.suppressed.append(f)
            else:
                new.append(f)
        self.findings = new
        self.stale_suppressions = sorted(set(baseline) - matched)
