"""containerd snapshots.v1 gRPC service over the Snapshotter core.

Reference cmd/containerd-nydus-grpc/snapshotter.go:60-94 serves the
containerd snapshots API on a UDS via ``snapshotservice.FromSnapshotter``.
Here the service is hand-wired with grpc generic method handlers over the
protoc-generated messages (no grpcio-tools codegen in the environment), so
the wire format matches containerd's proxy-plugin expectation.
"""

from __future__ import annotations

import logging
import re
from concurrent import futures
from typing import Iterator, Optional

import grpc
from google.protobuf import empty_pb2

from nydus_snapshotter_tpu import trace
from nydus_snapshotter_tpu.api import snapshots_pb2 as pb
from nydus_snapshotter_tpu.api.filters import compile_filters
from nydus_snapshotter_tpu.snapshot import metastore as ms
from nydus_snapshotter_tpu.snapshot.metastore import Info, Usage
from nydus_snapshotter_tpu.snapshot.snapshotter import Snapshotter
from nydus_snapshotter_tpu.utils import errdefs

logger = logging.getLogger(__name__)

SERVICE_NAME = "containerd.services.snapshots.v1.Snapshots"

_KIND_TO_PB = {
    ms.KIND_VIEW: pb.VIEW,
    ms.KIND_ACTIVE: pb.ACTIVE,
    ms.KIND_COMMITTED: pb.COMMITTED,
}
_PB_TO_KIND = {v: k for k, v in _KIND_TO_PB.items()}


def _abort_for(context: grpc.ServicerContext, err: Exception) -> None:
    if isinstance(err, errdefs.NotFound):
        context.abort(grpc.StatusCode.NOT_FOUND, str(err))
    if isinstance(err, errdefs.AlreadyExists):
        context.abort(grpc.StatusCode.ALREADY_EXISTS, str(err))
    if isinstance(err, errdefs.InvalidArgument):
        context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(err))
    if isinstance(err, errdefs.FailedPrecondition):
        context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(err))
    if isinstance(err, errdefs.Unavailable):
        context.abort(grpc.StatusCode.UNAVAILABLE, str(err))
    logger.exception("internal error in snapshots service")
    context.abort(grpc.StatusCode.INTERNAL, str(err))


def _info_to_pb(info: Info) -> pb.Info:
    out = pb.Info(
        name=info.name,
        parent=info.parent,
        kind=_KIND_TO_PB.get(info.kind, pb.UNKNOWN),
        labels=dict(info.labels),
    )
    out.created_at.FromNanoseconds(int(info.created * 1e9))
    out.updated_at.FromNanoseconds(int(info.updated * 1e9))
    return out


def _mounts_to_pb(mounts) -> list[pb.Mount]:
    return [
        pb.Mount(type=m.type, source=m.source, options=list(m.options)) for m in mounts
    ]


class SnapshotsService:
    """Method implementations; one instance wraps one Snapshotter."""

    def __init__(self, sn: Snapshotter):
        self.sn = sn

    # Each handler: (request) -> response, with errdefs mapped to gRPC codes.
    # Every RPC opens a ROOT trace span — the tree a slow pod start hangs
    # off: snapshotter op → metastore txns → daemon mount → blobcache
    # fetches, including background work the prepare board finishes later.

    def Prepare(self, req: pb.PrepareSnapshotRequest, context) -> pb.PrepareSnapshotResponse:
        with trace.span("grpc.Prepare", key=req.key, parent=req.parent):
            try:
                mounts = self.sn.prepare(req.key, req.parent, dict(req.labels))
            except Exception as e:  # noqa: BLE001 - mapped to status codes
                _abort_for(context, e)
        return pb.PrepareSnapshotResponse(mounts=_mounts_to_pb(mounts))

    def View(self, req: pb.ViewSnapshotRequest, context) -> pb.ViewSnapshotResponse:
        with trace.span("grpc.View", key=req.key, parent=req.parent):
            try:
                mounts = self.sn.view(req.key, req.parent, dict(req.labels))
            except Exception as e:
                _abort_for(context, e)
        return pb.ViewSnapshotResponse(mounts=_mounts_to_pb(mounts))

    def Mounts(self, req: pb.MountsRequest, context) -> pb.MountsResponse:
        with trace.span("grpc.Mounts", key=req.key):
            try:
                mounts = self.sn.mounts(req.key)
            except Exception as e:
                _abort_for(context, e)
        return pb.MountsResponse(mounts=_mounts_to_pb(mounts))

    def Commit(self, req: pb.CommitSnapshotRequest, context) -> empty_pb2.Empty:
        with trace.span("grpc.Commit", key=req.key, name=req.name):
            try:
                self.sn.commit(req.name, req.key, dict(req.labels))
            except Exception as e:
                _abort_for(context, e)
        return empty_pb2.Empty()

    def Remove(self, req: pb.RemoveSnapshotRequest, context) -> empty_pb2.Empty:
        with trace.span("grpc.Remove", key=req.key):
            try:
                self.sn.remove(req.key)
            except Exception as e:
                _abort_for(context, e)
        return empty_pb2.Empty()

    def Stat(self, req: pb.StatSnapshotRequest, context) -> pb.StatSnapshotResponse:
        try:
            info = self.sn.stat(req.key)
        except Exception as e:
            _abort_for(context, e)
        return pb.StatSnapshotResponse(info=_info_to_pb(info))

    def Update(self, req: pb.UpdateSnapshotRequest, context) -> pb.UpdateSnapshotResponse:
        try:
            info = Info(
                kind=_PB_TO_KIND.get(req.info.kind, ""),
                name=req.info.name,
                parent=req.info.parent,
                labels=dict(req.info.labels),
            )
            # Pass the mask through untouched: the metastore rejects
            # unsupported paths with InvalidArgument; filtering here would
            # turn an invalid mask into a destructive full replace.
            out = self.sn.update(info, *req.update_mask.paths)
        except Exception as e:
            _abort_for(context, e)
        return pb.UpdateSnapshotResponse(info=_info_to_pb(out))

    def List(self, req: pb.ListSnapshotsRequest, context) -> Iterator[pb.ListSnapshotsResponse]:
        infos: list[pb.Info] = []
        try:
            try:
                match = compile_filters(list(req.filters))
            except (ValueError, re.error) as e:
                # A malformed filter is a caller error, not an internal one.
                raise errdefs.InvalidArgument(f"invalid filter: {e}") from e
            self.sn.walk(
                lambda _sid, info: infos.append(_info_to_pb(info)) if match(info) else None
            )
        except Exception as e:
            _abort_for(context, e)
        # containerd streams in batches; one batch is fine for our sizes.
        if infos:
            yield pb.ListSnapshotsResponse(info=infos)

    def Usage(self, req: pb.UsageRequest, context) -> pb.UsageResponse:
        with trace.span("grpc.Usage", key=req.key):
            try:
                usage: Usage = self.sn.usage(req.key)
            except Exception as e:
                _abort_for(context, e)
        return pb.UsageResponse(size=usage.size, inodes=usage.inodes)

    def Cleanup(self, req: pb.CleanupRequest, context) -> empty_pb2.Empty:
        with trace.span("grpc.Cleanup"):
            try:
                self.sn.cleanup()
            except Exception as e:
                _abort_for(context, e)
        return empty_pb2.Empty()


_METHODS = {
    "Prepare": (pb.PrepareSnapshotRequest, pb.PrepareSnapshotResponse, False),
    "View": (pb.ViewSnapshotRequest, pb.ViewSnapshotResponse, False),
    "Mounts": (pb.MountsRequest, pb.MountsResponse, False),
    "Commit": (pb.CommitSnapshotRequest, empty_pb2.Empty, False),
    "Remove": (pb.RemoveSnapshotRequest, empty_pb2.Empty, False),
    "Stat": (pb.StatSnapshotRequest, pb.StatSnapshotResponse, False),
    "Update": (pb.UpdateSnapshotRequest, pb.UpdateSnapshotResponse, False),
    "List": (pb.ListSnapshotsRequest, pb.ListSnapshotsResponse, True),
    "Usage": (pb.UsageRequest, pb.UsageResponse, False),
    "Cleanup": (pb.CleanupRequest, empty_pb2.Empty, False),
}


def add_snapshots_service(server: grpc.Server, sn: Snapshotter) -> SnapshotsService:
    service = SnapshotsService(sn)
    handlers = {}
    for name, (req_cls, _resp_cls, streaming) in _METHODS.items():
        fn = getattr(service, name)
        if streaming:
            handlers[name] = grpc.unary_stream_rpc_method_handler(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )
        else:
            handlers[name] = grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )
    return service


def worker_count(snapshots_cfg=None) -> int:
    """gRPC handler pool sized to the control plane: with the metastore
    read pool and the prepare fanout absorbing concurrent RPCs, the
    handler pool — not a global metastore lock — is the admission bound,
    so it must be at least as wide as what the control plane can overlap."""
    read_pool = getattr(snapshots_cfg, "read_pool", 8)
    fanout = getattr(snapshots_cfg, "prepare_fanout", 4)
    return max(8, read_pool + fanout)


def serve(
    sn: Snapshotter, address: str, max_workers: Optional[int] = None
) -> grpc.Server:
    """Start the snapshots gRPC server on a UDS path; returns the server."""
    if max_workers is None:
        max_workers = worker_count()
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    add_snapshots_service(server, sn)
    server.add_insecure_port(f"unix:{address}")
    server.start()
    return server
