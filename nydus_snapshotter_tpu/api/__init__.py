"""gRPC API surface (containerd snapshots.v1-compatible)."""
