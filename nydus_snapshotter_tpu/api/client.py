"""Client for the snapshots.v1 gRPC service (tests + tooling).

containerd itself is the production client; this mirrors the minimal stub
surface so integration tests can drive the server exactly the way the
proxy plugin would.
"""

from __future__ import annotations

from typing import Optional

import grpc
from google.protobuf import empty_pb2

from nydus_snapshotter_tpu.api import snapshots_pb2 as pb
from nydus_snapshotter_tpu.api.service import SERVICE_NAME, _METHODS


class SnapshotsClient:
    def __init__(self, address: str, timeout: float = 30.0):
        self.channel = grpc.insecure_channel(f"unix:{address}")
        self.timeout = timeout
        self._stubs = {}
        for name, (req_cls, resp_cls, streaming) in _METHODS.items():
            path = f"/{SERVICE_NAME}/{name}"
            if streaming:
                self._stubs[name] = self.channel.unary_stream(
                    path,
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                )
            else:
                self._stubs[name] = self.channel.unary_unary(
                    path,
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                )

    def close(self) -> None:
        self.channel.close()

    def _call(self, name: str, req):
        return self._stubs[name](req, timeout=self.timeout)

    def prepare(self, key: str, parent: str = "", labels: Optional[dict] = None):
        resp = self._call(
            "Prepare", pb.PrepareSnapshotRequest(key=key, parent=parent, labels=labels or {})
        )
        return list(resp.mounts)

    def view(self, key: str, parent: str = "", labels: Optional[dict] = None):
        resp = self._call(
            "View", pb.ViewSnapshotRequest(key=key, parent=parent, labels=labels or {})
        )
        return list(resp.mounts)

    def mounts(self, key: str):
        return list(self._call("Mounts", pb.MountsRequest(key=key)).mounts)

    def commit(self, name: str, key: str, labels: Optional[dict] = None) -> None:
        self._call("Commit", pb.CommitSnapshotRequest(name=name, key=key, labels=labels or {}))

    def remove(self, key: str) -> None:
        self._call("Remove", pb.RemoveSnapshotRequest(key=key))

    def stat(self, key: str) -> pb.Info:
        return self._call("Stat", pb.StatSnapshotRequest(key=key)).info

    def update(self, info: pb.Info, *fieldpaths: str) -> pb.Info:
        req = pb.UpdateSnapshotRequest(info=info)
        req.update_mask.paths.extend(fieldpaths)
        return self._call("Update", req).info

    def list(self) -> list[pb.Info]:
        out: list[pb.Info] = []
        for batch in self._call("List", pb.ListSnapshotsRequest()):
            out.extend(batch.info)
        return out

    def usage(self, key: str) -> pb.UsageResponse:
        return self._call("Usage", pb.UsageRequest(key=key))

    def cleanup(self) -> None:
        self._call("Cleanup", pb.CleanupRequest())
