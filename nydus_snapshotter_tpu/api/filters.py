"""containerd filter expressions for snapshot List/Walk.

Subset of containerd's filters grammar (github.com/containerd/containerd
filters package) that snapshot walkers actually use: each filter string is
a comma-separated AND of clauses; the filter list is an OR. Clauses:

    field==value   field!=value   field~=regex   field (presence)

Fields: ``name``, ``parent``, ``kind``, ``labels.<key>`` where the key may
be quoted (``labels."containerd.io/snapshot.ref"``).
"""

from __future__ import annotations

import re
from typing import Callable, Sequence

_CLAUSE_RE = re.compile(
    r"""^\s*
    (?P<field>[A-Za-z_][\w]*(?:\.(?:"[^"]*"|[\w./-]+))?)
    \s*(?:(?P<op>==|!=|~=)\s*(?P<value>"[^"]*"|[^,]*))?\s*$""",
    re.VERBOSE,
)


def _unquote(s: str) -> str:
    s = s.strip()
    if len(s) >= 2 and s[0] == '"' and s[-1] == '"':
        return s[1:-1]
    return s


def _field_value(info, field: str) -> tuple[str, bool]:
    """(value, present) of a filter field on a snapshot Info."""
    if field.startswith("labels."):
        key = _unquote(field[len("labels."):])
        labels = getattr(info, "labels", None) or {}
        if key in labels:
            return labels[key], True
        return "", False
    if field in ("name", "parent", "kind"):
        val = getattr(info, field, "")
        return str(val), val != ""
    return "", False


def _split_clauses(filter_str: str) -> list[str]:
    """Split on commas not inside quotes."""
    out, cur, in_q = [], [], False
    for ch in filter_str:
        if ch == '"':
            in_q = not in_q
            cur.append(ch)
        elif ch == "," and not in_q:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return [c for c in (s.strip() for s in out) if c]


def _compile_clause(clause: str) -> Callable[[object], bool]:
    m = _CLAUSE_RE.match(clause)
    if not m:
        raise ValueError(f"invalid filter clause {clause!r}")
    field, op, value = m.group("field"), m.group("op"), m.group("value")
    if op is None:
        return lambda info: _field_value(info, field)[1]
    val = _unquote(value or "")
    if op == "==":
        return lambda info: _field_value(info, field) == (val, True)
    if op == "!=":
        return lambda info: _field_value(info, field) != (val, True)
    rx = re.compile(val)
    return lambda info: (lambda fv: fv[1] and rx.search(fv[0]) is not None)(_field_value(info, field))


def compile_filters(filters: Sequence[str]) -> Callable[[object], bool]:
    """Predicate over Info: OR of filter strings, AND of clauses. An empty
    filter list matches everything (containerd semantics)."""
    if not filters:
        return lambda _info: True
    alternatives: list[list[Callable[[object], bool]]] = []
    for f in filters:
        clauses = [_compile_clause(c) for c in _split_clauses(f)]
        alternatives.append(clauses)
    return lambda info: any(all(c(info) for c in clauses) for clauses in alternatives)
