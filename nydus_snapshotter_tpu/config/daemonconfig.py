"""Daemon (nydusd-equivalent) runtime config model.

Reference: config/daemonconfig/{daemonconfig,fuse,fscache}.go — a JSON
template per fs driver, supplemented at mount time with auth, cache dir and
prefetch settings, with ``secret`` fields filtered before any API exposure
(daemonconfig.go:191-239).
"""

from __future__ import annotations

import copy
import json
from dataclasses import dataclass, field, fields
from typing import Any, Optional

from nydus_snapshotter_tpu import constants

# Field names whose values are secrets; filtered from API-exposed dumps
# (reference tags `secret:"true"`).
_SECRET_FIELDS = {"auth", "registry_token", "access_key_secret", "secret_access_key", "password"}


class DaemonConfigError(ValueError):
    pass


@dataclass
class MirrorConfig:
    host: str = ""
    headers: dict[str, str] = field(default_factory=dict)
    health_check_interval: int = 5
    failure_limit: int = 5
    ping_url: str = ""


@dataclass
class BackendConfig:
    """Storage backend for lazy reads: registry / oss / s3 / localfs."""

    backend_type: str = "registry"
    # registry
    host: str = ""
    repo: str = ""
    auth: str = ""  # secret
    registry_token: str = ""  # secret
    scheme: str = "https"
    skip_verify: bool = False
    mirrors: list[MirrorConfig] = field(default_factory=list)
    # oss/s3
    endpoint: str = ""
    bucket_name: str = ""
    access_key_id: str = ""
    access_key_secret: str = ""  # secret
    # localfs
    blob_dir: str = ""
    # tuning
    connect_timeout: int = 5
    timeout: int = 5
    retry_limit: int = 2


@dataclass
class CacheConfig:
    cache_type: str = "blobcache"
    work_dir: str = ""
    disable_indexed_map: bool = False
    compressed: bool = False


@dataclass
class RafsInstanceConfig:
    mode: str = "direct"
    digest_validate: bool = False
    enable_xattr: bool = True
    amplify_io: int = 0
    prefetch_enable: bool = False
    prefetch_threads: int = 4
    prefetch_merging_size: int = 131072


@dataclass
class DaemonRuntimeConfig:
    """One daemon's full runtime config (fuse or fscache flavored)."""

    fs_driver: str = constants.FS_DRIVER_FUSEDEV
    backend: BackendConfig = field(default_factory=BackendConfig)
    cache: CacheConfig = field(default_factory=CacheConfig)
    rafs: RafsInstanceConfig = field(default_factory=RafsInstanceConfig)
    threads_number: int = 4

    @classmethod
    def from_template(cls, path: str, fs_driver: str) -> "DaemonRuntimeConfig":
        with open(path, "rb") as f:
            data = json.load(f)
        return cls.from_dict(data, fs_driver)

    @classmethod
    def from_dict(cls, data: dict[str, Any], fs_driver: str) -> "DaemonRuntimeConfig":
        cfg = cls(fs_driver=fs_driver)
        device = data.get("device", {})
        be = device.get("backend", {})
        cfg.backend.backend_type = be.get("type", cfg.backend.backend_type)
        bcfg = be.get("config", {})
        for f_ in fields(BackendConfig):
            json_key = {"backend_type": "type"}.get(f_.name, f_.name)
            if json_key in bcfg:
                setattr(cfg.backend, f_.name, bcfg[json_key])
        # Mirrors arrive as JSON objects; normalize to MirrorConfig records
        # (unknown keys dropped) so consumers get attribute access.
        cfg.backend.mirrors = [
            m
            if isinstance(m, MirrorConfig)
            else MirrorConfig(
                **{
                    k: v
                    for k, v in m.items()
                    if k in {f.name for f in fields(MirrorConfig)}
                }
            )
            for m in cfg.backend.mirrors
            if isinstance(m, (dict, MirrorConfig))
        ]
        cache = device.get("cache", {})
        cfg.cache.cache_type = cache.get("type", cfg.cache.cache_type)
        ccfg = cache.get("config", {})
        cfg.cache.work_dir = ccfg.get("work_dir", cfg.cache.work_dir)
        cfg.cache.compressed = ccfg.get("compressed", cfg.cache.compressed)
        rafs = data.get("rafs", data.get("fs", {}))
        for f_ in fields(RafsInstanceConfig):
            if f_.name in rafs:
                setattr(cfg.rafs, f_.name, rafs[f_.name])
        return cfg

    def to_dict(self, filter_secrets: bool = False) -> dict[str, Any]:
        def scrub(name: str, value: Any) -> Any:
            if filter_secrets and name in _SECRET_FIELDS:
                return ""
            return value

        backend_cfg = {
            f_.name: scrub(f_.name, getattr(self.backend, f_.name))
            for f_ in fields(BackendConfig)
            if f_.name != "backend_type"
        }
        backend_cfg["mirrors"] = [
            copy.deepcopy(m.__dict__) for m in self.backend.mirrors
        ]
        return {
            "fs_driver": self.fs_driver,
            "device": {
                "backend": {"type": self.backend.backend_type, "config": backend_cfg},
                "cache": {
                    "type": self.cache.cache_type,
                    "config": {
                        "work_dir": self.cache.work_dir,
                        "compressed": self.cache.compressed,
                    },
                },
            },
            "rafs": copy.deepcopy(self.rafs.__dict__),
            "threads_number": self.threads_number,
        }

    def dump(self, path: str) -> None:
        """Persist per-daemon config so mounts can be replayed after crash
        (reference fs.go:363-370, daemon.go:256-267)."""
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, sort_keys=True, indent=2)

    def exposed(self) -> dict[str, Any]:
        """Secret-filtered view for the system controller API."""
        return self.to_dict(filter_secrets=True)

    def supplement(
        self,
        *,
        image_ref: str = "",
        auth: str = "",
        work_dir: str = "",
        prefetch_files: Optional[list[str]] = None,
        mirrors_config_dir: str = "",
    ) -> None:
        """Per-mount supplementation (reference daemonconfig.go:150-189)."""
        if image_ref:
            host, _, repo = image_ref.partition("/")
            self.backend.host = host
            self.backend.repo = repo.split(":")[0].split("@")[0]
            if mirrors_config_dir:
                # per-host mirror dirs à la containerd certs.d
                # (daemonconfig.go:165-171 + mirrors.go)
                from nydus_snapshotter_tpu.config.mirrors import load_mirrors_config

                mirrors = load_mirrors_config(mirrors_config_dir, host)
                if mirrors:
                    self.backend.mirrors = mirrors
        if auth:
            self.backend.auth = auth
        if work_dir:
            self.cache.work_dir = work_dir
        if prefetch_files:
            self.rafs.prefetch_enable = True
