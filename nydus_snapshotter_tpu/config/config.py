"""Snapshotter configuration system.

Reference behavior (config/config.go:223-399, internal/constant/values.go):
a versioned TOML file with per-subsystem sections, deep-merged over defaults,
overridden by CLI parameters, validated (including the unix(7) sun_path
limit on the root path), then frozen behind package-global accessors.

Implemented as nested dataclasses + dict deep-merge: ``load_config`` is the
one entry point (defaults ← TOML ← overrides → validate).
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Any, Optional

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu.utils.tomlcompat import tomllib


class ConfigError(ValueError):
    pass


@dataclass
class SystemConfig:
    enable: bool = True
    address: str = constants.DEFAULT_SYSTEM_CONTROLLER_ADDRESS
    # pprof-equivalent debug profiler endpoint (reference DebugConfig)
    debug_profile_duration_secs: int = 5
    debug_pprof_address: str = ""


@dataclass
class MetricsConfig:
    address: str = constants.DEFAULT_METRICS_ADDRESS


@dataclass
class DaemonConfig:
    nydusd_path: str = ""
    nydusd_config_path: str = "/etc/nydus/nydusd-config.json"
    recover_policy: str = constants.RECOVER_POLICY_RESTART
    # Restart budget / circuit breaker for the restart+failover policies:
    # at most recover_max_restarts respawns per recover_window_secs, with
    # exponential backoff between them; past the budget the daemon is
    # degraded to passthrough instead of hot-looping.
    recover_max_restarts: int = 3
    recover_window_secs: float = 60.0
    recover_backoff_secs: float = 0.5
    recover_backoff_max_secs: float = 8.0
    fs_driver: str = constants.DEFAULT_FS_DRIVER
    threads_number: int = 4
    log_rotation_size: int = 100  # MiB
    # TPU sidecar (conversion data plane) settings
    accel_enable: bool = True
    accel_chunk_size: int = constants.CHUNK_SIZE_DEFAULT
    accel_backend: str = "hybrid"  # calibrated crossover, like PackOption


@dataclass
class CgroupConfig:
    enable: bool = False
    memory_limit: str = ""


@dataclass
class LoggingConfig:
    log_level: str = constants.DEFAULT_LOG_LEVEL
    log_dir: str = ""
    log_to_stdout: bool = True
    rotate_log_max_size: int = 200  # MiB
    rotate_log_max_backups: int = 5
    rotate_log_max_age: int = 0
    rotate_log_compress: bool = True


@dataclass
class MirrorConfig:
    host: str = ""
    headers: dict[str, str] = field(default_factory=dict)
    health_check_interval: int = 5
    failure_limit: int = 5
    ping_url: str = ""


@dataclass
class RemoteConfig:
    convert_vpc_registry: bool = False
    skip_ssl_verify: bool = False
    mirrors_config_dir: str = ""
    auth_config_path: str = ""


@dataclass
class SnapshotConfig:
    enable_nydus_overlayfs: bool = False
    nydus_overlayfs_path: str = "nydus-overlayfs"
    sync_remove: bool = False


@dataclass
class CacheManagerConfig:
    enable: bool = True
    gc_period: str = constants.DEFAULT_GC_PERIOD
    cache_dir: str = ""


@dataclass
class ImageConfig:
    public_key_file: str = ""
    validate_signature: bool = False
    check_pause_image: bool = False


@dataclass
class ConvertConfig:
    """Stage-parallel conversion pipeline knobs (parallel/pipeline.py).

    The pipeline overlaps chunk/digest, speculative compression and
    ordered blob assembly inside one layer, and bounds memory in BYTES:
    per-queue (``queue_mib``), actively-chunked window (``window_mib``)
    and compressed-bytes-in-flight aggregate (``memory_budget_mib``,
    shared across every concurrently converting layer). Worker counts of
    0 mean auto (the pack-path worker request, clamped to cores).
    Environment variables override per-process (``NTPU_PIPELINE``,
    ``NTPU_CHUNK_THREADS``, ``NTPU_COMPRESS_THREADS``,
    ``NTPU_PIPELINE_{QUEUE,BUDGET,WINDOW}_MIB``).
    """

    pipeline: str = "auto"  # auto | on | off
    chunk_workers: int = 0
    compress_workers: int = 0
    queue_mib: int = 32
    memory_budget_mib: int = 256
    window_mib: int = 64
    # Concurrently packing layers in batch conversion (0 = pool default).
    layer_fanout: int = 0


@dataclass
class CompressionConfig:
    """Adaptive per-chunk codec knobs (converter/codec.py).

    With ``adaptive`` on (and the pack compressor ``zstd``), every chunk
    gets a cheap compressibility probe — a sampled level-1
    trial-compress (``probe = "sample"``) or a byte-entropy estimate
    (``"entropy"``) — and is then stored raw (predicted ratio ≥
    ``bypass_ratio``: the incompressibility bypass), compressed at
    ``level_fast`` (≥ ``low_gain_ratio``), at ``level_best`` (≤
    ``high_gain_ratio``) or at ``level_default`` (0 = the fixed
    reference level). ``dict_path`` loads an epoch-stamped corpus-trained
    zstd dictionary; ``train`` trains one per namespace from chunk
    samples during batch convert (``train_dict_kib`` target size,
    ``train_sample_mib`` sample budget) and shares it through the dict
    service. OFF by default: pack output stays byte-identical to the
    reference lane. Enabling trained dictionaries is a chunk-frame
    format change — frames carry a versioned ``nZD1`` header and readers
    without the dictionary fail loudly.

    Two throughput knobs ride in this section because both are resolved
    with the codec config and both hold byte-identity: ``batch_chunks``
    sets how many queued chunks a pipeline compress worker drains into
    ONE GIL-released native batch-encode call (0/1 = per-chunk), and
    ``vectorized`` picks the CDC scan arm — ``auto`` uses the SIMD
    lane-parallel table scanner when built, ``on`` requires it, ``off``
    forces the sequential scanner; cut positions are identical across
    arms. Environment variables override per-process
    (``NTPU_COMPRESS_ADAPTIVE``, ``NTPU_COMPRESS_PROBE``,
    ``NTPU_COMPRESS_PROBE_SAMPLE_KIB``, ``NTPU_COMPRESS_BYPASS_RATIO``,
    ``NTPU_COMPRESS_DICT``, ``NTPU_COMPRESS_TRAIN``,
    ``NTPU_COMPRESS_LEVELS`` — "fast,default,best" triple,
    ``NTPU_COMPRESS_BATCH_CHUNKS``, ``NTPU_COMPRESS_VECTORIZED``) — that
    is also how the section reaches spawned converter processes.
    """

    adaptive: bool = False
    probe: str = "sample"  # sample | entropy | off
    probe_sample_kib: int = 16
    bypass_ratio: float = 0.97
    low_gain_ratio: float = 0.85
    high_gain_ratio: float = 0.35
    level_fast: int = 1
    level_default: int = 0  # 0 = constants.ZSTD_LEVEL
    level_best: int = 3  # ratio-neutral default; raise to trade speed → ratio
    dict_path: str = ""
    train: bool = False
    train_dict_kib: int = 112
    train_sample_mib: int = 8
    batch_chunks: int = 16  # compress-worker batch size (0/1 = per-chunk)
    vectorized: str = "auto"  # auto | on | off — CDC scan arm


@dataclass
class BlobcacheConfig:
    """Lazy-read data plane knobs (daemon/fetch_sched.py).

    Cache misses are scheduled on a per-blob fetch worker pool: adjacent
    miss gaps within ``merge_gap_kib`` coalesce into one ranged GET,
    sequential readers get ``readahead_kib`` of background warming, and
    all fetches draw from one ``inflight_budget_mib`` byte budget shared
    across every lazily-read blob. ``eviction_watermark_mib`` bounds
    total blob-cache capacity (0 disables; LRU whole-entry eviction in
    cache/manager.py). Environment variables override per-process
    (``NTPU_BLOBCACHE_WORKERS``, ``NTPU_BLOBCACHE_MERGE_GAP_KIB``,
    ``NTPU_BLOBCACHE_READAHEAD_KIB``, ``NTPU_BLOBCACHE_BUDGET_MIB``,
    ``NTPU_BLOBCACHE_WATERMARK_MIB``, ``NTPU_BLOBCACHE_PREFETCH``) —
    that is also how the section reaches spawned daemon processes.
    """

    fetch_workers: int = 4
    merge_gap_kib: int = 128
    readahead_kib: int = 1024
    inflight_budget_mib: int = 64
    eviction_watermark_mib: int = 0
    prefetch_replay: bool = True


@dataclass
class PeerConfig:
    """Peer chunk tier + QoS admission knobs (daemon/peer.py,
    daemon/fetch_sched.AdmissionGate).

    With ``enable`` on, the node serves ranged reads for locally cached
    chunk extents on ``listen`` (a UDS path or ``host:port``) and routes
    its own misses through the static ``peers`` list before the registry
    (registry -> peer -> local-cache waterfall): region ownership is
    rendezvous-hashed per ``region_kib`` region, the owner pull-throughs
    cold extents (``pull_through``) so a chunk leaves the origin at most
    ~once per cluster, and every peer read is bounded by ``timeout_ms``
    with transparent registry fallback. ``max_concurrent`` (0 = default
    64) bounds operations admitted through the node's QoS gate, of which
    ``demand_reserve`` slots only demand reads may use;
    ``tenant_weights`` sets weighted in-flight byte fairness between
    tenants (unlisted tenants weigh 1.0). Environment variables override
    per-process (``NTPU_PEER_ENABLE``, ``NTPU_PEER_LISTEN``,
    ``NTPU_PEER_PEERS``, ``NTPU_PEER_REGION_KIB``,
    ``NTPU_PEER_TIMEOUT_MS``, ``NTPU_PEER_PULL_THROUGH``,
    ``NTPU_PEER_MAX_CONCURRENT``, ``NTPU_PEER_DEMAND_RESERVE``,
    ``NTPU_PEER_TENANT_WEIGHTS``, ``NTPU_PEER_LOCALITY``,
    ``NTPU_PEER_HEDGE``, ``NTPU_PEER_HEDGE_WINDOW``,
    ``NTPU_PEER_TIER_BUDGETS``) — that is also how the section reaches
    spawned daemon processes.
    """

    enable: bool = False
    listen: str = ""
    peers: list[str] = field(default_factory=list)
    region_kib: int = 512
    timeout_ms: int = 1500
    pull_through: bool = True
    max_concurrent: int = 0
    demand_reserve: int = 1
    tenant_weights: dict[str, float] = field(default_factory=dict)
    # Dynamic membership (daemon/peer.PeerMembership): "fleet" discovers
    # the live peer set from the member registry (the static ``peers``
    # list stays as the seed/fallback), "static" pins the pre-dynamic
    # behavior, "auto" (default) goes dynamic exactly when a fleet
    # controller address is known to this process. Env overrides:
    # ``NTPU_PEER_MEMBERSHIP``, ``NTPU_PEER_MEMBERSHIP_REFRESH_MS``.
    membership: str = "auto"
    membership_refresh_secs: float = 2.0
    # Hierarchical topology (daemon/peer.PeerRouter): ``locality`` is a
    # ``rack:zone:region`` label (empty = flat single-tier routing);
    # lookups walk rack owner -> zone shield -> origin. ``hedge`` arms
    # the demand-lane hedged second request once a flight exceeds the
    # rolling per-tier p99 over the last ``hedge_window`` samples
    # (0 = default 64, minimum 8). ``tier_budgets`` caps in-flight bytes
    # per tier ({"zone": 32} = 32 MiB) so a melting zone cannot starve
    # rack-local service.
    locality: str = ""
    hedge: bool = True
    hedge_window: int = 0
    tier_budgets: dict[str, int] = field(default_factory=dict)


@dataclass
class SociConfig:
    """Seekable-OCI backend knobs (soci/).

    With ``enable`` on, plain OCI ``.tar.gz`` layers that carry no nydus,
    estargz or tarfs cooperation are claimed at Prepare and lazily served
    WITHOUT conversion: the first pull builds a persisted, checksummed
    zran checkpoint index (gzip inflate resume points every
    ``stride_kib`` of decompressed output + a per-layer
    file→decompressed-extent map) into the cache dir next to the blob's
    chunk map, and runtime reads resolve to compressed byte ranges of
    the original layer, fetched through the ordinary lazy-read data
    plane (fetch scheduler, eviction, peer tier, QoS lanes). A smaller
    stride means less read amplification but a bigger index (~32 KiB of
    window per checkpoint, compressed). With ``replicate`` on, a pod
    missing an index asks the blob's peer-tier region owner before
    rebuilding, so one pod's first-pull build amortizes across the
    fleet. Environment variables override per-process
    (``NTPU_SOCI_ENABLE``, ``NTPU_SOCI_STRIDE_KIB``,
    ``NTPU_SOCI_REPLICATE``) — that is also how the section reaches
    spawned daemon processes.
    """

    enable: bool = False
    stride_kib: int = 1024
    replicate: bool = True
    # zstd half of the lazy plane: frame-index zstd layers (seekable
    # seek-table parse, or a frame walk during the one first-pull pass)
    # instead of full pull + RAFS convert. NTPU_SOCI_ZSTD overrides.
    zstd: bool = True
    # Adopt a shipped TOC (eStargz / zstd:chunked) as the file→extent
    # map — zero build-pass bytes on those layers. NTPU_SOCI_TOC_ADOPT
    # overrides.
    toc_adopt: bool = True


@dataclass
class SnapshotsConfig:
    """Concurrent snapshot control-plane knobs
    (snapshot/{metastore,snapshotter,async_work}.py).

    The metastore serves reads from a pool of per-connection WAL readers
    (``read_pool``) while all mutations funnel through one serialized
    writer; ancestor chains are memoized in a bounded LRU
    (``ancestor_cache`` entries, 0 disables). Prepare's slow tail (daemon
    readiness, stargz bootstrap build) overlaps on a ``prepare_fanout``
    pool joined at ``mounts()``; commit's disk-usage scan moves to
    ``usage_workers`` async accountants joined at ``usage()``; Cleanup
    removes orphan dirs on ``cleanup_workers`` threads. A worker count of
    0 (prepare/usage) restores the fully serial control plane.
    Environment variables override per-process (``NTPU_SNAPSHOT_READ_POOL``,
    ``NTPU_SNAPSHOT_PREPARE_FANOUT``, ``NTPU_SNAPSHOT_USAGE_WORKERS``,
    ``NTPU_SNAPSHOT_CLEANUP_WORKERS``, ``NTPU_SNAPSHOT_ANCESTOR_CACHE``).
    """

    read_pool: int = 8
    prepare_fanout: int = 4
    usage_workers: int = 1
    cleanup_workers: int = 4
    ancestor_cache: int = 1024


@dataclass
class TraceConfig:
    """End-to-end request tracing knobs (trace/).

    Spans propagate a trace id from the gRPC entry points through the
    metastore, the prepare board, the daemon mount path and the lazy-read
    fetch scheduler, land in a bounded ring of ``ring_capacity`` spans
    (drop-oldest), and export as Chrome ``trace_event`` JSON on
    ``/api/v1/traces``. Any root operation slower than
    ``slow_op_threshold_ms`` gets its full span tree logged by the
    slow-op flight recorder. ``sample_ratio`` < 1 traces that fraction of
    roots (the decision is made once per trace). Environment variables
    override per-process (``NTPU_TRACE``, ``NTPU_TRACE_RING_CAPACITY``,
    ``NTPU_TRACE_SLOW_OP_MS``, ``NTPU_TRACE_SAMPLE_RATIO``) — that is
    also how the section reaches spawned daemon processes.
    """

    enabled: bool = True
    ring_capacity: int = 8192
    slow_op_threshold_ms: float = 1000.0
    sample_ratio: float = 1.0


@dataclass
class ChunkDictConfig:
    """Growable cross-repo chunk dictionary knobs
    (parallel/{sharded_dict,dict_service}.py).

    The dict builds its open-addressing tables with ``headroom``× spare
    capacity and grows in place: incremental inserts open-address into the
    spare slots (cost proportional to the inserted batch) until occupancy
    crosses ``load_factor``, at which point the table does one
    value-preserving rebuild with fresh headroom. ``service`` names the
    UDS address of a shared :class:`DictService` so converter workers
    dedup against one registry-wide table per ``namespace`` instead of
    per-process copies ("" = in-process dict, no service).
    ``service_backend`` picks the service's probe arm (``auto`` = native
    host probe on one shard, the mesh-routed ``device`` probe on a multi-
    chip mesh).

    HA replication (``ha/``, docs/chunk_dict_service.md HA section):
    ``shards`` is the placement controller's key-space shard count and
    ``replicas`` how many warm replicas each shard's primary gets
    (0 = HA off). ``replication_budget_kib`` bounds the bytes a replica
    holds in flight per record-tail pull (the bounded-memory catch-up
    contract) and ``replication_poll_ms`` the journal-tail poll cadence.

    Environment variables override per-process
    (``NTPU_DICT_LOAD_FACTOR``, ``NTPU_DICT_HEADROOM``,
    ``NTPU_DICT_SERVICE``, ``NTPU_DICT_NAMESPACE``,
    ``NTPU_DICT_HA_SHARDS``, ``NTPU_DICT_HA_REPLICAS``,
    ``NTPU_DICT_HA_BUDGET_KIB``, ``NTPU_DICT_HA_POLL_MS``) — that is
    also how the section reaches spawned converter/dict processes.
    """

    load_factor: float = 0.85
    headroom: float = 2.0
    service: str = ""
    namespace: str = "default"
    service_backend: str = "auto"
    shards: int = 1
    replicas: int = 0
    replication_budget_kib: int = 256
    replication_poll_ms: float = 50.0


@dataclass
class ProvenanceConfig:
    """Byte-provenance plane knobs (provenance/).

    With ``enable`` on, every fetched extent entering the lazy-read data
    plane is attributed to its cause (demand, readahead, prefetch,
    peer_serve, hedge_winner, hedge_loser, soci_index_build) in a
    lock-striped per-blob ledger with byte-exact conservation; overlap
    with the actually-read extent set yields per-cause wasted-bytes and
    prefetch-accuracy accounting (``ntpu_prov_*`` metrics, the
    ``/api/v1/provenance`` endpoint and the ``ntpuctl prov`` /
    ``ntpuctl waterfall`` views). With ``heat`` on, unmount distills the
    read-extent heat into a persisted, checksummed ``.heat`` prefetch
    artifact next to the blob cache, so the NEXT deploy prefetches in
    observed-heat order under a ``heat_budget_mib`` byte budget instead
    of bootstrap order; ``replicate`` shares the artifact over the peer
    artifact plane so one pod's first deploy warms the fleet's second.
    ``events`` bounds the per-blob waterfall event ring (drop-oldest).
    Environment variables override per-process (``NTPU_PROV``,
    ``NTPU_PROV_HEAT``, ``NTPU_PROV_HEAT_BUDGET_MIB``,
    ``NTPU_PROV_EVENTS``, ``NTPU_PROV_REPLICATE``) — that is also how
    the section reaches spawned daemon processes.
    """

    enable: bool = True
    heat: bool = True
    heat_budget_mib: int = 64
    events: int = 4096
    replicate: bool = True


@dataclass
class FleetConfig:
    """Fleet observability plane knobs (fleet/, metrics/federation.py,
    trace/aggregate.py).

    With ``enable`` on, the system controller keeps a member registry
    (spawned daemons, standalone dict services and peer servers
    self-register over the controller UDS), scrapes every member's
    metrics endpoint every ``scrape_interval_secs`` and serves the
    federated exposition (``node``/``component`` labels), the derived
    health scoreboard and the cluster-merged Chrome trace on
    ``/api/v1/fleet/...``. A member whose last successful scrape is
    older than ``stale_after_secs`` is flagged stale (the scoreboard
    degrades; the scrape never wedges). The scoreboard's local-process
    rows come from one cached ``collect_once`` snapshot at most
    ``scoreboard_max_age_secs`` old, so a slow collector cannot stall
    concurrent scrapes. ``controller`` is the member-side knob: the
    controller UDS a non-snapshotter process registers itself with
    ("" = don't register). Environment variables override per-process
    (``NTPU_FLEET``, ``NTPU_FLEET_CONTROLLER``, ``NTPU_FLEET_MEMBER``,
    ``NTPU_FLEET_SCRAPE_INTERVAL_SECS``, ``NTPU_FLEET_STALE_AFTER_SECS``,
    ``NTPU_FLEET_SCOREBOARD_MAX_AGE_SECS``) — the env is also how the
    controller address reaches spawned daemon processes.
    """

    enable: bool = False
    scrape_interval_secs: float = 15.0
    stale_after_secs: float = 45.0
    scoreboard_max_age_secs: float = 5.0
    controller: str = ""


@dataclass
class SloConfig:
    """Declarative service-level objectives (metrics/slo.py).

    Each ``[[slo.objectives]]`` table names an op-duration histogram
    (``metric`` + optional ``labels`` filter), a latency ``threshold_ms``
    that must align to a bucket boundary, and a ``target`` compliance
    fraction evaluated over a sliding ``window_secs`` window (plus a
    ``long_window_factor``× long window). The engine ticks every
    ``eval_interval_secs``, exports ``ntpu_slo_*`` series, accounts the
    error budget, and raises a breach event — with the slow-op flight
    recorder dump attached — when the burn rate exceeds
    ``burn_threshold`` on BOTH windows. Environment variables override
    per-process (``NTPU_SLO``, ``NTPU_SLO_EVAL_INTERVAL_SECS``,
    ``NTPU_SLO_OBJECTIVES`` — a JSON list of objective tables).
    """

    enable: bool = False
    eval_interval_secs: float = 10.0
    objectives: list[dict] = field(default_factory=list)
    # Close the loop (metrics/slo.SloActuator): with ``actuate`` on, a
    # multi-window breach sheds one more lane from ``shed_lanes`` per
    # evaluation tick (least-important first; the demand lane is not
    # sheddable) on the controller's admission gate, and member processes
    # following the published state (``follow``, applied by spawned
    # daemons) shed the same lanes on theirs. Lanes restore one per tick
    # once every objective's short-window burn drops under
    # ``restore_burn``. Env overrides: ``NTPU_SLO_ACTUATE``,
    # ``NTPU_SLO_SHED_LANES``, ``NTPU_SLO_RESTORE_BURN``,
    # ``NTPU_SLO_FOLLOW``.
    actuate: bool = False
    shed_lanes: list[str] = field(default_factory=list)
    restore_burn: float = 1.0
    follow: bool = True


@dataclass
class ScenarioConfig:
    """Scenario engine knobs (scenario/, tools/scenario_storm.py).

    ``spec_dir`` is the catalog of ``*.toml`` scenario specs
    (``ntpuctl scenario`` lists it; "" = the repo's ``misc/scenarios``).
    ``report_path`` is where the gated storm banks its last-run report
    JSON ("" = the repo's ``SCENARIO_STORM_r01.json``); ``seed`` and
    ``pods`` are the defaults a spec inherits when it doesn't pin its
    own. Environment variables override per-process
    (``NTPU_SCENARIO_SPEC_DIR``, ``NTPU_SCENARIO_REPORT``,
    ``NTPU_SCENARIO_SEED``, ``NTPU_SCENARIO_PODS``).
    """

    spec_dir: str = ""
    report_path: str = ""
    seed: int = 7
    pods: int = 16


@dataclass
class SoakConfig:
    """Endurance-soak runner knobs (scenario/soak.py, tools/soak_profile.py).

    ``epochs`` overrides the spec's ``[scenario.soak]`` epoch count
    (0 = use the spec's); ``spot_epochs`` is how many epochs the gated
    profile replays serially for the identity spot-check;
    ``report_path`` is where the profile banks its report JSON ("" =
    the repo's ``SOAK_r01.json``). Environment variables override
    per-process (``NTPU_SOAK_EPOCHS``, ``NTPU_SOAK_SPOT_EPOCHS``,
    ``NTPU_SOAK_REPORT``). The arrival/evolution/scale-up shape itself
    lives in the spec's ``[scenario.soak]`` table, not here — a soak
    must be reproducible from the spec alone.
    """

    epochs: int = 0
    spot_epochs: int = 2
    report_path: str = ""


@dataclass
class MeshConfig:
    """Device-mesh convert sharding knobs (ops/mesh_pack.py,
    __graft_entry__.sharded_convert_step).

    ``pack`` picks the pass-2 corpus operand layout: ``extent`` (default)
    gives each device only its contiguous byte shard plus the read-span
    halo (no operand is device-count-replicated; per-device addressable
    bytes stay ≤ corpus/devices + halo), ``replicated`` keeps the legacy
    whole-corpus broadcast (the differential / paired-measurement arm).
    ``devices`` caps how many local devices a default-constructed mesh
    uses (0 = all). ``halo_kib`` widens the shard halo beyond the
    engine's computed maximum read span (0 = auto) — the planner never
    shrinks it below the no-clamp minimum. Environment variables override
    per-process (``NTPU_MESH_PACK``, ``NTPU_MESH_DEVICES``,
    ``NTPU_MESH_HALO_KIB``).
    """

    pack: str = "extent"
    devices: int = 0
    halo_kib: int = 0


@dataclass
class ExperimentalConfig:
    enable_stargz: bool = False
    enable_referrer_detect: bool = False
    tarfs_enable: bool = False
    tarfs_mount_on_host: bool = False
    tarfs_export_mode: str = ""
    tarfs_max_concurrent_proc: int = 4


@dataclass
class SnapshotterConfig:
    """Top-level config: the 11 sections of the reference TOML."""

    version: int = 1
    root: str = constants.DEFAULT_ROOT_DIR
    address: str = constants.DEFAULT_ADDRESS
    daemon_mode: str = constants.DEFAULT_DAEMON_MODE
    cleanup_on_close: bool = False

    system: SystemConfig = field(default_factory=SystemConfig)
    metrics: MetricsConfig = field(default_factory=MetricsConfig)
    daemon: DaemonConfig = field(default_factory=DaemonConfig)
    cgroup: CgroupConfig = field(default_factory=CgroupConfig)
    log: LoggingConfig = field(default_factory=LoggingConfig)
    remote: RemoteConfig = field(default_factory=RemoteConfig)
    snapshot: SnapshotConfig = field(default_factory=SnapshotConfig)
    cache_manager: CacheManagerConfig = field(default_factory=CacheManagerConfig)
    image: ImageConfig = field(default_factory=ImageConfig)
    convert: ConvertConfig = field(default_factory=ConvertConfig)
    compression: CompressionConfig = field(default_factory=CompressionConfig)
    blobcache: BlobcacheConfig = field(default_factory=BlobcacheConfig)
    peer: PeerConfig = field(default_factory=PeerConfig)
    soci: SociConfig = field(default_factory=SociConfig)
    snapshots: SnapshotsConfig = field(default_factory=SnapshotsConfig)
    trace: TraceConfig = field(default_factory=TraceConfig)
    provenance: ProvenanceConfig = field(default_factory=ProvenanceConfig)
    chunk_dict: ChunkDictConfig = field(default_factory=ChunkDictConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    slo: SloConfig = field(default_factory=SloConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    scenario: ScenarioConfig = field(default_factory=ScenarioConfig)
    soak: SoakConfig = field(default_factory=SoakConfig)
    experimental: ExperimentalConfig = field(default_factory=ExperimentalConfig)

    # -- derived paths (reference config/global.go accessors) ---------------

    @property
    def socket_root(self) -> str:
        return os.path.join(self.root, "socket")

    @property
    def config_root(self) -> str:
        return os.path.join(self.root, "config")

    @property
    def cache_root(self) -> str:
        return self.cache_manager.cache_dir or os.path.join(self.root, "cache")

    @property
    def snapshots_root(self) -> str:
        return os.path.join(self.root, "snapshots")

    @property
    def database_path(self) -> str:
        return os.path.join(self.root, "nydus.db")

    def validate(self) -> None:
        if self.version != 1:
            raise ConfigError(f"unsupported config version {self.version} (expect 1)")
        # unix(7) sun_path is 108 bytes; the reference enforces root < 70 so
        # per-daemon socket paths still fit (config.go:50-59).
        if len(self.root) > constants.MAX_ROOT_PATH_LEN:
            raise ConfigError(
                f"root path {self.root!r} is longer than {constants.MAX_ROOT_PATH_LEN} bytes"
            )
        if not os.path.isabs(self.root):
            raise ConfigError("root path must be absolute")
        if self.daemon_mode not in (
            constants.DAEMON_MODE_SHARED,
            constants.DAEMON_MODE_DEDICATED,
            constants.DAEMON_MODE_NONE,
        ):
            raise ConfigError(f"invalid daemon mode {self.daemon_mode!r}")
        if self.daemon.fs_driver not in constants.FS_DRIVERS:
            raise ConfigError(f"invalid fs driver {self.daemon.fs_driver!r}")
        if self.daemon.recover_policy not in (
            constants.RECOVER_POLICY_NONE,
            constants.RECOVER_POLICY_RESTART,
            constants.RECOVER_POLICY_FAILOVER,
        ):
            raise ConfigError(f"invalid recover policy {self.daemon.recover_policy!r}")
        if self.daemon.accel_backend not in ("hybrid", "jax", "numpy"):
            raise ConfigError(f"invalid accel backend {self.daemon.accel_backend!r}")
        if self.daemon.recover_max_restarts < 1:
            raise ConfigError("daemon.recover_max_restarts must be >= 1")
        if self.daemon.recover_window_secs <= 0 or self.daemon.recover_backoff_secs < 0:
            raise ConfigError("daemon recover window/backoff must be positive")
        if self.convert.pipeline not in ("auto", "on", "off"):
            raise ConfigError(
                f"invalid convert.pipeline {self.convert.pipeline!r} "
                "(auto | on | off)"
            )
        if self.convert.chunk_workers < 0 or self.convert.compress_workers < 0:
            raise ConfigError("convert worker counts must be >= 0 (0 = auto)")
        if self.convert.layer_fanout < 0:
            raise ConfigError("convert.layer_fanout must be >= 0 (0 = auto)")
        if (
            self.convert.queue_mib <= 0
            or self.convert.memory_budget_mib <= 0
            or self.convert.window_mib <= 0
        ):
            raise ConfigError("convert queue/budget/window MiB must be positive")
        if self.compression.probe not in ("sample", "entropy", "off"):
            raise ConfigError(
                f"invalid compression.probe {self.compression.probe!r} "
                "(sample | entropy | off)"
            )
        if self.compression.probe_sample_kib < 1:
            raise ConfigError("compression.probe_sample_kib must be >= 1")
        if not (
            0.0
            < self.compression.high_gain_ratio
            < self.compression.low_gain_ratio
            < self.compression.bypass_ratio
            <= 1.0
        ):
            raise ConfigError(
                "compression ratios must satisfy 0 < high_gain_ratio < "
                "low_gain_ratio < bypass_ratio <= 1"
            )
        if not (
            1 <= self.compression.level_fast <= 19
            and 0 <= self.compression.level_default <= 19
            and 1 <= self.compression.level_best <= 19
        ):
            raise ConfigError(
                "compression levels must be in [1, 19] (level_default: 0 = "
                "the fixed reference level)"
            )
        if self.compression.train_dict_kib < 1 or self.compression.train_sample_mib < 1:
            raise ConfigError(
                "compression.train_dict_kib/train_sample_mib must be >= 1"
            )
        if self.compression.batch_chunks < 0:
            raise ConfigError(
                "compression.batch_chunks must be >= 0 (0/1 = per-chunk)"
            )
        if self.compression.vectorized not in ("auto", "on", "off"):
            raise ConfigError(
                f"invalid compression.vectorized "
                f"{self.compression.vectorized!r} (auto | on | off)"
            )
        if self.blobcache.fetch_workers < 1:
            raise ConfigError("blobcache.fetch_workers must be >= 1")
        if self.blobcache.merge_gap_kib < 0 or self.blobcache.readahead_kib < 0:
            raise ConfigError("blobcache merge_gap/readahead KiB must be >= 0")
        if self.blobcache.inflight_budget_mib <= 0:
            raise ConfigError("blobcache.inflight_budget_mib must be positive")
        if self.blobcache.eviction_watermark_mib < 0:
            raise ConfigError(
                "blobcache.eviction_watermark_mib must be >= 0 (0 = unbounded)"
            )
        if self.peer.enable and not self.peer.listen and not self.peer.peers:
            raise ConfigError(
                "peer.enable needs a listen address and/or a peers list"
            )
        if self.peer.region_kib <= 0:
            raise ConfigError("peer.region_kib must be positive")
        if self.peer.timeout_ms <= 0:
            raise ConfigError("peer.timeout_ms must be positive")
        if self.peer.max_concurrent < 0 or self.peer.demand_reserve < 0:
            raise ConfigError(
                "peer.max_concurrent/demand_reserve must be >= 0"
            )
        if any(w <= 0 for w in self.peer.tenant_weights.values()):
            raise ConfigError("peer.tenant_weights must all be positive")
        if self.peer.membership not in ("auto", "static", "fleet"):
            raise ConfigError(
                f"invalid peer.membership {self.peer.membership!r} "
                "(auto | static | fleet)"
            )
        if self.peer.membership_refresh_secs <= 0:
            raise ConfigError("peer.membership_refresh_secs must be positive")
        if self.peer.locality:
            parts = [p.strip() for p in self.peer.locality.split(":")]
            if len(parts) != 3 or not all(parts):
                raise ConfigError(
                    f"invalid peer.locality {self.peer.locality!r} "
                    "(expected rack:zone:region)"
                )
        if self.peer.hedge_window < 0:
            raise ConfigError("peer.hedge_window must be >= 0 (0 = default)")
        if any(v <= 0 for v in self.peer.tier_budgets.values()):
            raise ConfigError("peer.tier_budgets MiB caps must all be positive")
        if self.soci.stride_kib < 64:
            # Checkpoints below one deflate window apart are pure index
            # bloat: the window alone is 32 KiB.
            raise ConfigError("soci.stride_kib must be >= 64")
        if self.snapshots.read_pool < 1:
            raise ConfigError("snapshots.read_pool must be >= 1")
        if self.snapshots.prepare_fanout < 0 or self.snapshots.usage_workers < 0:
            raise ConfigError(
                "snapshots prepare_fanout/usage_workers must be >= 0 (0 = serial)"
            )
        if self.snapshots.cleanup_workers < 1:
            raise ConfigError("snapshots.cleanup_workers must be >= 1")
        if self.snapshots.ancestor_cache < 0:
            raise ConfigError("snapshots.ancestor_cache must be >= 0 (0 = disabled)")
        if self.trace.ring_capacity < 1:
            raise ConfigError("trace.ring_capacity must be >= 1")
        if self.trace.slow_op_threshold_ms < 0:
            raise ConfigError("trace.slow_op_threshold_ms must be >= 0 (0 = off)")
        if not 0.0 <= self.trace.sample_ratio <= 1.0:
            raise ConfigError("trace.sample_ratio must be within [0, 1]")
        if self.provenance.heat_budget_mib < 0:
            raise ConfigError(
                "provenance.heat_budget_mib must be >= 0 (0 = no heat warm)"
            )
        if self.provenance.events < 1:
            raise ConfigError("provenance.events must be >= 1")
        if self.fleet.scrape_interval_secs <= 0:
            raise ConfigError("fleet.scrape_interval_secs must be positive")
        if self.fleet.stale_after_secs <= 0:
            raise ConfigError("fleet.stale_after_secs must be positive")
        if self.fleet.scoreboard_max_age_secs < 0:
            raise ConfigError(
                "fleet.scoreboard_max_age_secs must be >= 0 (0 = always fresh)"
            )
        if self.slo.eval_interval_secs <= 0:
            raise ConfigError("slo.eval_interval_secs must be positive")
        if not isinstance(self.slo.objectives, list) or any(
            not isinstance(o, dict) for o in self.slo.objectives
        ):
            raise ConfigError("slo.objectives must be an array of tables")
        if not isinstance(self.slo.shed_lanes, list) or any(
            not isinstance(s, str) for s in self.slo.shed_lanes
        ):
            raise ConfigError("slo.shed_lanes must be an array of lane names")
        if "demand" in self.slo.shed_lanes:
            raise ConfigError("slo.shed_lanes: the demand lane is not sheddable")
        if self.slo.restore_burn < 0:
            raise ConfigError("slo.restore_burn must be >= 0")
        if self.mesh.pack not in ("extent", "replicated"):
            raise ConfigError(
                f"invalid mesh.pack {self.mesh.pack!r} (extent | replicated)"
            )
        if self.mesh.devices < 0:
            raise ConfigError("mesh.devices must be >= 0 (0 = all local devices)")
        if self.mesh.halo_kib < 0:
            raise ConfigError("mesh.halo_kib must be >= 0 (0 = auto read span)")
        if self.scenario.pods < 1:
            raise ConfigError("scenario.pods must be >= 1")
        if self.scenario.seed < 0:
            raise ConfigError("scenario.seed must be >= 0")
        if self.soak.epochs < 0:
            raise ConfigError("soak.epochs must be >= 0 (0 = spec's value)")
        if self.soak.spot_epochs < 1:
            raise ConfigError("soak.spot_epochs must be >= 1")
        if not 0.0 < self.chunk_dict.load_factor < 1.0:
            raise ConfigError("chunk_dict.load_factor must be within (0, 1)")
        if self.chunk_dict.headroom < 1.0:
            raise ConfigError("chunk_dict.headroom must be >= 1.0")
        if self.chunk_dict.service_backend not in ("auto", "host", "device", "pallas"):
            raise ConfigError(
                f"invalid chunk_dict.service_backend {self.chunk_dict.service_backend!r}"
            )
        if self.chunk_dict.shards < 1:
            raise ConfigError("chunk_dict.shards must be >= 1")
        if self.chunk_dict.replicas < 0:
            raise ConfigError("chunk_dict.replicas must be >= 0")
        if self.chunk_dict.replication_budget_kib < 64:
            raise ConfigError("chunk_dict.replication_budget_kib must be >= 64")
        if self.chunk_dict.replication_poll_ms <= 0:
            raise ConfigError("chunk_dict.replication_poll_ms must be > 0")
        if self.daemon.fs_driver in (constants.FS_DRIVER_BLOCKDEV, constants.FS_DRIVER_PROXY):
            # Proxy/blockdev modes run without nydusd daemons
            # (reference config.go:300-311 forces daemon_mode none).
            self.daemon_mode = constants.DAEMON_MODE_NONE


def _merge_into_dataclass(obj: Any, data: dict[str, Any], path: str = "") -> None:
    fields = {f.name: f for f in dataclasses.fields(obj)}
    for key, value in data.items():
        if key not in fields:
            raise ConfigError(f"unknown config key {path + key!r}")
        cur = getattr(obj, key)
        if dataclasses.is_dataclass(cur) and isinstance(value, dict):
            _merge_into_dataclass(cur, value, path=f"{path}{key}.")
        else:
            if cur is not None and value is not None and not isinstance(value, type(cur)):
                # tolerate int-for-bool style TOML looseness only for numbers
                if not (isinstance(cur, bool) is isinstance(value, bool) and isinstance(value, (int, float, str, list, dict))):
                    raise ConfigError(
                        f"config key {path + key!r}: expected {type(cur).__name__}, "
                        f"got {type(value).__name__}"
                    )
            setattr(obj, key, value)


def load_config(
    path: Optional[str] = None,
    overrides: Optional[dict[str, Any]] = None,
) -> SnapshotterConfig:
    """defaults ← TOML file ← CLI overrides → validate."""
    cfg = SnapshotterConfig()
    if path:
        with open(path, "rb") as f:
            data = tomllib.load(f)
        _merge_into_dataclass(cfg, data)
    if overrides:
        _merge_into_dataclass(cfg, overrides)
    cfg.validate()
    return cfg


# -- frozen global accessor (reference config/global.go:24-221) -------------

_global: Optional[SnapshotterConfig] = None


def set_global_config(cfg: SnapshotterConfig) -> None:
    global _global
    _global = cfg


def get_global_config() -> SnapshotterConfig:
    if _global is None:
        raise ConfigError("global config not initialized")
    return _global
