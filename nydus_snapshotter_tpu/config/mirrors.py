"""Per-registry mirror configuration directories, containerd certs.d style.

Reference config/daemonconfig/mirrors.go:90-259: the operator drops
``<dir>/<registry-host>/hosts.toml`` files (with the same ``host:port`` →
``host_port_`` directory-name mangling containerd uses, and a ``_default``
fallback dir); each ``[host."https://mirror"]`` section carries optional
headers plus the mirror health-check knobs consumed by the daemon's
backend config.
"""

from __future__ import annotations

import os
import urllib.parse

from nydus_snapshotter_tpu.config.daemonconfig import MirrorConfig
from nydus_snapshotter_tpu.utils import errdefs
from nydus_snapshotter_tpu.utils.tomlcompat import tomllib


def host_directory(host: str) -> str:
    """`registry:5000` → `registry_5000_` (mirrors.go:90-97)."""
    idx = host.rfind(":")
    if idx > 0:
        return f"{host[:idx]}_{host[idx + 1:]}_"
    return host


def host_paths(root: str, host: str) -> list[str]:
    """Candidate config dirs, most specific first (mirrors.go:99-108)."""
    paths = []
    mangled = host_directory(host)
    if mangled != host:
        paths.append(os.path.join(root, mangled))
    paths.append(os.path.join(root, host))
    paths.append(os.path.join(root, "_default"))
    return paths


def host_dir_from_root(root: str, host: str) -> str:
    """First existing candidate dir, or "" (mirrors.go:110-119)."""
    for path in host_paths(root, host):
        if os.path.isdir(path):
            return path
    return ""


def _parse_host_config(server: str, config: dict) -> MirrorConfig:
    """One ``[host."..."]`` section → MirrorConfig (mirrors.go:140-179)."""
    if not server.startswith("http"):
        server = "https://" + server
    parsed = urllib.parse.urlsplit(server)
    if not parsed.netloc:
        raise errdefs.InvalidArgument(f"unable to parse mirror server {server!r}")
    headers: dict[str, str] = {}
    for key, value in (config.get("header") or {}).items():
        if isinstance(value, str):
            headers[key] = value
        elif isinstance(value, list):
            headers[key] = ", ".join(str(v) for v in value)
        else:
            raise errdefs.InvalidArgument(
                f"invalid type {type(value).__name__} for header {key!r}"
            )
    return MirrorConfig(
        host=f"{parsed.scheme}://{parsed.netloc}",
        headers=headers,
        health_check_interval=int(config.get("health_check_interval", 5)),
        failure_limit=int(config.get("failure_limit", 5)),
        ping_url=str(config.get("ping_url", "")),
    )


def parse_hosts_file(data: bytes) -> list[MirrorConfig]:
    """hosts.toml → ordered mirror list (mirrors.go:181-219; tomllib keeps
    document order for table keys, matching getSortedHosts)."""
    try:
        tree = tomllib.loads(data.decode())
    except (tomllib.TOMLDecodeError, UnicodeDecodeError) as e:
        raise errdefs.InvalidArgument(f"failed to parse hosts.toml: {e}") from e
    hosts = tree.get("host")
    if not isinstance(hosts, dict):
        raise errdefs.InvalidArgument("invalid `host` tree in hosts.toml")
    return [
        _parse_host_config(server, config or {})
        for server, config in hosts.items()
        if server
    ]


def load_mirrors_config(mirrors_config_dir: str, registry_host: str) -> list[MirrorConfig]:
    """Mirrors for ``registry_host`` from the config dir tree
    (mirrors.go LoadMirrorsConfig :240-259)."""
    if not mirrors_config_dir:
        return []
    host_dir = host_dir_from_root(mirrors_config_dir, registry_host)
    if not host_dir:
        return []
    hosts_file = os.path.join(host_dir, "hosts.toml")
    if not os.path.exists(hosts_file):
        return []
    with open(hosts_file, "rb") as f:
        return parse_hosts_file(f.read())
