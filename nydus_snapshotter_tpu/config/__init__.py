"""Layered configuration: defaults ← TOML ← CLI, validated then frozen."""

from nydus_snapshotter_tpu.config.config import (  # noqa: F401
    SnapshotterConfig,
    ConfigError,
    load_config,
    set_global_config,
    get_global_config,
)
