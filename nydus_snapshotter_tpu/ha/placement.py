"""Placement controller: shard -> primary + R replicas over live fleet
members, with sticky primaries and automatic promotion.

Runs on the system controller, ticked by the fleet plane's scrape loop
(:class:`~nydus_snapshotter_tpu.fleet.FleetPlane`). Inputs are the
fleet registry's ``dict``-component members and the federator's scrape
liveness, plus peer-reported down signals (``report_down``, fed by
``daemon/peer.py`` and the ``/api/v1/fleet/placement/report`` route).

Assignment rules (the minimal-churn contract, property-tested in
tests/test_dict_ha.py):

- candidates for shard ``s`` are ranked by rendezvous hash
  ``blake2b(f"{s}|{member}")`` — a member join/leave only disturbs the
  assignments where its rank actually lands in the top ``1 + R``;
- the primary is STICKY: a live primary is never displaced by ranking
  (re-ranking primaries on every join would churn client routing for
  nothing);
- a dead/stale/reported-down primary is replaced by the MOST-CAUGHT-UP
  live replica (``/api/v1/ha/status`` applied-chunk totals), which is
  promoted over its ``/api/v1/ha/promote`` route — the placement epoch
  bumps, the event lands on the SLO surface
  (:meth:`~nydus_snapshotter_tpu.metrics.slo.SloEngine.record_event`)
  and in ``ntpu_dict_ha_promotions_total``;
- replica slots refill from the live rendezvous ranking (primary
  excluded).

Role assignments are PUSHED to members' ``/api/v1/ha/configure`` after
every map change and re-pushed until acknowledged — a member that raced
the controller's startup still converges. All member RPCs happen
outside the controller's lock (no blocking under lock).

Two operator/actuation entries ride on the same machinery:
:meth:`PlacementController.demote` runs a PLANNED primary handoff
(drain -> replica catch-up to the frozen journal head -> promote ->
demoted member re-joins as replica; ``ntpuctl dict demote <shard>``),
and :meth:`PlacementController.scale_replicas` adjusts the per-shard
replica target — the dict-replica half of SLO scale-up actuation
(metrics/slo.py :class:`SloScaleUp`).
"""

from __future__ import annotations

import hashlib
import logging
import time
from collections import deque
from typing import Callable, Optional

from nydus_snapshotter_tpu import failpoint, trace
from nydus_snapshotter_tpu import ha as _ha
from nydus_snapshotter_tpu.analysis import runtime as _an
from nydus_snapshotter_tpu.utils import udshttp

logger = logging.getLogger(__name__)

# A peer-reported down signal outlives scrape liveness for this long; a
# successful scrape after the window clears it.
REPORT_COOLDOWN_SECS = 10.0


def _rank(shard: int, names: list[str]) -> list[str]:
    """Rendezvous ranking of ``names`` for one shard (desc by score)."""
    def score(name: str) -> int:
        h = hashlib.blake2b(f"{shard}|{name}".encode(), digest_size=8)
        return int.from_bytes(h.digest(), "little")

    return sorted(names, key=lambda n: (-score(n), n))


class ShardAssignment:
    """One shard's current placement."""

    __slots__ = ("shard", "primary", "replicas")

    def __init__(self, shard: int):
        self.shard = shard
        self.primary: str = ""
        self.replicas: list[str] = []

    def to_dict(self, addr_of: Callable[[str], str]) -> dict:
        return {
            "shard": self.shard,
            "primary": {"name": self.primary, "address": addr_of(self.primary)},
            "replicas": [
                {"name": r, "address": addr_of(r)} for r in self.replicas
            ],
        }


class PlacementController:
    def __init__(
        self,
        members_fn: Callable[[], list],
        liveness_fn: Callable[[], dict],
        shards: int = 1,
        replicas: int = 1,
        engine=None,
        clock: Callable[[], float] = time.monotonic,
        rpc_timeout_s: float = 2.0,
        keep_events: int = 32,
    ):
        self._members_fn = members_fn
        self._liveness_fn = liveness_fn
        self.shards = max(1, int(shards))
        self.replicas = max(0, int(replicas))
        self._engine = engine  # SloEngine (promotion events surface)
        self._clock = clock
        self._rpc_timeout_s = rpc_timeout_s
        self._lock = _an.make_lock("ha.placement")
        self._state_shared = _an.shared("ha.placement.state")
        self.epoch = 0
        self._assign = [ShardAssignment(s) for s in range(self.shards)]
        self._addr: dict[str, str] = {}
        self._pids: dict[str, int] = {}
        self._reports: dict[str, float] = {}
        # member -> last acked (role, upstream, shard, epoch, pid). The
        # pid is part of the key: a member that RESTARTED under the same
        # name re-registered with a fresh pid and lost its role — it
        # must be re-pushed or it would sit unassigned, rejecting writes.
        self._pushed: dict[str, tuple] = {}
        self._events: deque = deque(maxlen=keep_events)
        self.promotions = 0

    # -- health inputs -------------------------------------------------------

    def report_down(self, name: str, source: str = "") -> None:
        """External down signal (a peer/client that watched the member's
        socket die) — faster than waiting out scrape staleness."""
        now = self._clock()
        with self._lock:
            self._state_shared.write()
            self._reports[name] = now
        logger.warning(
            "dict-ha: member %s reported down%s", name,
            f" by {source}" if source else "",
        )

    def _live_members(self) -> tuple[list[str], dict[str, str]]:
        """(live dict-member names, name -> address) right now."""
        liveness = self._liveness_fn()
        now = self._clock()
        with self._lock:
            self._state_shared.read()
            reports = dict(self._reports)
        names, addr = [], {}
        pids: dict[str, int] = {}
        for m in self._members_fn():
            # Candidates: dedicated dict members, plus any member
            # advertising a dict socket via the ``dict_listen`` extra
            # (a snapshotter whose one member slot is already taken —
            # the peer_listen pattern).
            address = m.extra.get("dict_listen", "") or (
                m.address if m.component == "dict" else ""
            )
            if not address:
                continue
            addr[m.name] = address
            pids[m.name] = m.pid
            live = liveness.get(m.name)
            # Never scraped yet counts as up (a joining member must not
            # be shunned at birth — the peer_listing rule).
            up = True if live is None else bool(live["up"]) and not live["stale"]
            reported = reports.get(m.name)
            if reported is not None:
                if now - reported < REPORT_COOLDOWN_SECS:
                    up = False
                elif live is not None and live["up"]:
                    with self._lock:
                        self._state_shared.write()
                        self._reports.pop(m.name, None)
            if up:
                names.append(m.name)
        with self._lock:
            self._state_shared.write()
            self._pids = pids
        return names, addr

    # -- member RPCs (always outside the lock) -------------------------------

    def _ha_status(self, address: str) -> Optional[dict]:
        try:
            return udshttp.get_json(
                address, "/api/v1/ha/status", timeout=self._rpc_timeout_s
            )
        except Exception:  # noqa: BLE001 — a dead member is an absent vote
            return None

    def _applied_chunks(self, status: Optional[dict]) -> int:
        if not status:
            return -1
        repl = status.get("replication", {}) or {}
        return sum(
            int(ns.get("chunks", 0))
            for ns in (repl.get("namespaces", {}) or {}).values()
        )

    def _push_role(self, name: str, address: str, payload: dict) -> bool:
        try:
            udshttp.post_json(
                address, "/api/v1/ha/configure", payload,
                timeout=self._rpc_timeout_s,
            )
            return True
        except Exception:  # noqa: BLE001 — retried next tick
            logger.warning("dict-ha: role push to %s (%s) failed", name, address)
            return False

    # -- the tick ------------------------------------------------------------

    def tick(self) -> bool:
        """One placement round; returns whether the map changed.

        Decides on a snapshot (member RPCs outside the lock), applies
        under the lock, then pushes roles/promotions — so ``map()``
        readers never observe a half-updated assignment."""
        failpoint.hit("ha.place")
        live, addr = self._live_members()
        with self._lock:
            self._state_shared.read()
            snapshot = [(a.shard, a.primary, list(a.replicas)) for a in self._assign]
        changed = False
        promoted: list[dict] = []
        # A member holds AT MOST ONE slot: a replica tails exactly one
        # upstream, and a shard primary must never be pushed a replica
        # role for another shard (the role is per-member). Primaries are
        # decided first so replica refills can't steal a primary seat;
        # only when shards outnumber live members does a member serve as
        # primary of more than one shard (degraded but role-consistent).
        used: set[str] = set()
        primaries: list[str] = []
        for shard, primary, replicas in snapshot:
            order = _rank(shard, live)
            if primary and primary in live:
                pass  # sticky primary
            elif primary and replicas:
                # Primary is dead/stale: promote the most-caught-up live
                # replica (status RPCs happen outside the lock).
                candidates = [r for r in replicas if r in live and r not in used]
                if candidates:
                    scored = [
                        (self._applied_chunks(self._ha_status(addr[r])), r)
                        for r in candidates
                    ]
                    scored.sort(key=lambda t: (-t[0], t[1]))
                    promoted.append(
                        {
                            "shard": shard,
                            "from": primary,
                            "to": scored[0][1],
                            "applied_chunks": scored[0][0],
                        }
                    )
                    primary = scored[0][1]
                    changed = True
                # No live replica: hold the assignment — clients keep
                # failing loudly, and the next live replica wins.
            elif not primary and order:
                avail = [n for n in order if n not in used]
                primary = avail[0] if avail else order[0]
                changed = True
            if primary:
                used.add(primary)
            primaries.append(primary)
        decided: list[tuple[int, str, list[str]]] = []
        for (shard, _old_primary, replicas), primary in zip(snapshot, primaries):
            order = _rank(shard, live)
            want = [n for n in order if n != primary and n not in used][
                : self.replicas
            ]
            if want != replicas and (primary or want):
                replicas = want
                changed = True
            used.update(want)
            decided.append((shard, primary, replicas))
        with self._lock:
            self._state_shared.write()
            self._addr = dict(addr)
            for a, (_s, primary, replicas) in zip(self._assign, decided):
                a.primary = primary
                a.replicas = replicas
        for event in promoted:
            failpoint.hit("ha.promote")
            with trace.span(
                "ha.promote", shard=str(event["shard"]), member=event["to"]
            ):
                ok = self._promote_member(event["to"], addr.get(event["to"], ""))
            event["acked"] = ok
            _ha.PROMOTIONS.labels(str(event["shard"])).inc()
            logger.warning(
                "dict-ha: promoted %s to primary of shard %d (was %s, "
                "applied_chunks=%d, acked=%s)",
                event["to"], event["shard"], event["from"],
                event["applied_chunks"], ok,
            )
            if self._engine is not None:
                self._engine.record_event(
                    "dict_ha_promotion",
                    shard=event["shard"],
                    promoted=event["to"],
                    previous=event["from"],
                    applied_chunks=event["applied_chunks"],
                )
        if changed:
            with self._lock:
                self._state_shared.write()
                self.epoch += 1
                self.promotions += len(promoted)
                for event in promoted:
                    self._events.append(
                        {"kind": "promotion", "at": self._clock(), **event}
                    )
                epoch = self.epoch
            _ha.PLACEMENT_EPOCH.set(epoch)
        self._push_assignments(addr)
        return changed

    def scale_replicas(self, delta: int, max_replicas: int = 8) -> int:
        """Adjust the per-shard replica target (the dict-replica half of
        SLO scale-up actuation: spawn -> +1, retire -> -1). Returns the
        new target; the next tick refills/shrinks slots from the live
        rendezvous ranking."""
        with self._lock:
            self._state_shared.write()
            self.replicas = min(max_replicas, max(0, self.replicas + int(delta)))
            target = self.replicas
        logger.info("dict-ha: replica target scaled to %d", target)
        return target

    def demote(self, shard: int, timeout_s: float = 10.0,
               poll_s: float = 0.05) -> dict:
        """Planned primary demotion for one shard: drain, catch up, hand
        off, THEN demote — zero client-visible errors by construction.

        1. The primary is told to DRAIN (``/api/v1/ha/demote``): merges
           bounce 503 from here on, freezing the journal head, while
           writing clients park in their failover poll loop.
        2. Replicas are polled until one reaches the frozen head (equal
           applied-chunk totals — an exact condition, not a heuristic,
           because nothing can advance the head anymore).
        3. That replica is promoted (same RPC as crash promotion) and
           the map is re-pointed; the drained member is pushed a replica
           role of the successor (full resync — its tables are a foreign
           prefix once the successor accepts writes).

        If no replica catches up inside ``timeout_s`` the drain is
        ABORTED by re-promoting the drained primary — the shard never
        stays headless longer than the timeout.
        """
        if not 0 <= shard < self.shards:
            raise ValueError(f"shard {shard} out of range (0..{self.shards - 1})")
        with self._lock:
            self._state_shared.read()
            a = self._assign[shard]
            primary, replicas = a.primary, list(a.replicas)
            addr = dict(self._addr)
        if not primary or not addr.get(primary):
            raise ValueError(f"shard {shard} has no addressable primary")
        if not replicas:
            raise ValueError(f"shard {shard} has no replica to hand off to")
        primary_addr = addr[primary]
        udshttp.post_json(
            primary_addr, "/api/v1/ha/demote", {}, timeout=self._rpc_timeout_s
        )
        want = self._applied_chunks(self._ha_status(primary_addr))
        deadline = self._clock() + timeout_s
        best: Optional[tuple[int, str]] = None
        while want >= 0:
            scored = [
                (self._applied_chunks(self._ha_status(addr[r])), r)
                for r in replicas if addr.get(r)
            ]
            scored.sort(key=lambda t: (-t[0], t[1]))
            if scored and scored[0][0] >= want:
                best = scored[0]
                break
            if self._clock() >= deadline:
                break
            time.sleep(poll_s)
        if best is None:
            # Abort: hand the role straight back — clients were parked,
            # not failed, and resume against the same primary.
            self._promote_member(primary, primary_addr)
            raise RuntimeError(
                f"planned demotion of shard {shard} aborted: no replica "
                f"reached the journal head ({want} chunks) in {timeout_s}s"
            )
        applied, successor = best
        failpoint.hit("ha.promote")
        with trace.span(
            "ha.promote", shard=str(shard), member=successor, planned="true"
        ):
            acked = self._promote_member(successor, addr.get(successor, ""))
        event = {
            "kind": "planned_demotion",
            "at": self._clock(),
            "shard": shard,
            "from": primary,
            "to": successor,
            "applied_chunks": applied,
            "acked": acked,
        }
        with self._lock:
            self._state_shared.write()
            a = self._assign[shard]
            a.primary = successor
            a.replicas = [r for r in replicas if r != successor] + [primary]
            self.epoch += 1
            self.promotions += 1
            self._events.append(event)
            epoch = self.epoch
        _ha.PROMOTIONS.labels(str(shard)).inc()
        _ha.PLACEMENT_EPOCH.set(epoch)
        logger.warning(
            "dict-ha: planned demotion handed shard %d from %s to %s "
            "(applied_chunks=%d, acked=%s)",
            shard, primary, successor, applied, acked,
        )
        if self._engine is not None:
            self._engine.record_event(
                "dict_ha_planned_demotion",
                shard=shard, promoted=successor, previous=primary,
                applied_chunks=applied,
            )
        # The drained member's re-push as replica happens here (its
        # pushed-state key still holds the old primary tuple, so the
        # push is not suppressed).
        self._push_assignments(addr)
        return event

    def _promote_member(self, name: str, address: str) -> bool:
        if not address:
            return False
        try:
            udshttp.post_json(
                address, "/api/v1/ha/promote",
                {"epoch": self.epoch + 1},
                timeout=self._rpc_timeout_s,
            )
            return True
        except Exception:  # noqa: BLE001 — the role push below retries
            logger.warning("dict-ha: promote RPC to %s (%s) failed", name, address)
            return False

    def _push_assignments(self, addr: dict[str, str]) -> None:
        """Push each member's (role, upstream, shard) until acked."""
        with self._lock:
            self._state_shared.read()
            epoch = self.epoch
            roles: dict[str, tuple] = {}
            for a in self._assign:
                if a.primary:
                    roles[a.primary] = ("primary", "", a.shard)
                for r in a.replicas:
                    roles[r] = ("replica", addr.get(a.primary, ""), a.shard)
            pushed = dict(self._pushed)
            pids = dict(self._pids)
        for name, (role, upstream, shard) in roles.items():
            address = addr.get(name, "")
            want = (role, upstream, shard, epoch, pids.get(name, 0))
            if not address or pushed.get(name) == want:
                continue
            ok = self._push_role(
                name, address,
                {"role": role, "upstream": upstream, "shard": shard,
                 "epoch": epoch},
            )
            if ok:
                with self._lock:
                    self._state_shared.write()
                    self._pushed[name] = want

    # -- published surface ---------------------------------------------------

    def map(self) -> dict:
        """The ``/api/v1/fleet/placement`` payload."""
        with self._lock:
            self._state_shared.read()
            addr = dict(self._addr)
            return {
                "epoch": self.epoch,
                "shards": self.shards,
                "replicas": self.replicas,
                "promotions": self.promotions,
                "assignments": [
                    a.to_dict(lambda n: addr.get(n, "")) for a in self._assign
                ],
                "events": [dict(e) for e in self._events],
            }
