"""Replica-side replication: journal tailing, verbatim apply, promotion.

:class:`ReplicaTailer` runs inside a replica dict-service process. Per
poll round, for every namespace the primary lists:

1. **epoch probe** — tail the primary's ``since`` journal RPC in
   ``count_only`` mode from the last reconciled index epoch: one cheap
   header answers "how many index entries landed since I looked", and
   carries the primary's ``rebuild_epoch`` for reconciliation. An epoch
   that went BACKWARDS (or a chunk total below what this replica
   already applied) means the primary restarted with a younger table —
   the replica cannot reconcile its cursor and RESYNCS from a full
   snapshot, loudly (error log + ``ntpu_dict_ha_resyncs_total``; the
   local namespace is wiped and re-pulled from record zero). A 409
   (journal compacted past the cursor) only re-baselines the epoch
   cursor — the RECORD stream is append-only and never compacted, so
   the record cursor stays valid.
2. **record pull** — fetch the append-only record tail past the
   replica's counts via the ``entries`` RPC with a chunk-row ``limit``
   sized to the byte budget (``limit = budget_bytes // 64``; a chunk
   row is 64 wire bytes). The tailer applies each payload before
   requesting the next, so replication holds AT MOST one budgeted
   payload in flight — the bounded-memory contract that keeps catch-up
   from competing with demand traffic (gated in
   ``tools/dict_ha_profile.py``).
3. **verbatim apply** — rows land at exactly the table positions the
   primary holds them
   (:meth:`~nydus_snapshotter_tpu.parallel.dict_service.ServiceDict.
   apply_replica_tail`), which is what lets a promoted replica honor
   the surviving clients' counts-based replay cursors unchanged.

:class:`HaAgent` is the member-side control surface the placement
controller drives: ``/api/v1/ha/status`` (role + per-namespace lag),
``/api/v1/ha/configure`` (role/upstream assignment),
``/api/v1/ha/promote`` (replica -> primary, tailer stopped) and
``/api/v1/ha/demote`` (primary -> draining: planned handoff). A
non-primary member answers probe/entries/since reads but rejects
merges with 503 — a client that reaches a replica fails loudly and
fails over, it never forks the table.

The **draining** role is the planned-demotion window: the member stops
accepting merges (``is_primary()`` false -> writes bounce 503 and
clients park in their failover poll loop), but keeps serving journal
reads so its replicas can catch up to the journal head and the
controller can verify they did before promoting one. Once a successor
is primary the controller re-configures the drained member as its
replica (full resync — its tables are a foreign prefix by then).
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Optional

from nydus_snapshotter_tpu import failpoint, trace
from nydus_snapshotter_tpu import ha as _ha
from nydus_snapshotter_tpu.analysis import runtime as _an

logger = logging.getLogger(__name__)

# Wire bytes per chunk record row (_CHUNK_DT itemsize): the budget ->
# chunk-row-limit conversion used for the in-flight bound.
CHUNK_ROW_BYTES = 64


class _NsCursor:
    """Replication cursor for one namespace against the primary."""

    __slots__ = (
        "chunks", "blobs", "batches", "ciphers", "index_epoch",
        "primary_epoch", "primary_chunks", "resyncs",
    )

    def __init__(self):
        self.chunks = 0
        self.blobs = 0
        self.batches = 0
        self.ciphers = 0
        self.index_epoch = 0  # last reconciled primary index epoch
        self.primary_epoch = 0
        self.primary_chunks = 0
        self.resyncs = 0

    def to_dict(self) -> dict:
        return {
            "chunks": self.chunks,
            "blobs": self.blobs,
            "batches": self.batches,
            "ciphers": self.ciphers,
            "index_epoch": self.index_epoch,
            "primary_epoch": self.primary_epoch,
            "lag_chunks": max(0, self.primary_chunks - self.chunks),
            "resyncs": self.resyncs,
        }


class ReplicaTailer:
    """Tail one primary's journals into the local (replica) service."""

    def __init__(
        self,
        service,
        upstream: str,
        budget_bytes: int = _ha.DEFAULT_BUDGET_KIB << 10,
        poll_s: float = _ha.DEFAULT_POLL_MS / 1000.0,
        rpc_timeout_s: float = 10.0,
    ):
        from nydus_snapshotter_tpu.parallel.dict_service import DictClient

        self.service = service
        self.upstream = upstream
        self.budget_bytes = max(CHUNK_ROW_BYTES, int(budget_bytes))
        self.poll_s = poll_s
        self.client = DictClient(upstream, timeout=rpc_timeout_s)
        self._mu = _an.make_lock("ha.tailer")
        self._cursors: dict[str, _NsCursor] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.max_pull_bytes = 0  # observed in-flight bound (gate evidence)
        self.pulls = 0
        self.errors = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="ntpu-dict-ha-tail", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
        self.client.close()

    def _loop(self) -> None:
        while True:
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the tailer must survive anything
                self.errors += 1
                logger.exception("dict-ha: replication round against %s failed",
                                 self.upstream)
            if self._stop.wait(self.poll_s):
                return

    # -- one replication round ----------------------------------------------

    def poll_once(self) -> int:
        """One poll over every primary namespace; returns chunk records
        applied this round."""
        failpoint.hit("ha.replicate")
        applied = 0
        for stats in self.client.namespaces():
            ns = stats.get("namespace", "")
            if not ns:
                continue
            applied += self._poll_namespace(ns, stats)
        return applied

    def _poll_namespace(self, ns: str, stats: dict) -> int:
        from nydus_snapshotter_tpu.parallel.sharded_dict import DictEpochError

        with self._mu:
            cur = self._cursors.get(ns)
            if cur is None:
                cur = self._cursors[ns] = _NsCursor()
        try:
            meta, _d, _v = self.client.entries_since(
                ns, epoch=cur.index_epoch, count_only=True
            )
        except DictEpochError:
            # The journal was compacted past our cursor (a rebuild on
            # the primary). Records are append-only and unaffected —
            # only the epoch cursor re-baselines; the record pull below
            # still measures true lag via total_chunks.
            meta = {"epoch": -1, "entries": 0}
        if (
            0 <= meta["epoch"] < cur.primary_epoch
            or int(stats.get("chunks", 0)) < cur.chunks
        ):
            self._resync(
                ns,
                f"primary {self.upstream} went backwards (epoch "
                f"{meta['epoch']} < {cur.primary_epoch} or "
                f"{stats.get('chunks', 0)} chunks < the {cur.chunks} "
                "already applied)",
            )
            with self._mu:
                cur = self._cursors[ns]
        applied = self._pull_records(ns, cur)
        if meta["epoch"] >= 0:
            cur.index_epoch = meta["epoch"]
            cur.primary_epoch = meta["epoch"]
        else:
            # Re-baseline after compaction: trust the next probe.
            st = self.service.dict_for(ns)
            cur.index_epoch = cur.primary_epoch = max(
                cur.primary_epoch, st.index.epoch
            )
        _ha.REPLICA_LAG.labels(ns).set(max(0, cur.primary_chunks - cur.chunks))
        return applied

    def _pull_records(self, ns: str, cur: _NsCursor) -> int:
        """Budget-bounded record-tail pulls until the namespace is flush."""
        limit = max(1, self.budget_bytes // CHUNK_ROW_BYTES)
        sd = self.service.dict_for(ns)
        applied = 0
        while True:
            meta, ca, ba, ta, ea = self.client.entries(
                ns,
                chunks=cur.chunks,
                blobs=cur.blobs,
                batches=cur.batches,
                ciphers=cur.ciphers,
                limit=limit,
            )
            cur.primary_chunks = meta["total_chunks"]
            payload = ca.nbytes + ba.nbytes + ta.nbytes + ea.nbytes
            if not (len(ca) or len(ba) or len(ta) or len(ea)):
                break
            self.pulls += 1
            self.max_pull_bytes = max(self.max_pull_bytes, payload)
            _ha.REPLICATION_PULLS.inc()
            _ha.REPLICATION_BYTES.inc(payload)
            try:
                sd.apply_replica_tail(
                    meta, ca, ba, ta, ea,
                    base=(cur.chunks, cur.blobs, cur.batches, cur.ciphers),
                )
            except Exception as e:  # noqa: BLE001 — a gap means resync
                self._resync(ns, f"verbatim apply failed: {e}")
                return applied
            cur.chunks += len(ca)
            cur.blobs += len(ba)
            cur.batches += len(ta)
            cur.ciphers += len(ea)
            applied += len(ca)
            if cur.chunks >= meta["total_chunks"]:
                break
        # Trained-dict replication rides along (epoch-stamped blob; the
        # newer epoch wins on the replica exactly as on the primary).
        self._replicate_zdict(ns, sd)
        return applied

    def _replicate_zdict(self, ns: str, sd) -> None:
        try:
            stats = sd.stats()
            want = self.client.stats(ns)
        except Exception:  # noqa: BLE001 — next round retries
            return
        if want.get("zdict_epoch", -1) > stats.get("zdict_epoch", -1):
            blob = self.client.get_zdict(ns)
            if blob:
                try:
                    sd.put_zdict(blob)
                except Exception:  # noqa: BLE001 — a bad blob must not stop records
                    logger.exception("dict-ha: zdict adopt failed for %s", ns)

    def _resync(self, ns: str, why: str) -> None:
        """LOUD full resync: wipe the local namespace and re-pull the
        full record snapshot from zero (budget-bounded, like any tail)."""
        logger.error(
            "dict-ha: replica of %s cannot reconcile namespace %s — %s; "
            "resyncing from a full snapshot",
            self.upstream, ns, why,
        )
        _ha.RESYNCS.inc()
        with self._mu:
            old = self._cursors.get(ns)
            cur = self._cursors[ns] = _NsCursor()
            cur.resyncs = (old.resyncs if old else 0) + 1
        self.service.reset_namespace(ns)

    # -- surface -------------------------------------------------------------

    def status(self) -> dict:
        with self._mu:
            namespaces = {ns: c.to_dict() for ns, c in self._cursors.items()}
        return {
            "upstream": self.upstream,
            "budget_bytes": self.budget_bytes,
            "poll_ms": round(self.poll_s * 1000.0, 3),
            "pulls": self.pulls,
            "errors": self.errors,
            "max_pull_bytes": self.max_pull_bytes,
            "namespaces": namespaces,
        }


class HaAgent:
    """Member-side HA control surface, mounted on the dict service's
    socket under ``/api/v1/ha`` (see ``DictService.handle``)."""

    def __init__(self, service, cfg: Optional[_ha.HaRuntimeConfig] = None,
                 role: str = "primary"):
        self.service = service
        self.cfg = cfg or _ha.resolve_ha_config()
        self._mu = _an.make_lock("ha.agent")
        self.role = role  # primary | replica | draining
        self.shard = -1
        self.epoch = 0
        self.upstream = ""
        self.tailer: Optional[ReplicaTailer] = None
        service.ha = self

    # -- role transitions ----------------------------------------------------

    def configure(self, role: str, upstream: str = "", shard: int = -1,
                  epoch: int = 0) -> dict:
        if role not in ("primary", "replica"):
            raise ValueError(f"unknown ha role {role!r}")
        if role == "replica" and not upstream:
            raise ValueError("replica role needs an upstream")
        with self._mu:
            stale = self.tailer
            retarget = role == "replica" and (
                stale is None or stale.upstream != upstream
            )
            if role == "primary" or retarget:
                self.tailer = None
            self.role = role
            self.upstream = upstream if role == "replica" else ""
            self.shard = shard
            self.epoch = max(self.epoch, epoch)
            if retarget:
                self.tailer = ReplicaTailer(
                    self.service, upstream,
                    budget_bytes=self.cfg.budget_bytes,
                    poll_s=self.cfg.poll_s,
                )
        if (role == "primary" or retarget) and stale is not None:
            stale.stop()
        if retarget:
            if stale is not None:
                # Retargeted to a DIFFERENT shard's primary: the tables
                # replicated from the old upstream are a foreign prefix —
                # wipe and re-pull rather than wedging on a cursor gap.
                dropped = self.service.reset_all()
                if dropped:
                    logger.warning(
                        "dict-ha: retarget %s -> %s dropped %d replicated "
                        "namespace(s)", stale.upstream, upstream, dropped,
                    )
            self.tailer.start()
        logger.info(
            "dict-ha: %s configured as %s of shard %d (upstream %s, epoch %d)",
            getattr(self.service, "sock_path", "") or "local", role, shard,
            upstream or "-", epoch,
        )
        return self.status()

    def promote(self, epoch: int = 0) -> dict:
        """Replica -> primary (the controller's automatic promotion)."""
        failpoint.hit("ha.promote")
        with trace.span("ha.promote", shard=str(self.shard)):
            with self._mu:
                tailer, self.tailer = self.tailer, None
                was = self.role
                self.role = "primary"
                self.upstream = ""
                self.epoch = max(self.epoch, epoch)
            if tailer is not None:
                # Final best-effort drain: the primary is usually dead by
                # now, but a clean switchover (tests, rolling restart)
                # catches the last records before the cursor freezes.
                try:
                    tailer.poll_once()
                except Exception:  # noqa: BLE001 — the primary is gone
                    pass
                tailer.stop()
            logger.warning(
                "dict-ha: promoted to primary of shard %d (was %s)",
                self.shard, was,
            )
        return self.status()

    def demote(self) -> dict:
        """Primary -> draining (the controller's PLANNED handoff entry).

        Merges start bouncing 503 immediately (``is_primary()`` flips
        false), which parks writing clients in their failover poll loop;
        journal reads keep flowing so replicas drain to the head. The
        journal head is frozen by construction from this point — no
        merge can advance it — so "replica chunks == drained primary
        chunks" is a stable handoff condition, not a race.
        """
        with self._mu:
            was = self.role
            if was == "primary":
                self.role = "draining"
        if was != "primary":
            raise ValueError(f"cannot demote from role {was!r}")
        logger.warning(
            "dict-ha: shard %d primary draining for planned demotion",
            self.shard,
        )
        return self.status()

    def is_primary(self) -> bool:
        with self._mu:
            return self.role == "primary"

    # -- HTTP surface --------------------------------------------------------

    def status(self) -> dict:
        with self._mu:
            tailer = self.tailer
            out = {
                "role": self.role,
                "shard": self.shard,
                "epoch": self.epoch,
                "upstream": self.upstream,
            }
        out["replication"] = tailer.status() if tailer is not None else {}
        if tailer is None and out["role"] in ("primary", "draining"):
            # A promoted primary reports what it had applied — the
            # controller's most-caught-up ranking reads this. A DRAINING
            # primary reports the same view: that is the frozen journal
            # head the drain loop compares replicas against.
            out["replication"] = {
                "namespaces": {
                    s["namespace"]: {"chunks": s["chunks"]}
                    for s in self.service.namespace_stats()
                }
            }
        return out

    def handle(self, method: str, path: str, body: bytes):
        """(status, ctype, payload) for ``/api/v1/ha/...`` routes."""
        if path == "/api/v1/ha/status" and method == "GET":
            return 200, "application/json", json.dumps(self.status()).encode()
        if path == "/api/v1/ha/configure" and method == "POST":
            req = json.loads(body or b"{}")
            try:
                out = self.configure(
                    str(req.get("role", "")),
                    upstream=str(req.get("upstream", "")),
                    shard=int(req.get("shard", -1)),
                    epoch=int(req.get("epoch", 0)),
                )
            except ValueError as e:
                return 400, "application/json", json.dumps(
                    {"message": str(e)}
                ).encode()
            return 200, "application/json", json.dumps(out).encode()
        if path == "/api/v1/ha/promote" and method == "POST":
            req = json.loads(body or b"{}")
            out = self.promote(epoch=int(req.get("epoch", 0)))
            return 200, "application/json", json.dumps(out).encode()
        if path == "/api/v1/ha/demote" and method == "POST":
            try:
                out = self.demote()
            except ValueError as e:
                return 409, "application/json", json.dumps(
                    {"message": str(e)}
                ).encode()
            return 200, "application/json", json.dumps(out).encode()
        return 404, "application/json", b'{"message": "no such ha endpoint"}'
