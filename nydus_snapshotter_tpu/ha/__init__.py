"""Dict-shard HA plane: placement, journal-streaming replication, and
automatic replica promotion.

PR 13 sharded the chunk-dict service across N processes — and made each
shard a single point of failure: kill a shard's host mid-convert and
every converter in the fleet wedges or fails loudly with no path back.
This package closes that gap with three cooperating pieces:

- :mod:`ha.placement` — a **placement controller** on the system
  controller assigns each shard a primary + R replicas across the live
  ``dict`` members of the fleet registry (rendezvous placement: join/
  leave moves only the assignments whose ranking actually changed;
  primaries are STICKY — a healthy primary is never displaced, so the
  map only churns when a member dies or joins into a replica slot).
  The map is published with an epoch on ``/api/v1/fleet/placement``,
  and role assignments are pushed to the members' ``/api/v1/ha``
  surface.
- :mod:`ha.replicate` — **journal-streaming replication**: each replica
  tails its primary's ``since`` journal RPC (epoch probe, count-only)
  and pulls the append-only record tail in byte-budgeted slices
  (``replication_budget_kib`` — the in-flight bound of "Bounded-Memory
  Parallel Image Pulling": catch-up never holds more than one budgeted
  payload, so it cannot compete with demand traffic). Rows are applied
  VERBATIM at the same table positions the primary holds them, which is
  what makes a promoted replica byte-compatible with the clients'
  replay cursors. A replica whose primary regressed (restart with a
  younger table) cannot reconcile its cursor and resyncs from a full
  snapshot — loudly (error log + ``ntpu_dict_ha_resyncs_total``).
- **automatic promotion** — when the fleet registry flags a primary
  stale/dead (scrape liveness, or a peer-reported down signal from
  ``daemon/peer.py``), the controller promotes the most-caught-up live
  replica, bumps the placement epoch, and records the event on the SLO
  surface. ``ServiceChunkDict`` clients fail over mid-merge: the
  un-acked sub-bootstrap is replayed against the promoted replica, and
  any record tail the client's mirror holds beyond the replica's tables
  is repaired back first — every mirror's per-shard knowledge is a
  PREFIX of the shard's record sequence, so concurrent repairs compose
  and the reconstructed table is position-identical to the dead
  primary's. Converter output stays byte-identical to the no-failure
  path (gated by ``tools/dict_ha_profile.py``).

Config: ``[chunk_dict]`` ``shards`` / ``replicas`` /
``replication_budget_kib`` / ``replication_poll_ms`` with
``NTPU_DICT_HA_SHARDS`` / ``NTPU_DICT_HA_REPLICAS`` /
``NTPU_DICT_HA_BUDGET_KIB`` / ``NTPU_DICT_HA_POLL_MS`` env overrides
(the env is how the section reaches spawned dict-service processes).
Failpoints: ``ha.place`` / ``ha.replicate`` / ``ha.promote``. Metrics:
``ntpu_dict_ha_*``. Docs: chunk_dict_service.md (HA section).
"""

from __future__ import annotations

import os

from nydus_snapshotter_tpu.metrics import registry as _metrics

_reg = _metrics.default_registry

PLACEMENT_EPOCH = _reg.register(
    _metrics.Gauge(
        "ntpu_dict_ha_placement_epoch",
        "Current dict-shard placement map epoch (controller)",
    )
)
PROMOTIONS = _reg.register(
    _metrics.Counter(
        "ntpu_dict_ha_promotions_total",
        "Automatic replica promotions performed, by shard",
        ("shard",),
    )
)
REPLICATION_PULLS = _reg.register(
    _metrics.Counter(
        "ntpu_dict_ha_replication_pulls_total",
        "Byte-budgeted record-tail pulls performed by replica tailers",
    )
)
REPLICATION_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_dict_ha_replication_bytes_total",
        "Record-tail payload bytes replicated onto this replica",
    )
)
REPLICA_LAG = _reg.register(
    _metrics.Gauge(
        "ntpu_dict_ha_replica_lag_chunks",
        "Chunk records this replica is behind its primary, per namespace",
        ("namespace",),
    )
)
RESYNCS = _reg.register(
    _metrics.Counter(
        "ntpu_dict_ha_resyncs_total",
        "Loud full-snapshot resyncs after a replica failed to reconcile",
    )
)
FAILOVERS = _reg.register(
    _metrics.Counter(
        "ntpu_dict_ha_failovers_total",
        "Client-side shard failovers (un-acked batch replayed onto the "
        "promoted replica)",
    )
)

DEFAULT_BUDGET_KIB = 256
DEFAULT_POLL_MS = 50.0


class HaRuntimeConfig:
    """Resolved dict-HA knobs for this process."""

    __slots__ = ("shards", "replicas", "budget_bytes", "poll_s")

    def __init__(self, shards: int, replicas: int, budget_bytes: int, poll_s: float):
        self.shards = shards
        self.replicas = replicas
        self.budget_bytes = budget_bytes
        self.poll_s = poll_s

    @property
    def enabled(self) -> bool:
        return self.replicas > 0


def _env_num(name: str, default: float) -> float:
    try:
        return float(os.environ[name])
    except (KeyError, ValueError):
        return default


def resolve_ha_config() -> HaRuntimeConfig:
    """env (``NTPU_DICT_HA*``) > ``[chunk_dict]`` global config >
    defaults. The env is also how the knobs reach spawned dict-service
    processes, which carry no global snapshotter config."""
    try:
        from nydus_snapshotter_tpu.config import config as _cfg

        cd = _cfg.get_global_config().chunk_dict
    except Exception:
        cd = None
    shards = int(_env_num("NTPU_DICT_HA_SHARDS", getattr(cd, "shards", 1)))
    replicas = int(_env_num("NTPU_DICT_HA_REPLICAS", getattr(cd, "replicas", 0)))
    budget_kib = _env_num(
        "NTPU_DICT_HA_BUDGET_KIB",
        getattr(cd, "replication_budget_kib", DEFAULT_BUDGET_KIB),
    )
    poll_ms = _env_num(
        "NTPU_DICT_HA_POLL_MS", getattr(cd, "replication_poll_ms", DEFAULT_POLL_MS)
    )
    return HaRuntimeConfig(
        shards=max(1, shards),
        replicas=max(0, replicas),
        budget_bytes=max(64 << 10, int(budget_kib * 1024)),
        poll_s=max(0.001, poll_ms / 1000.0),
    )


from nydus_snapshotter_tpu.ha.placement import (  # noqa: E402
    PlacementController,
    ShardAssignment,
)
from nydus_snapshotter_tpu.ha.replicate import HaAgent, ReplicaTailer  # noqa: E402

__all__ = [
    "HaAgent",
    "HaRuntimeConfig",
    "PlacementController",
    "ReplicaTailer",
    "ShardAssignment",
    "resolve_ha_config",
]
