"""Standalone HA-aware dict-service process.

``python -m nydus_snapshotter_tpu.ha.runner --listen <uds>
[--controller <uds>] [--role primary|replica|unassigned]
[--upstream <uds>]``

Starts a :class:`~nydus_snapshotter_tpu.parallel.dict_service.
DictService` on ``--listen`` with an :class:`~nydus_snapshotter_tpu.ha.
replicate.HaAgent` attached, self-registers with the fleet controller
(component ``dict`` — the placement controller's candidate pool; the
controller address comes from ``--controller`` or
``NTPU_FLEET_CONTROLLER``), and serves until SIGTERM. This is the
process ``tools/dict_ha_profile.py`` SIGKILLs mid-storm: everything it
holds dies with it, and the plane must recover without it.

``--role unassigned`` (the default under a controller) rejects merges
until the placement controller pushes a role — two fresh members must
never both accept writes for the same shard. ``--role primary`` serves
immediately (the single-process, no-controller deployment).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ntpu-dict-ha-runner")
    p.add_argument("--listen", required=True, help="UDS to serve the dict RPCs on")
    p.add_argument("--controller", default="", help="fleet controller UDS")
    p.add_argument(
        "--role", default="", choices=["", "primary", "replica", "unassigned"],
        help="initial role (default: unassigned under a controller, "
        "primary without one)",
    )
    p.add_argument("--upstream", default="", help="primary UDS for --role replica")
    p.add_argument("--name", default="", help="fleet member name override")
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s %(message)s",
        stream=sys.stderr,
    )
    if args.controller:
        os.environ["NTPU_FLEET_CONTROLLER"] = args.controller
    if args.name:
        os.environ.setdefault("NTPU_FLEET_MEMBER", args.name)

    from nydus_snapshotter_tpu import ha as ha_mod
    from nydus_snapshotter_tpu.ha.replicate import HaAgent
    from nydus_snapshotter_tpu.parallel.dict_service import DictService

    role = args.role or (
        "unassigned" if os.environ.get("NTPU_FLEET_CONTROLLER") else "primary"
    )
    service = DictService()
    agent = HaAgent(service, cfg=ha_mod.resolve_ha_config(), role=role)
    if role == "replica":
        agent.configure("replica", upstream=args.upstream)
    service.run(args.listen)

    stop = threading.Event()

    def _on_signal(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    try:
        stop.wait()
    finally:
        tailer = agent.tailer
        if tailer is not None:
            tailer.stop()
        service.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
