"""HTTP-over-UDS client for the daemon API.

Parity surface of reference pkg/daemon/client.go:31-58,62-79: daemon info,
mount/umount, metrics (fs/cache/inflight), start/exit/takeover/sendfd, plus
this framework's userspace read API.
"""

from __future__ import annotations

import errno
import http.client
import json
import os
import socket
import time
from typing import Any, Optional

from nydus_snapshotter_tpu import failpoint
from nydus_snapshotter_tpu.utils import errdefs
from nydus_snapshotter_tpu.utils import retry as retry_lib


class ClientError(errdefs.NydusError):
    def __init__(self, status: int, message: str):
        super().__init__(f"daemon API {status}: {message}")
        self.status = status


class _UDSConnection(http.client.HTTPConnection):
    def __init__(self, sock_path: str, timeout: float = 10.0):
        super().__init__("localhost", timeout=timeout)
        self._sock_path = sock_path

    def connect(self):
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.settimeout(self.timeout)
        # A full accept backlog surfaces as EAGAIN on UDS connect (it does
        # not queue); retry briefly so a mount storm doesn't turn into
        # spurious hard failures. ECONNREFUSED is NOT retried: it means no
        # listener (daemon dead), and liveness polling/failover detection
        # depends on that failing fast.
        deadline = time.monotonic() + self.timeout
        while True:
            try:
                self.sock.connect(self._sock_path)
                return
            except OSError as e:
                if e.errno != errno.EAGAIN or time.monotonic() >= deadline:
                    raise
                time.sleep(0.01)


class NydusdClient:
    def __init__(self, sock_path: str, timeout: float = 10.0):
        self.sock_path = sock_path
        self.timeout = timeout

    # -- plumbing -----------------------------------------------------------

    def _request(
        self, method: str, path: str, body: Optional[dict] = None, raw: bool = False
    ) -> Any:
        failpoint.hit("daemon.rpc")
        if method == "GET":
            # Idempotent reads retry through a daemon restarting mid-RPC
            # (connection torn down after connect); the deadline keeps the
            # whole loop inside this client's timeout. Non-idempotent
            # mounts/starts fail fast — their callers own recovery.
            try:
                return retry_lib.do_with_deadline(
                    lambda: self._request_once(method, path, body, raw),
                    deadline=self.timeout,
                    attempts=3,
                    delay=0.05,
                    retry_on=(ConnectionResetError, BrokenPipeError),
                )
            except retry_lib.RetryError as e:
                raise e.last
        return self._request_once(method, path, body, raw)

    def _request_once(
        self, method: str, path: str, body: Optional[dict] = None, raw: bool = False
    ) -> Any:
        conn = _UDSConnection(self.sock_path, self.timeout)
        try:
            data = json.dumps(body) if body is not None else None
            conn.request(method, path, body=data, headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status >= 400:
                try:
                    message = json.loads(payload).get("error", "")
                except Exception:
                    message = payload.decode(errors="replace")
                if resp.status == 404:
                    raise errdefs.NotFound(message or path)
                if resp.status == 409:
                    raise errdefs.AlreadyExists(message or path)
                raise ClientError(resp.status, message)
            if raw:
                return payload
            return json.loads(payload) if payload else None
        finally:
            conn.close()

    def wait_until_socket_exists(self, timeout: float = 10.0) -> None:
        """Reference WaitUntilSocketExisted (client.go:171)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if os.path.exists(self.sock_path):
                try:
                    self.get_daemon_info()
                    return
                except (OSError, errdefs.NydusError):
                    pass
            time.sleep(0.05)
        raise TimeoutError(f"daemon socket {self.sock_path} never became ready")

    # -- daemon lifecycle ---------------------------------------------------

    def get_daemon_info(self) -> dict[str, Any]:
        return self._request("GET", "/api/v1/daemon")

    def start(self) -> None:
        self._request("PUT", "/api/v1/daemon/start")

    def exit(self) -> None:
        self._request("PUT", "/api/v1/daemon/exit")

    def send_fd(self, driver: str = "fuse") -> None:
        self._request("PUT", f"/api/v1/daemon/{driver}/sendfd")

    def takeover(self, driver: str = "fuse") -> None:
        self._request("PUT", f"/api/v1/daemon/{driver}/takeover")

    # -- mounts -------------------------------------------------------------

    def mount(self, mountpoint: str, source: str, config: str, fs_type: str = "rafs") -> None:
        self._request(
            "POST",
            f"/api/v1/mount?mountpoint={mountpoint}",
            {"fs_type": fs_type, "source": source, "config": config},
        )

    def umount(self, mountpoint: str) -> None:
        self._request("DELETE", f"/api/v1/mount?mountpoint={mountpoint}")

    # -- fscache v2 blobs API (reference client.go:47-58) --------------------

    def bind_blob(self, daemon_config: str) -> None:
        self._request("PUT", "/api/v2/blobs", {"config": daemon_config})

    def unbind_blob(self, domain_id: str, blob_id: str) -> None:
        self._request("DELETE", f"/api/v2/blobs?domain_id={domain_id}&blob_id={blob_id}")

    # -- metrics ------------------------------------------------------------

    def fs_metrics(self, mountpoint: str = "") -> dict[str, Any]:
        suffix = f"?id={mountpoint}" if mountpoint else ""
        return self._request("GET", f"/api/v1/metrics{suffix}")

    def cache_metrics(self) -> dict[str, Any]:
        return self._request("GET", "/api/v1/metrics/blobcache")

    def inflight_metrics(self) -> list:
        return self._request("GET", "/api/v1/metrics/inflight") or []

    # -- userspace data plane ----------------------------------------------

    def read_file(self, mountpoint: str, path: str, offset: int = 0, size: int = -1) -> bytes:
        return self._request(
            "GET",
            f"/api/v1/fs?mountpoint={mountpoint}&op=read&path={path}"
            f"&offset={offset}&size={size}",
            raw=True,
        )

    def stat_file(self, mountpoint: str, path: str) -> dict[str, Any]:
        return self._request("GET", f"/api/v1/fs?mountpoint={mountpoint}&op=stat&path={path}")

    def list_dir(self, mountpoint: str, path: str) -> list[str]:
        return self._request("GET", f"/api/v1/fs?mountpoint={mountpoint}&op=list&path={path}")
