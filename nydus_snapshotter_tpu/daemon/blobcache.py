"""Registry-backed lazy blob reads with a chunk-granular local cache.

This is the half of nydusd's data plane the daemon was missing: with a
``registry`` backend in the instance config, chunk reads become ranged
HTTP GETs against the blob (mirrors first, origin last — the failover the
reference configures through mirror lists, daemonconfig mirrors.go), and
every fetched extent is written through to a local cache file so the
second access is a local pread. Cache artifacts use the reference's
blobcache names — ``<blob_id>.blob.data`` + ``<blob_id>.chunk_map`` — the
exact files pkg/cache's accounting/GC already manages (cache/manager.py).

The miss path is parallel (daemon/fetch_sched.py): concurrent misses on
overlapping extents share one flight, adjacent miss gaps coalesce into
larger ranged GETs, sequential readers get readahead, and all fetches run
on a multi-connection worker pool under a byte-bounded in-flight budget.

The chunk map is an append-only sequence of ``(u64 offset, u32 size)``
little-endian records; a torn final record (crash mid-append) is dropped
on load, and the corresponding extent simply re-fetches. Appends are
batched: each fetch batch (one ``read_at`` miss, one prefetch-replay
file) flushes once instead of once per record.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time
from time import perf_counter
from typing import Callable, Optional

from nydus_snapshotter_tpu import trace
from nydus_snapshotter_tpu.analysis import runtime as _an
from nydus_snapshotter_tpu.daemon import fetch_sched
from nydus_snapshotter_tpu.daemon.fetch_sched import (
    BACKGROUND,
    DEMAND,
    PREFETCH,
    FetchConfig,
    FetchScheduler,
    IntervalSet,
)
from nydus_snapshotter_tpu.provenance import ledger as provenance
from nydus_snapshotter_tpu.remote import mirror as mirror_mod
from nydus_snapshotter_tpu.remote.mirror import HostHealth

logger = logging.getLogger(__name__)

_RECORD = struct.Struct("<QI")

# A throttling registry's Retry-After is honored in place (the host is
# being polite, not failing), bounded like remote/transport.py.
RETRY_AFTER_CAP = 5.0


class RegistryBlobFetcher:
    """Ranged blob GETs with health-scored mirror failover.

    ``backend`` is a daemonconfig.BackendConfig-shaped object (host, repo,
    scheme, auth, skip_verify, mirrors). Mirrors are tried in listed order,
    the origin host last. Each host carries a
    :class:`~nydus_snapshotter_tpu.remote.mirror.HostHealth` consecutive-
    failure scorer: a host that trips its failure limit goes on cooldown
    and is skipped by the ordering until the cooldown expires, then gets a
    fresh budget — no host stays demoted forever. Cooled-down hosts are
    still tried last-resort when every healthy candidate failed. HTTP 429
    honors Retry-After with one bounded in-place retry, the same contract
    as remote/transport.py.

    ``read_range`` is thread-safe and is called concurrently by the fetch
    scheduler's worker pool (one pooled RegistryClient per host; the
    client itself opens one connection per request).
    """

    def __init__(
        self,
        backend,
        blob_id: str,
        clock=time.monotonic,
        sleep=time.sleep,
        health_registry=None,
    ):
        self.backend = backend
        self.blob_id = blob_id
        self._sleep = sleep
        mirrors = [m for m in getattr(backend, "mirrors", []) if m.host]
        hosts = [m.host for m in mirrors]
        hosts.append(backend.host)
        self._hosts = hosts
        self._clients: dict[str, object] = {}
        # Host health lives in the PROCESS-WIDE registry shared with the
        # converter transport (remote/transport.Pool) and the peer router
        # (daemon/peer.py): a host one component demotes is avoided by
        # all. A custom clock (tests) gets a private table instead.
        if health_registry is None:
            if clock is time.monotonic:
                health_registry = mirror_mod.global_health_registry()
            else:
                health_registry = mirror_mod.HostHealthRegistry(clock=clock)
        self._registry = health_registry
        self._health: dict[str, HostHealth] = {}
        for m in mirrors:
            self._health[m.host] = health_registry.health_for(
                m.host,
                failure_limit=getattr(m, "failure_limit", 5),
                cooldown=float(getattr(m, "health_check_interval", 5)),
            )
        self._health[backend.host] = health_registry.health_for(backend.host)
        self._lock = _an.make_lock(f"blobcache.fetcher[{blob_id[:8]}]")

    def _client(self, host: str):
        from nydus_snapshotter_tpu.auth import keychain as authmod
        from nydus_snapshotter_tpu.remote.registry import RegistryClient

        with self._lock:
            client = self._clients.get(host)
            if client is None:
                kc = None
                if getattr(self.backend, "auth", ""):
                    kc = authmod.from_base64(self.backend.auth)
                # Scheme is per host: an explicit URL prefix wins, the
                # origin scheme is only the default for bare hosts (an
                # https:// mirror must never be contacted in cleartext).
                if host.startswith("https://"):
                    plain = False
                elif host.startswith("http://"):
                    plain = True
                else:
                    plain = self.backend.scheme == "http"
                client = RegistryClient(
                    host.replace("http://", "").replace("https://", ""),
                    keychain=kc,
                    plain_http=plain,
                    insecure_tls=getattr(self.backend, "skip_verify", False),
                )
                self._clients[host] = client
        return client

    def _candidates(self) -> list[str]:
        """Healthy hosts in configured order, cooled-down hosts after —
        a last resort, not a permanent exclusion."""
        with self._lock:
            healthy = [h for h in self._hosts if self._health[h].available()]
            cooling = [h for h in self._hosts if not self._health[h].available()]
        return healthy + cooling

    def _record(self, host: str, ok: bool) -> None:
        with self._lock:
            h = self._health[host]
            if ok:
                h.record_success()
            else:
                h.record_failure()

    def _fetch_once(self, host: str, digest: str, offset: int, size: int) -> bytes:
        r = self._client(host).fetch_blob(
            self.backend.repo, digest, byte_range=(offset, offset + size - 1)
        )
        try:
            status = r.status
            data = r.read()
        finally:
            r.close()
        if status == 200 and len(data) > size:
            # Registry ignored the Range header and served the whole
            # blob (fetch_blob whitelists 200 for exactly this case).
            data = data[offset : offset + size]
        if len(data) != size:
            raise OSError(f"ranged GET returned {len(data)} bytes, wanted {size}")
        return data

    def read_range(self, offset: int, size: int) -> bytes:
        from nydus_snapshotter_tpu.remote.registry import HTTPError

        if size <= 0:
            return b""
        digest = self.blob_id if ":" in self.blob_id else f"sha256:{self.blob_id}"
        last_error: Optional[Exception] = None
        for host in self._candidates():
            try:
                try:
                    data = self._fetch_once(host, digest, offset, size)
                except HTTPError as e:
                    if e.code != 429:
                        raise
                    # Throttled, not broken: pause as asked (bounded) and
                    # retry this host once before moving on.
                    self._sleep(min(max(e.retry_after, 0.0), RETRY_AFTER_CAP))
                    data = self._fetch_once(host, digest, offset, size)
                self._record(host, ok=True)
                return data
            except Exception as e:  # noqa: BLE001 — any failure scores, next host tries
                last_error = e
                self._record(host, ok=False)
                logger.warning("blob fetch from %s failed: %s", host, e)
        raise OSError(f"all registry hosts failed for {self.blob_id}: {last_error}")


class CachedBlob:
    """Write-through extent cache over a remote fetcher.

    ``read_at(offset, size)`` serves from ``<blob_id>.blob.data`` when the
    requested extent is covered by previously fetched intervals, else
    schedules the miss gaps on the fetch scheduler (singleflight +
    coalescing + readahead), waits, and preads the now-resident range.

    ``blob_size`` (when known) clamps readahead so sequential warming
    never runs past the blob's end. An eviction that unlinks the cache
    files under a live instance is survived transparently: the next read
    notices the dropped link, re-creates the files and re-fetches.
    """

    def __init__(
        self,
        cache_dir: str,
        blob_id: str,
        fetch_range: Callable[[int, int], bytes],
        blob_size: int = 0,
        config: Optional[FetchConfig] = None,
        budget=None,
        gate=None,
        tenant: str = "default",
    ):
        os.makedirs(cache_dir, exist_ok=True)
        self.blob_id = blob_id
        self.data_path = os.path.join(cache_dir, f"{blob_id}.blob.data")
        self.map_path = os.path.join(cache_dir, f"{blob_id}.chunk_map")
        self.fetch_range = fetch_range
        self.blob_size = max(0, int(blob_size))
        self._lock = _an.make_lock(f"blobcache.blob[{blob_id[:8]}]")
        self._intervals = IntervalSet()
        # Lockset annotation: interval/chunk-map state is only ever
        # touched under self._lock (shared with the fetch scheduler).
        self._intervals_shared = _an.shared(f"blobcache.intervals[{blob_id[:8]}]")
        self._ra_spans = IntervalSet()  # readahead-fetched, not yet read
        self._data_fd = os.open(self.data_path, os.O_RDWR | os.O_CREAT, 0o644)
        self._map_f = open(self.map_path, "ab")
        self._map_dirty = False
        self._closed = False
        self._last_end = -1  # sequential-access detector
        self._load_map()
        self.remote_bytes = 0  # fetched over the network (metrics)
        self.tenant = tenant
        self.sched = FetchScheduler(
            self._lock,
            self._intervals,
            self._fetch,
            self._deliver,
            config=config,
            budget=budget,
            name=blob_id[:8],
            gate=gate,
            tenant=tenant,
            on_fetched=self._prov_fetched,
        )
        provenance.set_blob_meta(blob_id, tenant=tenant)

    # -- persistence ---------------------------------------------------------

    def _load_map(self) -> None:
        try:
            with open(self.map_path, "rb") as f:
                raw = f.read()
        except OSError:
            return
        usable = len(raw) - len(raw) % _RECORD.size  # drop a torn tail record
        for i in range(0, usable, _RECORD.size):
            off, size = _RECORD.unpack_from(raw, i)
            self._intervals.add(off, off + size)

    def _fetch(self, offset: int, size: int) -> bytes:
        data = self.fetch_range(offset, size)
        if len(data) != size:
            raise OSError(
                f"fetcher returned {len(data)} bytes for [{offset}, {offset + size})"
            )
        return data

    def _deliver(self, offset: int, data: bytes) -> None:
        """Persist one completed flight (runs under self._lock): sparse
        pwrite + chunk-map append (flushed per batch, not per record)."""
        self._intervals_shared.write()
        os.pwrite(self._data_fd, data, offset)
        self._map_f.write(_RECORD.pack(offset, len(data)))
        self._map_dirty = True
        self._intervals.add(offset, offset + len(data))
        self.remote_bytes += len(data)

    def _flush_map_locked(self) -> None:
        if self._map_dirty:
            self._map_f.flush()
            self._map_dirty = False

    def _prov_fetched(self, flight, n: int) -> None:
        """Attribute one delivered flight in the provenance ledger
        (called by the scheduler under self._lock, on the worker thread
        that ran the fetch). Cause resolution: a plan-time tag override
        (e.g. the seekable-index build) wins, then a fired hedge race,
        then the flight's QoS lane. Attribution can degrade (the
        ``prov.record`` chaos contract) but can never fail the read."""
        try:
            notes = fetch_sched.take_fetch_notes()
            if flight.tag:
                cause = flight.tag
            elif notes.get("hedged"):
                cause = provenance.CAUSE_HEDGE_WINNER
            else:
                cause = fetch_sched.LANE_NAMES[flight.priority]
            provenance.record_fetch(
                self.blob_id,
                flight.start,
                n,
                cause,
                tier=str(notes.get("tier", "")),
            )
        except Exception:  # noqa: BLE001 — attribution never fails a read
            logger.debug("provenance record failed", exc_info=True)

    # -- eviction survival ---------------------------------------------------

    def _revalidate_locked(self) -> None:
        """A capacity-watermark eviction (cache/manager.py) may unlink the
        cache files under a live instance. The open fd keeps old bytes
        readable but new write-through would land in an unlinked inode —
        so detect the dropped link and start the cache over."""
        try:
            if os.fstat(self._data_fd).st_nlink > 0:
                return
        except OSError:
            return
        try:
            os.close(self._data_fd)
        except OSError:
            pass
        try:
            self._map_f.close()
        except OSError:
            pass
        self._data_fd = os.open(self.data_path, os.O_RDWR | os.O_CREAT, 0o644)
        self._map_f = open(self.map_path, "ab")
        self._map_dirty = False
        self._intervals.clear()
        self._ra_spans.clear()
        self._load_map()  # a concurrent writer may have re-seeded it

    # -- reads ---------------------------------------------------------------

    def _plan_readahead_locked(self, end: int) -> None:
        """Sequential reader: extend the window ahead of the read as
        BACKGROUND flights (never merged into the demand fetch, so a
        readahead failure can't fail the read)."""
        ra = self.sched.cfg.readahead
        if ra <= 0:
            return
        ra_end = end + ra
        if self.blob_size:
            ra_end = min(ra_end, self.blob_size)
        if ra_end <= end:
            return
        from nydus_snapshotter_tpu import failpoint

        with trace.span(
            "blobcache.readahead", blob=self.blob_id[:8], window=(end, ra_end)
        ) as sp:
            failpoint.hit("blobcache.readahead")
            planned = 0
            pre = {id(f) for f in self.sched.overlapping_flights(end, ra_end)}
            for f in self.sched.plan_locked(end, ra_end, priority=BACKGROUND):
                if id(f) not in pre and f.priority == BACKGROUND:
                    # New flights cover exactly uncovered, not-in-flight gaps.
                    fetch_sched.READAHEAD_BYTES.inc(f.end - f.start)
                    self._ra_spans.add(f.start, f.end)
                    planned += f.end - f.start
            sp.annotate(planned_bytes=planned)

    def _account_ra_hit_locked(self, start: int, end: int) -> None:
        hit = self._ra_spans.remove(start, end)
        if hit:
            fetch_sched.READAHEAD_HIT_BYTES.inc(hit)

    def read_at(self, offset: int, size: int, lane: int = DEMAND) -> bytes:
        """Serve ``[offset, offset+size)``. ``lane`` is the QoS lane the
        miss fetches run at: DEMAND for real reads, PEER_SERVE when a
        peer chunk server pulls through on behalf of another node
        (daemon/peer.py) — local demand must always outrank it."""
        if size <= 0:
            return b""
        # One span + one histogram sample per read, both metering the
        # same window — the trace shows WHERE this read's time went (its
        # fetch flights carry this context), the histogram shows the
        # population.
        t0 = perf_counter()
        with trace.span(
            "blobcache.read_at", blob=self.blob_id[:8], offset=offset, bytes=size
        ):
            try:
                return self._read_at(offset, size, lane)
            finally:
                fetch_sched.OP_HIST.labels("read_at").observe(
                    (perf_counter() - t0) * 1000.0
                )

    def _read_at(self, offset: int, size: int, lane: int = DEMAND) -> bytes:
        end = offset + size
        first_pass = True
        while True:
            with self._lock:
                if self._closed:
                    raise OSError(f"blob cache {self.data_path} is closed")
                self._revalidate_locked()
                self._intervals_shared.write()
                # Peer-serve pull-throughs must not pollute the LOCAL
                # sequential-reader detector (readahead is a demand-lane
                # heuristic).
                sequential = lane == DEMAND and offset == self._last_end
                if lane == DEMAND:
                    self._last_end = end
                if self._intervals.covered(offset, end):
                    if first_pass:
                        fetch_sched.HIT_BYTES.inc(size)
                    self._account_ra_hit_locked(offset, end)
                    if sequential and lane == DEMAND:
                        self._plan_readahead_locked(end)
                    if lane == DEMAND:
                        # The read set the provenance waste accounting
                        # overlays on the attributed extents (peer-serve
                        # pull-throughs are a remote node's reads, not
                        # local heat).
                        provenance.record_read(self.blob_id, offset, size)
                    return os.pread(self._data_fd, size, offset)
                flights = self.sched.plan_locked(offset, end, priority=lane)
                if sequential and first_pass and lane == DEMAND:
                    self._plan_readahead_locked(end)
            first_pass = False
            for f in flights:
                f.wait()
            errors = [f.error for f in flights if f.error is not None]
            if errors:
                hard = [
                    e for e in errors
                    if not isinstance(e, fetch_sched.LaneShedError)
                ]
                if hard:
                    raise hard[0]
                if lane != DEMAND:
                    # This read's own lane is shed: degrade like any other
                    # background failure (prefetch warms nothing, a peer
                    # requester falls back to the registry).
                    raise errors[0]
                # A demand read piggybacked on a background flight that SLO
                # actuation shed: replan — the while loop re-plans the
                # still-uncovered extent at DEMAND priority, which is never
                # shed, so actuation cannot fail or starve a real read.
                continue
            with self._lock:
                if self._closed:
                    raise OSError(f"blob cache {self.data_path} is closed")
                self._flush_map_locked()
                self._intervals_shared.read()
                # A concurrent eviction can drop coverage between flight
                # delivery and this pread — replan instead of returning
                # holes (the while-loop re-checks under the lock).
                if self._intervals.covered(offset, end):
                    self._account_ra_hit_locked(offset, end)
                    if lane == DEMAND:
                        provenance.record_read(self.blob_id, offset, size)
                    return os.pread(self._data_fd, size, offset)

    def covered(self, offset: int, size: int) -> bool:
        """Whether ``[offset, offset+size)`` is resident locally — the
        peer chunk server (daemon/peer.py) answers cover-only requests
        from this, never fetching on a stranger's behalf."""
        with self._lock:
            if self._closed:
                return False
            self._intervals_shared.read()
            return self._intervals.covered(offset, offset + size)

    def coverage_bytes(self) -> int:
        """Total resident bytes (peer announce/stat endpoint)."""
        with self._lock:
            if self._closed:
                return 0
            self._intervals_shared.read()
            return self._intervals.total_bytes()

    def warm(self, offset: int, size: int) -> list:
        """Schedule ``[offset, offset+size)`` residency at PREFETCH
        priority (prefetch-list replay — below the readahead lane, above
        peer-serve); returns the flights to optionally wait on. Never
        raises on a closed cache — warming is advisory."""
        if size <= 0:
            return []
        with self._lock:
            if self._closed:
                return []
            self._intervals_shared.read()
            if self._intervals.covered(offset, offset + size):
                return []
            try:
                return self.sched.plan_locked(offset, offset + size, priority=PREFETCH)
            except OSError:
                return []

    def flush_map(self) -> None:
        """One batched chunk-map flush (prefetch replay calls this per
        replayed file; read_at flushes per miss batch)."""
        with self._lock:
            if not self._closed:
                self._flush_map_locked()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        # Scheduler teardown happens outside the lock: in-flight workers
        # need it to finish delivering before they observe the close.
        self.sched.close()
        with self._lock:
            try:
                try:
                    self._map_f.flush()
                finally:
                    os.close(self._data_fd)
            finally:
                self._map_f.close()
