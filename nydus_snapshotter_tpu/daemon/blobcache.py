"""Registry-backed lazy blob reads with a chunk-granular local cache.

This is the half of nydusd's data plane the daemon was missing: with a
``registry`` backend in the instance config, chunk reads become ranged
HTTP GETs against the blob (mirrors first, origin last — the failover the
reference configures through mirror lists, daemonconfig mirrors.go), and
every fetched extent is written through to a local cache file so the
second access is a local pread. Cache artifacts use the reference's
blobcache names — ``<blob_id>.blob.data`` + ``<blob_id>.chunk_map`` — the
exact files pkg/cache's accounting/GC already manages (cache/manager.py).

The chunk map is an append-only sequence of ``(u64 offset, u32 size)``
little-endian records; a torn final record (crash mid-append) is dropped
on load, and the corresponding extent simply re-fetches.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
from typing import Callable, Optional

logger = logging.getLogger(__name__)

_RECORD = struct.Struct("<QI")


class RegistryBlobFetcher:
    """Ranged blob GETs with mirror failover.

    ``backend`` is a daemonconfig.BackendConfig-shaped object (host, repo,
    scheme, auth, skip_verify, mirrors). Mirrors are tried in listed order,
    the origin host last; a host that fails is skipped for subsequent
    reads until every other candidate has also failed (simple demotion —
    the reference delegates richer health checking to nydusd's config,
    mirrors.go:63-69).
    """

    def __init__(self, backend, blob_id: str):
        self.backend = backend
        self.blob_id = blob_id
        hosts = [m.host for m in getattr(backend, "mirrors", []) if m.host]
        hosts.append(backend.host)
        self._hosts = hosts
        self._clients: dict[str, object] = {}
        self._demoted: set[str] = set()
        self._lock = threading.Lock()

    def _client(self, host: str):
        from nydus_snapshotter_tpu.auth import keychain as authmod
        from nydus_snapshotter_tpu.remote.registry import RegistryClient

        with self._lock:
            client = self._clients.get(host)
            if client is None:
                kc = None
                if getattr(self.backend, "auth", ""):
                    kc = authmod.from_base64(self.backend.auth)
                # Scheme is per host: an explicit URL prefix wins, the
                # origin scheme is only the default for bare hosts (an
                # https:// mirror must never be contacted in cleartext).
                if host.startswith("https://"):
                    plain = False
                elif host.startswith("http://"):
                    plain = True
                else:
                    plain = self.backend.scheme == "http"
                client = RegistryClient(
                    host.replace("http://", "").replace("https://", ""),
                    keychain=kc,
                    plain_http=plain,
                    insecure_tls=getattr(self.backend, "skip_verify", False),
                )
                self._clients[host] = client
        return client

    def read_range(self, offset: int, size: int) -> bytes:
        if size <= 0:
            return b""
        digest = self.blob_id if ":" in self.blob_id else f"sha256:{self.blob_id}"
        last_error: Optional[Exception] = None
        with self._lock:
            order = [h for h in self._hosts if h not in self._demoted] + [
                h for h in self._hosts if h in self._demoted
            ]
        for host in order:
            try:
                r = self._client(host).fetch_blob(
                    self.backend.repo, digest, byte_range=(offset, offset + size - 1)
                )
                try:
                    status = r.status
                    data = r.read()
                finally:
                    r.close()
                if status == 200 and len(data) > size:
                    # Registry ignored the Range header and served the whole
                    # blob (fetch_blob whitelists 200 for exactly this case).
                    data = data[offset : offset + size]
                if len(data) != size:
                    raise OSError(
                        f"ranged GET returned {len(data)} bytes, wanted {size}"
                    )
                with self._lock:
                    self._demoted.discard(host)
                return data
            except Exception as e:  # noqa: BLE001 — any failure demotes, next host tries
                last_error = e
                with self._lock:
                    self._demoted.add(host)
                logger.warning("blob fetch from %s failed: %s", host, e)
        raise OSError(f"all registry hosts failed for {self.blob_id}: {last_error}")


class CachedBlob:
    """Write-through extent cache over a remote fetcher.

    ``read_at(offset, size)`` serves from ``<blob_id>.blob.data`` when the
    requested extent is covered by previously fetched intervals, else
    fetches, persists (sparse pwrite + chunk-map append) and returns.
    """

    def __init__(self, cache_dir: str, blob_id: str, fetch_range: Callable[[int, int], bytes]):
        os.makedirs(cache_dir, exist_ok=True)
        self.data_path = os.path.join(cache_dir, f"{blob_id}.blob.data")
        self.map_path = os.path.join(cache_dir, f"{blob_id}.chunk_map")
        self.fetch_range = fetch_range
        self._lock = threading.Lock()
        self._intervals: list[tuple[int, int]] = []  # merged (start, end)
        self._data_fd = os.open(self.data_path, os.O_RDWR | os.O_CREAT, 0o644)
        self._map_f = open(self.map_path, "ab")
        self._closed = False
        self._load_map()
        self.remote_bytes = 0  # fetched over the network (metrics)

    def _load_map(self) -> None:
        try:
            with open(self.map_path, "rb") as f:
                raw = f.read()
        except OSError:
            return
        usable = len(raw) - len(raw) % _RECORD.size  # drop a torn tail record
        for i in range(0, usable, _RECORD.size):
            off, size = _RECORD.unpack_from(raw, i)
            self._insert(off, off + size)

    def _insert(self, start: int, end: int) -> None:
        merged = []
        for s, e in self._intervals:
            if e < start or s > end:
                merged.append((s, e))
            else:
                start, end = min(start, s), max(end, e)
        merged.append((start, end))
        merged.sort()
        self._intervals = merged

    def _covered(self, start: int, end: int) -> bool:
        for s, e in self._intervals:
            if s <= start and end <= e:
                return True
        return False

    def read_at(self, offset: int, size: int) -> bytes:
        if size <= 0:
            return b""
        with self._lock:
            if self._closed:
                raise OSError(f"blob cache {self.data_path} is closed")
            if self._covered(offset, offset + size):
                return os.pread(self._data_fd, size, offset)
        data = self.fetch_range(offset, size)
        with self._lock:
            if self._closed:
                # Umount raced the fetch: return the data, skip the
                # write-through (the fd is gone).
                return data
            os.pwrite(self._data_fd, data, offset)
            self._map_f.write(_RECORD.pack(offset, size))
            self._map_f.flush()
            self._insert(offset, offset + size)
            self.remote_bytes += len(data)
        return data

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                os.close(self._data_fd)
            finally:
                self._map_f.close()
