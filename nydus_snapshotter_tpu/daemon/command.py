"""Daemon command-line builder.

The reference builds nydusd argv reflectively from struct tags
(pkg/daemon/command/command.go:20-102); here a dataclass maps 1:1 onto the
daemon server's argparse flags — one definition, typo-proof both ways.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, fields


@dataclass
class DaemonCommand:
    id: str = ""
    apisock: str = ""
    supervisor: str = ""
    workdir: str = ""
    log_file: str = ""
    upgrade: bool = False

    def build(self) -> list[str]:
        argv = [sys.executable, "-m", "nydus_snapshotter_tpu.daemon.server"]
        for f in fields(self):
            value = getattr(self, f.name)
            flag = "--" + f.name.replace("_", "-")
            if isinstance(value, bool):
                if value:
                    argv.append(flag)
            elif value:
                argv += [flag, str(value)]
        return argv
