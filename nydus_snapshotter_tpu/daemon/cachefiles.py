"""cachefiles ondemand daemon: the in-kernel EROFS-over-fscache data path.

Reference correspondence: nydusd's fscache mode — the daemon the
reference binds blobs through at pkg/daemon/daemon.go:275-324, mounting
EROFS with ``fsid=`` so the KERNEL pages data through cachefiles and the
userspace daemon only answers cache-miss reads. The Go side never speaks
this protocol itself (nydusd does); here the daemon is in-repo.

Protocol (kernel uapi include/uapi/linux/cachefiles.h, 5.19+ ondemand
mode):

- open ``/dev/cachefiles``; write ``dir <cache_root>``, ``tag <tag>``,
  ``bind ondemand``.
- each ``read()`` returns one ``cachefiles_msg``:
  ``{u32 msg_id, u32 object_id, u32 opcode, u32 len, u8 data[]}``.
- OPEN(0): data = ``cachefiles_open {u32 volume_key_size, u32
  cookie_key_size, u32 fd, u32 flags, u8 keys[]}``; the kernel passes an
  anon fd for the cache object; the daemon answers
  ``copen <msg_id>,<object_size>`` (negative size = error). For the
  erofs fsid domain, cookie_key is the blob/fscache id string.
- READ(2): data = ``cachefiles_read {u64 off, u64 len}``; the daemon
  pwrite()s the blob bytes into the object fd at ``off`` and acks with
  ``ioctl(fd, CACHEFILES_IOC_READ_COMPLETE, msg_id)``.
- CLOSE(1): drop the object fd.

The device is injectable (``DeviceIO``) so the message parser, copen
formatting, read servicing, and error paths are unit-tested on any
kernel (tests/test_cachefiles.py drives crafted msgs through pipes);
``supported()`` gates the real /dev/cachefiles path, which THIS
environment can never take: the container kernel exposes no cachefiles
device, no /proc/misc entry, and no module loading (see PARITY.md
environmental limits). On a cachefiles-capable kernel the same class
binds for real and `mount -t erofs -o fsid=` serves through it.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
from dataclasses import dataclass
from typing import Callable, Optional

logger = logging.getLogger(__name__)

DEVICE_PATH = "/dev/cachefiles"

OP_OPEN = 0
OP_CLOSE = 1
OP_READ = 2

_MSG_HDR = struct.Struct("<IIII")  # msg_id, object_id, opcode, len
_OPEN_HDR = struct.Struct("<IIII")  # volume_key_size, cookie_key_size, fd, flags
_READ_REQ = struct.Struct("<QQ")  # off, len

# _IOW(0x98, 1, int): dir=write(1)<<30 | sizeof(int)<<16 | 0x98<<8 | 1
CACHEFILES_IOC_READ_COMPLETE = 0x40049801


class CachefilesError(RuntimeError):
    pass


def supported() -> bool:
    """True when this kernel exposes the cachefiles ondemand device."""
    return os.path.exists(DEVICE_PATH)


class DeviceIO:
    """Thin fd wrapper so tests can substitute pipes for /dev/cachefiles."""

    def __init__(self, fd: int):
        self.fd = fd

    def poll(self, timeout: float) -> bool:
        """True when a read would not block (select works on the char
        device and on the test pipes alike). The service loop polls so a
        stop() request is observed even on a quiescent device — closing
        an fd does NOT wake another thread blocked in read(2) on Linux."""
        import select

        r, _w, _x = select.select([self.fd], [], [], timeout)
        return bool(r)

    def read(self, n: int) -> bytes:
        return os.read(self.fd, n)

    def write(self, data: bytes) -> int:
        return os.write(self.fd, data)

    def ioctl(self, obj_fd: int, req: int, arg: int) -> None:
        import fcntl

        fcntl.ioctl(obj_fd, req, arg)

    def close(self) -> None:
        os.close(self.fd)


@dataclass
class _Object:
    object_id: int
    fd: int
    cookie_key: str
    volume_key: str
    size: int
    # resolved ONCE at open: READs must not re-invoke the resolver (an
    # unbind while the mount is live would kill them, and per-read
    # resolution leaked one fd per cache miss)
    reader: Callable[[int, int], bytes] = None
    closer: Optional[Callable[[], None]] = None


class CachefilesOndemandDaemon:
    """Serve cachefiles ondemand requests from a blob resolver.

    ``resolver(cookie_key) -> (size, reader[, closer])`` where
    ``reader(off, ln)`` returns exactly ``ln`` bytes of the blob — the
    blobcache's lazy read plane (daemon/blobcache.py) plugs straight in;
    the optional ``closer`` releases whatever the reader holds when the
    kernel closes the object. The resolver runs ONCE per OPEN; the
    result lives for the object's lifetime, so an unbind cannot break a
    live mount. Unknown cookies get a negative copen (the kernel fails
    the mount instead of hanging it).
    """

    def __init__(
        self,
        resolver: Callable[[str], tuple[int, Callable[[int, int], bytes]]],
        device: Optional[DeviceIO] = None,
        cache_dir: str = "",
        tag: str = "ntpu",
    ):
        self.resolver = resolver
        self.cache_dir = cache_dir
        self.tag = tag
        self.device = device
        self.objects: dict[int, _Object] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def bind(self) -> None:
        """Open the real device and enter ondemand mode (kernel-gated)."""
        if self.device is None:
            if not supported():
                raise CachefilesError(f"{DEVICE_PATH} not present on this kernel")
            self.device = DeviceIO(os.open(DEVICE_PATH, os.O_RDWR))
        os.makedirs(self.cache_dir, exist_ok=True)
        for cmd in (f"dir {self.cache_dir}", f"tag {self.tag}", "bind ondemand"):
            self.device.write(cmd.encode())

    def run_forever(self) -> None:
        while not self._stop.is_set():
            try:
                if not self.device.poll(0.5):
                    continue
                buf = self.device.read(16 << 10)
            except OSError as e:
                if self._stop.is_set():
                    return
                raise CachefilesError(f"device read failed: {e}") from e
            if not buf:
                return  # device closed
            try:
                self.handle_msg(buf)
            except CachefilesError:
                # framing failure: the rest of this buffer is unparseable
                logger.exception("cachefiles framing failed; buffer dropped")

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run_forever, name="cachefiles-ondemand", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        # Join FIRST: the loop observes _stop within one poll interval,
        # and object fds must not be closed under an in-flight pwrite.
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self.device is not None:
            try:
                self.device.close()
            except OSError:
                pass
        for obj in self.objects.values():
            self._release(obj)
        self.objects.clear()

    @staticmethod
    def _release(obj: _Object) -> None:
        try:
            os.close(obj.fd)
        except OSError:
            pass
        if obj.closer is not None:
            try:
                obj.closer()
            except Exception:
                logger.exception("cachefiles object closer failed")

    # -- protocol ------------------------------------------------------------

    def handle_msg(self, buf: bytes) -> None:
        """Parse and dispatch cachefiles_msg(s) from one read.

        The kernel returns one message per read; the embedded ``len``
        framing also lets coalesced buffers (the test pipes) carry
        several back-to-back.
        """
        while buf:
            if len(buf) < _MSG_HDR.size:
                raise CachefilesError(f"short cachefiles msg: {len(buf)} bytes")
            msg_id, object_id, opcode, ln = _MSG_HDR.unpack_from(buf)
            if ln < _MSG_HDR.size or ln > len(buf):
                raise CachefilesError(
                    f"cachefiles msg length {ln} outside read size {len(buf)}"
                )
            data = buf[_MSG_HDR.size : ln]
            buf = buf[ln:]
            # Per-message containment: one bad message (or one failing
            # blob read) must not take down the others — framing is
            # intact past this point, so later messages still serve.
            # A dead service loop would hang EVERY fscache mount.
            try:
                if opcode == OP_OPEN:
                    self._on_open(msg_id, object_id, data)
                elif opcode == OP_READ:
                    self._on_read(msg_id, object_id, data)
                elif opcode == OP_CLOSE:
                    self._on_close(object_id)
                else:
                    raise CachefilesError(f"unknown cachefiles opcode {opcode}")
            except (CachefilesError, OSError, KeyError):
                if threading.current_thread() is not self._thread:
                    raise  # direct handle_msg() callers see errors
                logger.exception("cachefiles message failed; loop continues")

    def _on_open(self, msg_id: int, object_id: int, data: bytes) -> None:
        if len(data) < _OPEN_HDR.size:
            raise CachefilesError("short cachefiles_open payload")
        vks, cks, fd, _flags = _OPEN_HDR.unpack_from(data)
        keys = data[_OPEN_HDR.size :]
        if len(keys) < vks + cks:
            raise CachefilesError("cachefiles_open keys overflow payload")
        volume_key = keys[:vks].split(b"\x00", 1)[0].decode(errors="replace")
        cookie_key = keys[vks : vks + cks].split(b"\x00", 1)[0].decode(
            errors="replace"
        )
        try:
            resolved = self.resolver(cookie_key)
            size, reader = resolved[0], resolved[1]
            closer = resolved[2] if len(resolved) > 2 else None
        except Exception:
            # ANY resolver failure (unknown cookie, unreadable bootstrap,
            # render error) must fail the open: the kernel surfaces ENOENT
            # to the mount instead of wedging it waiting for a copen that
            # would never come.
            logger.exception("cachefiles open failed for cookie %r", cookie_key)
            if fd >= 0:
                try:
                    os.close(fd)
                except OSError:
                    pass
            self.device.write(f"copen {msg_id},-2".encode())  # -ENOENT
            return
        self.objects[object_id] = _Object(
            object_id=object_id,
            fd=fd,
            cookie_key=cookie_key,
            volume_key=volume_key,
            size=size,
            reader=reader,
            closer=closer,
        )
        self.device.write(f"copen {msg_id},{size}".encode())

    def _on_read(self, msg_id: int, object_id: int, data: bytes) -> None:
        if len(data) < _READ_REQ.size:
            raise CachefilesError("short cachefiles_read payload")
        off, ln = _READ_REQ.unpack_from(data)
        obj = self.objects.get(object_id)
        if obj is None:
            raise CachefilesError(f"read for unknown object {object_id}")
        # clamp to the object: the kernel may round the window up
        end = min(off + ln, obj.size)
        chunk = obj.reader(off, max(0, end - off)) if end > off else b""
        pos = off
        view = memoryview(chunk)
        while view:
            n = os.pwrite(obj.fd, view, pos)
            pos += n
            view = view[n:]
        self.device.ioctl(obj.fd, CACHEFILES_IOC_READ_COMPLETE, msg_id)

    def _on_close(self, object_id: int) -> None:
        obj = self.objects.pop(object_id, None)
        if obj is not None:
            self._release(obj)
