"""The daemon process — this framework's nydusd equivalent.

Serves the reference nydusd HTTP-over-UDS API (surface catalogued at
pkg/daemon/client.go:31-58): daemon info/state, mount/umount, blob binding,
metrics (fs/cache/inflight), start/exit, and the supervisor
sendfd/takeover dance used for failover and live upgrade
(SURVEY §3.4). The data plane is a userspace read API (stat/list/read on
mounted RAFS instances, chunks resolved from local blob cache) instead of a
kernel FUSE session — lazy serving is I/O-bound and out of the TPU north
star, but the full control surface exists for parity with the reference's
lifecycle, failover, and upgrade flows.

Run: ``python -m nydus_snapshotter_tpu.daemon.server --id ID --apisock PATH
[--supervisor PATH] [--upgrade] [--workdir DIR]``
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import socket
import socketserver
import stat as stat_mod
import sys
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler
from typing import Any, Optional

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu import trace
from nydus_snapshotter_tpu.converter.convert import BlobReader
from nydus_snapshotter_tpu.daemon.types import DaemonState, FsMetrics
from nydus_snapshotter_tpu.models.bootstrap import Bootstrap

__version__ = "0.1.0"

logger = logging.getLogger(__name__)


class _Instance:
    """One mounted RAFS instance."""

    def __init__(self, mountpoint: str, source: str, config_json: str):
        self.mountpoint = mountpoint
        self.source = source
        self.config_json = config_json
        with open(source, "rb") as f:
            # Either layout: native, or a real nydus-toolchain bootstrap
            # (bridged) — the daemon serves both (models/nydus_real.py).
            from nydus_snapshotter_tpu.models.nydus_real import load_any_bootstrap

            self.bootstrap = load_any_bootstrap(f.read())
        self.by_path = self.bootstrap.inode_by_path()
        self.metrics = FsMetrics()
        # Per-blob readers with open fds — the per-chunk open() of the naive
        # path made every read O(chunks) syscalls.
        self._batch_map = self.bootstrap.batch_map()
        self._readers: dict[int, BlobReader] = {}
        self._reader_lock = threading.Lock()
        self._closed = False
        self.prefetched_bytes = 0
        self._cached_blobs: list = []  # CachedBlob instances (registry backend)
        self._cached_by_index: dict[int, object] = {}  # blob_index -> CachedBlob
        # blob_index -> SociStreamReader for gzip-stream blobs with a
        # persisted checkpoint index (soci backend): cold reads resume at
        # the nearest inflate checkpoint instead of from byte 0.
        self._soci_by_index: dict[int, object] = {}
        self._replayer = None  # PrefetchReplayer while a replay is running
        # In-flight data-plane requests (API and FUSE reads both funnel
        # through read()); the inflight metrics endpoint snapshots this so
        # the collector's hung-IO gauge sees real request ages
        # (reference nydusd inflight metrics, client.go:31-58).
        self._inflight: dict[int, dict] = {}
        self._inflight_seq = 0
        self._inflight_lock = threading.Lock()
        self.fuse = None  # FuseSession when a kernel mount is being served

    def start_fuse(self, default_blob_dir: str, fd: Optional[int] = None) -> bool:
        """Serve this instance's mountpoint as a real kernel filesystem.

        Returns False (API-only serving remains) when /dev/fuse is
        unavailable, the mountpoint isn't a directory, or FUSE is disabled
        via NTPU_DISABLE_FUSE. ``fd`` adopts an existing session after a
        failover/upgrade takeover instead of mounting fresh.
        """
        if os.environ.get("NTPU_DISABLE_FUSE"):
            return False
        from nydus_snapshotter_tpu.fusedev.session import (
            FuseSession,
            RafsFuseOps,
            fuse_available,
        )

        if fd is None and not (fuse_available() and os.path.isdir(self.mountpoint)):
            return False
        blob_dir = self.blob_dir(default_blob_dir)
        ops = RafsFuseOps(
            self.bootstrap, lambda p, off, size: self.read(p, off, size, blob_dir)
        )
        session = FuseSession(ops, self.mountpoint)
        try:
            if fd is None:
                session.mount()
            else:
                session.attach(fd)
        except Exception:
            return False
        self.fuse = session
        return True

    def close(self, unmount: bool = True) -> None:
        if self.fuse is not None:
            self.fuse.close(unmount=unmount)
            self.fuse = None
        # Umount cancels any background prefetch replay first, so cache
        # teardown never waits behind low-priority warming fetches.
        replayer = self._replayer
        if replayer is not None:
            replayer.cancel()
        # Drop the readers; each blob file closes when its last in-flight
        # read releases the closure reference (no explicit close — closing
        # under a racing read would either raise on a closed file or, worse,
        # pread a recycled fd).
        with self._reader_lock:
            self._closed = True
            self._readers.clear()
            cached_blobs = list(self._cached_blobs)
            self._cached_blobs.clear()
            self._cached_by_index.clear()
        # CachedBlob.close joins fetch workers; doing that under
        # _reader_lock would deadlock against a worker delivering.
        if cached_blobs:
            from nydus_snapshotter_tpu import provenance
            from nydus_snapshotter_tpu.daemon import peer as peer_mod

            export = peer_mod.default_export()
            prov_cfg = provenance.config()
            for cached in cached_blobs:
                export.unregister(cached.blob_id, cached)
                export.unregister_soci(cached.blob_id)
                export.unregister_artifact("zsoci", cached.blob_id)
                # Heat closed loop: distill this deploy's observed read
                # heat into the blob's .heat artifact before the cache
                # closes — the next deploy (here, or a cold neighbour via
                # the peer artifact plane) prefetches only what this one
                # actually read. The artifact deliberately STAYS
                # registered past the unmount: its whole value is to the
                # next deploy.
                if prov_cfg.enable and prov_cfg.heat:
                    cache_dir = os.path.dirname(cached.data_path)
                    art = provenance.compile_heat(
                        cached.blob_id, cache_dir,
                        source_size=cached.blob_size,
                    )
                    if art is not None and prov_cfg.replicate:
                        export.register_artifact(
                            provenance.ARTIFACT_KIND,
                            cached.blob_id,
                            provenance.heat_path(cache_dir, cached.blob_id),
                        )
        for cached in cached_blobs:
            try:
                cached.close()
            except OSError:
                pass

    def _parsed_config(self):
        if not hasattr(self, "_cfg_cache"):
            from nydus_snapshotter_tpu.config import daemonconfig

            try:
                data = json.loads(self.config_json) if self.config_json else {}
            except json.JSONDecodeError:
                data = {}
            try:
                self._cfg_cache = daemonconfig.DaemonRuntimeConfig.from_dict(
                    data, data.get("fs_driver", "fusedev")
                )
            except Exception:
                logger.warning("unparseable instance config", exc_info=True)
                self._cfg_cache = None
        return self._cfg_cache

    def _reader(self, blob_index: int, blob_dir: str) -> BlobReader:
        soci_args = None
        with self._reader_lock:
            if self._closed:
                # A read racing a legitimate unmount: fail instead of
                # resurrecting a reader for the discarded instance.
                raise FileNotFoundError(self.mountpoint)
            reader = self._readers.get(blob_index)
            if reader is None:
                blob_id = self.bootstrap.blobs[blob_index].blob_id
                cfg = self._parsed_config()
                if cfg is not None and cfg.backend.backend_type == "registry" and cfg.backend.host:
                    # True lazy pull: ranged registry GETs (mirrors first,
                    # origin last) written through a chunk-granular local
                    # cache — the nydusd registry backend behavior.
                    from nydus_snapshotter_tpu.daemon.blobcache import (
                        CachedBlob,
                        RegistryBlobFetcher,
                    )

                    from nydus_snapshotter_tpu.daemon import peer as peer_mod

                    cache_dir = cfg.cache.work_dir or os.path.join(blob_dir, "cache")
                    fetcher = RegistryBlobFetcher(cfg.backend, blob_id)
                    fetch_range = fetcher.read_range
                    # Peer waterfall: try the extent's healthy region
                    # owner before the registry (daemon/peer.py); the
                    # origin fetcher stays the transparent fallback.
                    router = peer_mod.default_router()
                    if router is not None:
                        fetch_range = peer_mod.PeerAwareFetcher(
                            blob_id, fetch_range, router
                        ).read_range
                    cached = CachedBlob(
                        cache_dir,
                        blob_id,
                        fetch_range,
                        # Clamps readahead at the blob's end (the record's
                        # compressed_size IS the published data section).
                        blob_size=self.bootstrap.blobs[blob_index].compressed_size,
                        # QoS tenant: the image repository — per-image
                        # weighted fairness under a deploy storm.
                        tenant=getattr(cfg.backend, "repo", "") or "default",
                    )
                    self._cached_blobs.append(cached)
                    self._cached_by_index[blob_index] = cached
                    # Announce to the local peer chunk server: this node
                    # can now serve the extents it caches.
                    peer_mod.default_export().register(blob_id, cached)
                    read_at = cached.read_at
                else:
                    f = open(os.path.join(blob_dir, blob_id), "rb")
                    cache_dir = os.path.join(blob_dir, "cache")

                    def read_at(off: int, size: int, _f=f) -> bytes:
                        # pread is positional: no seek state, no lock, one
                        # syscall; _f in the closure keeps the fd alive.
                        return os.pread(_f.fileno(), size, off)

                reader = BlobReader(
                    self.bootstrap, blob_index, read_at,
                    batch_map=self._batch_map,
                )
                self._readers[blob_index] = reader
                soci_args = (blob_id, read_at, [cache_dir, blob_dir])
        if soci_args is not None:
            # Index store OFF the reader lock: resolving it may touch the
            # peer tier or (rebuild-once) the origin, and other blobs'
            # reads must not queue behind that. Reads racing ahead of the
            # mount use the sequential path — identical bytes, then the
            # checkpointed reader takes over.
            stream = self._soci_stream(blob_index, *soci_args)
            if stream is not None:
                reader.mount_gzip_stream(stream)
            else:
                zstream = self._zsoci_stream(blob_index, *soci_args)
                if zstream is not None:
                    reader.mount_zstd_stream(zstream)
        return reader

    def _soci_stream(self, blob_index: int, blob_id: str, read_at, dirs):
        """A checkpoint-indexed stream reader for a gzip-stream (soci /
        OCIRef) blob, when an index can be had: persisted locally by the
        first-pull build, replicated from the blob's peer-tier region
        owner, or — with the backend enabled — rebuilt once from the
        original bytes. Returns None when this blob has no gzip-stream
        chunks or no index is obtainable (BlobReader then falls back to
        the sequential in-process reader; correctness never depends on
        the index)."""
        from nydus_snapshotter_tpu.converter.zran import CHUNK_FLAG_GZIP_STREAM

        if not any(
            rec.blob_index == blob_index and rec.flags & CHUNK_FLAG_GZIP_STREAM
            for rec in self.bootstrap.chunks
        ):
            return None
        from nydus_snapshotter_tpu.daemon import peer as peer_mod
        from nydus_snapshotter_tpu.soci import blob as soci_blob
        from nydus_snapshotter_tpu.soci.index import index_path

        cfg = soci_blob.resolve_soci_config()
        csize = self.bootstrap.blobs[blob_index].compressed_size
        fetch_remote = None
        if cfg.enable and cfg.replicate:
            router = peer_mod.default_router()
            if router is not None:
                owner = router.route(blob_id, 0)
                if owner is not None:
                    fetch_remote = lambda: peer_mod.PeerClient(  # noqa: E731
                        owner
                    ).fetch_soci_index(blob_id)
        from nydus_snapshotter_tpu.daemon import fetch_sched

        def build_pull():
            # Provenance: the whole-layer pull an index (re)build costs is
            # its own cause, not "demand" — the tag scope pins it onto
            # every flight the pull plans.
            with fetch_sched.fetch_tag("soci_index_build"):
                return read_at(0, csize)

        try:
            index, outcome = soci_blob.load_or_build_index(
                [d for d in dirs if d],
                blob_id,
                csize=csize,
                # Rebuild-once (evicted/corrupt index) only when the
                # backend is on: it costs one full pull of the original
                # blob, written through the chunk cache like any fetch.
                builder=(build_pull if cfg.enable and csize else None),
                fetch_remote=fetch_remote,
                stride=cfg.stride_bytes,
            )
        except Exception:  # noqa: BLE001 — incl. an armed soci.index
            # failpoint: a broken index STORE degrades this blob to the
            # sequential in-process reader; it must never fail reads.
            logger.warning("soci index store failed for %s; serving "
                           "sequentially", blob_id[:12], exc_info=True)
            return None
        if index is None:
            return None
        stream = soci_blob.SociStreamReader(index, read_at, name=blob_id[:8])
        self._soci_by_index[blob_index] = stream
        from nydus_snapshotter_tpu import provenance

        provenance.set_blob_meta(blob_id, fmt="soci_gzip")
        # Announce the index itself to the peer tier: one pod's build
        # amortizes across the fleet.
        for d in dirs:
            if d and os.path.exists(index_path(d, blob_id)):
                peer_mod.default_export().register_soci(
                    blob_id, index_path(d, blob_id)
                )
                break
        logger.info("soci index for %s: %s (%d checkpoints)",
                    blob_id[:12], outcome, len(index.checkpoints))
        return stream

    def _zsoci_stream(self, blob_index: int, blob_id: str, read_at, dirs):
        """The zstd mirror of :meth:`_soci_stream`: a frame-indexed
        stream reader for a zstd-stream (OCIRef) blob. Same store
        waterfall — persisted ``.soci.zidx``, peer replication (generic
        artifact kind ``zsoci``), rebuild-once from the original bytes —
        and the same contract: returns None when no index is obtainable
        (BlobReader then uses the sequential zstd cursor; correctness
        never depends on the index)."""
        from nydus_snapshotter_tpu.converter.zstd_ref import (
            CHUNK_FLAG_ZSTD_STREAM,
        )

        if not any(
            rec.blob_index == blob_index and rec.flags & CHUNK_FLAG_ZSTD_STREAM
            for rec in self.bootstrap.chunks
        ):
            return None
        from nydus_snapshotter_tpu.daemon import peer as peer_mod
        from nydus_snapshotter_tpu.soci import blob as soci_blob
        from nydus_snapshotter_tpu.soci import zblob
        from nydus_snapshotter_tpu.soci.zindex import zindex_path

        cfg = soci_blob.resolve_soci_config()
        csize = self.bootstrap.blobs[blob_index].compressed_size
        fetch_remote = None
        if cfg.enable and cfg.replicate:
            router = peer_mod.default_router()
            if router is not None:
                owner = router.route(blob_id, 0)
                if owner is not None:
                    fetch_remote = lambda: peer_mod.PeerClient(  # noqa: E731
                        owner
                    ).fetch_artifact(zblob.ZSOCI_ARTIFACT_KIND, blob_id)
        from nydus_snapshotter_tpu.daemon import fetch_sched

        def build_pull():
            with fetch_sched.fetch_tag("soci_index_build"):
                return read_at(0, csize)

        try:
            index, outcome = zblob.load_or_build_zindex(
                [d for d in dirs if d],
                blob_id,
                csize=csize,
                builder=(build_pull if cfg.enable and csize else None),
                fetch_remote=fetch_remote,
            )
        except Exception:  # noqa: BLE001 — incl. an armed soci.index
            # failpoint: a broken index STORE degrades this blob to the
            # sequential in-process reader; it must never fail reads.
            logger.warning("zstd index store failed for %s; serving "
                           "sequentially", blob_id[:12], exc_info=True)
            return None
        if index is None:
            return None
        stream = zblob.ZstdStreamReader(index, read_at, name=blob_id[:8])
        self._soci_by_index[blob_index] = stream
        from nydus_snapshotter_tpu import provenance

        provenance.set_blob_meta(blob_id, fmt="soci_zstd")
        # Announce the index to the peer tier under the generic artifact
        # plane: one pod's build amortizes across the fleet.
        for d in dirs:
            if d and os.path.exists(zindex_path(d, blob_id)):
                peer_mod.default_export().register_artifact(
                    zblob.ZSOCI_ARTIFACT_KIND, blob_id, zindex_path(d, blob_id)
                )
                break
        logger.info("zstd index for %s: %s (%d frames, %s)",
                    blob_id[:12], outcome, len(index.frames),
                    index.source_name)
        return stream

    def blob_dir(self, default_dir: str) -> str:
        cfg = self._parsed_config()
        if cfg is not None and cfg.backend.blob_dir:
            return cfg.backend.blob_dir
        return default_dir

    def prefetch(self, default_blob_dir: str, extra_paths: Optional[list] = None) -> int:
        """Warm the bootstrap's prefetch-table files (reference nydusd's
        --prefetch-files behavior) through the background replayer
        (daemon/fetch_sched.PrefetchReplayer): registry-backed blobs are
        warmed at the PREFETCH lane (below demand and readahead) so
        demand reads always win the worker pool and the admission gate,
        any other backend reads through the blob reader.
        Returns bytes warmed; cancelled by umount. Errors are contained
        per file (hints, not requirements), warming counts only into
        prefetch_data_amount — not the fs read metrics, which track
        client traffic."""
        from nydus_snapshotter_tpu.daemon.fetch_sched import PrefetchReplayer

        blob_dir = self.blob_dir(default_blob_dir)
        heat_covered: set = set()

        def warm_chunk(rec) -> int:
            if rec.blob_index in heat_covered:
                # This blob was already warmed from its .heat artifact —
                # replaying its bootstrap chunks on top would re-warm
                # exactly the speculative bytes the heat loop exists to
                # avoid fetching.
                return 0
            from nydus_snapshotter_tpu.converter.zran import (
                CHUNK_FLAG_GZIP_STREAM,
            )
            from nydus_snapshotter_tpu.converter.zstd_ref import (
                CHUNK_FLAG_ZSTD_STREAM,
            )

            # Ensure the blob's reader (and CachedBlob, for registry
            # backends) exists; raises after close(), ending the replay.
            reader = self._reader(rec.blob_index, blob_dir)
            cached = self._cached_by_index.get(rec.blob_index)
            if cached is not None and rec.flags & (
                CHUNK_FLAG_GZIP_STREAM | CHUNK_FLAG_ZSTD_STREAM
            ):
                # Stream-addressed (soci/OCIRef) chunks — gzip or zstd —
                # address the DECOMPRESSED stream; warming those offsets
                # against the compressed blob would warm garbage.
                # Translate through the mounted index when one exists,
                # else warm through the reader (sequential, still
                # background-lane contained).
                soci = self._soci_by_index.get(rec.blob_index)
                if soci is not None:
                    c0, c1 = soci.resolve_compressed(
                        rec.uncompressed_offset, rec.uncompressed_size
                    )
                    rec_off, rec_size = c0, max(0, c1 - c0)
                else:
                    n = len(reader.chunk_data(rec))
                    self.prefetched_bytes += n
                    return n
            else:
                rec_off, rec_size = rec.compressed_offset, rec.compressed_size
            if cached is not None:
                flights = cached.warm(rec_off, rec_size)
                for f in flights:
                    while not f.wait(0.1):
                        if replayer.cancelled:
                            return 0
                if any(f.error is not None for f in flights):
                    return 0
                n = rec_size
            else:
                n = len(reader.chunk_data(rec))
            self.prefetched_bytes += n
            return n

        def flush_maps() -> None:
            with self._reader_lock:
                cached_blobs = list(self._cached_blobs)
            for c in cached_blobs:
                c.flush_map()

        replayer = PrefetchReplayer(
            self.bootstrap,
            self.by_path,
            warm_chunk,
            name=self.mountpoint,
            on_file=flush_maps,
        )
        self._replayer = replayer
        try:
            # Heat-closed-loop arm first: blobs with a .heat artifact are
            # warmed in observed-read order under the byte budget and
            # their bootstrap records drop out of the replay below.
            heat_covered.update(self._prefetch_via_heat(replayer, blob_dir))
            paths = list(self.bootstrap.prefetch) + list(extra_paths or ())
            # Index-mapped paths warm straight from the soci file→extent
            # table (and accrue into replayer.warmed_bytes); the replay
            # below handles whatever the index couldn't translate.
            paths = self._prefetch_via_soci_index(paths, replayer)
            return replayer.replay(paths)
        finally:
            flush_maps()
            self._replayer = None

    def _prefetch_via_heat(self, replayer, blob_dir: str) -> set:
        """The heat-closed-loop prefetch arm: a blob with a valid
        ``.heat`` artifact (compiled by a previous deploy's close here,
        or adopted from the blob's peer-tier region owner) is warmed in
        observed first-touch order under the ``[provenance]`` byte
        budget INSTEAD of walking its bootstrap chunk list — the second
        deploy prefetches only what the first one actually read.
        Returns the covered blob indexes (their bootstrap records are
        skipped by ``warm_chunk``). Heat is a hint: any failure here
        degrades to the bootstrap-order replay the daemon always had."""
        from nydus_snapshotter_tpu import provenance
        from nydus_snapshotter_tpu.daemon import fetch_sched, peer as peer_mod

        covered: set = set()
        cfg = provenance.config()
        if not (cfg.enable and cfg.heat):
            return covered
        budget = max(0, cfg.heat_budget_mib) << 20
        router = peer_mod.default_router()
        for blob_index in range(len(self.bootstrap.blobs)):
            if replayer.cancelled or budget <= 0:
                break
            try:
                self._reader(blob_index, blob_dir)
            except Exception:  # noqa: BLE001 — heat is advisory
                continue
            cached = self._cached_by_index.get(blob_index)
            if cached is None:
                continue
            blob_id = cached.blob_id
            cache_dir = os.path.dirname(cached.data_path)
            fetch_remote = None
            if cfg.replicate and router is not None:
                owner = router.route(blob_id, 0)
                if owner is not None:
                    fetch_remote = lambda _o=owner, _b=blob_id: (  # noqa: E731
                        peer_mod.PeerClient(_o).fetch_artifact(
                            provenance.ARTIFACT_KIND, _b
                        )
                    )
            art = provenance.load_or_adopt_heat(
                [cache_dir, blob_dir],
                blob_id,
                source_size=cached.blob_size,
                fetch_remote=fetch_remote,
            )
            if art is None or not art.extents:
                continue
            covered.add(blob_index)
            # Re-announce on the peer artifact plane (an adopted artifact
            # makes this node a serving replica too).
            if cfg.replicate:
                peer_mod.default_export().register_artifact(
                    provenance.ARTIFACT_KIND, blob_id,
                    provenance.heat_path(cache_dir, blob_id),
                )
            warmed = 0
            for off, size in art.extents:
                if replayer.cancelled:
                    return covered
                if budget <= 0:
                    break
                flights = cached.warm(off, size)
                for f in flights:
                    while not f.wait(0.1):
                        if replayer.cancelled:
                            return covered
                budget -= size
                if all(f.error is None for f in flights):
                    warmed += size
            if warmed:
                self.prefetched_bytes += warmed
                replayer.warmed_bytes += warmed
                replayer.files_replayed += 1
                fetch_sched.PREFETCH_BYTES.inc(warmed)
            logger.info(
                "heat prefetch for %s: %d extents, %d bytes warmed",
                blob_id[:12], len(art.extents), warmed,
            )
        return covered

    def _prefetch_via_soci_index(self, paths: list, replayer) -> list:
        """The soci index as a prefetch-trace source: paths the mounted
        checkpoint index maps are warmed straight from its file →
        extent table — ONE compressed range per file at PREFETCH lane,
        no per-chunk bootstrap walk — and dropped from the bootstrap
        replay. Paths the index doesn't know fall through unchanged
        (hints, not requirements; a failed warm is contained)."""
        if not self._soci_by_index or not paths:
            return paths
        from nydus_snapshotter_tpu.soci import blob as soci_blob

        remaining = list(paths)
        with self._reader_lock:
            soci_streams = dict(self._soci_by_index)
        for blob_index, stream in soci_streams.items():
            cached = self._cached_by_index.get(blob_index)
            if cached is None:
                continue
            try:
                warms, remaining = soci_blob.warm_list_from_index(
                    stream.index, remaining
                )
            except Exception:  # noqa: BLE001 — a bad map is a bad hint
                logger.warning("soci prefetch-map translation failed",
                               exc_info=True)
                continue
            for _path, c0, c1 in warms:
                if replayer.cancelled:
                    return []
                try:
                    flights = cached.warm(c0, max(0, c1 - c0))
                    for f in flights:
                        while not f.wait(0.1):
                            if replayer.cancelled:
                                return []
                    if all(f.error is None for f in flights):
                        n = max(0, c1 - c0)
                        self.prefetched_bytes += n
                        replayer.warmed_bytes += n
                        from nydus_snapshotter_tpu.daemon import fetch_sched

                        fetch_sched.PREFETCH_BYTES.inc(n)
                        replayer.files_replayed += 1
                except Exception:  # noqa: BLE001 — contained per file
                    logger.warning("soci prefetch warm failed", exc_info=True)
        return remaining

    def inflight_snapshot(self) -> list[dict]:
        with self._inflight_lock:
            return [dict(v) for v in self._inflight.values()]

    def read(self, path: str, offset: int, size: int, blob_dir: str) -> bytes:
        import time as time_mod

        with self._inflight_lock:
            self._inflight_seq += 1
            token = self._inflight_seq
            self._inflight[token] = {
                "opcode": "Read",
                "inode": path,
                "unique": token,
                "timestamp_secs": time_mod.time(),
            }
        try:
            # Root span in the daemon process: FUSE and API reads funnel
            # through here, and any blobcache fetch/readahead this read
            # triggers lands in its trace (exported on /api/v1/traces).
            with trace.span("nydusd.read", path=path, offset=offset, size=size):
                return self._read_locked_out(path, offset, size, blob_dir)
        finally:
            with self._inflight_lock:
                self._inflight.pop(token, None)

    def _read_locked_out(self, path: str, offset: int, size: int, blob_dir: str) -> bytes:
        inode = self.by_path.get(path)
        if inode is None:
            raise FileNotFoundError(path)
        if inode.hardlink_target:
            inode = self.by_path[inode.hardlink_target]
        if not stat_mod.S_ISREG(inode.mode):
            raise IsADirectoryError(path)
        out = bytearray()
        pos = 0
        end = min(offset + size, inode.size) if size >= 0 else inode.size
        for rec in self.bootstrap.chunks[
            inode.chunk_index : inode.chunk_index + inode.chunk_count
        ]:
            clen = rec.uncompressed_size
            if pos + clen <= offset:
                pos += clen
                continue
            if pos >= end:
                break
            data = self._reader(rec.blob_index, blob_dir).chunk_data(rec)
            lo = max(0, offset - pos)
            hi = min(clen, end - pos)
            out += data[lo:hi]
            pos += clen
        self.metrics.data_read += len(out)
        self.metrics.fop_hits["Read"] = self.metrics.fop_hits.get("Read", 0) + 1
        return bytes(out)


class DaemonServer:
    def __init__(
        self,
        daemon_id: str,
        apisock: str,
        supervisor: str = "",
        workdir: str = "",
        upgrade: bool = False,
    ):
        self.id = daemon_id
        self.apisock = apisock
        self.supervisor = supervisor
        self.workdir = workdir or os.getcwd()
        self.state = DaemonState.INIT
        self.instances: dict[str, _Instance] = {}
        self.bound_blobs: set[str] = set()
        self._blob_bind_configs: dict[str, dict] = {}
        # fscache_id -> metadata_path: survives same-blob re-binds (two
        # snapshots sharing a layer blob clobber _blob_bind_configs[id],
        # but each keeps its own fsid cookie here until ITS unbind)
        self._meta_binds: dict[str, str] = {}
        self._erofs_meta_cache: dict[str, bytes] = {}
        self._cachefiles = None  # CachefilesOndemandDaemon on capable kernels
        self._lock = threading.RLock()
        self._httpd: Optional[socketserver.ThreadingMixIn] = None
        self._started_in_upgrade = upgrade
        if not upgrade:
            # Normal boot: nothing to restore, become READY immediately.
            self.state = DaemonState.READY

    # -- state snapshot for failover/upgrade -------------------------------

    def snapshot_state(self) -> tuple[bytes, list[int]]:
        """(state JSON, live FUSE session fds). Each instance's ``fuse_fd``
        field is a 1-based index into the fd array that accompanies the
        state on the supervisor socket (slot 0 is the state memfd)."""
        with self._lock:
            fds: list[int] = []
            instances = []
            for i in self.instances.values():
                rec = {
                    "mountpoint": i.mountpoint,
                    "source": i.source,
                    "config": i.config_json,
                    "prefetched": i.prefetched_bytes,
                }
                if i.fuse is not None and i.fuse.fd >= 0:
                    fds.append(i.fuse.fd)
                    rec["fuse_fd"] = len(fds)  # 1-based: memfd occupies slot 0
                instances.append(rec)
            state = json.dumps({"id": self.id, "instances": instances}, sort_keys=True)
            return state.encode(), fds

    def restore_state(self, blob: bytes, fds: Optional[list[int]] = None) -> None:
        data = json.loads(blob)
        fds = fds or []
        with self._lock:
            for rec in data.get("instances", []):
                inst = _Instance(rec["mountpoint"], rec["source"], rec["config"])
                # Metric continuity across failover/upgrade: already-warmed
                # bytes stay reported (the successor does not re-prefetch).
                inst.prefetched_bytes = int(rec.get("prefetched", 0))
                self.instances[rec["mountpoint"]] = inst
                idx = rec.get("fuse_fd")
                if idx and 0 < idx < len(fds):
                    # Adopt the live kernel session: the mount survived the
                    # previous daemon, reads resume as soon as we attach.
                    inst.start_fuse(self.workdir, fd=fds[idx])
                elif idx:
                    # A recorded session fd that did not arrive means the
                    # kernel mount now has no reader — every access hangs.
                    # Loud beats silent; the operator must remount.
                    logger.error(
                        "takeover state references session fd %d for %s but "
                        "only %d fds arrived; kernel mount is orphaned",
                        idx, rec["mountpoint"], len(fds),
                    )
            self.state = DaemonState.READY

    # -- supervisor interaction (SCM_RIGHTS fd passing) ---------------------

    def send_states_to_supervisor(self, handoff: bool = False) -> None:
        """Push state + live session fds to the supervisor socket (reference
        supervisor.go:107-178 receiver side). ``handoff=True`` is the
        explicit sendfd API: after pushing, this daemon stops serving its
        FUSE sessions (keeping the mounts alive) so the successor that
        takes the fds over is the only reader."""
        if not self.supervisor:
            raise RuntimeError("daemon started without --supervisor")
        state, session_fds = self.snapshot_state()
        fd = os.memfd_create(f"nydus-session-{self.id}")
        try:
            os.write(fd, state)
            with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
                s.connect(self.supervisor)
                socket.send_fds(s, [state], [fd] + session_fds)
        finally:
            os.close(fd)
        if handoff:
            with self._lock:
                for inst in self.instances.values():
                    if inst.fuse is not None:
                        # Stop serving but leave the kernel mount alive for
                        # the successor; forget the session so a later
                        # close()/umount here can't tear the mount down
                        # under the new daemon.
                        inst.fuse.close(unmount=False)
                        inst.fuse = None

    def takeover_from_supervisor(self) -> None:
        """PUT .../takeover: pull state + fds back and restore mounts."""
        if not self.supervisor:
            raise RuntimeError("daemon started without --supervisor")
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
            s.connect(self.supervisor)
            # Announce we want the saved session back.
            s.sendall(b"TAKEOVER")
            # 253 = SCM_MAX_FD (kernel per-message ceiling); matches the
            # supervisor's receive cap so no session fd is ever truncated.
            msg, fds, _flags, _addr = socket.recv_fds(s, 1 << 20, 253)
        consumed: set[int] = set()
        try:
            state = msg
            if fds:
                size = os.fstat(fds[0]).st_size
                os.lseek(fds[0], 0, os.SEEK_SET)
                state = os.read(fds[0], size)
                consumed.add(0)
            self.restore_state(state, fds)
            for inst in self.instances.values():
                if inst.fuse is not None:
                    consumed.add(fds.index(inst.fuse.fd))
        finally:
            for i, fd in enumerate(fds):
                if i not in consumed:
                    try:
                        os.close(fd)
                    except OSError:
                        pass

    # -- http server --------------------------------------------------------

    def serve_forever(self) -> None:
        os.makedirs(os.path.dirname(self.apisock) or ".", exist_ok=True)
        if os.path.exists(self.apisock):
            os.unlink(self.apisock)
        daemon = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _reply(self, code: int, obj: Any = None) -> None:
                body = b"" if obj is None else json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _reply_raw(self, code: int, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length") or 0)
                return self.rfile.read(n) if n else b""

            def do_GET(self):
                u = urllib.parse.urlparse(self.path)
                q = urllib.parse.parse_qs(u.query)
                if u.path == "/api/v1/daemon":
                    self._reply(200, daemon.info())
                elif u.path == "/api/v1/metrics":
                    mp = q.get("id", [""])[0]
                    self._reply(200, daemon.fs_metrics(mp))
                elif u.path == "/api/v1/metrics/blobcache":
                    from nydus_snapshotter_tpu.daemon import fetch_sched
                    from nydus_snapshotter_tpu.soci import blob as soci_blob

                    with daemon._lock:
                        amount = sum(
                            i.prefetched_bytes for i in daemon.instances.values()
                        )
                    body = {"prefetch_data_amount": amount}
                    body.update(fetch_sched.snapshot_counters())
                    body["soci"] = soci_blob.snapshot_counters()
                    from nydus_snapshotter_tpu.soci import router as soci_router
                    from nydus_snapshotter_tpu.soci import zblob as soci_zblob

                    body["soci"]["zindex_frames"] = (
                        soci_zblob.ZINDEX_FRAMES.value()
                    )
                    routes = soci_router.route_counts()
                    if routes:
                        body["soci"]["routes"] = routes
                    # Metrics → traces link: the last root trace ids whose
                    # duration exceeded the rolling p95 (fetch them from
                    # /api/v1/traces or /debug/pprof/trace).
                    body["trace_exemplars"] = trace.exemplars()
                    self._reply(200, body)
                elif u.path == "/api/v1/traces":
                    self._reply(200, trace.chrome_trace())
                elif u.path == "/api/v1/provenance":
                    # Byte-provenance accounting (provenance/ledger.py):
                    # ?blob= narrows to one blob, ?waterfall=1 returns the
                    # time-ordered cause breakdown joined to trace ids.
                    from nydus_snapshotter_tpu import provenance

                    blob = q.get("blob", [""])[0]
                    if q.get("waterfall", ["0"])[0] not in ("", "0"):
                        limit = int(q.get("limit", ["0"])[0] or 0)
                        self._reply(
                            200,
                            {
                                "waterfall": provenance.waterfall(
                                    blob, limit=limit
                                ),
                                "heat": provenance.heat_counters(),
                            },
                        )
                    elif blob:
                        view = provenance.blob_snapshot(blob)
                        if view is None:
                            self._reply(404, {"error": f"no ledger for {blob}"})
                        else:
                            view["conservation"] = provenance.conservation(blob)
                            self._reply(200, view)
                    else:
                        body = provenance.snapshot()
                        body["heat"] = provenance.heat_counters()
                        self._reply(200, body)
                elif u.path in ("/metrics", "/v1/metrics"):
                    # Prometheus text exposition of this daemon process's
                    # registry — the fleet federator's per-member scrape
                    # target (metrics/federation.py).
                    from nydus_snapshotter_tpu.metrics.registry import (
                        default_registry,
                    )

                    body = default_registry.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif u.path == "/api/v1/metrics/inflight":
                    with daemon._lock:
                        instances = list(daemon.instances.values())
                    ops = [
                        op for inst in instances for op in inst.inflight_snapshot()
                    ]
                    self._reply(200, ops)
                elif u.path == "/api/v1/fs":
                    try:
                        self._handle_fs(q)
                    except (FileNotFoundError, KeyError) as e:
                        self._reply(404, {"error": str(e)})
                    except IsADirectoryError as e:
                        self._reply(400, {"error": f"not a regular file: {e}"})
                else:
                    self._reply(404, {"error": f"no route {u.path}"})

            def _handle_fs(self, q):
                mp = q.get("mountpoint", [""])[0]
                op = q.get("op", ["stat"])[0]
                path = q.get("path", ["/"])[0]
                inst = daemon.instance(mp)
                if op == "read":
                    offset = int(q.get("offset", ["0"])[0])
                    size = int(q.get("size", ["-1"])[0])
                    data = inst.read(path, offset, size, inst.blob_dir(daemon.workdir))
                    self._reply_raw(200, data)
                elif op == "stat":
                    inode = inst.by_path.get(path)
                    if inode is None:
                        raise FileNotFoundError(path)
                    self._reply(
                        200,
                        {
                            "path": inode.path,
                            "mode": inode.mode,
                            "size": inode.size,
                            "uid": inode.uid,
                            "gid": inode.gid,
                            "symlink": inode.symlink_target,
                            "hardlink": inode.hardlink_target,
                        },
                    )
                elif op == "list":
                    prefix = path.rstrip("/") + "/" if path != "/" else "/"
                    names = sorted(
                        p[len(prefix) :]
                        for p in inst.by_path
                        if p.startswith(prefix) and p != "/" and "/" not in p[len(prefix) :]
                        and p != path
                    )
                    self._reply(200, names)
                else:
                    self._reply(400, {"error": f"bad op {op}"})

            def do_POST(self):
                u = urllib.parse.urlparse(self.path)
                q = urllib.parse.parse_qs(u.query)
                if u.path == "/api/v1/mount":
                    mp = q.get("mountpoint", [""])[0]
                    body = json.loads(self._body() or b"{}")
                    try:
                        daemon.mount(mp, body.get("source", ""), body.get("config", ""))
                        self._reply(204)
                    except FileExistsError:
                        self._reply(409, {"error": f"{mp} already mounted"})
                    except Exception as e:
                        self._reply(400, {"error": str(e)})
                else:
                    self._reply(404, {"error": f"no route {u.path}"})

            def do_PUT(self):
                u = urllib.parse.urlparse(self.path)
                if u.path == "/api/v1/daemon/start":
                    daemon.start()
                    self._reply(204)
                elif u.path == "/api/v1/daemon/exit":
                    self._reply(204)
                    threading.Thread(target=daemon.shutdown, daemon=True).start()
                elif u.path in ("/api/v1/daemon/fuse/sendfd", "/api/v1/daemon/fscache/sendfd"):
                    try:
                        # Explicit sendfd = upgrade/failover handoff: stop
                        # serving the sessions after passing them on.
                        daemon.send_states_to_supervisor(handoff=True)
                        self._reply(204)
                    except Exception as e:
                        self._reply(500, {"error": str(e)})
                elif u.path in ("/api/v1/daemon/fuse/takeover", "/api/v1/daemon/fscache/takeover"):
                    try:
                        daemon.takeover_from_supervisor()
                        self._reply(204)
                    except Exception as e:
                        self._reply(500, {"error": str(e)})
                elif u.path == "/api/v2/blobs":
                    try:
                        body = json.loads(self._body() or b"{}")
                        daemon.bind_blob(body.get("config", ""))
                        self._reply(204)
                    except Exception as e:
                        self._reply(400, {"error": str(e)})
                else:
                    self._reply(404, {"error": f"no route {u.path}"})

            def do_DELETE(self):
                u = urllib.parse.urlparse(self.path)
                q = urllib.parse.parse_qs(u.query)
                if u.path == "/api/v1/mount":
                    mp = q.get("mountpoint", [""])[0]
                    try:
                        daemon.umount(mp)
                        self._reply(204)
                    except KeyError:
                        self._reply(404, {"error": f"{mp} not mounted"})
                elif u.path == "/api/v2/blobs":
                    daemon.unbind_blob(
                        q.get("domain_id", [""])[0], q.get("blob_id", [""])[0]
                    )
                    self._reply(204)
                else:
                    self._reply(404, {"error": f"no route {u.path}"})

        class Server(socketserver.ThreadingMixIn, socketserver.UnixStreamServer):
            daemon_threads = True
            allow_reuse_address = True
            # socketserver's default backlog of 5 overflows under connect
            # storms (many snapshots mounting at once): excess UDS connects
            # fail with EAGAIN instead of queueing.
            request_queue_size = 128

            # BaseHTTPRequestHandler expects a (host, port) client address.
            def get_request(self):
                request, _ = super().get_request()
                return request, ("uds", 0)

        self._httpd = Server(self.apisock, Handler)
        self._httpd.serve_forever(poll_interval=0.1)

    # -- operations ---------------------------------------------------------

    def info(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "version": {"package_ver": __version__, "git_commit": ""},
            "state": self.state.value,
            "backend_collection": {},
            "supervisor": self.supervisor,
            "pid": os.getpid(),
        }

    def instance(self, mountpoint: str) -> _Instance:
        with self._lock:
            inst = self.instances.get(mountpoint)
        if inst is None:
            raise KeyError(f"no instance mounted at {mountpoint}")
        return inst

    def mount(self, mountpoint: str, source: str, config: str) -> None:
        if not mountpoint:
            raise ValueError("mountpoint required")
        with self._lock:
            if self.state not in (DaemonState.READY, DaemonState.RUNNING):
                raise RuntimeError(f"daemon in state {self.state}, cannot mount")
            if mountpoint in self.instances:
                raise FileExistsError(mountpoint)
            inst = _Instance(mountpoint, source, config)
            self.instances[mountpoint] = inst
            # Kernel mount when the environment allows it; API-only
            # otherwise. Under the lock: a concurrent umount() popping the
            # half-mounted instance would otherwise leave an orphaned kernel
            # mount no API call can ever tear down. (mount(2) itself is
            # fast; FUSE INIT is answered async by the serve thread.)
            try:
                inst.start_fuse(self.workdir)
            except Exception:
                self.instances.pop(mountpoint, None)
                raise
        if inst.bootstrap.prefetch:
            threading.Thread(
                target=inst.prefetch, args=(self.workdir,),
                name=f"prefetch:{mountpoint}", daemon=True,
            ).start()
        self._push_state_async()

    def umount(self, mountpoint: str) -> None:
        with self._lock:
            inst = self.instances.pop(mountpoint)
        inst.close()
        self._push_state_async()

    # -- fscache v2 blobs (reference nydusd /api/v2/blobs) -------------------

    def bind_blob(self, daemon_config: str) -> None:
        with self._lock:
            try:
                cfg = json.loads(daemon_config or "{}")
            except ValueError:
                cfg = {}
            blob_id = cfg.get("id", "")
            if blob_id:
                self.bound_blobs.add(blob_id)
                self._blob_bind_configs[blob_id] = cfg
                if cfg.get("fscache_id") and cfg.get("metadata_path"):
                    self._meta_binds[cfg["fscache_id"]] = cfg["metadata_path"]
                self._ensure_cachefiles()

    def unbind_blob(self, domain_id: str, blob_id: str) -> None:
        with self._lock:
            self.bound_blobs.discard(blob_id)
            self._blob_bind_configs.pop(blob_id, None)
            # domain_id is the mount's fsid (daemon.py passes
            # erofs_fscache_id): drop exactly this mount's meta cookie and
            # its rendered image — other snapshots' binds stay live
            path = self._meta_binds.pop(domain_id, None)
            if path is not None and path not in self._meta_binds.values():
                self._erofs_meta_cache.pop(path, None)

    # -- cachefiles ondemand (the in-kernel erofs-over-fscache data path) ----

    def _ensure_cachefiles(self) -> None:
        """Start the cachefiles ondemand daemon on first blob bind, where
        the kernel has the device (daemon/cachefiles.py; the build
        environment never does — PARITY.md environmental limit #3). Bound
        blobs become resolvable cookies so `mount -t erofs -o fsid=`
        pages through this process exactly like the reference's nydusd
        fscache mode (daemon.go:275-324)."""
        from nydus_snapshotter_tpu.daemon import cachefiles

        if self._cachefiles is not None or not cachefiles.supported():
            return
        try:
            d = cachefiles.CachefilesOndemandDaemon(
                self._resolve_cachefiles_cookie,
                cache_dir=os.path.join(self.workdir, "cachefiles"),
                tag=f"ntpu-{self.id}",
            )
            d.bind()
            d.start()
            self._cachefiles = d
        except Exception:
            logger.exception("cachefiles ondemand bind failed; fscache "
                             "mounts will not be served by this daemon")

    def _resolve_cachefiles_cookie(self, cookie_key: str):
        """(size, reader, closer) for a bound blob's bytes; KeyError when
        the cookie was never bound. Runs once per kernel OPEN (the
        ondemand daemon caches the result on the object, so the fd lives
        exactly as long as the kernel's cache object); the blob file is
        looked up in the bind config's backend dir, then the workdir."""
        with self._lock:
            cfg = self._blob_bind_configs.get(cookie_key)
            meta_path = None
            if cfg is None:
                # the EROFS meta cookie: the fsid mount's first open —
                # rendered from the bind's metadata_path bootstrap
                meta_path = self._meta_binds.get(cookie_key)
                if meta_path is None:
                    raise KeyError(cookie_key)
            else:
                backend = (cfg.get("device") or {}).get("backend") or {}
                bcfg = backend.get("config") or {}
                candidates = [
                    os.path.join(d, cookie_key)
                    for d in (bcfg.get("blob_dir"), bcfg.get("dir"), self.workdir)
                    if d
                ]
        if meta_path is not None:
            # render OUTSIDE the lock: building a large image under
            # self._lock would stall every concurrent API operation
            meta = self._erofs_meta_bytes(meta_path)
            return (len(meta), lambda off, ln, _m=meta: _m[off : off + ln], None)
        for path in candidates:
            if os.path.exists(path):
                size = os.path.getsize(path)
                fd = os.open(path, os.O_RDONLY)
                return (
                    size,
                    lambda off, ln, _fd=fd: os.pread(_fd, ln, off),
                    lambda _fd=fd: os.close(_fd),
                )
        raise KeyError(cookie_key)

    def _erofs_meta_bytes(self, bootstrap_path: str) -> bytes:
        """Kernel-mountable EROFS meta image rendered from a bootstrap
        (internal or real layout), cached per path — the bytes the fsid
        mount's metadata cookie reads."""
        meta = self._erofs_meta_cache.get(bootstrap_path)
        if meta is None:
            from nydus_snapshotter_tpu.models.erofs_image import erofs_from_rafs
            from nydus_snapshotter_tpu.models.nydus_real import load_any_bootstrap

            with open(bootstrap_path, "rb") as f:
                meta = erofs_from_rafs(load_any_bootstrap(f.read()))
            self._erofs_meta_cache[bootstrap_path] = meta
        return meta

    def _push_state_async(self) -> None:
        """Keep the supervisor's saved session current after every mount
        change, so a SIGKILL'd daemon can still be failed over (the
        reference nydusd continuously syncs state to --supervisor)."""
        if not self.supervisor:
            return

        def push():
            try:
                self.send_states_to_supervisor()
            except OSError:
                pass  # supervisor not up yet; next change retries

        threading.Thread(target=push, daemon=True).start()

    def start(self) -> None:
        with self._lock:
            self.state = DaemonState.RUNNING

    def fs_metrics(self, mountpoint: str) -> dict[str, Any]:
        with self._lock:
            if mountpoint and mountpoint in self.instances:
                return self.instances[mountpoint].metrics.to_dict()
            total = FsMetrics()
            for inst in self.instances.values():
                total.data_read += inst.metrics.data_read
                for k, v in inst.metrics.fop_hits.items():
                    total.fop_hits[k] = total.fop_hits.get(k, 0) + v
            return total.to_dict()

    def shutdown(self) -> None:
        with self._lock:
            self.state = DaemonState.DESTROYED
            instances = list(self.instances.values())
        # Graceful exit tears down kernel mounts this daemon still serves
        # (handed-off sessions were already forgotten and stay alive).
        for inst in instances:
            inst.close(unmount=True)
        if self._cachefiles is not None:
            self._cachefiles.stop()
            self._cachefiles = None
        if self._httpd is not None:
            self._httpd.shutdown()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="nydus-tpu-daemon")
    p.add_argument("--id", required=True)
    p.add_argument("--apisock", required=True)
    p.add_argument("--supervisor", default="")
    p.add_argument("--workdir", default="")
    p.add_argument("--upgrade", action="store_true")
    p.add_argument("--log-file", default="")
    args = p.parse_args(argv)

    if args.log_file:
        sys.stderr = sys.stdout = open(args.log_file, "a", buffering=1)

    server = DaemonServer(
        args.id,
        args.apisock,
        supervisor=args.supervisor,
        workdir=args.workdir,
        upgrade=args.upgrade,
    )
    # Peer chunk tier: the daemon process reaches the [peer] section via
    # the NTPU_PEER* environment (like every blobcache knob); when it
    # names a listen address, this daemon serves its cached extents to
    # cluster peers (daemon/peer.py).
    # Fleet plane: when NTPU_FLEET_CONTROLLER names the controller UDS
    # (exported by cmd/snapshotter.py when [fleet] is on), this daemon
    # self-registers so the controller scrapes its metrics and pulls its
    # trace ring into the cluster-merged view (fleet/__init__.py).
    # Registered BEFORE the peer server starts: one process is one
    # member, and the daemon role (full API surface) must win the slot.
    from nydus_snapshotter_tpu import fleet

    fleet.register_self("daemon", args.apisock, name=args.id)
    from nydus_snapshotter_tpu.daemon import peer as peer_mod

    peer_mod.start_from_config()
    # SLO actuation follower: when the controller actuates (sheds QoS
    # lanes on burn-rate breach, [slo] actuate + follow), this daemon
    # applies the published lane state to its OWN shared admission gate,
    # so actuation reaches the processes actually moving bytes.
    from nydus_snapshotter_tpu.metrics import slo as slo_mod

    slo_follower = None
    _controller = os.environ.get("NTPU_FLEET_CONTROLLER", "")
    if _controller and slo_mod.resolve_slo_actuation()[0] and os.environ.get(
        "NTPU_SLO_FOLLOW", "1"
    ) not in ("0", "off", "false"):
        slo_follower = slo_mod.SloActuationFollower(_controller)
        slo_follower.start()
    # shutdown() must not run on the main (serve_forever) thread: the signal
    # handler interrupts serve_forever's select, and BaseServer.shutdown()
    # then waits for a loop exit that can never happen — deadlock, daemon
    # survives SIGTERM. Hand it to a helper thread instead.
    signal.signal(
        signal.SIGTERM,
        lambda *_: threading.Thread(target=server.shutdown, daemon=True).start(),
    )
    try:
        server.serve_forever()
    finally:
        if slo_follower is not None:
            slo_follower.stop()
        fleet.deregister_self()
        peer_mod.stop_default()
        try:
            os.unlink(args.apisock)
        except OSError:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
