"""Daemon subsystem: the nydusd-equivalent process, its client, lifecycle.

The reference forks the external Rust ``nydusd`` and drives it over an
HTTP-over-UDS API (pkg/daemon/client.go:31-58). This framework ships its own
daemon process (daemon/server.py) with the same API surface — state machine,
mounts, metrics, takeover — serving RAFS reads from bootstrap + blob cache
in userspace.
"""

from nydus_snapshotter_tpu.daemon.types import (  # noqa: F401
    DaemonState,
    DaemonInfo,
    FsMetrics,
    CacheMetrics,
    MountRequest,
)
from nydus_snapshotter_tpu.daemon.daemon import Daemon  # noqa: F401
from nydus_snapshotter_tpu.daemon.client import NydusdClient, ClientError  # noqa: F401
