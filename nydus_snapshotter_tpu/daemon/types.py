"""Daemon API DTOs (reference pkg/daemon/types/types.go:10-106)."""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional


class DaemonState(str, enum.Enum):
    UNKNOWN = "UNKNOWN"
    INIT = "INIT"
    READY = "READY"
    RUNNING = "RUNNING"
    DIED = "DIED"
    DESTROYED = "DESTROYED"


@dataclass
class DaemonInfo:
    id: str
    version: str
    state: str
    backend_type: str = ""
    supervisor: str = ""
    pid: int = 0

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "version": {"package_ver": self.version, "git_commit": ""},
            "state": self.state,
            "backend_collection": {"type": self.backend_type},
            "supervisor": self.supervisor,
            "pid": self.pid,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "DaemonInfo":
        version = d.get("version", {})
        return cls(
            id=d.get("id", ""),
            version=version.get("package_ver", "") if isinstance(version, dict) else str(version),
            state=d.get("state", DaemonState.UNKNOWN.value),
            backend_type=(d.get("backend_collection") or {}).get("type", ""),
            supervisor=d.get("supervisor", ""),
            pid=d.get("pid", 0),
        )


@dataclass
class FsMetrics:
    files_account_enabled: bool = False
    measure_latency: bool = True
    data_read: int = 0
    block_count_read: dict[str, int] = field(default_factory=dict)
    fop_hits: dict[str, int] = field(default_factory=dict)
    fop_errors: dict[str, int] = field(default_factory=dict)
    read_latency_dist: list[int] = field(default_factory=lambda: [0] * 8)

    def to_dict(self) -> dict[str, Any]:
        return dict(self.__dict__)


@dataclass
class CacheMetrics:
    prefetch_data_amount: int = 0
    buffered_backend_size: int = 0
    underlying_files: list[str] = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        return dict(self.__dict__)


@dataclass
class InflightMetrics:
    values: list[dict[str, Any]] = field(default_factory=list)


@dataclass
class MountRequest:
    fs_type: str
    source: str  # bootstrap path
    config: str  # daemon runtime config JSON

    def to_dict(self) -> dict[str, Any]:
        return {"fs_type": self.fs_type, "source": self.source, "config": self.config}
