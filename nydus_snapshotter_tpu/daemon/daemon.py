"""Host-side daemon model (reference pkg/daemon/daemon.go:64-662).

Tracks one daemon process: identity, sockets, config, lifecycle state
polling, ref-counted RAFS instance attachment, shared mounts through the
API client, and vestige cleanup after crashes.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from nydus_snapshotter_tpu import constants, failpoint
from nydus_snapshotter_tpu.daemon.client import NydusdClient
from nydus_snapshotter_tpu.daemon.command import DaemonCommand
from nydus_snapshotter_tpu.daemon.types import DaemonState
from nydus_snapshotter_tpu.rafs.rafs import Rafs, RafsCache
from nydus_snapshotter_tpu.utils import errdefs
from nydus_snapshotter_tpu.utils import mount as mount_utils

SHARED_DAEMON_ID = "shared_daemon"


@dataclass
class ConfigState:
    """Persisted daemon identity/config (reference daemon.go ConfigState)."""

    daemon_id: str
    fs_driver: str = constants.FS_DRIVER_FUSEDEV
    daemon_mode: str = constants.DAEMON_MODE_DEDICATED
    api_socket: str = ""
    log_file: str = ""
    workdir: str = ""
    supervisor_path: str = ""
    config_path: str = ""
    process_id: int = 0

    def to_dict(self) -> dict[str, Any]:
        return dict(self.__dict__)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ConfigState":
        return cls(**d)


class Daemon:
    def __init__(self, states: ConfigState):
        self.states = states
        self.instances = RafsCache()
        self._proc: Optional[subprocess.Popen] = None
        self._client: Optional[NydusdClient] = None

    # -- identity -----------------------------------------------------------

    @property
    def id(self) -> str:
        return self.states.daemon_id

    @property
    def pid(self) -> int:
        if self._proc is not None:
            return self._proc.pid
        return self.states.process_id

    def client(self) -> NydusdClient:
        if self._client is None:
            self._client = NydusdClient(self.states.api_socket)
        return self._client

    def is_shared(self) -> bool:
        return self.states.daemon_mode == constants.DAEMON_MODE_SHARED

    # -- process ------------------------------------------------------------

    def command(self, upgrade: bool = False) -> DaemonCommand:
        return DaemonCommand(
            id=self.id,
            apisock=self.states.api_socket,
            supervisor=self.states.supervisor_path,
            workdir=self.states.workdir,
            log_file=self.states.log_file,
            upgrade=upgrade,
        )

    def spawn(self, upgrade: bool = False) -> int:
        failpoint.hit("daemon.spawn")
        argv = self.command(upgrade=upgrade).build()
        # The daemon runs `-m nydus_snapshotter_tpu.daemon.server`; make sure
        # the package root is importable regardless of the caller's cwd.
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        self._proc = subprocess.Popen(
            argv, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env
        )
        self.states.process_id = self._proc.pid
        return self._proc.pid

    def terminate(self) -> None:
        pid = self.pid
        if pid:
            try:
                os.kill(pid, signal.SIGTERM)
            except ProcessLookupError:
                pass

    def wait(self, timeout: float = 10.0) -> None:
        if self._proc is not None:
            try:
                self._proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait(timeout=5)
        else:
            deadline = time.time() + timeout
            while time.time() < deadline and _pid_alive(self.states.process_id):
                time.sleep(0.05)

    # -- state machine ------------------------------------------------------

    def state(self) -> DaemonState:
        try:
            info = self.client().get_daemon_info()
            return DaemonState(info.get("state", DaemonState.UNKNOWN.value))
        except (OSError, errdefs.NydusError, ValueError):
            return DaemonState.UNKNOWN

    def wait_until_state(self, want: DaemonState, timeout: float = 30.0) -> None:
        """Poll the API until the daemon reaches `want`
        (reference daemon.go:197-227)."""
        deadline = time.time() + timeout
        last = DaemonState.UNKNOWN
        while time.time() < deadline:
            last = self.state()
            if last == want:
                return
            time.sleep(0.05)
        raise TimeoutError(f"daemon {self.id} stuck in {last.value}, wanted {want.value}")

    def start(self) -> None:
        self.client().start()

    def exit(self) -> None:
        self.client().exit()

    def send_fd(self) -> None:
        self.client().send_fd(self._fd_driver())

    def takeover(self) -> None:
        self.client().takeover(self._fd_driver())

    def _fd_driver(self) -> str:
        return "fscache" if self.states.fs_driver == constants.FS_DRIVER_FSCACHE else "fuse"

    # -- instances ----------------------------------------------------------

    def add_rafs_instance(self, rafs: Rafs) -> None:
        self.instances.add(rafs)

    def remove_rafs_instance(self, snapshot_id: str) -> None:
        self.instances.remove(snapshot_id)

    def ref_count(self) -> int:
        return len(self.instances)

    def shared_mount(self, rafs: Rafs, bootstrap: str, config_json: str) -> None:
        """Attach one RAFS instance to a running daemon via the API
        (reference daemon.go:229-273). The fscache driver's in-kernel
        EROFS attach is the explicit :meth:`shared_erofs_mount` — it
        requires a cachefiles-capable daemon, which the bundled userspace
        daemon is not (it serves FUSE and API reads)."""
        self.client().mount(rafs.relative_mountpoint(), bootstrap, config_json)
        self.add_rafs_instance(rafs)

    def shared_umount(self, rafs: Rafs) -> None:
        self.client().umount(rafs.relative_mountpoint())
        self.remove_rafs_instance(rafs.snapshot_id)

    # Annotation key remembering which blob a snapshot's erofs mount bound,
    # so umount can unbind exactly it.
    _EROFS_BLOB_ANNO = "nydus.erofs.blob_id"

    def shared_erofs_mount(
        self, rafs: Rafs, bootstrap: str, config_json: str, mounter=None
    ) -> None:
        """fscache arm (reference daemon.go:275-324): PUT the blob config
        to the daemon's v2 API (a cachefiles-capable daemon opens the
        kernel session), then mount in-kernel EROFS over fscache at the
        snapshot mountpoint. ``mounter`` injects the mount(2) step for
        tests — kernel fscache support isn't universal.

        This is an EXPLICIT surface for cachefiles-capable daemons: the
        Filesystem facade routes the fscache driver through shared_mount
        (API reads) because the bundled userspace daemon serves FUSE and
        API reads, not cachefiles. Do not mix the two surfaces for one
        instance — their teardowns differ.
        """
        mp = rafs.mountpoint or os.path.join(
            self.states.workdir, "erofs", rafs.snapshot_id
        )
        fscache_id = mount_utils.erofs_fscache_id(rafs.snapshot_id)
        # Carry the bootstrap + fsid in the bind config (the reference's
        # fscache daemon config has metadata_path the same way): a
        # cachefiles-capable daemon then serves the EROFS meta cookie —
        # the fsid mount's first read — not just the data blob cookies.
        try:
            cfg = json.loads(config_json or "{}")
            blob_id = cfg.get("id", "")
            # direct assignment, not setdefault: the cookie keys must
            # match the fsid/bootstrap THIS mount actually uses
            cfg["metadata_path"] = bootstrap
            cfg["fscache_id"] = fscache_id
            config_json = json.dumps(cfg)
        except ValueError:
            blob_id = ""
        self.client().bind_blob(config_json)
        try:
            os.makedirs(mp, exist_ok=True)
            (mounter or mount_utils.erofs_mount)(bootstrap, fscache_id, fscache_id, mp)
        except Exception:
            # roll the bind back: nothing else will ever unbind it
            try:
                self.client().unbind_blob(fscache_id, blob_id)
            except (OSError, errdefs.NydusError):
                pass
            raise
        rafs.mountpoint = mp
        if blob_id:
            rafs.annotations[self._EROFS_BLOB_ANNO] = blob_id
        self.add_rafs_instance(rafs)

    def shared_erofs_umount(self, rafs: Rafs, umounter=None) -> None:
        if rafs.mountpoint:
            (umounter or mount_utils.erofs_umount)(rafs.mountpoint)
        # Mirror the mount-failure rollback (which unbinds unconditionally,
        # tolerating failure): bind_blob was issued at mount time even when
        # the config JSON had no id, so always attempt the unbind — but a
        # server rejecting an empty-id unbind must not block instance
        # removal after the kernel umount already succeeded.
        blob_id = rafs.annotations.pop(self._EROFS_BLOB_ANNO, "")
        try:
            self.client().unbind_blob(
                mount_utils.erofs_fscache_id(rafs.snapshot_id), blob_id
            )
        except (OSError, errdefs.NydusError):
            if blob_id:
                raise  # a real bound blob failing to unbind IS an error
        self.remove_rafs_instance(rafs.snapshot_id)

    def recover_rafs_instances(self, instances: list[Rafs], configs: dict[str, str]) -> None:
        """Replay persisted mounts in seq order after daemon restart
        (reference daemon.go:618-660)."""
        for rafs in sorted(instances, key=lambda r: r.seq):
            bootstrap = rafs.bootstrap_file()
            self.client().mount(
                rafs.relative_mountpoint(), bootstrap, configs.get(rafs.snapshot_id, "")
            )
            self.add_rafs_instance(rafs)

    # -- cleanup ------------------------------------------------------------

    def clear_vestige(self) -> None:
        """Remove leftovers of a dead daemon: stale api socket
        (reference daemon.go:579-605)."""
        sock = self.states.api_socket
        if sock and os.path.exists(sock) and not _pid_alive(self.states.process_id):
            try:
                os.unlink(sock)
            except OSError:
                pass

    def get_daemon_version(self) -> str:
        info = self.client().get_daemon_info()
        version = info.get("version", {})
        return version.get("package_ver", "") if isinstance(version, dict) else str(version)


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
