"""Peer chunk tier: nodes serve each other's lazy-read chunk fetches.

PR 3 made one node's cold reads fast; at cluster scale a new image deploy
makes thousands of nodes hammer the registry for the SAME chunks at the
same moment, so aggregate registry egress scales as N x unique bytes and
the storm collapses the origin. This module adds the second cache tier of
the registry -> peer -> local-cache waterfall:

- **PeerChunkServer** — every node serves ranged reads for extents its
  :class:`~nydus_snapshotter_tpu.daemon.blobcache.CachedBlob`\\ s already
  cover, over the same HTTP-over-UDS/TCP machinery the chunk-dict service
  uses (parallel/dict_service.py). With ``pull_through`` on, the REGION
  OWNER of a cold extent fetches it from the registry on behalf of the
  cluster — through its own CachedBlob, whose per-blob singleflight table
  collapses every concurrent peer request into one origin GET, so a chunk
  is fetched from origin at most ~once per cluster.
- **PeerRouter** — the peer-announce/lookup map: a static peer list from
  the ``[peer]`` config (no gossip protocol), rendezvous-hashed per
  ``(blob, region)`` so every node independently agrees which peer owns a
  region. Peers are scored through the process-wide
  :class:`~nydus_snapshotter_tpu.remote.mirror.HostHealthRegistry` —
  the same table the registry-mirror failover and the converter transport
  score through — so a dead peer goes on cooldown and the ranking walks
  to the next owner (or the origin) instead of timing out every read.
- **PeerAwareFetcher** — the planner's waterfall: each planned flight
  tries the healthy region owner first and falls back to the registry on
  miss / timeout / error / corrupt payload (CRC32-trailer verified), so a
  dead peer can never fail a read, only slow it by one bounded timeout.

Serving peers is the LOWEST QoS lane: the chunk server admits its bytes
through the node's :class:`~nydus_snapshotter_tpu.daemon.fetch_sched.
AdmissionGate` at PEER_SERVE priority, below local demand, readahead and
prefetch replay — a node under local pressure sheds peer traffic first
(requesters transparently fall back to the registry).

Failpoint sites ``peer.{serve,fetch,admit}`` make every boundary
chaos-testable (docs/robustness.md); metrics land as ``ntpu_peer_*``;
trace context rides the same ``x-ntpu-trace-*`` headers the dict service
uses, so a peer-served read's span tree spans both nodes.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import logging
import os
import socket
import socketserver
import threading
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from time import perf_counter
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

from nydus_snapshotter_tpu import failpoint, trace
from nydus_snapshotter_tpu.analysis import runtime as _an
from nydus_snapshotter_tpu.daemon import fetch_sched
from nydus_snapshotter_tpu.daemon.fetch_sched import PEER_SERVE
from nydus_snapshotter_tpu.metrics import registry as _metrics
from nydus_snapshotter_tpu.remote import mirror as mirror_mod

logger = logging.getLogger(__name__)

DEFAULT_REGION_KIB = 512
DEFAULT_TIMEOUT_MS = 1500
PEER_FAILURE_LIMIT = 3
PEER_COOLDOWN_SECS = 2.0
MAX_SERVE_BYTES = 64 << 20  # one ranged peer read, not a blob mirror

_reg = _metrics.default_registry
SERVE_REQUESTS = _reg.register(
    _metrics.Counter(
        "ntpu_peer_serve_requests",
        "Ranged peer-read requests served by this node's chunk server,"
        " by outcome (hit / pull / miss / error)",
        ("outcome",),
    )
)
SERVED_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_peer_served_bytes",
        "Bytes this node served to cluster peers",
    )
)
FETCH_REQUESTS = _reg.register(
    _metrics.Counter(
        "ntpu_peer_fetch_requests",
        "Ranged reads this node attempted against a peer",
    )
)
FETCH_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_peer_fetch_bytes",
        "Bytes this node fetched from cluster peers instead of the registry",
    )
)
FETCH_FALLBACKS = _reg.register(
    _metrics.Counter(
        "ntpu_peer_fetch_fallbacks",
        "Peer reads that fell back to the registry, by reason"
        " (miss / timeout / error / corrupt)",
        ("reason",),
    )
)
SERVE_MS = _reg.register(
    _metrics.Histogram(
        "ntpu_peer_serve_duration_milliseconds",
        "Peer chunk-server request latency",
        ("outcome",),
    )
)
MEMBERSHIP_EPOCH = _reg.register(
    _metrics.Gauge(
        "ntpu_peer_membership_epoch",
        "Region-ownership epoch: bumps whenever the live peer set changes",
    )
)
MEMBERSHIP_PEERS = _reg.register(
    _metrics.Gauge(
        "ntpu_peer_membership_peers",
        "Peers in the current live membership view (incl. this node)",
    )
)
MEMBERSHIP_EVENTS = _reg.register(
    _metrics.Counter(
        "ntpu_peer_membership_events_total",
        "Peer membership transitions observed, by kind"
        " (join / leave / down / refresh_error)",
        ("kind",),
    )
)


def snapshot_counters() -> dict:
    """Cumulative ``ntpu_peer_*`` values (tools delta these around runs)."""
    return {
        "serve_hit": SERVE_REQUESTS.value("hit"),
        "serve_pull": SERVE_REQUESTS.value("pull"),
        "serve_miss": SERVE_REQUESTS.value("miss"),
        "serve_error": SERVE_REQUESTS.value("error"),
        "served_bytes": SERVED_BYTES.value(),
        "fetch_requests": FETCH_REQUESTS.value(),
        "fetch_bytes": FETCH_BYTES.value(),
        "fallback_miss": FETCH_FALLBACKS.value("miss"),
        "fallback_timeout": FETCH_FALLBACKS.value("timeout"),
        "fallback_error": FETCH_FALLBACKS.value("error"),
        "fallback_corrupt": FETCH_FALLBACKS.value("corrupt"),
    }


class PeerError(OSError):
    """A peer request failed (connection, protocol, or server error)."""


class PeerMiss(PeerError):
    """The peer does not cover the requested extent (HTTP 404)."""


# ---------------------------------------------------------------------------
# Config resolution (env > [peer] config > defaults)
# ---------------------------------------------------------------------------


class PeerRuntimeConfig:
    """Resolved ``[peer]`` knobs for this process."""

    __slots__ = (
        "enable", "listen", "peers", "region_bytes", "timeout_s",
        "pull_through", "membership", "membership_refresh_s",
    )

    def __init__(self, enable, listen, peers, region_bytes, timeout_s,
                 pull_through, membership="auto", membership_refresh_s=2.0):
        self.enable = enable
        self.listen = listen
        self.peers = peers
        self.region_bytes = region_bytes
        self.timeout_s = timeout_s
        self.pull_through = pull_through
        # "static" = the [peer] list only; "fleet" = the member registry
        # (seeded by the list); "auto" = fleet when a controller address
        # is known, static otherwise.
        self.membership = membership
        self.membership_refresh_s = membership_refresh_s


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name, "")
    if not v:
        return default
    return v not in ("0", "off", "false")


def _global_peer_config():
    try:
        from nydus_snapshotter_tpu.config import config as _cfg

        return _cfg.get_global_config().peer
    except Exception:
        return None


def resolve_peer_config() -> PeerRuntimeConfig:
    """env (``NTPU_PEER*``) > ``[peer]`` global config > defaults. Env
    overrides are also how the section reaches the spawned daemon
    processes, which have no global snapshotter config."""
    pc = _global_peer_config()
    peers_env = os.environ.get("NTPU_PEER_PEERS", "")
    if peers_env:
        peers = [p.strip() for p in peers_env.split(",") if p.strip()]
    else:
        peers = list(getattr(pc, "peers", None) or [])
    region_kib = fetch_sched._env_int(
        "NTPU_PEER_REGION_KIB",
        getattr(pc, "region_kib", 0) or DEFAULT_REGION_KIB,
    )
    timeout_ms = fetch_sched._env_int(
        "NTPU_PEER_TIMEOUT_MS",
        getattr(pc, "timeout_ms", 0) or DEFAULT_TIMEOUT_MS,
    )
    refresh_ms = fetch_sched._env_int(
        "NTPU_PEER_MEMBERSHIP_REFRESH_MS",
        int(float(getattr(pc, "membership_refresh_secs", 0) or 2.0) * 1000),
    )
    return PeerRuntimeConfig(
        enable=_env_bool("NTPU_PEER_ENABLE", bool(getattr(pc, "enable", False))),
        listen=os.environ.get("NTPU_PEER_LISTEN", getattr(pc, "listen", "")),
        peers=peers,
        region_bytes=max(1, region_kib) << 10,
        timeout_s=max(1, timeout_ms) / 1000.0,
        pull_through=_env_bool(
            "NTPU_PEER_PULL_THROUGH", bool(getattr(pc, "pull_through", True))
        ),
        membership=os.environ.get(
            "NTPU_PEER_MEMBERSHIP", getattr(pc, "membership", "auto") or "auto"
        ),
        membership_refresh_s=max(0.05, refresh_ms / 1000.0),
    )


def _normalize_addr(addr: str) -> str:
    """``uds:///run/x.sock`` / ``/run/x.sock`` / ``host:port`` — strip the
    scheme so an address compares equal however it was written."""
    if addr.startswith("uds://"):
        return addr[len("uds://"):]
    return addr


def _is_uds(addr: str) -> bool:
    return "/" in addr


# ---------------------------------------------------------------------------
# Local export map: which blobs this node can serve
# ---------------------------------------------------------------------------


class PeerExport:
    """blob_id -> live CachedBlob announce map for the local chunk server.

    The daemon registers every registry-backed CachedBlob it opens and
    unregisters on instance close; the server resolves requests against
    this map only (a blob nobody lazily reads here is a 404, never a
    registry fetch on a stranger's behalf)."""

    def __init__(self):
        self._mu = _an.make_lock("peer.export")
        # Lockset annotation: the blob map is only ever touched under
        # self._mu (NTPU_ANALYZE=1 verifies).
        self._blobs_shared = _an.shared("peer.export.blobs")
        self._blobs: dict[str, object] = {}
        # blob_id -> persisted soci index path this node can replicate
        # (checksummed on the wire by the requester's index load).
        self._soci: dict[str, str] = {}

    def register(self, blob_id: str, cached_blob) -> None:
        with self._mu:
            self._blobs_shared.write()
            self._blobs[blob_id] = cached_blob

    def unregister(self, blob_id: str, cached_blob=None) -> None:
        """Drop the announce; with ``cached_blob`` given, only when the
        map still points at that instance (two instances of one blob:
        closing the first must not unannounce the survivor)."""
        with self._mu:
            self._blobs_shared.write()
            if cached_blob is None or self._blobs.get(blob_id) is cached_blob:
                self._blobs.pop(blob_id, None)

    def get(self, blob_id: str):
        with self._mu:
            self._blobs_shared.read()
            return self._blobs.get(blob_id)

    def register_soci(self, blob_id: str, index_path: str) -> None:
        """Announce a persisted soci index: peers missing one replicate
        it instead of re-pulling the whole layer to rebuild."""
        with self._mu:
            self._blobs_shared.write()
            self._soci[blob_id] = index_path

    def unregister_soci(self, blob_id: str) -> None:
        with self._mu:
            self._blobs_shared.write()
            self._soci.pop(blob_id, None)

    def soci_path(self, blob_id: str):
        with self._mu:
            self._blobs_shared.read()
            return self._soci.get(blob_id)

    def stats(self) -> dict:
        with self._mu:
            self._blobs_shared.read()
            blobs = dict(self._blobs)
            soci = dict(self._soci)
        return {
            "blobs": {
                bid: {"covered_bytes": cb.coverage_bytes()}
                for bid, cb in blobs.items()
            },
            "soci_indexes": sorted(soci),
        }


# ---------------------------------------------------------------------------
# Chunk server (HTTP over UDS or TCP)
# ---------------------------------------------------------------------------


_BLOB_ROUTE = "/api/v1/peer/blob/"
_SOCI_ROUTE = "/api/v1/peer/soci/"
_STAT_ROUTE = "/api/v1/peer/stat"


def _crc32(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


class _UnixHTTPServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    # The default backlog of 5 overflows when a whole deploy storm's
    # worth of peers dials the region owner at once: excess connects
    # fail instead of queueing (same fix as the daemon API server).
    request_queue_size = 128

    def finish_request(self, request, client_address):
        self.RequestHandlerClass(request, ("uds", 0), self)


class _TCPHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    request_queue_size = 128


class PeerChunkServer:
    """Serves ranged chunk reads for locally cached extents to peers.

    ``handle()`` is transport-agnostic (the same split as DictService);
    ``run(address)`` serves on a UDS path (contains ``/``) or a TCP
    ``host:port``. Responses carry an ``x-ntpu-peer-crc32`` trailer header
    so a requester detects transit corruption and falls back to the
    registry instead of caching poisoned bytes.
    """

    def __init__(
        self,
        export: PeerExport,
        gate=None,
        pull_through: Optional[bool] = None,
        tenant: str = "peer",
        router: Optional["PeerRouter"] = None,
    ):
        cfg = resolve_peer_config()
        self.export = export
        self.gate = gate if gate is not None else fetch_sched.shared_gate()
        self.pull_through = (
            cfg.pull_through if pull_through is None else pull_through
        )
        self.tenant = tenant
        # Introspection only: the stat route surfaces this node's dynamic
        # membership view + admission actuation state (ntpuctl peers).
        self.router = router
        self._httpd = None
        self._closed = False
        self.address = ""

    # -- request handling ----------------------------------------------------

    def handle(self, method: str, path: str, headers) -> tuple[int, dict, bytes]:
        """(method, path?query, headers) -> (status, extra headers, body)."""
        parsed = urlparse(path)
        if parsed.path == _STAT_ROUTE:
            stat = self.export.stats()
            stat["admission"] = self.gate.lane_state()
            if self.router is not None and self.router.membership is not None:
                stat["membership"] = self.router.membership.snapshot()
            body = json.dumps(stat).encode()
            return 200, {"Content-Type": "application/json"}, body
        if parsed.path == "/api/v1/traces":
            # A standalone peer server is a fleet member: its process's
            # span ring joins the cluster-merged trace (trace/aggregate.py).
            body = trace.chrome_trace_bytes()
            return 200, {"Content-Type": "application/json"}, body
        if parsed.path in ("/metrics", "/v1/metrics"):
            body = _reg.render().encode()
            return 200, {"Content-Type": "text/plain; version=0.0.4"}, body
        if parsed.path.startswith(_SOCI_ROUTE) and method == "GET":
            # Seekable-OCI index replication: serve the persisted,
            # checksummed artifact so one pod's first-pull build
            # amortizes across the fleet. The requester revalidates the
            # embedded SHA-256 before adopting (a corrupt relay costs a
            # local rebuild, never a poisoned read).
            path = self.export.soci_path(parsed.path[len(_SOCI_ROUTE):])
            if path is None:
                SERVE_REQUESTS.labels("miss").inc()
                return 404, {}, b'{"message": "no soci index"}'
            try:
                with open(path, "rb") as f:
                    body = f.read()
            except OSError as e:
                SERVE_REQUESTS.labels("error").inc()
                return 500, {}, json.dumps({"message": str(e)}).encode()
            SERVE_REQUESTS.labels("hit").inc()
            SERVED_BYTES.inc(len(body))
            return 200, {
                "Content-Type": "application/octet-stream",
                "x-ntpu-peer-crc32": f"{_crc32(body):08x}",
            }, body
        if not parsed.path.startswith(_BLOB_ROUTE) or method != "GET":
            return 404, {}, b'{"message": "no such endpoint"}'
        blob_id = parsed.path[len(_BLOB_ROUTE):]
        q = parse_qs(parsed.query)
        try:
            offset = int(q.get("offset", ["-1"])[0])
            size = int(q.get("size", ["0"])[0])
            depth = int(headers.get("x-ntpu-peer-depth", "0"))
        except ValueError:
            return 400, {}, b'{"message": "bad range"}'
        if offset < 0 or size <= 0 or size > MAX_SERVE_BYTES:
            return 400, {}, b'{"message": "bad range"}'
        try:
            tid = int(headers.get("x-ntpu-trace-id", "0"), 16)
            pid = int(headers.get("x-ntpu-parent-id", "0"), 16)
        except ValueError:
            tid = pid = 0
        t0 = perf_counter()
        outcome = "error"
        try:
            with trace.with_context(trace.remote_context(tid, pid)):
                with trace.span(
                    "peer.serve", blob=blob_id[:8], offset=offset, bytes=size
                ) as sp:
                    failpoint.hit("peer.serve")
                    cb = self.export.get(blob_id)
                    if cb is None:
                        outcome = "miss"
                        return 404, {}, b'{"message": "unknown blob"}'
                    covered = cb.covered(offset, size)
                    if not covered and (depth > 0 or not self.pull_through):
                        # Cover-only serving: never fetch on behalf of a
                        # forwarded request — bounds the relay depth.
                        outcome = "miss"
                        return 404, {}, b'{"message": "extent not cached"}'
                    if covered:
                        outcome = "hit"
                        # Serving cached bytes still consumes this node's
                        # uplink: admit it at the lowest lane.
                        self.gate.acquire(
                            size,
                            tenant=self.tenant,
                            lane=PEER_SERVE,
                            aborted=lambda: self._closed,
                        )
                        try:
                            data = cb.read_at(offset, size, lane=PEER_SERVE)
                        finally:
                            self.gate.release(
                                size, tenant=self.tenant, lane=PEER_SERVE
                            )
                    else:
                        # Pull-through: this node is the region owner —
                        # fetch once through the local CachedBlob (its
                        # singleflight table collapses the cluster's
                        # concurrent requests); the flight itself admits
                        # at PEER_SERVE lane.
                        outcome = "pull"
                        data = cb.read_at(offset, size, lane=PEER_SERVE)
                    sp.annotate(outcome=outcome)
                    SERVED_BYTES.inc(len(data))
                    return 200, {
                        "Content-Type": "application/octet-stream",
                        "x-ntpu-peer-crc32": f"{_crc32(data):08x}",
                        "x-ntpu-peer-outcome": outcome,
                    }, data
        except Exception as e:  # noqa: BLE001 - mapped to a wire status
            outcome = "error"
            logger.warning("peer serve %s[%d,+%d) failed: %s",
                           blob_id[:12], offset, size, e)
            return 500, {}, json.dumps({"message": str(e)}).encode()
        finally:
            SERVE_REQUESTS.labels(outcome).inc()
            SERVE_MS.labels(outcome).observe((perf_counter() - t0) * 1000.0)

    # -- server lifecycle ----------------------------------------------------

    def run(self, address: str) -> None:
        """Serve on ``address``: a UDS path or ``host:port``."""
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                status, extra, payload = server.handle(
                    self.command, self.path, self.headers
                )
                self.send_response(status)
                if "Content-Type" not in extra:
                    self.send_header("Content-Type", "application/json")
                for k, v in extra.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        addr = _normalize_addr(address)
        if _is_uds(addr):
            os.makedirs(os.path.dirname(addr) or ".", exist_ok=True)
            try:
                os.remove(addr)
            except FileNotFoundError:
                pass
            self._httpd = _UnixHTTPServer(addr, Handler)
        else:
            host, _, port = addr.rpartition(":")
            self._httpd = _TCPHTTPServer((host or "0.0.0.0", int(port)), Handler)
        self.address = addr
        threading.Thread(
            target=self._httpd.serve_forever, name="ntpu-peer-serve", daemon=True
        ).start()
        logger.info("peer chunk server on %s", addr)

    def stop(self) -> None:
        self._closed = True
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self.address and _is_uds(self.address):
            try:
                os.remove(self.address)
            except OSError:
                pass
        self.address = ""


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class _UDSHTTPConnection(http.client.HTTPConnection):
    def __init__(self, sock_path: str, timeout: float):
        super().__init__("localhost", timeout=timeout)
        self._sock_path = sock_path

    def connect(self) -> None:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        try:
            s.connect(self._sock_path)
        except BaseException:
            # A dead peer must not leak the half-made socket (close()
            # only knows about self.sock once the connect succeeded).
            s.close()
            raise
        self.sock = s


class PeerClient:
    """One ranged read against one peer. Connections are per-call (peer
    reads fan out across fetch workers; a UDS/TCP dial is cheap next to
    the range it carries) and every phase is bounded by ``timeout_s``."""

    def __init__(self, address: str, timeout_s: float = DEFAULT_TIMEOUT_MS / 1000.0):
        self.address = _normalize_addr(address)
        self.timeout_s = timeout_s

    def _connect(self) -> http.client.HTTPConnection:
        if _is_uds(self.address):
            return _UDSHTTPConnection(self.address, self.timeout_s)
        host, _, port = self.address.rpartition(":")
        return http.client.HTTPConnection(
            host or "localhost", int(port), timeout=self.timeout_s
        )

    def read_range(
        self, blob_id: str, offset: int, size: int, depth: int = 0
    ) -> bytes:
        """Bytes of ``blob_id[offset, offset+size)`` from this peer.
        Raises :class:`PeerMiss` when the peer doesn't cover the extent,
        :class:`PeerError` on any transport/server/integrity failure."""
        headers = {"x-ntpu-peer-depth": str(depth)}
        ctx = trace.capture()
        if ctx is not None and ctx.sampled:
            headers["x-ntpu-trace-id"] = f"{ctx.trace_id:x}"
            headers["x-ntpu-parent-id"] = f"{ctx.span_id:x}"
        conn = self._connect()
        try:
            conn.request(
                "GET",
                f"{_BLOB_ROUTE}{blob_id}?offset={offset}&size={size}",
                headers=headers,
            )
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status == 404:
                raise PeerMiss(f"peer {self.address} misses {blob_id}[{offset})")
            if resp.status != 200:
                raise PeerError(
                    f"peer {self.address} -> {resp.status}: {payload[:120]!r}"
                )
            want_crc = resp.headers.get("x-ntpu-peer-crc32", "")
        except (http.client.HTTPException, OSError) as e:
            if isinstance(e, PeerError):
                raise
            raise PeerError(f"peer {self.address} request failed: {e}") from e
        finally:
            conn.close()
        if len(payload) != size:
            raise PeerError(
                f"peer {self.address} returned {len(payload)} bytes, wanted {size}"
            )
        # Deliberately NOT the server's _crc32 helper: the two sides must
        # compute independently for the check to mean anything (tests
        # inject corruption by patching the server-side helper).
        if want_crc and f"{zlib.crc32(payload) & 0xFFFFFFFF:08x}" != want_crc:
            raise PeerError(f"peer {self.address} payload failed CRC32 check")
        return payload

    def fetch_soci_index(self, blob_id: str) -> bytes:
        """The peer's persisted soci index artifact for ``blob_id``
        (serialized; the caller revalidates its embedded checksum).
        Raises :class:`PeerMiss`/:class:`PeerError` like ``read_range``."""
        conn = self._connect()
        try:
            conn.request("GET", f"{_SOCI_ROUTE}{blob_id}")
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status == 404:
                raise PeerMiss(f"peer {self.address} has no index for {blob_id}")
            if resp.status != 200:
                raise PeerError(
                    f"peer {self.address} -> {resp.status}: {payload[:120]!r}"
                )
            want_crc = resp.headers.get("x-ntpu-peer-crc32", "")
        except (http.client.HTTPException, OSError) as e:
            if isinstance(e, PeerError):
                raise
            raise PeerError(f"peer {self.address} request failed: {e}") from e
        finally:
            conn.close()
        if want_crc and f"{zlib.crc32(payload) & 0xFFFFFFFF:08x}" != want_crc:
            raise PeerError(f"peer {self.address} index failed CRC32 check")
        return payload

    def stat(self) -> dict:
        conn = self._connect()
        try:
            conn.request("GET", _STAT_ROUTE)
            resp = conn.getresponse()
            payload = resp.read()
        except (http.client.HTTPException, OSError) as e:
            raise PeerError(f"peer {self.address} stat failed: {e}") from e
        finally:
            conn.close()
        if resp.status != 200:
            raise PeerError(f"peer {self.address} stat -> {resp.status}")
        return json.loads(payload)


# ---------------------------------------------------------------------------
# Dynamic membership: the fleet registry as the peer discovery source
# ---------------------------------------------------------------------------


class PeerMembership:
    """Live peer-address view driven by the fleet member registry.

    The static ``[peer] peers`` list is kept as the SEED: it is the
    membership whenever the registry is unreachable or empty (fresh
    cluster, controller restarting), so a config-only deployment keeps
    working unchanged. With a reachable controller, the registry IS the
    membership — peers joining (self-registering) and leaving
    (deregistering) re-shape rendezvous region ownership without a config
    edit, and members the fleet plane flags down/stale are pushed onto
    the shared :class:`~nydus_snapshotter_tpu.remote.mirror.
    HostHealthRegistry` cooldown so routing walks past them immediately.

    ``fetch`` returns ``[{"address", "up", "stale"}, ...]`` rows; the
    default implementation pulls the controller's
    ``/api/v1/fleet/peers`` route. Refreshes are rate-limited to
    ``refresh_secs`` and serialized (concurrent callers reuse the cached
    view); a failing refresh keeps the last-good membership — discovery
    outages degrade to a stale view, never to an empty cluster.
    """

    def __init__(
        self,
        seed: Optional[list] = None,
        controller: str = "",
        fetch=None,
        refresh_secs: float = 2.0,
        clock=None,
        health_registry=None,
        stale_cooldown: float = PEER_COOLDOWN_SECS,
    ):
        from time import monotonic

        self.seed = sorted(
            {a for a in (_normalize_addr(p) for p in (seed or [])) if a}
        )
        self.controller = controller
        self._fetch = fetch if fetch is not None else self._fetch_controller
        self.refresh_secs = max(0.0, float(refresh_secs))
        self._clock = clock or monotonic
        self._health = (
            health_registry
            if health_registry is not None
            else mirror_mod.global_health_registry()
        )
        self.stale_cooldown = float(stale_cooldown)
        self._mu = _an.make_lock("peer.membership")
        # Lockset annotation: the live view + event log only mutate under
        # self._mu (the refresh fetch itself runs outside it).
        self._view_shared = _an.shared("peer.membership.view")
        self._live: list[str] = list(self.seed)
        self._epoch = 0
        self._events: list[dict] = []
        self._last_refresh = float("-inf")
        self._last_error = ""
        self._refreshing = False
        # address -> member name from the last registry listing, and the
        # rate limiter for upward health reports (report_down).
        self._names: dict[str, str] = {}
        self._reported: dict[str, float] = {}

    def _fetch_controller(self) -> list[dict]:
        if not self.controller:
            return []
        from nydus_snapshotter_tpu.utils import udshttp

        rows = udshttp.get_json(
            self.controller, "/api/v1/fleet/peers", timeout=2.0
        )
        return rows if isinstance(rows, list) else []

    def _maybe_refresh(self) -> None:
        now = self._clock()
        with self._mu:
            self._view_shared.read()
            if now - self._last_refresh < self.refresh_secs or self._refreshing:
                return
            self._refreshing = True
        rows: Optional[list] = None
        err = ""
        try:
            failpoint.hit("peer.member")
            rows = self._fetch()
        except Exception as e:  # noqa: BLE001 — keep the last-good view
            err = str(e)
            MEMBERSHIP_EVENTS.labels("refresh_error").inc()
        down: list[str] = []
        live: Optional[list[str]] = None
        if rows is not None:
            addrs = set()
            names: dict[str, str] = {}
            for r in rows:
                addr = _normalize_addr(str(r.get("address", "")))
                if not addr:
                    continue
                if r.get("name"):
                    names[addr] = str(r["name"])
                if r.get("up", True) and not r.get("stale", False):
                    addrs.add(addr)
                else:
                    # Crashed-but-registered: keep it OUT of the live set
                    # (its regions re-own immediately) and cool it down in
                    # the shared health table so an in-flight route walks
                    # past it instead of timing out.
                    down.append(addr)
            # Registry empty (or only down members) => the seed list is
            # the fallback floor, exactly the pre-dynamic behavior.
            live = sorted(addrs) if addrs else list(self.seed)
        for addr in down:
            self._health.health_for(
                addr,
                failure_limit=PEER_FAILURE_LIMIT,
                cooldown=PEER_COOLDOWN_SECS,
            ).mark_down(self.stale_cooldown)
            MEMBERSHIP_EVENTS.labels("down").inc()
        with self._mu:
            self._view_shared.write()
            self._refreshing = False
            self._last_refresh = now
            self._last_error = err
            if rows is not None:
                self._names.update(names)
            if live is not None and live != self._live:
                prev = set(self._live)
                cur = set(live)
                for addr in sorted(cur - prev):
                    self._events.append(
                        {"at": now, "kind": "join", "address": addr}
                    )
                    MEMBERSHIP_EVENTS.labels("join").inc()
                for addr in sorted(prev - cur):
                    self._events.append(
                        {"at": now, "kind": "leave", "address": addr}
                    )
                    MEMBERSHIP_EVENTS.labels("leave").inc()
                del self._events[:-64]
                self._live = live
                self._epoch += 1
                MEMBERSHIP_EPOCH.set(self._epoch)
            MEMBERSHIP_PEERS.set(len(self._live))

    def addresses(self) -> list[str]:
        """The current live peer set (refreshing if the view is stale)."""
        self._maybe_refresh()
        with self._mu:
            self._view_shared.read()
            return list(self._live)

    def report_down(self, address: str, source: str = "peer-router") -> bool:
        """Upward health signal: a peer at ``address`` stopped answering
        (its health cooldown tripped). Resolves the member name from the
        last registry listing and posts it to the controller's
        ``/api/v1/fleet/placement/report`` — the dict-HA placement plane
        promotes around a reported-down member without waiting out
        scrape staleness. Rate-limited per address; best-effort (the
        report rides a background thread, a down controller drops it)."""
        addr = _normalize_addr(address)
        now = self._clock()
        with self._mu:
            self._view_shared.write()
            name = self._names.get(addr, "")
            if not self.controller or not name:
                return False
            last = self._reported.get(addr, float("-inf"))
            if now - last < self.stale_cooldown:
                return False
            self._reported[addr] = now
        controller = self.controller

        def push():
            from nydus_snapshotter_tpu.utils import udshttp

            try:
                udshttp.post_json(
                    controller,
                    "/api/v1/fleet/placement/report",
                    {"name": name, "source": source},
                    timeout=2.0,
                )
                MEMBERSHIP_EVENTS.labels("report_down").inc()
            except Exception:  # noqa: BLE001 — best-effort signal
                pass

        threading.Thread(
            target=push, name="ntpu-peer-report-down", daemon=True
        ).start()
        return True

    @property
    def epoch(self) -> int:
        with self._mu:
            self._view_shared.read()
            return self._epoch

    def snapshot(self) -> dict:
        with self._mu:
            self._view_shared.read()
            return {
                "epoch": self._epoch,
                "peers": list(self._live),
                "seed": list(self.seed),
                "events": [dict(e) for e in self._events[-16:]],
                "last_error": self._last_error,
                "controller": self.controller,
            }


# ---------------------------------------------------------------------------
# Router: which peer owns which region
# ---------------------------------------------------------------------------


class PeerRouter:
    """Rendezvous region ownership over a (possibly dynamic) peer set.

    Every node, given the same peer set, independently computes the same
    owner for a ``(blob, region)`` — the lookup map that needs no gossip.
    The set comes from the static ``[peer]`` list, or — with a
    :class:`PeerMembership` attached — from the live fleet registry, so
    autoscaling re-shapes ownership with minimal churn: rendezvous
    hashing moves only the ~K/n regions the joining/leaving peer wins or
    owned (property-tested in tests/test_peer_membership.py). Ownership
    walks the rendezvous ranking past unhealthy peers (cooldown via the
    process-wide HostHealthRegistry), and returns None when this node
    itself ranks first (fetch from origin: we ARE the serve point for
    this region).
    """

    def __init__(
        self,
        peers: list[str],
        self_address: str = "",
        region_bytes: int = DEFAULT_REGION_KIB << 10,
        health_registry=None,
        membership: Optional[PeerMembership] = None,
    ):
        self.self_address = _normalize_addr(self_address)
        self.peers = [
            a for a in (_normalize_addr(p) for p in peers) if a
        ]
        self.region_bytes = max(1, int(region_bytes))
        self.membership = membership
        self.health = (
            health_registry
            if health_registry is not None
            else mirror_mod.global_health_registry()
        )

    @staticmethod
    def _score(addr: str, blob_id: str, region: int) -> int:
        h = hashlib.blake2b(
            f"{addr}|{blob_id}|{region}".encode(), digest_size=8
        )
        return int.from_bytes(h.digest(), "little")

    def current_peers(self) -> list[str]:
        """The peer set ownership hashes over right now: the live
        membership view when one is attached, else the static list."""
        if self.membership is not None:
            return self.membership.addresses()
        return list(self.peers)

    def ranked(self, blob_id: str, offset: int) -> list[str]:
        region = offset // self.region_bytes
        members = set(self.current_peers())
        if self.self_address:
            members.add(self.self_address)
        return sorted(
            members,
            key=lambda a: self._score(a, blob_id, region),
            reverse=True,
        )

    def route(self, blob_id: str, offset: int) -> Optional[str]:
        """The healthy peer to ask for this extent, or None for the
        registry (self-owned region, or every peer cooling down)."""
        for addr in self.ranked(blob_id, offset):
            if addr == self.self_address:
                return None
            if self.health.health_for(
                addr,
                failure_limit=PEER_FAILURE_LIMIT,
                cooldown=PEER_COOLDOWN_SECS,
            ).available():
                return addr
        return None

    def record(self, addr: str, ok: bool) -> None:
        h = self.health.health_for(
            addr, failure_limit=PEER_FAILURE_LIMIT, cooldown=PEER_COOLDOWN_SECS
        )
        if ok:
            h.record_success()
        else:
            h.record_failure()
            if self.membership is not None and not h.available():
                # Cooldown tripped: this node just WATCHED the member
                # fail repeatedly — tell the controller so the dict-HA
                # plane can promote around it before scrape staleness.
                self.membership.report_down(addr)


# ---------------------------------------------------------------------------
# The waterfall: registry -> peer -> local cache
# ---------------------------------------------------------------------------


class PeerAwareFetcher:
    """Wraps a blob's origin ``fetch_range`` with the peer tier.

    Drop-in for the callable CachedBlob takes: the fetch scheduler's
    flights call ``read_range`` concurrently, each flight first trying
    the extent's healthy region owner and falling back to the origin
    fetcher on any failure — transparently, so a dead/slow/corrupt peer
    never fails a read (chaos-pinned via the ``peer.fetch`` site).
    """

    def __init__(
        self,
        blob_id: str,
        origin_fetch: Callable[[int, int], bytes],
        router: PeerRouter,
        timeout_s: float = 0.0,
    ):
        self.blob_id = blob_id
        self.origin_fetch = origin_fetch
        self.router = router
        self.timeout_s = timeout_s or resolve_peer_config().timeout_s

    def read_range(self, offset: int, size: int) -> bytes:
        addr = self.router.route(self.blob_id, offset)
        if addr is not None:
            FETCH_REQUESTS.inc()
            with trace.span(
                "peer.fetch",
                blob=self.blob_id[:8],
                peer=addr,
                offset=offset,
                bytes=size,
            ) as sp:
                try:
                    failpoint.hit("peer.fetch")
                    data = PeerClient(addr, self.timeout_s).read_range(
                        self.blob_id, offset, size
                    )
                    self.router.record(addr, ok=True)
                    FETCH_BYTES.inc(size)
                    sp.annotate(outcome="hit")
                    return data
                except Exception as e:  # noqa: BLE001 — any peer failure
                    # degrades to the registry, never to the reader
                    reason = self._reason(e)
                    # A miss is an honest answer, not ill health.
                    self.router.record(addr, ok=isinstance(e, PeerMiss))
                    FETCH_FALLBACKS.labels(reason).inc()
                    sp.annotate(outcome=f"fallback:{reason}")
        return self.origin_fetch(offset, size)

    @staticmethod
    def _reason(e: Exception) -> str:
        if isinstance(e, PeerMiss):
            return "miss"
        msg = str(e).lower()
        if "timed out" in msg or "timeout" in msg:
            return "timeout"
        if "crc32" in msg:
            return "corrupt"
        return "error"


# ---------------------------------------------------------------------------
# Process wiring (cmd/snapshotter.py + daemon/server.py)
# ---------------------------------------------------------------------------

_default_lock = threading.Lock()
_default_export: Optional[PeerExport] = None
_default_router: Optional[PeerRouter] = None
_default_server: Optional[PeerChunkServer] = None
_default_resolved = False


def default_export() -> PeerExport:
    """The process-wide announce map local CachedBlobs register with."""
    global _default_export
    with _default_lock:
        if _default_export is None:
            _default_export = PeerExport()
        return _default_export


def _fleet_controller() -> str:
    """The controller UDS this process would register itself with —
    the same resolution fleet.register_self uses."""
    try:
        from nydus_snapshotter_tpu import fleet

        return fleet.resolve_fleet_config().controller
    except Exception:
        return os.environ.get("NTPU_FLEET_CONTROLLER", "")


def build_membership(cfg: PeerRuntimeConfig) -> Optional[PeerMembership]:
    """The dynamic membership view for this config, or None when
    ``[peer] membership`` resolves static (no controller under "auto",
    or "static" pinned)."""
    if cfg.membership == "static":
        return None
    controller = _fleet_controller()
    if not controller and cfg.membership != "fleet":
        return None
    return PeerMembership(
        seed=cfg.peers,
        controller=controller,
        refresh_secs=cfg.membership_refresh_s,
    )


def default_router() -> Optional[PeerRouter]:
    """The configured peer router, or None when the peer tier is off.
    Resolved once per process from env/``[peer]`` config. With dynamic
    membership configured, the router needs no static peer list — the
    fleet registry is the discovery source."""
    global _default_router, _default_resolved
    with _default_lock:
        if not _default_resolved:
            _default_resolved = True
            cfg = resolve_peer_config()
            if cfg.enable:
                membership = build_membership(cfg)
                if cfg.peers or membership is not None:
                    _default_router = PeerRouter(
                        cfg.peers,
                        self_address=cfg.listen,
                        region_bytes=cfg.region_bytes,
                        membership=membership,
                    )
        return _default_router


def start_from_config() -> Optional[PeerChunkServer]:
    """Start the chunk server when ``[peer]`` enables one (idempotent);
    returns the running server (caller stops it on shutdown)."""
    global _default_server
    cfg = resolve_peer_config()
    if not (cfg.enable and cfg.listen):
        return None
    with _default_lock:
        if _default_server is not None:
            return _default_server
    server = PeerChunkServer(
        default_export(), pull_through=cfg.pull_through, router=default_router()
    )
    server.run(cfg.listen)
    with _default_lock:
        _default_server = server
    # Fleet plane: a standalone peer-server process self-registers with
    # the controller so its metrics/traces federate. No-op when this
    # process already registered under another role (daemon/snapshotter):
    # one process is ONE member — one ring, one registry. Either way the
    # serve address is annotated on the member record, which is what the
    # controller's /api/v1/fleet/peers route (dynamic peer discovery)
    # lists for the cluster.
    from nydus_snapshotter_tpu import fleet

    fleet.register_self(
        "peer", server.address, extra={"peer_listen": server.address}
    )
    fleet.annotate_self("peer_listen", server.address)
    return server


def stop_default() -> None:
    global _default_server, _default_router, _default_resolved
    with _default_lock:
        server = _default_server
        _default_server = None
        _default_router = None
        _default_resolved = False
    if server is not None:
        server.stop()
