"""Peer chunk tier: nodes serve each other's lazy-read chunk fetches.

PR 3 made one node's cold reads fast; at cluster scale a new image deploy
makes thousands of nodes hammer the registry for the SAME chunks at the
same moment, so aggregate registry egress scales as N x unique bytes and
the storm collapses the origin. This module adds the second cache tier of
the registry -> peer -> local-cache waterfall:

- **PeerChunkServer** — every node serves ranged reads for extents its
  :class:`~nydus_snapshotter_tpu.daemon.blobcache.CachedBlob`\\ s already
  cover, over the same HTTP-over-UDS/TCP machinery the chunk-dict service
  uses (parallel/dict_service.py). With ``pull_through`` on, the REGION
  OWNER of a cold extent fetches it from the registry on behalf of the
  cluster — through its own CachedBlob, whose per-blob singleflight table
  collapses every concurrent peer request into one origin GET, so a chunk
  is fetched from origin at most ~once per cluster.
- **PeerRouter** — the peer-announce/lookup map: a static peer list from
  the ``[peer]`` config (no gossip protocol), rendezvous-hashed per
  ``(blob, region)`` so every node independently agrees which peer owns a
  region. Peers are scored through the process-wide
  :class:`~nydus_snapshotter_tpu.remote.mirror.HostHealthRegistry` —
  the same table the registry-mirror failover and the converter transport
  score through — so a dead peer goes on cooldown and the ranking walks
  to the next owner (or the origin) instead of timing out every read.
- **PeerAwareFetcher** — the planner's waterfall: each planned flight
  tries the healthy region owner first and falls back to the registry on
  miss / timeout / error / corrupt payload (CRC32-trailer verified), so a
  dead peer can never fail a read, only slow it by one bounded timeout.

Serving peers is the LOWEST QoS lane: the chunk server admits its bytes
through the node's :class:`~nydus_snapshotter_tpu.daemon.fetch_sched.
AdmissionGate` at PEER_SERVE priority, below local demand, readahead and
prefetch replay — a node under local pressure sheds peer traffic first
(requesters transparently fall back to the registry).

At planet scale the flat ring is not enough: racks × zones × regions
have wildly asymmetric link costs, and one slow peer can hold a demand
read's tail hostage. The **hierarchical read tier** makes the topology
explicit: every member carries a ``rack:zone:region`` locality label
(``[peer] locality`` / ``NTPU_PEER_LOCALITY``, advertised through the
membership records), and lookups walk a two-level rendezvous —

- **rack owner**: rendezvous over this node's rack members; the cheap
  hop, tried first;
- **zone shield**: rendezvous over the zone's members; the shield is
  the zone's single serve point against origin — with pull-through it
  fetches a forwarded cold extent ONCE per zone (the only node whose
  pull-through rule ignores the relay-depth bound), so a region's
  unique bytes cross the zone boundary exactly once;
- **origin**: the registry, reached only by the shield (or by a node
  whose tiers are all cooling down — health cooldowns walk past dead
  tiers immediately, never time out twice).

Zone shields double as caching proxies for the hot small artifacts
(soci indexes via ``/api/v1/peer/soci/*``, trained zdicts / dict
journal tails via ``/api/v1/peer/artifact/<kind>/<key>``): on a miss
the shield adopts the artifact from the flat owner once and re-serves
it zone-locally. Tail latency rides the fetch scheduler's
:class:`~nydus_snapshotter_tpu.daemon.fetch_sched.Hedger`: a flight
past its tier's rolling p99 races a hedged second request at the next
tier, loser cancelled by accounting (never a double charge, never a
double-fetch into the cache — the winner's bytes are the only bytes
delivered).

Failpoint sites ``peer.{serve,fetch,admit,member,hedge,tier}`` make
every boundary chaos-testable (docs/robustness.md); metrics land as
``ntpu_peer_*`` (per-tier egress under ``ntpu_peer_tier_egress_bytes``);
trace context rides the same ``x-ntpu-trace-*`` headers the dict service
uses, so a peer-served read's span tree spans both nodes.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import logging
import os
import socket
import socketserver
import threading
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from time import perf_counter
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

from nydus_snapshotter_tpu import failpoint, trace
from nydus_snapshotter_tpu.analysis import runtime as _an
from nydus_snapshotter_tpu.daemon import fetch_sched
from nydus_snapshotter_tpu.daemon.fetch_sched import PEER_SERVE
from nydus_snapshotter_tpu.metrics import registry as _metrics
from nydus_snapshotter_tpu.remote import mirror as mirror_mod

logger = logging.getLogger(__name__)

DEFAULT_REGION_KIB = 512
DEFAULT_TIMEOUT_MS = 1500
PEER_FAILURE_LIMIT = 3
PEER_COOLDOWN_SECS = 2.0
MAX_SERVE_BYTES = 64 << 20  # one ranged peer read, not a blob mirror

# Topology tiers, in link-cost order. "flat" is the pre-topology single
# rendezvous ring (no locality configured); "origin" is the registry
# fallthrough. TIER_COSTS is the score multiplier of the cost-aware
# ranking: the tier distance DOMINATES the rendezvous score, so a rack
# hop always outranks a zone hop and the numbers only matter relative
# to each other.
TIER_RACK = "rack"
TIER_ZONE = "zone"
TIER_FLAT = "flat"
TIER_ORIGIN = "origin"
TIER_COSTS = {TIER_RACK: 1.0, TIER_FLAT: 1.0, TIER_ZONE: 4.0}


def parse_locality(label: str) -> Optional[tuple[str, str, str]]:
    """``"rack:zone:region"`` → ``(rack, zone, region)``, or None for an
    empty/malformed label (a label-less member routes flat — topology is
    strictly opt-in, mixed fleets keep working)."""
    if not label:
        return None
    parts = [p.strip() for p in str(label).split(":")]
    if len(parts) != 3 or not all(parts):
        return None
    return parts[0], parts[1], parts[2]

_reg = _metrics.default_registry
SERVE_REQUESTS = _reg.register(
    _metrics.Counter(
        "ntpu_peer_serve_requests",
        "Ranged peer-read requests served by this node's chunk server,"
        " by outcome (hit / pull / miss / error)",
        ("outcome",),
    )
)
SERVED_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_peer_served_bytes",
        "Bytes this node served to cluster peers",
    )
)
FETCH_REQUESTS = _reg.register(
    _metrics.Counter(
        "ntpu_peer_fetch_requests",
        "Ranged reads this node attempted against a peer",
    )
)
FETCH_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_peer_fetch_bytes",
        "Bytes this node fetched from cluster peers instead of the registry",
    )
)
FETCH_FALLBACKS = _reg.register(
    _metrics.Counter(
        "ntpu_peer_fetch_fallbacks",
        "Peer reads that fell back to the registry, by reason"
        " (miss / timeout / error / corrupt)",
        ("reason",),
    )
)
SERVE_MS = _reg.register(
    _metrics.Histogram(
        "ntpu_peer_serve_duration_milliseconds",
        "Peer chunk-server request latency",
        ("outcome",),
    )
)
MEMBERSHIP_EPOCH = _reg.register(
    _metrics.Gauge(
        "ntpu_peer_membership_epoch",
        "Region-ownership epoch: bumps whenever the live peer set changes",
    )
)
MEMBERSHIP_PEERS = _reg.register(
    _metrics.Gauge(
        "ntpu_peer_membership_peers",
        "Peers in the current live membership view (incl. this node)",
    )
)
MEMBERSHIP_EVENTS = _reg.register(
    _metrics.Counter(
        "ntpu_peer_membership_events_total",
        "Peer membership transitions observed, by kind"
        " (join / leave / down / refresh_error)",
        ("kind",),
    )
)
TIER_EGRESS = _reg.register(
    _metrics.Counter(
        "ntpu_peer_tier_egress_bytes",
        "Peer-read bytes by the topology tier that served them"
        " (rack / zone / flat peer / origin fallthrough)",
        ("tier",),
    )
)


def snapshot_counters() -> dict:
    """Cumulative ``ntpu_peer_*`` values (tools delta these around runs)."""
    return {
        "serve_hit": SERVE_REQUESTS.value("hit"),
        "serve_pull": SERVE_REQUESTS.value("pull"),
        "serve_miss": SERVE_REQUESTS.value("miss"),
        "serve_error": SERVE_REQUESTS.value("error"),
        "served_bytes": SERVED_BYTES.value(),
        "fetch_requests": FETCH_REQUESTS.value(),
        "fetch_bytes": FETCH_BYTES.value(),
        "fallback_miss": FETCH_FALLBACKS.value("miss"),
        "fallback_timeout": FETCH_FALLBACKS.value("timeout"),
        "fallback_error": FETCH_FALLBACKS.value("error"),
        "fallback_corrupt": FETCH_FALLBACKS.value("corrupt"),
        "fallback_budget": FETCH_FALLBACKS.value("budget"),
        "tier_rack_bytes": TIER_EGRESS.value(TIER_RACK),
        "tier_zone_bytes": TIER_EGRESS.value(TIER_ZONE),
        "tier_flat_bytes": TIER_EGRESS.value(TIER_FLAT),
        "tier_origin_bytes": TIER_EGRESS.value(TIER_ORIGIN),
        **{
            f"hedge_{k}": v
            for k, v in fetch_sched.hedge_counters().items()
        },
    }


class PeerError(OSError):
    """A peer request failed (connection, protocol, or server error)."""


class PeerMiss(PeerError):
    """The peer does not cover the requested extent (HTTP 404)."""


# ---------------------------------------------------------------------------
# Config resolution (env > [peer] config > defaults)
# ---------------------------------------------------------------------------


class PeerRuntimeConfig:
    """Resolved ``[peer]`` knobs for this process."""

    __slots__ = (
        "enable", "listen", "peers", "region_bytes", "timeout_s",
        "pull_through", "membership", "membership_refresh_s",
        "locality", "hedge", "hedge_window", "tier_budgets",
    )

    def __init__(self, enable, listen, peers, region_bytes, timeout_s,
                 pull_through, membership="auto", membership_refresh_s=2.0,
                 locality="", hedge=True, hedge_window=0, tier_budgets=None):
        self.enable = enable
        self.listen = listen
        self.peers = peers
        self.region_bytes = region_bytes
        self.timeout_s = timeout_s
        self.pull_through = pull_through
        # "static" = the [peer] list only; "fleet" = the member registry
        # (seeded by the list); "auto" = fleet when a controller address
        # is known, static otherwise.
        self.membership = membership
        self.membership_refresh_s = membership_refresh_s
        # "rack:zone:region" label of THIS node ("" = flat routing).
        self.locality = locality
        self.hedge = hedge
        self.hedge_window = hedge_window
        # tier name -> in-flight byte cap (bytes, resolved from MiB).
        self.tier_budgets = dict(tier_budgets or {})


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name, "")
    if not v:
        return default
    return v not in ("0", "off", "false")


def _global_peer_config():
    try:
        from nydus_snapshotter_tpu.config import config as _cfg

        return _cfg.get_global_config().peer
    except Exception:
        return None


def resolve_peer_config() -> PeerRuntimeConfig:
    """env (``NTPU_PEER*``) > ``[peer]`` global config > defaults. Env
    overrides are also how the section reaches the spawned daemon
    processes, which have no global snapshotter config."""
    pc = _global_peer_config()
    peers_env = os.environ.get("NTPU_PEER_PEERS", "")
    if peers_env:
        peers = [p.strip() for p in peers_env.split(",") if p.strip()]
    else:
        peers = list(getattr(pc, "peers", None) or [])
    region_kib = fetch_sched._env_int(
        "NTPU_PEER_REGION_KIB",
        getattr(pc, "region_kib", 0) or DEFAULT_REGION_KIB,
    )
    timeout_ms = fetch_sched._env_int(
        "NTPU_PEER_TIMEOUT_MS",
        getattr(pc, "timeout_ms", 0) or DEFAULT_TIMEOUT_MS,
    )
    refresh_ms = fetch_sched._env_int(
        "NTPU_PEER_MEMBERSHIP_REFRESH_MS",
        int(float(getattr(pc, "membership_refresh_secs", 0) or 2.0) * 1000),
    )
    hedge_on, hedge_window = fetch_sched.resolve_hedge()
    return PeerRuntimeConfig(
        enable=_env_bool("NTPU_PEER_ENABLE", bool(getattr(pc, "enable", False))),
        listen=os.environ.get("NTPU_PEER_LISTEN", getattr(pc, "listen", "")),
        peers=peers,
        region_bytes=max(1, region_kib) << 10,
        timeout_s=max(1, timeout_ms) / 1000.0,
        pull_through=_env_bool(
            "NTPU_PEER_PULL_THROUGH", bool(getattr(pc, "pull_through", True))
        ),
        membership=os.environ.get(
            "NTPU_PEER_MEMBERSHIP", getattr(pc, "membership", "auto") or "auto"
        ),
        membership_refresh_s=max(0.05, refresh_ms / 1000.0),
        locality=os.environ.get(
            "NTPU_PEER_LOCALITY", getattr(pc, "locality", "") or ""
        ),
        hedge=hedge_on,
        hedge_window=hedge_window,
        tier_budgets=fetch_sched.resolve_tier_budgets(),
    )


def _normalize_addr(addr: str) -> str:
    """``uds:///run/x.sock`` / ``/run/x.sock`` / ``host:port`` — strip the
    scheme so an address compares equal however it was written."""
    if addr.startswith("uds://"):
        return addr[len("uds://"):]
    return addr


def _is_uds(addr: str) -> bool:
    return "/" in addr


# ---------------------------------------------------------------------------
# Local export map: which blobs this node can serve
# ---------------------------------------------------------------------------


class PeerExport:
    """blob_id -> live CachedBlob announce map for the local chunk server.

    The daemon registers every registry-backed CachedBlob it opens and
    unregisters on instance close; the server resolves requests against
    this map only (a blob nobody lazily reads here is a 404, never a
    registry fetch on a stranger's behalf)."""

    def __init__(self):
        self._mu = _an.make_lock("peer.export")
        # Lockset annotation: the blob map is only ever touched under
        # self._mu (NTPU_ANALYZE=1 verifies).
        self._blobs_shared = _an.shared("peer.export.blobs")
        self._blobs: dict[str, object] = {}
        # blob_id -> persisted soci index path this node can replicate
        # (checksummed on the wire by the requester's index load).
        self._soci: dict[str, str] = {}
        # Small hot artifacts beyond soci indexes (trained zdicts, dict
        # journal tails): (kind, key) -> file path. Zone shields adopt
        # remote ones into _adopted and re-serve them zone-locally.
        self._artifacts: dict[tuple[str, str], str] = {}
        self._adopted: dict[tuple[str, str], bytes] = {}

    def register(self, blob_id: str, cached_blob) -> None:
        with self._mu:
            self._blobs_shared.write()
            self._blobs[blob_id] = cached_blob

    def unregister(self, blob_id: str, cached_blob=None) -> None:
        """Drop the announce; with ``cached_blob`` given, only when the
        map still points at that instance (two instances of one blob:
        closing the first must not unannounce the survivor)."""
        with self._mu:
            self._blobs_shared.write()
            if cached_blob is None or self._blobs.get(blob_id) is cached_blob:
                self._blobs.pop(blob_id, None)

    def get(self, blob_id: str):
        with self._mu:
            self._blobs_shared.read()
            return self._blobs.get(blob_id)

    def register_soci(self, blob_id: str, index_path: str) -> None:
        """Announce a persisted soci index: peers missing one replicate
        it instead of re-pulling the whole layer to rebuild."""
        with self._mu:
            self._blobs_shared.write()
            self._soci[blob_id] = index_path

    def unregister_soci(self, blob_id: str) -> None:
        with self._mu:
            self._blobs_shared.write()
            self._soci.pop(blob_id, None)

    def soci_path(self, blob_id: str):
        with self._mu:
            self._blobs_shared.read()
            return self._soci.get(blob_id)

    # -- generic artifact plane (zdicts, journal tails, ...) -----------------

    def register_artifact(self, kind: str, key: str, path: str) -> None:
        """Announce a small persisted artifact (a trained zdict, a dict
        journal tail snapshot) under ``/api/v1/peer/artifact/kind/key``
        — the hierarchy's replication unit beyond chunk extents."""
        with self._mu:
            self._blobs_shared.write()
            self._artifacts[(kind, key)] = path

    def unregister_artifact(self, kind: str, key: str) -> None:
        with self._mu:
            self._blobs_shared.write()
            self._artifacts.pop((kind, key), None)

    def artifact_path(self, kind: str, key: str):
        with self._mu:
            self._blobs_shared.read()
            return self._artifacts.get((kind, key))

    def adopt_artifact(self, kind: str, key: str, payload: bytes) -> None:
        """Shield-adopted remote artifact, re-served from memory.
        Bounded count with oldest-first eviction — these are small hot
        artifacts, not a blob mirror."""
        with self._mu:
            self._blobs_shared.write()
            self._adopted[(kind, key)] = bytes(payload)
            while len(self._adopted) > 64:
                self._adopted.pop(next(iter(self._adopted)))

    def adopted_artifact(self, kind: str, key: str):
        with self._mu:
            self._blobs_shared.read()
            return self._adopted.get((kind, key))

    def stats(self) -> dict:
        with self._mu:
            self._blobs_shared.read()
            blobs = dict(self._blobs)
            soci = dict(self._soci)
            artifacts = sorted(self._artifacts)
            adopted = sorted(self._adopted)
        return {
            "blobs": {
                bid: {"covered_bytes": cb.coverage_bytes()}
                for bid, cb in blobs.items()
            },
            "soci_indexes": sorted(soci),
            "artifacts": [f"{k}/{key}" for k, key in artifacts],
            "adopted": [f"{k}/{key}" for k, key in adopted],
        }


# ---------------------------------------------------------------------------
# Chunk server (HTTP over UDS or TCP)
# ---------------------------------------------------------------------------


_BLOB_ROUTE = "/api/v1/peer/blob/"
_SOCI_ROUTE = "/api/v1/peer/soci/"
_ART_ROUTE = "/api/v1/peer/artifact/"
_STAT_ROUTE = "/api/v1/peer/stat"


def _crc32(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


class _UnixHTTPServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    # The default backlog of 5 overflows when a whole deploy storm's
    # worth of peers dials the region owner at once: excess connects
    # fail instead of queueing (same fix as the daemon API server).
    request_queue_size = 128

    def finish_request(self, request, client_address):
        self.RequestHandlerClass(request, ("uds", 0), self)


class _TCPHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    request_queue_size = 128


class PeerChunkServer:
    """Serves ranged chunk reads for locally cached extents to peers.

    ``handle()`` is transport-agnostic (the same split as DictService);
    ``run(address)`` serves on a UDS path (contains ``/``) or a TCP
    ``host:port``. Responses carry an ``x-ntpu-peer-crc32`` trailer header
    so a requester detects transit corruption and falls back to the
    registry instead of caching poisoned bytes.
    """

    def __init__(
        self,
        export: PeerExport,
        gate=None,
        pull_through: Optional[bool] = None,
        tenant: str = "peer",
        router: Optional["PeerRouter"] = None,
    ):
        cfg = resolve_peer_config()
        self.export = export
        self.gate = gate if gate is not None else fetch_sched.shared_gate()
        self.pull_through = (
            cfg.pull_through if pull_through is None else pull_through
        )
        self.tenant = tenant
        # Introspection only: the stat route surfaces this node's dynamic
        # membership view + admission actuation state (ntpuctl peers).
        self.router = router
        self._httpd = None
        self._closed = False
        self.address = ""

    # -- request handling ----------------------------------------------------

    def handle(self, method: str, path: str, headers) -> tuple[int, dict, bytes]:
        """(method, path?query, headers) -> (status, extra headers, body)."""
        parsed = urlparse(path)
        if parsed.path == _STAT_ROUTE:
            stat = self.export.stats()
            stat["admission"] = self.gate.lane_state()
            stat["tiers"] = self.gate.tier_state()
            stat["hedge"] = fetch_sched.hedge_counters()
            if self.router is not None:
                stat["topology"] = self.router.topology()
                if self.router.membership is not None:
                    stat["membership"] = self.router.membership.snapshot()
            body = json.dumps(stat).encode()
            return 200, {"Content-Type": "application/json"}, body
        if parsed.path == "/api/v1/traces":
            # A standalone peer server is a fleet member: its process's
            # span ring joins the cluster-merged trace (trace/aggregate.py).
            body = trace.chrome_trace_bytes()
            return 200, {"Content-Type": "application/json"}, body
        if parsed.path in ("/metrics", "/v1/metrics"):
            body = _reg.render().encode()
            return 200, {"Content-Type": "text/plain; version=0.0.4"}, body
        if parsed.path.startswith(_SOCI_ROUTE) and method == "GET":
            # Seekable-OCI index replication: serve the persisted,
            # checksummed artifact so one pod's first-pull build
            # amortizes across the fleet. The requester revalidates the
            # embedded SHA-256 before adopting (a corrupt relay costs a
            # local rebuild, never a poisoned read).
            blob_id = parsed.path[len(_SOCI_ROUTE):]
            return self._serve_artifact("soci", blob_id, headers)
        if parsed.path.startswith(_ART_ROUTE) and method == "GET":
            # Generic small-artifact replication (trained zdicts, dict
            # journal tail snapshots): same serve/adopt discipline as
            # soci indexes, keyed "<kind>/<key>".
            kind, _, key = parsed.path[len(_ART_ROUTE):].partition("/")
            if not kind or not key:
                return 400, {}, b'{"message": "bad artifact key"}'
            return self._serve_artifact(kind, key, headers)
        if not parsed.path.startswith(_BLOB_ROUTE) or method != "GET":
            return 404, {}, b'{"message": "no such endpoint"}'
        blob_id = parsed.path[len(_BLOB_ROUTE):]
        q = parse_qs(parsed.query)
        try:
            offset = int(q.get("offset", ["-1"])[0])
            size = int(q.get("size", ["0"])[0])
            depth = int(headers.get("x-ntpu-peer-depth", "0"))
        except ValueError:
            return 400, {}, b'{"message": "bad range"}'
        if offset < 0 or size <= 0 or size > MAX_SERVE_BYTES:
            return 400, {}, b'{"message": "bad range"}'
        try:
            tid = int(headers.get("x-ntpu-trace-id", "0"), 16)
            pid = int(headers.get("x-ntpu-parent-id", "0"), 16)
        except ValueError:
            tid = pid = 0
        t0 = perf_counter()
        outcome = "error"
        try:
            with trace.with_context(trace.remote_context(tid, pid)):
                with trace.span(
                    "peer.serve", blob=blob_id[:8], offset=offset, bytes=size
                ) as sp:
                    failpoint.hit("peer.serve")
                    cb = self.export.get(blob_id)
                    if cb is None:
                        outcome = "miss"
                        return 404, {}, b'{"message": "unknown blob"}'
                    covered = cb.covered(offset, size)
                    if not covered and not self.pull_through:
                        outcome = "miss"
                        return 404, {}, b'{"message": "extent not cached"}'
                    if not covered and depth > 0 and not (
                        self.router is not None
                        and self.router.is_shield(blob_id, offset)
                    ):
                        # Cover-only serving for forwarded requests —
                        # bounds the relay depth — EXCEPT at the zone
                        # shield, whose whole job is pulling a forwarded
                        # cold extent through origin once per zone.
                        outcome = "miss"
                        return 404, {}, b'{"message": "extent not cached"}'
                    if covered:
                        outcome = "hit"
                        # Serving cached bytes still consumes this node's
                        # uplink: admit it at the lowest lane.
                        self.gate.acquire(
                            size,
                            tenant=self.tenant,
                            lane=PEER_SERVE,
                            aborted=lambda: self._closed,
                        )
                        try:
                            data = cb.read_at(offset, size, lane=PEER_SERVE)
                        finally:
                            self.gate.release(
                                size, tenant=self.tenant, lane=PEER_SERVE
                            )
                    else:
                        # Pull-through: this node is the region owner —
                        # fetch once through the local CachedBlob (its
                        # singleflight table collapses the cluster's
                        # concurrent requests); the flight itself admits
                        # at PEER_SERVE lane.
                        outcome = "pull"
                        data = cb.read_at(offset, size, lane=PEER_SERVE)
                    sp.annotate(outcome=outcome)
                    SERVED_BYTES.inc(len(data))
                    return 200, {
                        "Content-Type": "application/octet-stream",
                        "x-ntpu-peer-crc32": f"{_crc32(data):08x}",
                        "x-ntpu-peer-outcome": outcome,
                    }, data
        except Exception as e:  # noqa: BLE001 - mapped to a wire status
            outcome = "error"
            logger.warning("peer serve %s[%d,+%d) failed: %s",
                           blob_id[:12], offset, size, e)
            return 500, {}, json.dumps({"message": str(e)}).encode()
        finally:
            SERVE_REQUESTS.labels(outcome).inc()
            SERVE_MS.labels(outcome).observe((perf_counter() - t0) * 1000.0)

    # -- artifact serving (soci indexes, zdicts, journal tails) --------------

    def _serve_artifact(
        self, kind: str, key: str, headers
    ) -> tuple[int, dict, bytes]:
        path = (
            self.export.soci_path(key)
            if kind == "soci"
            else self.export.artifact_path(kind, key)
        )
        body = None
        outcome = "hit"
        if path is not None:
            try:
                with open(path, "rb") as f:
                    body = f.read()
            except OSError as e:
                SERVE_REQUESTS.labels("error").inc()
                return 500, {}, json.dumps({"message": str(e)}).encode()
        if body is None:
            body = self.export.adopted_artifact(kind, key)
        if body is None:
            body = self._shield_adopt(
                kind, key, headers.get("x-ntpu-peer-depth", "0")
            )
            outcome = "pull"
        if body is None:
            SERVE_REQUESTS.labels("miss").inc()
            return 404, {}, b'{"message": "no such artifact"}'
        SERVE_REQUESTS.labels(outcome).inc()
        SERVED_BYTES.inc(len(body))
        return 200, {
            "Content-Type": "application/octet-stream",
            "x-ntpu-peer-crc32": f"{_crc32(body):08x}",
        }, body

    def _shield_adopt(self, kind: str, key: str, depth) -> Optional[bytes]:
        """Zone-shield caching proxy: on an artifact miss the shield
        pulls it ONCE from the flat owner (the topology-blind rendezvous
        owner — where the artifact was first built and persisted),
        adopts it, and re-serves it zone-locally — replicate down the
        hierarchy instead of re-deriving per zone. Best-effort: any
        failure is a plain miss (the requester rebuilds locally), and a
        forwarded (depth > 0) request never adopts, which bounds the
        relay exactly like chunk serving."""
        try:
            if int(depth) > 0:
                return None
        except (TypeError, ValueError):
            return None
        if (
            not self.pull_through
            or self.router is None
            or not self.router.is_shield(key, 0)
        ):
            return None
        owner = self.router.flat_owner(key)
        if owner is None:
            return None
        try:
            client = PeerClient(owner, resolve_peer_config().timeout_s)
            if kind == "soci":
                body = client.fetch_soci_index(key, depth=1)
            else:
                body = client.fetch_artifact(kind, key, depth=1)
        except PeerError:
            return None
        self.export.adopt_artifact(kind, key, body)
        return body

    # -- server lifecycle ----------------------------------------------------

    def run(self, address: str) -> None:
        """Serve on ``address``: a UDS path or ``host:port``."""
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                status, extra, payload = server.handle(
                    self.command, self.path, self.headers
                )
                self.send_response(status)
                if "Content-Type" not in extra:
                    self.send_header("Content-Type", "application/json")
                for k, v in extra.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        addr = _normalize_addr(address)
        if _is_uds(addr):
            os.makedirs(os.path.dirname(addr) or ".", exist_ok=True)
            try:
                os.remove(addr)
            except FileNotFoundError:
                pass
            self._httpd = _UnixHTTPServer(addr, Handler)
        else:
            host, _, port = addr.rpartition(":")
            self._httpd = _TCPHTTPServer((host or "0.0.0.0", int(port)), Handler)
        self.address = addr
        threading.Thread(
            target=self._httpd.serve_forever, name="ntpu-peer-serve", daemon=True
        ).start()
        logger.info("peer chunk server on %s", addr)

    def stop(self) -> None:
        self._closed = True
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self.address and _is_uds(self.address):
            try:
                os.remove(self.address)
            except OSError:
                pass
        self.address = ""


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class _UDSHTTPConnection(http.client.HTTPConnection):
    def __init__(self, sock_path: str, timeout: float):
        super().__init__("localhost", timeout=timeout)
        self._sock_path = sock_path

    def connect(self) -> None:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        try:
            s.connect(self._sock_path)
        except BaseException:
            # A dead peer must not leak the half-made socket (close()
            # only knows about self.sock once the connect succeeded).
            s.close()
            raise
        self.sock = s


class PeerClient:
    """One ranged read against one peer. Connections are per-call (peer
    reads fan out across fetch workers; a UDS/TCP dial is cheap next to
    the range it carries) and every phase is bounded by ``timeout_s``."""

    def __init__(self, address: str, timeout_s: float = DEFAULT_TIMEOUT_MS / 1000.0):
        self.address = _normalize_addr(address)
        self.timeout_s = timeout_s

    def _connect(self) -> http.client.HTTPConnection:
        if _is_uds(self.address):
            return _UDSHTTPConnection(self.address, self.timeout_s)
        host, _, port = self.address.rpartition(":")
        return http.client.HTTPConnection(
            host or "localhost", int(port), timeout=self.timeout_s
        )

    def read_range(
        self, blob_id: str, offset: int, size: int, depth: int = 0
    ) -> bytes:
        """Bytes of ``blob_id[offset, offset+size)`` from this peer.
        Raises :class:`PeerMiss` when the peer doesn't cover the extent,
        :class:`PeerError` on any transport/server/integrity failure."""
        headers = {"x-ntpu-peer-depth": str(depth)}
        ctx = trace.capture()
        if ctx is not None and ctx.sampled:
            headers["x-ntpu-trace-id"] = f"{ctx.trace_id:x}"
            headers["x-ntpu-parent-id"] = f"{ctx.span_id:x}"
        conn = self._connect()
        try:
            conn.request(
                "GET",
                f"{_BLOB_ROUTE}{blob_id}?offset={offset}&size={size}",
                headers=headers,
            )
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status == 404:
                raise PeerMiss(f"peer {self.address} misses {blob_id}[{offset})")
            if resp.status != 200:
                raise PeerError(
                    f"peer {self.address} -> {resp.status}: {payload[:120]!r}"
                )
            want_crc = resp.headers.get("x-ntpu-peer-crc32", "")
        except (http.client.HTTPException, OSError) as e:
            if isinstance(e, PeerError):
                raise
            raise PeerError(f"peer {self.address} request failed: {e}") from e
        finally:
            conn.close()
        if len(payload) != size:
            raise PeerError(
                f"peer {self.address} returned {len(payload)} bytes, wanted {size}"
            )
        # Deliberately NOT the server's _crc32 helper: the two sides must
        # compute independently for the check to mean anything (tests
        # inject corruption by patching the server-side helper).
        if want_crc and f"{zlib.crc32(payload) & 0xFFFFFFFF:08x}" != want_crc:
            raise PeerError(f"peer {self.address} payload failed CRC32 check")
        return payload

    def fetch_soci_index(self, blob_id: str, depth: int = 0) -> bytes:
        """The peer's persisted soci index artifact for ``blob_id``
        (serialized; the caller revalidates its embedded checksum).
        Raises :class:`PeerMiss`/:class:`PeerError` like ``read_range``."""
        return self._fetch_checked(f"{_SOCI_ROUTE}{blob_id}", depth)

    def fetch_artifact(self, kind: str, key: str, depth: int = 0) -> bytes:
        """A small named artifact (``zdict``, ``journal``, ...) from the
        peer's export — the zone-shield replication unit beyond chunk
        extents. Raises :class:`PeerMiss`/:class:`PeerError`."""
        return self._fetch_checked(f"{_ART_ROUTE}{kind}/{key}", depth)

    def _fetch_checked(self, route: str, depth: int) -> bytes:
        conn = self._connect()
        try:
            conn.request(
                "GET", route, headers={"x-ntpu-peer-depth": str(depth)}
            )
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status == 404:
                raise PeerMiss(f"peer {self.address} misses {route}")
            if resp.status != 200:
                raise PeerError(
                    f"peer {self.address} -> {resp.status}: {payload[:120]!r}"
                )
            want_crc = resp.headers.get("x-ntpu-peer-crc32", "")
        except (http.client.HTTPException, OSError) as e:
            if isinstance(e, PeerError):
                raise
            raise PeerError(f"peer {self.address} request failed: {e}") from e
        finally:
            conn.close()
        if want_crc and f"{zlib.crc32(payload) & 0xFFFFFFFF:08x}" != want_crc:
            raise PeerError(f"peer {self.address} artifact failed CRC32 check")
        return payload

    def stat(self) -> dict:
        conn = self._connect()
        try:
            conn.request("GET", _STAT_ROUTE)
            resp = conn.getresponse()
            payload = resp.read()
        except (http.client.HTTPException, OSError) as e:
            raise PeerError(f"peer {self.address} stat failed: {e}") from e
        finally:
            conn.close()
        if resp.status != 200:
            raise PeerError(f"peer {self.address} stat -> {resp.status}")
        return json.loads(payload)


# ---------------------------------------------------------------------------
# Dynamic membership: the fleet registry as the peer discovery source
# ---------------------------------------------------------------------------


class PeerMembership:
    """Live peer-address view driven by the fleet member registry.

    The static ``[peer] peers`` list is kept as the SEED: it is the
    membership whenever the registry is unreachable or empty (fresh
    cluster, controller restarting), so a config-only deployment keeps
    working unchanged. With a reachable controller, the registry IS the
    membership — peers joining (self-registering) and leaving
    (deregistering) re-shape rendezvous region ownership without a config
    edit, and members the fleet plane flags down/stale are pushed onto
    the shared :class:`~nydus_snapshotter_tpu.remote.mirror.
    HostHealthRegistry` cooldown so routing walks past them immediately.

    ``fetch`` returns ``[{"address", "up", "stale"}, ...]`` rows; the
    default implementation pulls the controller's
    ``/api/v1/fleet/peers`` route. Refreshes are rate-limited to
    ``refresh_secs`` and serialized (concurrent callers reuse the cached
    view); a failing refresh keeps the last-good membership — discovery
    outages degrade to a stale view, never to an empty cluster.
    """

    def __init__(
        self,
        seed: Optional[list] = None,
        controller: str = "",
        fetch=None,
        refresh_secs: float = 2.0,
        clock=None,
        health_registry=None,
        stale_cooldown: float = PEER_COOLDOWN_SECS,
    ):
        from time import monotonic

        self.seed = sorted(
            {a for a in (_normalize_addr(p) for p in (seed or [])) if a}
        )
        self.controller = controller
        self._fetch = fetch if fetch is not None else self._fetch_controller
        self.refresh_secs = max(0.0, float(refresh_secs))
        self._clock = clock or monotonic
        self._health = (
            health_registry
            if health_registry is not None
            else mirror_mod.global_health_registry()
        )
        self.stale_cooldown = float(stale_cooldown)
        self._mu = _an.make_lock("peer.membership")
        # Lockset annotation: the live view + event log only mutate under
        # self._mu (the refresh fetch itself runs outside it).
        self._view_shared = _an.shared("peer.membership.view")
        self._live: list[str] = list(self.seed)
        self._epoch = 0
        self._events: list[dict] = []
        self._last_refresh = float("-inf")
        self._last_error = ""
        self._refreshing = False
        # address -> member name from the last registry listing, and the
        # rate limiter for upward health reports (report_down).
        self._names: dict[str, str] = {}
        self._reported: dict[str, float] = {}
        # address -> "rack:zone:region" locality labels advertised on
        # the member records — the topology source for tiered routing.
        self._localities: dict[str, str] = {}

    def _fetch_controller(self) -> list[dict]:
        if not self.controller:
            return []
        from nydus_snapshotter_tpu.utils import udshttp

        rows = udshttp.get_json(
            self.controller, "/api/v1/fleet/peers", timeout=2.0
        )
        return rows if isinstance(rows, list) else []

    def _maybe_refresh(self) -> None:
        now = self._clock()
        with self._mu:
            self._view_shared.read()
            if now - self._last_refresh < self.refresh_secs or self._refreshing:
                return
            self._refreshing = True
        rows: Optional[list] = None
        err = ""
        try:
            failpoint.hit("peer.member")
            rows = self._fetch()
        except Exception as e:  # noqa: BLE001 — keep the last-good view
            err = str(e)
            MEMBERSHIP_EVENTS.labels("refresh_error").inc()
        down: list[str] = []
        live: Optional[list[str]] = None
        if rows is not None:
            addrs = set()
            names: dict[str, str] = {}
            locs: dict[str, str] = {}
            for r in rows:
                addr = _normalize_addr(str(r.get("address", "")))
                if not addr:
                    continue
                if r.get("name"):
                    names[addr] = str(r["name"])
                if r.get("locality"):
                    locs[addr] = str(r["locality"])
                if r.get("up", True) and not r.get("stale", False):
                    addrs.add(addr)
                else:
                    # Crashed-but-registered: keep it OUT of the live set
                    # (its regions re-own immediately) and cool it down in
                    # the shared health table so an in-flight route walks
                    # past it instead of timing out.
                    down.append(addr)
            # Registry empty (or only down members) => the seed list is
            # the fallback floor, exactly the pre-dynamic behavior.
            live = sorted(addrs) if addrs else list(self.seed)
        for addr in down:
            self._health.health_for(
                addr,
                failure_limit=PEER_FAILURE_LIMIT,
                cooldown=PEER_COOLDOWN_SECS,
            ).mark_down(self.stale_cooldown)
            MEMBERSHIP_EVENTS.labels("down").inc()
        with self._mu:
            self._view_shared.write()
            self._refreshing = False
            self._last_refresh = now
            self._last_error = err
            if rows is not None:
                self._names.update(names)
                self._localities.update(locs)
            if live is not None and live != self._live:
                prev = set(self._live)
                cur = set(live)
                for addr in sorted(cur - prev):
                    self._events.append(
                        {"at": now, "kind": "join", "address": addr}
                    )
                    MEMBERSHIP_EVENTS.labels("join").inc()
                for addr in sorted(prev - cur):
                    self._events.append(
                        {"at": now, "kind": "leave", "address": addr}
                    )
                    MEMBERSHIP_EVENTS.labels("leave").inc()
                del self._events[:-64]
                self._live = live
                self._epoch += 1
                MEMBERSHIP_EPOCH.set(self._epoch)
            MEMBERSHIP_PEERS.set(len(self._live))

    def addresses(self) -> list[str]:
        """The current live peer set (refreshing if the view is stale)."""
        self._maybe_refresh()
        with self._mu:
            self._view_shared.read()
            return list(self._live)

    def localities(self) -> dict[str, str]:
        """address -> advertised ``rack:zone:region`` label, from the
        last registry listing (no refresh of its own: callers pair this
        with :meth:`addresses`, which refreshes)."""
        with self._mu:
            self._view_shared.read()
            return dict(self._localities)

    def report_down(self, address: str, source: str = "peer-router") -> bool:
        """Upward health signal: a peer at ``address`` stopped answering
        (its health cooldown tripped). Resolves the member name from the
        last registry listing and posts it to the controller's
        ``/api/v1/fleet/placement/report`` — the dict-HA placement plane
        promotes around a reported-down member without waiting out
        scrape staleness. Rate-limited per address; best-effort (the
        report rides a background thread, a down controller drops it)."""
        addr = _normalize_addr(address)
        now = self._clock()
        with self._mu:
            self._view_shared.write()
            name = self._names.get(addr, "")
            if not self.controller or not name:
                return False
            last = self._reported.get(addr, float("-inf"))
            if now - last < self.stale_cooldown:
                return False
            self._reported[addr] = now
        controller = self.controller

        def push():
            from nydus_snapshotter_tpu.utils import udshttp

            try:
                udshttp.post_json(
                    controller,
                    "/api/v1/fleet/placement/report",
                    {"name": name, "source": source},
                    timeout=2.0,
                )
                MEMBERSHIP_EVENTS.labels("report_down").inc()
            except Exception:  # noqa: BLE001 — best-effort signal
                pass

        threading.Thread(
            target=push, name="ntpu-peer-report-down", daemon=True
        ).start()
        return True

    @property
    def epoch(self) -> int:
        with self._mu:
            self._view_shared.read()
            return self._epoch

    def snapshot(self) -> dict:
        with self._mu:
            self._view_shared.read()
            return {
                "epoch": self._epoch,
                "peers": list(self._live),
                "seed": list(self.seed),
                "events": [dict(e) for e in self._events[-16:]],
                "localities": dict(self._localities),
                "last_error": self._last_error,
                "controller": self.controller,
            }


# ---------------------------------------------------------------------------
# Router: which peer owns which region
# ---------------------------------------------------------------------------


class PeerRouter:
    """Rendezvous region ownership over a (possibly dynamic) peer set.

    Every node, given the same peer set, independently computes the same
    owner for a ``(blob, region)`` — the lookup map that needs no gossip.
    The set comes from the static ``[peer]`` list, or — with a
    :class:`PeerMembership` attached — from the live fleet registry, so
    autoscaling re-shapes ownership with minimal churn: rendezvous
    hashing moves only the ~K/n regions the joining/leaving peer wins or
    owned (property-tested in tests/test_peer_membership.py). Ownership
    walks the rendezvous ranking past unhealthy peers (cooldown via the
    process-wide HostHealthRegistry), and returns None when this node
    itself ranks first (fetch from origin: we ARE the serve point for
    this region).

    With a ``locality`` label (``rack:zone:region``) the flat ring
    becomes a two-level hierarchy (:meth:`routes`): the rack-local
    rendezvous owner is the cheap first hop, the zone's shield owner the
    second, origin the last — and role-based cycle avoidance bounds
    relays (the shield itself goes straight to origin; a rack owner
    routes only upward to the shield). Members without a locality, or
    in a foreign region, never own our tiers; a node with no locality
    of its own keeps the flat single-ring behavior unchanged.
    """

    def __init__(
        self,
        peers: list[str],
        self_address: str = "",
        region_bytes: int = DEFAULT_REGION_KIB << 10,
        health_registry=None,
        membership: Optional[PeerMembership] = None,
        locality: str = "",
        localities: Optional[dict[str, str]] = None,
    ):
        self.self_address = _normalize_addr(self_address)
        self.peers = [
            a for a in (_normalize_addr(p) for p in peers) if a
        ]
        self.region_bytes = max(1, int(region_bytes))
        self.membership = membership
        self.health = (
            health_registry
            if health_registry is not None
            else mirror_mod.global_health_registry()
        )
        self.locality = str(locality or "")
        self._loc = parse_locality(self.locality)
        # Static address -> locality map (tests, storm tooling); the
        # membership's advertised labels overlay it when attached.
        self.localities = {
            _normalize_addr(a): str(l)
            for a, l in (localities or {}).items()
        }

    @staticmethod
    def _score(addr: str, blob_id: str, region: int) -> int:
        h = hashlib.blake2b(
            f"{addr}|{blob_id}|{region}".encode(), digest_size=8
        )
        return int.from_bytes(h.digest(), "little")

    def current_peers(self) -> list[str]:
        """The peer set ownership hashes over right now: the live
        membership view when one is attached, else the static list."""
        if self.membership is not None:
            return self.membership.addresses()
        return list(self.peers)

    def ranked(self, blob_id: str, offset: int) -> list[str]:
        region = offset // self.region_bytes
        members = set(self.current_peers())
        if self.self_address:
            members.add(self.self_address)
        return sorted(
            members,
            key=lambda a: self._score(a, blob_id, region),
            reverse=True,
        )

    def _available(self, addr: str) -> bool:
        return self.health.health_for(
            addr,
            failure_limit=PEER_FAILURE_LIMIT,
            cooldown=PEER_COOLDOWN_SECS,
        ).available()

    def locality_map(self) -> dict[str, str]:
        """address -> locality label: static map overlaid by the
        membership's advertised labels, plus this node's own."""
        out = dict(self.localities)
        if self.membership is not None:
            out.update(self.membership.localities())
        if self.self_address and self.locality:
            out[self.self_address] = self.locality
        return out

    def _tier_sets(self, members: set, locs: dict) -> tuple[list, list]:
        """(rack members, zone members) sharing this node's locality
        coordinates; foreign/unknown localities own no tier of ours."""
        mine = self._loc
        rack: list[str] = []
        zone: list[str] = []
        for a in members:
            loc = parse_locality(locs.get(a, ""))
            if loc is None or loc[2] != mine[2] or loc[1] != mine[1]:
                continue
            zone.append(a)
            if loc[0] == mine[0]:
                rack.append(a)
        return rack, zone

    def routes(self, blob_id: str, offset: int) -> list[tuple[str, str]]:
        """The tier waterfall for this extent: healthy ``(addr, tier)``
        candidates in cost order — the rack-local owner, then the zone's
        shield owner; ``[]`` = fetch from origin. Without a locality
        this is the flat single-owner route.

        The ranking is cost-aware: tier distance dominates the
        rendezvous score (TIER_COSTS — a rack hop always outranks a zone
        hop), and cooled-down candidates are dropped HERE, so a dead
        rack owner walks to the shield immediately instead of timing out
        first. Role-based cycle avoidance bounds relays: the shield
        itself returns ``[]`` (it IS the zone's serve point against
        origin), and the rack owner routes only upward to the shield."""
        if self._loc is None:
            addr = self._flat_route(blob_id, offset)
            return [(addr, TIER_FLAT)] if addr is not None else []
        region = offset // self.region_bytes
        members = set(self.current_peers())
        if self.self_address:
            members.add(self.self_address)
        rack, zone = self._tier_sets(members, self.locality_map())

        def score(a: str) -> int:
            return self._score(a, blob_id, region)

        shield = max(zone, key=score) if zone else None
        if self.self_address and shield == self.self_address:
            return []  # we ARE the zone shield: pull from origin
        rack_owner = max(rack, key=score) if rack else None
        out: list[tuple[str, str]] = []
        if rack_owner is not None and rack_owner != self.self_address:
            out.append((rack_owner, TIER_RACK))
        if shield is not None and shield != rack_owner:
            out.append((shield, TIER_ZONE))
        out.sort(key=lambda at: (TIER_COSTS.get(at[1], 9.0), -score(at[0])))
        return [(a, t) for a, t in out if self._available(a)]

    def is_shield(self, blob_id: str, offset: int) -> bool:
        """Is THIS node the zone's shield owner for the extent's region?
        Shield-ness widens the server's pull-through rule: a shield may
        fetch a forwarded (depth > 0) cold extent from origin on the
        zone's behalf — the point where a region's unique bytes cross
        the zone boundary exactly once."""
        if self._loc is None or not self.self_address:
            return False
        region = offset // self.region_bytes
        members = set(self.current_peers())
        members.add(self.self_address)
        _, zone = self._tier_sets(members, self.locality_map())
        if not zone:
            return False
        return (
            max(zone, key=lambda a: self._score(a, blob_id, region))
            == self.self_address
        )

    def flat_owner(self, blob_id: str, offset: int = 0) -> Optional[str]:
        """The flat (topology-blind) healthy owner, excluding self —
        where a cluster-wide artifact (soci index, trained zdict) lives
        before zone shields adopt it."""
        for addr in self.ranked(blob_id, offset):
            if addr == self.self_address:
                continue
            if self._available(addr):
                return addr
        return None

    def topology(self, sample_regions: int = 64) -> dict:
        """Introspection for ``ntpuctl peers``: this node's locality,
        per-tier member counts, and its shield-ownership share over a
        deterministic synthetic region sample."""
        locs = self.locality_map()
        members = set(self.current_peers())
        if self.self_address:
            members.add(self.self_address)
        mine = self._loc
        counts = {"rack": 0, "zone": 0, "region": 0, "remote": 0, "flat": 0}
        racks: set = set()
        zones: set = set()
        for a in members:
            loc = parse_locality(locs.get(a, ""))
            if loc is None:
                counts["flat"] += 1
                continue
            racks.add((loc[2], loc[1], loc[0]))
            zones.add((loc[2], loc[1]))
            if mine is None or loc[2] != mine[2]:
                counts["remote"] += 1
            elif loc[1] != mine[1]:
                counts["region"] += 1
            elif loc[0] != mine[0]:
                counts["zone"] += 1
            else:
                counts["rack"] += 1
        shielded = sum(
            1
            for r in range(max(0, int(sample_regions)))
            if self.is_shield("_topology", r * self.region_bytes)
        )
        return {
            "locality": self.locality,
            "members": len(members),
            "tiers": counts,
            "racks": len(racks),
            "zones": len(zones),
            "shield_share": (
                round(shielded / sample_regions, 3) if sample_regions else 0.0
            ),
        }

    def route(self, blob_id: str, offset: int) -> Optional[str]:
        """The healthy peer to ask FIRST for this extent, or None for
        the registry — the head of the tier waterfall when a locality is
        configured, the flat rendezvous owner otherwise."""
        if self._loc is not None:
            tiers = self.routes(blob_id, offset)
            return tiers[0][0] if tiers else None
        return self._flat_route(blob_id, offset)

    def _flat_route(self, blob_id: str, offset: int) -> Optional[str]:
        for addr in self.ranked(blob_id, offset):
            if addr == self.self_address:
                return None
            if self._available(addr):
                return addr
        return None

    def record(self, addr: str, ok: bool) -> None:
        h = self.health.health_for(
            addr, failure_limit=PEER_FAILURE_LIMIT, cooldown=PEER_COOLDOWN_SECS
        )
        if ok:
            h.record_success()
        else:
            h.record_failure()
            if self.membership is not None and not h.available():
                # Cooldown tripped: this node just WATCHED the member
                # fail repeatedly — tell the controller so the dict-HA
                # plane can promote around it before scrape staleness.
                self.membership.report_down(addr)


# ---------------------------------------------------------------------------
# The waterfall: registry -> peer -> local cache
# ---------------------------------------------------------------------------


class PeerAwareFetcher:
    """Wraps a blob's origin ``fetch_range`` with the peer tier.

    Drop-in for the callable CachedBlob takes: the fetch scheduler's
    flights call ``read_range`` concurrently, each flight walking the
    extent's tier waterfall (rack owner → zone shield → origin; flat
    single owner without topology) and falling back a tier on miss /
    timeout / error / corrupt payload / full tier budget — so a dead,
    slow or melting tier never fails a read (chaos-pinned via the
    ``peer.fetch`` and ``peer.tier`` sites).

    With a :class:`~nydus_snapshotter_tpu.daemon.fetch_sched.Hedger`
    attached, a flight past its tier's rolling p99 races a hedged
    second request at the NEXT tier; the hedge admits and releases its
    own gate charge (loser cancellation, never a double charge) and
    only the winner's bytes are returned — a hedge can never
    double-fetch into the cache. With an
    :class:`~nydus_snapshotter_tpu.daemon.fetch_sched.AdmissionGate`
    attached, per-tier in-flight byte budgets bound how much demand a
    melting tier can absorb before the waterfall walks on.
    """

    def __init__(
        self,
        blob_id: str,
        origin_fetch: Callable[[int, int], bytes],
        router: PeerRouter,
        timeout_s: float = 0.0,
        hedger=None,
        gate=None,
        tenant: str = fetch_sched.DEFAULT_TENANT,
    ):
        self.blob_id = blob_id
        self.origin_fetch = origin_fetch
        self.router = router
        self.timeout_s = timeout_s or resolve_peer_config().timeout_s
        self.hedger = hedger
        self.gate = gate
        self.tenant = tenant

    def _peer_read(self, addr: str, tier: str, offset: int, size: int):
        depth = 1 if tier == TIER_ZONE else 0

        def fetch() -> bytes:
            return PeerClient(addr, self.timeout_s).read_range(
                self.blob_id, offset, size, depth=depth
            )

        return fetch

    def _hedge_target(self, rest, offset: int, size: int):
        """(tier, fn) for the hedged second request: the next tier of
        the waterfall, else origin."""
        for addr, tier in rest:
            return tier, self._peer_read(addr, tier, offset, size)
        return TIER_ORIGIN, lambda: self.origin_fetch(offset, size)

    def _record_hedge_loss(self, offset: int):
        """on_loser callback for the hedger: a cancelled-by-accounting
        loser's bytes enter the provenance ledger as pure waste (they
        crossed the network but were never delivered to any cache)."""
        from nydus_snapshotter_tpu.provenance import ledger as provenance

        def on_loser(loser_tier: str, nbytes: int) -> None:
            provenance.record_hedge_loss(
                self.blob_id, offset, nbytes, tier=loser_tier
            )

        return on_loser

    def read_range(self, offset: int, size: int) -> bytes:
        tiers = self.router.routes(self.blob_id, offset)
        for i, (addr, tier) in enumerate(tiers):
            data = self._attempt(addr, tier, tiers[i + 1:], offset, size)
            if data is not None:
                return data
        TIER_EGRESS.labels(TIER_ORIGIN).inc(size)
        return self.origin_fetch(offset, size)

    def _attempt(
        self, addr: str, tier: str, rest, offset: int, size: int
    ) -> Optional[bytes]:
        """One tier of the waterfall; None = walk to the next tier."""
        if self.gate is not None and not self.gate.tier_acquire(tier, size):
            # Tier budget full (melting zone): walk on immediately —
            # rack-local service never queues behind a saturated tier.
            FETCH_FALLBACKS.labels("budget").inc()
            return None
        try:
            FETCH_REQUESTS.inc()
            with trace.span(
                "peer.fetch",
                blob=self.blob_id[:8],
                peer=addr,
                tier=tier,
                offset=offset,
                bytes=size,
            ) as sp:
                try:
                    failpoint.hit("peer.tier")
                    failpoint.hit("peer.fetch")
                    primary = self._peer_read(addr, tier, offset, size)
                    if self.hedger is not None:
                        hedge_tier, hedge_fn = self._hedge_target(
                            rest, offset, size
                        )
                        data, winner = self.hedger.fetch(
                            size,
                            tier,
                            primary,
                            hedge_tier,
                            hedge_fn,
                            tenant=self.tenant,
                            on_loser=self._record_hedge_loss(offset),
                        )
                    else:
                        data, winner = primary(), tier
                    self.router.record(addr, ok=True)
                    if winner != TIER_ORIGIN:
                        FETCH_BYTES.inc(size)
                    TIER_EGRESS.labels(winner).inc(size)
                    # Provenance: the delivery hook on this same worker
                    # thread attributes these bytes to the serving tier.
                    fetch_sched.fetch_note("tier", winner)
                    sp.annotate(outcome="hit", tier=winner)
                    return data
                except Exception as e:  # noqa: BLE001 — any peer failure
                    # degrades to the next tier / registry, never to the
                    # reader
                    reason = self._reason(e)
                    # A miss is an honest answer, not ill health.
                    self.router.record(addr, ok=isinstance(e, PeerMiss))
                    FETCH_FALLBACKS.labels(reason).inc()
                    sp.annotate(outcome=f"fallback:{reason}")
                    return None
        finally:
            if self.gate is not None:
                self.gate.tier_release(tier, size)

    @staticmethod
    def _reason(e: Exception) -> str:
        if isinstance(e, PeerMiss):
            return "miss"
        msg = str(e).lower()
        if "timed out" in msg or "timeout" in msg:
            return "timeout"
        if "crc32" in msg:
            return "corrupt"
        return "error"


# ---------------------------------------------------------------------------
# Process wiring (cmd/snapshotter.py + daemon/server.py)
# ---------------------------------------------------------------------------

_default_lock = threading.Lock()
_default_export: Optional[PeerExport] = None
_default_router: Optional[PeerRouter] = None
_default_server: Optional[PeerChunkServer] = None
_default_resolved = False


def default_export() -> PeerExport:
    """The process-wide announce map local CachedBlobs register with."""
    global _default_export
    with _default_lock:
        if _default_export is None:
            _default_export = PeerExport()
        return _default_export


def _fleet_controller() -> str:
    """The controller UDS this process would register itself with —
    the same resolution fleet.register_self uses."""
    try:
        from nydus_snapshotter_tpu import fleet

        return fleet.resolve_fleet_config().controller
    except Exception:
        return os.environ.get("NTPU_FLEET_CONTROLLER", "")


def build_membership(cfg: PeerRuntimeConfig) -> Optional[PeerMembership]:
    """The dynamic membership view for this config, or None when
    ``[peer] membership`` resolves static (no controller under "auto",
    or "static" pinned)."""
    if cfg.membership == "static":
        return None
    controller = _fleet_controller()
    if not controller and cfg.membership != "fleet":
        return None
    return PeerMembership(
        seed=cfg.peers,
        controller=controller,
        refresh_secs=cfg.membership_refresh_s,
    )


def default_router() -> Optional[PeerRouter]:
    """The configured peer router, or None when the peer tier is off.
    Resolved once per process from env/``[peer]`` config. With dynamic
    membership configured, the router needs no static peer list — the
    fleet registry is the discovery source."""
    global _default_router, _default_resolved
    with _default_lock:
        if not _default_resolved:
            _default_resolved = True
            cfg = resolve_peer_config()
            if cfg.enable:
                membership = build_membership(cfg)
                if cfg.peers or membership is not None:
                    _default_router = PeerRouter(
                        cfg.peers,
                        self_address=cfg.listen,
                        region_bytes=cfg.region_bytes,
                        membership=membership,
                        locality=cfg.locality,
                    )
        return _default_router


def start_from_config() -> Optional[PeerChunkServer]:
    """Start the chunk server when ``[peer]`` enables one (idempotent);
    returns the running server (caller stops it on shutdown)."""
    global _default_server
    cfg = resolve_peer_config()
    if not (cfg.enable and cfg.listen):
        return None
    with _default_lock:
        if _default_server is not None:
            return _default_server
    server = PeerChunkServer(
        default_export(), pull_through=cfg.pull_through, router=default_router()
    )
    server.run(cfg.listen)
    with _default_lock:
        _default_server = server
    # Fleet plane: a standalone peer-server process self-registers with
    # the controller so its metrics/traces federate. No-op when this
    # process already registered under another role (daemon/snapshotter):
    # one process is ONE member — one ring, one registry. Either way the
    # serve address is annotated on the member record, which is what the
    # controller's /api/v1/fleet/peers route (dynamic peer discovery)
    # lists for the cluster.
    from nydus_snapshotter_tpu import fleet

    extra = {"peer_listen": server.address}
    if cfg.locality:
        # The locality label rides the member record: the fleet peers
        # listing re-advertises it, which is how every router learns the
        # cluster's topology without a topology service.
        extra["locality"] = cfg.locality
    fleet.register_self("peer", server.address, extra=extra)
    fleet.annotate_self("peer_listen", server.address)
    if cfg.locality:
        fleet.annotate_self("locality", cfg.locality)
    return server


def stop_default() -> None:
    global _default_server, _default_router, _default_resolved
    with _default_lock:
        server = _default_server
        _default_server = None
        _default_router = None
        _default_resolved = False
    if server is not None:
        server.stop()
