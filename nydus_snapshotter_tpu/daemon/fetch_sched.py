"""Parallel fetch scheduler for the lazy-read data plane.

The serial lazy-read path (daemon/blobcache.py before this module) issued
one blocking ranged GET per miss, duplicate-fetched the same extent under
concurrent readers, and never looked ahead. This module is the data-plane
counterpart of the convert pipeline (parallel/pipeline.py): it turns every
cache miss into *flights* — in-flight ranged fetches tracked in a per-blob
singleflight table — and executes them on a multi-connection worker pool
under a byte-bounded in-flight budget (the same
:class:`~nydus_snapshotter_tpu.parallel.pipeline.MemoryBudget` discipline
the convert path uses):

- **singleflight**: concurrent misses on overlapping extents wait on the
  existing flight instead of re-fetching; only uncovered gaps spawn new
  flights, so no byte is ever fetched twice by racing readers;
- **coalescing**: adjacent miss gaps closer than ``merge_gap`` merge into
  one larger ranged GET (re-fetching the few covered bytes in between is
  cheaper than another HTTP round trip);
- **readahead**: a sequential reader extends its miss window ahead of the
  read as *background* flights, clamped to the blob size and isolated
  from the demand read — a failed readahead never fails a read;
- **prefetch replay**: :class:`PrefetchReplayer` walks prefetch file
  lists / fanotify traces through the bootstrap chunk index and warms the
  cache through the same scheduler at background priority, cancellable on
  umount.

Flights dispatch in strict lane order (demand > readahead > prefetch
replay > peer serve); a demand read that lands on a queued lower-lane
flight promotes it. On top of the per-blob scheduling sits the process
QoS layer (:class:`AdmissionGate`): every fetch passes a global
concurrency + byte admission gate with strict priority across lanes and
weighted-tenant fairness inside a lane, so a thousand-pod deploy storm
queues gracefully instead of oversubscribing the node (docs/lazy_read.md;
the peer chunk tier in daemon/peer.py serves through the same gate).
Observability lands in ``metrics/registry.default_registry`` as
``ntpu_blobcache_*`` and ``ntpu_admission_*``; ``failpoint.hit`` fires at
the fetch / coalesce / readahead / admission boundaries
(``blobcache.{fetch,coalesce,readahead}``, ``peer.admit``) so the overlap
is chaos-testable (docs/robustness.md).

Tail-latency weapons for the topology-aware peer tier (daemon/peer.py)
also live here, because they are admission-gate disciplines: per-tier
in-flight byte budgets on :class:`AdmissionGate` (``tier_acquire`` is
strictly non-blocking — a melting zone sheds, it never starves
rack-local service) and :class:`Hedger`, the rolling-p99 hedged second
request with loser cancellation that can never double-charge the
``MemoryBudget`` (the ``peer.hedge`` failpoint arms its launch point).
"""

from __future__ import annotations

import os
import threading
from bisect import bisect_left, bisect_right
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass
from time import perf_counter
from typing import Callable, Optional

from nydus_snapshotter_tpu import failpoint
from nydus_snapshotter_tpu import trace
from nydus_snapshotter_tpu.analysis import runtime as _an
from nydus_snapshotter_tpu.metrics import registry as _metrics
from nydus_snapshotter_tpu.parallel.pipeline import MemoryBudget

DEFAULT_FETCH_WORKERS = 4
DEFAULT_MERGE_GAP = 128 << 10
DEFAULT_READAHEAD = 1 << 20
DEFAULT_BUDGET_BYTES = 64 << 20
MAX_FETCH_WORKERS = 32
DEFAULT_ADMIT_CONCURRENT = 64
DEFAULT_DEMAND_RESERVE = 1
DEFAULT_TENANT = "default"
# Tail-latency hedging (daemon/peer.py tier waterfall): a demand peer
# read past its tier's rolling p99 fires ONE hedged second request at
# the next tier. The p99 trigger bounds added egress to ~1% of flights
# by construction; the window is the rolling-percentile sample count.
DEFAULT_HEDGE_WINDOW = 64
HEDGE_MIN_SAMPLES = 20
HEDGE_PERCENTILE = 0.99

# Flight priority lanes, strictly ordered: a demand read outranks the
# sequential readahead window, which outranks prefetch-list replay, which
# outranks serving chunk ranges to cluster peers (daemon/peer.py). Lane
# order is both the scheduler's queue-pop order and the admission gate's
# strict-priority order. BACKGROUND is the pre-QoS name of the readahead
# lane, kept as an alias.
DEMAND = 0
READAHEAD = 1
PREFETCH = 2
PEER_SERVE = 3
BACKGROUND = READAHEAD
N_LANES = 4
LANE_NAMES = ("demand", "readahead", "prefetch", "peer_serve")

_reg = _metrics.default_registry
HIT_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_hit_bytes",
        "Lazy-read bytes served from the local chunk cache",
    )
)
MISS_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_miss_bytes",
        "Lazy-read bytes that required a remote fetch",
    )
)
FETCH_REQUESTS = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_fetch_requests",
        "Ranged GETs issued by the fetch scheduler",
    )
)
COALESCED_REQUESTS = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_coalesced_requests",
        "Ranged GETs that merged more than one miss gap",
    )
)
INFLIGHT_BYTES = _reg.register(
    _metrics.Gauge(
        "ntpu_blobcache_inflight_bytes",
        "Bytes currently being fetched by blobcache workers",
    )
)
READAHEAD_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_readahead_bytes",
        "Bytes fetched speculatively ahead of sequential readers",
    )
)
READAHEAD_HIT_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_readahead_hit_bytes",
        "Readahead bytes later served to a real read (accuracy numerator)",
    )
)
PREFETCH_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_prefetch_bytes",
        "Bytes warmed by the background prefetch replayer",
    )
)
SINGLEFLIGHT_WAITS = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_singleflight_waits",
        "Reads that piggybacked on another reader's in-flight fetch",
    )
)
EVICTED_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_evicted_bytes",
        "Bytes removed by capacity-watermark blob cache eviction",
    )
)
EVICTED_ENTRIES = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_evicted_entries",
        "Whole blob cache entries removed by capacity-watermark eviction",
    )
)
OP_HIST = _reg.register(
    _metrics.Histogram(
        "ntpu_blobcache_op_duration_milliseconds",
        "Latency of lazy-read data-plane operations (read_at / fetch),"
        " metered by the same window the trace spans record",
        ("op",),
    )
)
ADMITTED = _reg.register(
    _metrics.Counter(
        "ntpu_admission_admitted_total",
        "Fetch/serve operations admitted through the QoS gate, per lane",
        ("lane",),
    )
)
ADMIT_WAIT_MS = _reg.register(
    _metrics.Histogram(
        "ntpu_admission_wait_milliseconds",
        "Time operations queued in the QoS admission gate before a slot,"
        " per lane",
        ("lane",),
    )
)
ADMIT_QUEUED = _reg.register(
    _metrics.Gauge(
        "ntpu_admission_queued",
        "Operations currently waiting in the QoS admission gate, per lane",
        ("lane",),
    )
)
ADMIT_TENANT_BYTES = _reg.register(
    _metrics.Gauge(
        "ntpu_admission_tenant_inflight_bytes",
        "In-flight bytes currently admitted per tenant",
        ("tenant",),
    )
)
ADMIT_SHED = _reg.register(
    _metrics.Counter(
        "ntpu_admission_shed_total",
        "Operations rejected because their lane was shed by SLO actuation",
        ("lane",),
    )
)
ADMIT_LANE_CAP = _reg.register(
    _metrics.Gauge(
        "ntpu_admission_lane_cap",
        "Current per-lane concurrency cap (-1 = unlimited, 0 = lane shed)",
        ("lane",),
    )
)
ADMIT_TIER_INFLIGHT = _reg.register(
    _metrics.Gauge(
        "ntpu_admission_tier_inflight_bytes",
        "In-flight peer-read bytes currently admitted per topology tier",
        ("tier",),
    )
)
ADMIT_TIER_REJECTED = _reg.register(
    _metrics.Counter(
        "ntpu_admission_tier_rejected_total",
        "Peer-read attempts a tier's in-flight byte budget walked past"
        " (the caller fell through to the next tier immediately)",
        ("tier",),
    )
)
HEDGE_TOTAL = _reg.register(
    _metrics.Counter(
        "ntpu_peer_hedge_total",
        "Hedged second requests on slow peer-tier demand reads, by"
        " outcome (fired / won / cancelled / skipped / error)",
        ("outcome",),
    )
)
HEDGE_WASTED_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_peer_hedge_wasted_bytes_total",
        "Bytes fetched by the losing side of a hedge race and discarded"
        " (cancelled by accounting — real network cost, zero delivery)",
    )
)


# -- provenance plumbing (provenance/ledger.py) -----------------------------
#
# Two thread-local channels carry attribution context across the planner /
# worker boundary without the scheduler knowing about the ledger:
#
# * ``fetch_tag``: a cause override captured at PLAN time (the planning
#   thread) and pinned onto every flight it creates — e.g. the seekable-
#   index build wraps its whole-layer pull in
#   ``with fetch_tag("soci_index_build")``.
# * ``fetch_note``: per-fetch annotations set by the WORKER thread while
#   the fetch runs (the peer fetcher notes the winning tier and whether a
#   hedge fired) and consumed by the delivery hook on the same thread.

_prov_tls = threading.local()


@contextmanager
def fetch_tag(tag: str):
    """Scope a provenance cause override onto flights planned within."""
    prev = getattr(_prov_tls, "tag", None)
    _prov_tls.tag = tag
    try:
        yield
    finally:
        _prov_tls.tag = prev


def current_fetch_tag():
    return getattr(_prov_tls, "tag", None)


def fetch_note(key: str, value) -> None:
    """Annotate the in-progress fetch on THIS worker thread."""
    notes = getattr(_prov_tls, "notes", None)
    if notes is None:
        notes = _prov_tls.notes = {}
    notes[key] = value


def take_fetch_notes() -> dict:
    """Drain this thread's fetch notes (cleared so a note can never leak
    onto the worker's next flight)."""
    notes = getattr(_prov_tls, "notes", None)
    if not notes:
        return {}
    _prov_tls.notes = {}
    return notes


class LaneShedError(OSError):
    """The operation's QoS lane is currently shed by SLO actuation.

    Non-demand callers degrade exactly as they do on any other transient
    failure: a shed readahead/prefetch flight is replanned at demand
    priority only when a real read needs the bytes, a shed peer-serve
    request makes the requester fall back to the registry."""


def snapshot_counters() -> dict:
    """Current cumulative ``ntpu_blobcache_*`` values (bench/tools delta
    these around a run)."""
    ra = READAHEAD_BYTES.value()
    return {
        "hit_bytes": HIT_BYTES.value(),
        "miss_bytes": MISS_BYTES.value(),
        "fetch_requests": FETCH_REQUESTS.value(),
        "coalesced_requests": COALESCED_REQUESTS.value(),
        "readahead_bytes": ra,
        "readahead_hit_bytes": READAHEAD_HIT_BYTES.value(),
        "readahead_accuracy": (
            READAHEAD_HIT_BYTES.value() / ra if ra else None
        ),
        "prefetch_bytes": PREFETCH_BYTES.value(),
        "singleflight_waits": SINGLEFLIGHT_WAITS.value(),
        "evicted_bytes": EVICTED_BYTES.value(),
        "evicted_entries": EVICTED_ENTRIES.value(),
    }


# ---------------------------------------------------------------------------
# Sorted-interval coverage
# ---------------------------------------------------------------------------


class IntervalSet:
    """Disjoint, sorted, half-open ``[start, end)`` intervals with
    bisect-based point/range queries — O(log n + k) where the previous
    blobcache scan was O(n) per read. Touching intervals merge."""

    __slots__ = ("_starts", "_ends")

    def __init__(self):
        self._starts: list[int] = []
        self._ends: list[int] = []

    def __len__(self) -> int:
        return len(self._starts)

    def add(self, start: int, end: int) -> None:
        if end <= start:
            return
        # Intervals whose end >= start and whose start <= end overlap or
        # touch [start, end): one contiguous run in the sorted lists.
        i = bisect_left(self._ends, start)
        j = bisect_right(self._starts, end)
        if i < j:
            start = min(start, self._starts[i])
            end = max(end, self._ends[j - 1])
        self._starts[i:j] = [start]
        self._ends[i:j] = [end]

    def covered(self, start: int, end: int) -> bool:
        if end <= start:
            return True
        i = bisect_right(self._starts, start) - 1
        return i >= 0 and self._ends[i] >= end

    def missing(self, start: int, end: int) -> list[tuple[int, int]]:
        """Sub-ranges of ``[start, end)`` not covered, in order."""
        if end <= start:
            return []
        gaps: list[tuple[int, int]] = []
        i = bisect_right(self._starts, start) - 1
        if i < 0 or self._ends[i] <= start:
            i += 1
        pos = start
        while pos < end and i < len(self._starts):
            s, e = self._starts[i], self._ends[i]
            if s >= end:
                break
            if pos < s:
                gaps.append((pos, s))
            pos = max(pos, e)
            i += 1
        if pos < end:
            gaps.append((pos, end))
        return gaps

    def spans(self) -> list[tuple[int, int]]:
        return list(zip(self._starts, self._ends))

    def total_bytes(self) -> int:
        return sum(e - s for s, e in zip(self._starts, self._ends))

    def clear(self) -> None:
        self._starts.clear()
        self._ends.clear()

    def remove(self, start: int, end: int) -> int:
        """Uncover ``[start, end)``; returns bytes actually removed."""
        if end <= start:
            return 0
        removed = 0
        keep_s: list[int] = []
        keep_e: list[int] = []
        for s, e in zip(self._starts, self._ends):
            if e <= start or s >= end:
                keep_s.append(s)
                keep_e.append(e)
                continue
            removed += min(e, end) - max(s, start)
            if s < start:
                keep_s.append(s)
                keep_e.append(start)
            if e > end:
                keep_s.append(end)
                keep_e.append(e)
        self._starts, self._ends = keep_s, keep_e
        return removed


# ---------------------------------------------------------------------------
# Configuration
# ---------------------------------------------------------------------------


@dataclass
class FetchConfig:
    fetch_workers: int = DEFAULT_FETCH_WORKERS
    merge_gap: int = DEFAULT_MERGE_GAP
    readahead: int = DEFAULT_READAHEAD
    budget_bytes: int = DEFAULT_BUDGET_BYTES
    prefetch_replay: bool = True


def _env_int(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, ""))
        return v if v >= 0 else default
    except ValueError:
        return default


def _global_blobcache_config():
    """The snapshotter's ``[blobcache]`` section when a global config is
    set (config/config.py); None in the daemon process / library use."""
    try:
        from nydus_snapshotter_tpu.config import config as _cfg

        return _cfg.get_global_config().blobcache
    except Exception:
        return None


def resolve_config() -> FetchConfig:
    """Resolve the lazy-read knobs: env > ``[blobcache]`` config > defaults.

    Environment overrides (``NTPU_BLOBCACHE*``) matter doubly here: the
    daemon is a separate process with no global snapshotter config, so the
    spawned environment is how the section reaches the data plane.
    """
    bc = _global_blobcache_config()
    workers = _env_int(
        "NTPU_BLOBCACHE_WORKERS",
        getattr(bc, "fetch_workers", 0) or DEFAULT_FETCH_WORKERS,
    )
    merge_gap = _env_int(
        "NTPU_BLOBCACHE_MERGE_GAP_KIB",
        -1,
    )
    if merge_gap < 0:
        gap_kib = getattr(bc, "merge_gap_kib", None)
        merge_gap = gap_kib if gap_kib is not None else (DEFAULT_MERGE_GAP >> 10)
    readahead = _env_int("NTPU_BLOBCACHE_READAHEAD_KIB", -1)
    if readahead < 0:
        ra_kib = getattr(bc, "readahead_kib", None)
        readahead = ra_kib if ra_kib is not None else (DEFAULT_READAHEAD >> 10)
    budget = _env_int(
        "NTPU_BLOBCACHE_BUDGET_MIB",
        getattr(bc, "inflight_budget_mib", 0) or (DEFAULT_BUDGET_BYTES >> 20),
    )
    prefetch_env = os.environ.get("NTPU_BLOBCACHE_PREFETCH", "")
    if prefetch_env:
        prefetch = prefetch_env not in ("0", "off", "false")
    else:
        prefetch = bool(getattr(bc, "prefetch_replay", True))
    return FetchConfig(
        fetch_workers=min(MAX_FETCH_WORKERS, max(1, workers)),
        merge_gap=merge_gap << 10,
        readahead=readahead << 10,
        budget_bytes=max(1, budget) << 20,
        prefetch_replay=prefetch,
    )


def resolve_watermark_bytes(config_mib: int) -> int:
    """``[blobcache].eviction_watermark_mib`` with its documented
    ``NTPU_BLOBCACHE_WATERMARK_MIB`` env override (env > config, like
    every other blobcache knob; 0 disables capacity eviction)."""
    mib = _env_int("NTPU_BLOBCACHE_WATERMARK_MIB", -1)
    if mib < 0:
        mib = max(0, int(config_mib))
    return mib << 20


_shared_budget: Optional[MemoryBudget] = None
_shared_budget_lock = threading.Lock()


def shared_budget() -> MemoryBudget:
    """Process-wide in-flight byte budget every scheduler without an
    explicit budget shares, so aggregate fetch memory is independent of
    how many blobs are being lazily read at once."""
    global _shared_budget
    with _shared_budget_lock:
        if _shared_budget is None:
            _shared_budget = MemoryBudget(resolve_config().budget_bytes)
        return _shared_budget


# ---------------------------------------------------------------------------
# QoS admission control
# ---------------------------------------------------------------------------


def parse_tenant_weights(spec: str) -> dict[str, float]:
    """``"team-a=2,team-b=1"`` → weight map (bad entries ignored; an
    unlisted tenant weighs 1.0)."""
    out: dict[str, float] = {}
    for part in spec.split(","):
        name, _, w = part.strip().partition("=")
        if not name or not w:
            continue
        try:
            val = float(w)
        except ValueError:
            continue
        if val > 0:
            out[name] = val
    return out


def _global_peer_config():
    try:
        from nydus_snapshotter_tpu.config import config as _cfg

        return _cfg.get_global_config().peer
    except Exception:
        return None


def resolve_admission() -> tuple[int, int, dict[str, float]]:
    """(max_concurrent, demand_reserve, tenant_weights) for the process
    admission gate: env (``NTPU_PEER_MAX_CONCURRENT``,
    ``NTPU_PEER_DEMAND_RESERVE``, ``NTPU_PEER_TENANT_WEIGHTS``) >
    ``[peer]`` config > defaults. Env is also how the section reaches
    spawned daemon processes, like every other blobcache knob."""
    pc = _global_peer_config()
    max_c = _env_int(
        "NTPU_PEER_MAX_CONCURRENT",
        getattr(pc, "max_concurrent", 0) or DEFAULT_ADMIT_CONCURRENT,
    )
    reserve = _env_int(
        "NTPU_PEER_DEMAND_RESERVE",
        getattr(pc, "demand_reserve", DEFAULT_DEMAND_RESERVE),
    )
    weights = dict(getattr(pc, "tenant_weights", None) or {})
    env_w = os.environ.get("NTPU_PEER_TENANT_WEIGHTS", "")
    if env_w:
        weights = parse_tenant_weights(env_w)
    return max(1, max_c), max(0, reserve), weights


def parse_tier_budgets(spec: str) -> dict[str, int]:
    """``"zone=32,origin=64"`` (MiB per tier) → per-tier in-flight byte
    caps (bad entries ignored; an unlisted tier is unbudgeted)."""
    out: dict[str, int] = {}
    for part in spec.split(","):
        name, _, v = part.strip().partition("=")
        if not name or not v:
            continue
        try:
            mib = int(v)
        except ValueError:
            continue
        if mib > 0:
            out[name] = mib << 20
    return out


def resolve_tier_budgets() -> dict[str, int]:
    """Per-tier in-flight byte budgets for the admission gate: env
    (``NTPU_PEER_TIER_BUDGETS``, MiB spec) > ``[peer] tier_budgets`` >
    unbudgeted. A budgeted tier sheds (walks past) rather than queues —
    a melting zone cannot starve rack-local service."""
    env = os.environ.get("NTPU_PEER_TIER_BUDGETS", "")
    if env:
        return parse_tier_budgets(env)
    pc = _global_peer_config()
    out: dict[str, int] = {}
    for k, v in dict(getattr(pc, "tier_budgets", None) or {}).items():
        try:
            mib = int(v)
        except (TypeError, ValueError):
            continue
        if mib > 0:
            out[str(k)] = mib << 20
    return out


def resolve_hedge() -> tuple[bool, int]:
    """(enabled, window) for peer-read tail hedging: env
    (``NTPU_PEER_HEDGE``, ``NTPU_PEER_HEDGE_WINDOW``) > ``[peer]``
    config > defaults."""
    pc = _global_peer_config()
    env = os.environ.get("NTPU_PEER_HEDGE", "")
    if env:
        enabled = env not in ("0", "off", "false")
    else:
        enabled = bool(getattr(pc, "hedge", True))
    window = _env_int(
        "NTPU_PEER_HEDGE_WINDOW",
        getattr(pc, "hedge_window", 0) or DEFAULT_HEDGE_WINDOW,
    )
    return enabled, max(8, window)


class _Ticket:
    __slots__ = ("tenant", "lane", "n", "seq")

    def __init__(self, tenant: str, lane: int, n: int, seq: int):
        self.tenant = tenant
        self.lane = lane
        self.n = n
        self.seq = seq


class AdmissionGate:
    """Cross-pod QoS admission: strict priority lanes + weighted-tenant
    fairness + a global concurrency gate, layered on the shared
    :class:`MemoryBudget`.

    A thousand-pod deploy storm must queue gracefully, not oversubscribe:
    every fetch/serve operation passes ``acquire(n, tenant, lane)`` before
    touching the network, and is admitted only when

    - **strict priority** holds: no waiter in a higher lane (demand >
      readahead > prefetch-replay > peer-serve) is queued;
    - a **concurrency slot** is free — at most ``max_concurrent`` admitted
      operations, of which ``demand_reserve`` slots only the demand lane
      may use (so a demand read never waits behind more than the
      non-reserved in-service operations);
    - the **byte cap** holds: admitted bytes fit the budget's total, with
      the bounded-queue degrade-to-serial discipline (one op larger than
      the whole cap is admitted alone rather than deadlocking);
    - **weighted fairness** holds: among waiting tenants in the same
      lane, the tenant with the smallest in-flight-bytes/weight score is
      admitted first (weighted fair queuing on in-flight byte service),
      unless that tenant cannot currently fit (no slot / bytes) — an
      oversized under-served waiter never wedges the lane.

    The gate does its own accounting under one condition variable and
    settles the byte grant against the shared ``MemoryBudget`` AFTER the
    admission decision, outside the gate lock, so budget co-users (other
    schedulers without a gate) still see one consistent byte pool.
    """

    def __init__(
        self,
        budget: Optional[MemoryBudget] = None,
        max_concurrent: int = 0,
        demand_reserve: int = DEFAULT_DEMAND_RESERVE,
        weights: Optional[dict[str, float]] = None,
        name: str = "gate",
        tier_budgets: Optional[dict[str, int]] = None,
    ):
        self.budget = budget or shared_budget()
        self.cap = self.budget.total
        self.max_concurrent = max(1, max_concurrent or DEFAULT_ADMIT_CONCURRENT)
        self.demand_reserve = min(max(0, demand_reserve), self.max_concurrent - 1)
        self.weights = dict(weights or {})
        self.name = name
        self._cv = _an.make_condition(f"fetch.admission[{name}]")
        # Lockset annotation: every gate field below is only ever touched
        # under the condition's lock (NTPU_ANALYZE=1 verifies).
        self._state_shared = _an.shared(f"fetch.admission.state[{name}]")
        self._waiters: list[_Ticket] = []
        self._seq = 0
        self._in_service = 0
        self._held = 0
        self._tenant_bytes: dict[str, int] = {}
        self._tenant_service: dict[str, int] = {}
        self._admitted = [0] * N_LANES
        # SLO actuation state: per-lane concurrency caps (None = unlimited,
        # 0 = lane shed — new acquires raise LaneShedError immediately).
        # The demand lane is never cappable: actuation protects demand by
        # construction, it must not be able to starve it.
        self._lane_caps: list[Optional[int]] = [None] * N_LANES
        self._lane_in_service = [0] * N_LANES
        self._shed_total = [0] * N_LANES
        # Per-tier in-flight byte budgets (peer-read topology tiers:
        # rack / zone / origin). Orthogonal to lanes: a tier cap never
        # queues — tier_acquire is strictly non-blocking, the caller
        # walks to the next tier on a full budget.
        self._tier_caps: dict[str, int] = {
            str(t): max(0, int(c)) for t, c in (tier_budgets or {}).items()
        }
        self._tier_bytes: dict[str, int] = {}
        self._tier_rejected: dict[str, int] = {}
        # Demand-pressure signal (scale-up actuation, metrics/slo.py
        # SloScaleUp): an EWMA of demand-lane queue waits plus the live
        # queue depth — cheap enough to keep on every acquire, read
        # rarely.
        self._demand_wait_ewma_ms = 0.0
        self._demand_wait_samples = 0
        self._demand_queued_peak = 0

    def weight(self, tenant: str) -> float:
        return max(1e-9, float(self.weights.get(tenant, 1.0)))

    # -- SLO actuation --------------------------------------------------------

    def set_lane_cap(self, lane: int, cap: Optional[int]) -> None:
        """Actuate one lane: ``None`` restores it, ``0`` sheds it (new
        acquires fail fast with :class:`LaneShedError`), ``k > 0`` bounds
        its in-service operations. The DEMAND lane cannot be actuated."""
        lane = int(lane)
        if lane == DEMAND or not 0 < lane < N_LANES:
            raise ValueError(f"lane {lane} is not actuatable")
        with self._cv:
            self._state_shared.write()
            self._lane_caps[lane] = None if cap is None else max(0, int(cap))
            self._cv.notify_all()
        ADMIT_LANE_CAP.labels(LANE_NAMES[lane]).set(
            -1 if cap is None else max(0, int(cap))
        )

    def lane_state(self) -> dict:
        """{lane: {cap, in_service, shed_total}} actuation view."""
        with self._cv:
            self._state_shared.read()
            return {
                LANE_NAMES[i]: {
                    "cap": self._lane_caps[i],
                    "in_service": self._lane_in_service[i],
                    "shed_total": self._shed_total[i],
                }
                for i in range(N_LANES)
            }

    # -- per-tier byte budgets (peer-read topology) ---------------------------

    def set_tier_budget(self, tier: str, cap: Optional[int]) -> None:
        """Bound one tier's in-flight peer-read bytes (``None`` removes
        the cap). Like the MemoryBudget, one read larger than the whole
        cap admits alone rather than wedging the tier."""
        with self._cv:
            self._state_shared.write()
            if cap is None:
                self._tier_caps.pop(tier, None)
            else:
                self._tier_caps[tier] = max(0, int(cap))

    def tier_acquire(self, tier: str, n: int) -> bool:
        """Non-blocking per-tier byte admission. False = the tier's
        budget is full RIGHT NOW: the caller falls through to the next
        tier (or origin) immediately — a melting zone must not starve
        rack-local service by queueing demand reads behind it. A True
        must be paired with :meth:`tier_release`."""
        n = max(0, int(n))
        with self._cv:
            self._state_shared.write()
            cap = self._tier_caps.get(tier)
            used = self._tier_bytes.get(tier, 0)
            if cap is not None and used > 0 and used + n > cap:
                self._tier_rejected[tier] = self._tier_rejected.get(tier, 0) + 1
                ADMIT_TIER_REJECTED.labels(tier).inc()
                return False
            self._tier_bytes[tier] = used + n
            ADMIT_TIER_INFLIGHT.labels(tier).set(used + n)
        return True

    def tier_release(self, tier: str, n: int) -> None:
        n = max(0, int(n))
        with self._cv:
            self._state_shared.write()
            left = max(0, self._tier_bytes.get(tier, 0) - n)
            self._tier_bytes[tier] = left
            ADMIT_TIER_INFLIGHT.labels(tier).set(left)

    def tier_state(self) -> dict:
        """{tier: {cap, inflight_bytes, rejected_total}} budget view."""
        with self._cv:
            self._state_shared.read()
            tiers = (
                set(self._tier_caps)
                | set(self._tier_bytes)
                | set(self._tier_rejected)
            )
            return {
                t: {
                    "cap": self._tier_caps.get(t),
                    "inflight_bytes": self._tier_bytes.get(t, 0),
                    "rejected_total": self._tier_rejected.get(t, 0),
                }
                for t in sorted(tiers)
            }

    # -- admission predicate (caller holds self._cv) -------------------------

    def _fits(self, t: _Ticket) -> bool:
        """Slot + byte feasibility, ignoring priority/fairness."""
        if self._in_service >= self.max_concurrent:
            return False
        if t.lane != DEMAND and self._in_service >= (
            self.max_concurrent - self.demand_reserve
        ):
            return False
        cap = self._lane_caps[t.lane]
        if cap is not None and self._lane_in_service[t.lane] >= cap:
            return False
        return self._held == 0 or self._held + t.n <= self.cap

    def _admissible(self, t: _Ticket) -> bool:
        for w in self._waiters:
            if w.lane < t.lane:
                return False  # strict priority: higher lanes drain first
        if not self._fits(t):
            return False
        score = self._tenant_bytes.get(t.tenant, 0) / self.weight(t.tenant)
        for w in self._waiters:
            if w is t or w.lane != t.lane or w.tenant == t.tenant:
                continue
            ws = self._tenant_bytes.get(w.tenant, 0) / self.weight(w.tenant)
            if (ws < score or (ws == score and w.seq < t.seq)) and self._fits(w):
                return False  # the under-served tenant goes first
        return True

    # -- acquire / release ---------------------------------------------------

    def acquire(
        self,
        n: int,
        tenant: str = DEFAULT_TENANT,
        lane: int = DEMAND,
        aborted: Optional[Callable[[], bool]] = None,
    ) -> float:
        """Block until admitted; returns seconds spent queued. Raises
        OSError when ``aborted()`` flips while waiting."""
        failpoint.hit("peer.admit")
        n = max(0, int(n))
        lane = min(max(0, int(lane)), N_LANES - 1)
        t0 = perf_counter()
        with self._cv:
            self._state_shared.write()
            if self._lane_caps[lane] == 0:
                # Lane shed by SLO actuation: fail fast instead of queueing
                # — background callers degrade, peer requesters fall back.
                self._shed_total[lane] += 1
                ADMIT_SHED.labels(LANE_NAMES[lane]).inc()
                raise LaneShedError(
                    f"admission gate {self.name!r}: lane "
                    f"{LANE_NAMES[lane]} is shed"
                )
            self._seq += 1
            t = _Ticket(tenant, lane, n, self._seq)
            self._waiters.append(t)
            depth = sum(1 for w in self._waiters if w.lane == lane)
            ADMIT_QUEUED.labels(LANE_NAMES[lane]).set(depth)
            if lane == DEMAND and depth > self._demand_queued_peak:
                self._demand_queued_peak = depth
            try:
                while not self._admissible(t):
                    if self._lane_caps[lane] == 0:
                        # Shed while queued: same fail-fast contract.
                        self._shed_total[lane] += 1
                        ADMIT_SHED.labels(LANE_NAMES[lane]).inc()
                        raise LaneShedError(
                            f"admission gate {self.name!r}: lane "
                            f"{LANE_NAMES[lane]} is shed"
                        )
                    if aborted is not None and aborted():
                        raise OSError(
                            f"admission gate {self.name!r} wait aborted"
                        )
                    # Short poll: an aborted() flip has no notifier.
                    self._cv.wait(0.05)
                self._in_service += 1
                self._lane_in_service[lane] += 1
                self._held += n
                self._tenant_bytes[tenant] = self._tenant_bytes.get(tenant, 0) + n
                self._tenant_service[tenant] = (
                    self._tenant_service.get(tenant, 0) + n
                )
                self._admitted[lane] += 1
            finally:
                self._waiters.remove(t)
                ADMIT_QUEUED.labels(LANE_NAMES[lane]).set(
                    sum(1 for w in self._waiters if w.lane == lane)
                )
                # The waiter set changed either way: strict-priority and
                # fairness predicates of other waiters may now pass.
                self._cv.notify_all()
            ADMIT_TENANT_BYTES.labels(tenant).set(self._tenant_bytes[tenant])
            if lane == DEMAND:
                self._demand_wait_samples += 1
                self._demand_wait_ewma_ms += 0.2 * (
                    (perf_counter() - t0) * 1000.0 - self._demand_wait_ewma_ms
                )
        waited = perf_counter() - t0
        ADMITTED.labels(LANE_NAMES[lane]).inc()
        ADMIT_WAIT_MS.labels(LANE_NAMES[lane]).observe(waited * 1000.0)
        # Settle against the shared byte pool OUTSIDE the gate lock; the
        # gate's own cap makes this non-blocking unless budget co-users
        # (ungated schedulers) hold bytes.
        try:
            self.budget.acquire(n, aborted=aborted)
        except BaseException:
            with self._cv:
                self._state_shared.write()
                self._in_service -= 1
                self._lane_in_service[lane] = max(
                    0, self._lane_in_service[lane] - 1
                )
                self._held -= n
                self._tenant_bytes[tenant] = max(
                    0, self._tenant_bytes.get(tenant, 0) - n
                )
                self._cv.notify_all()
            raise
        return waited

    def try_acquire(
        self, n: int, tenant: str = DEFAULT_TENANT, lane: int = DEMAND
    ) -> bool:
        """Non-blocking acquire for hedged second requests: admitted
        only when a slot AND the bytes are free right now with nobody
        queued at this or a higher lane — a hedge is pure opportunism,
        it must never displace or delay first-request traffic. Returns
        False instead of queueing; a True must be paired with the usual
        ``release(n, tenant, lane)``."""
        n = max(0, int(n))
        lane = min(max(0, int(lane)), N_LANES - 1)
        with self._cv:
            self._state_shared.write()
            if self._lane_caps[lane] == 0:
                self._shed_total[lane] += 1
                ADMIT_SHED.labels(LANE_NAMES[lane]).inc()
                return False
            t = _Ticket(tenant, lane, n, self._seq + 1)
            if any(w.lane <= lane for w in self._waiters) or not self._fits(t):
                return False
            self._seq += 1
            self._in_service += 1
            self._lane_in_service[lane] += 1
            self._held += n
            self._tenant_bytes[tenant] = self._tenant_bytes.get(tenant, 0) + n
            self._tenant_service[tenant] = (
                self._tenant_service.get(tenant, 0) + n
            )
            self._admitted[lane] += 1
            ADMIT_TENANT_BYTES.labels(tenant).set(self._tenant_bytes[tenant])
        ADMITTED.labels(LANE_NAMES[lane]).inc()
        # Settle against the shared byte pool outside the gate lock, non-
        # blocking: budget co-users holding bytes fail the hedge instead
        # of queueing it.
        if not self.budget.try_acquire(n, timeout=0.0):
            with self._cv:
                self._state_shared.write()
                self._in_service = max(0, self._in_service - 1)
                self._lane_in_service[lane] = max(
                    0, self._lane_in_service[lane] - 1
                )
                self._held = max(0, self._held - n)
                self._tenant_bytes[tenant] = max(
                    0, self._tenant_bytes.get(tenant, 0) - n
                )
                ADMIT_TENANT_BYTES.labels(tenant).set(
                    self._tenant_bytes[tenant]
                )
                self._cv.notify_all()
            return False
        return True

    def release(
        self, n: int, tenant: str = DEFAULT_TENANT, lane: int = DEMAND
    ) -> None:
        n = max(0, int(n))
        lane = min(max(0, int(lane)), N_LANES - 1)
        self.budget.release(n)
        with self._cv:
            self._state_shared.write()
            self._in_service = max(0, self._in_service - 1)
            self._lane_in_service[lane] = max(0, self._lane_in_service[lane] - 1)
            self._held = max(0, self._held - n)
            self._tenant_bytes[tenant] = max(
                0, self._tenant_bytes.get(tenant, 0) - n
            )
            ADMIT_TENANT_BYTES.labels(tenant).set(self._tenant_bytes[tenant])
            self._cv.notify_all()

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> dict:
        with self._cv:
            self._state_shared.read()
            return {
                "max_concurrent": self.max_concurrent,
                "demand_reserve": self.demand_reserve,
                "in_service": self._in_service,
                "held_bytes": self._held,
                "queued": len(self._waiters),
                "admitted_per_lane": dict(
                    zip(LANE_NAMES, self._admitted)
                ),
                "lane_caps": dict(zip(LANE_NAMES, self._lane_caps)),
                "lane_in_service": dict(
                    zip(LANE_NAMES, self._lane_in_service)
                ),
                "shed_per_lane": dict(zip(LANE_NAMES, self._shed_total)),
                "tenant_inflight_bytes": dict(self._tenant_bytes),
                "tenant_service_bytes": dict(self._tenant_service),
                "tiers": {
                    t: {
                        "cap": self._tier_caps.get(t),
                        "inflight_bytes": self._tier_bytes.get(t, 0),
                        "rejected_total": self._tier_rejected.get(t, 0),
                    }
                    for t in sorted(
                        set(self._tier_caps)
                        | set(self._tier_bytes)
                        | set(self._tier_rejected)
                    )
                },
            }

    def demand_pressure(self) -> dict:
        """The scale-up demand signal: live demand-lane queue depth, the
        deepest queue seen over this gate's lifetime, and the wait EWMA.
        Burn-clean-but-growing pressure here means the node is
        UNDERSIZED, not misbehaving — the SLO scale-up policy
        (metrics/slo.py) spawns capacity instead of shedding load."""
        with self._cv:
            self._state_shared.read()
            return {
                "queued": sum(1 for w in self._waiters if w.lane == DEMAND),
                "queued_peak": self._demand_queued_peak,
                "wait_ms": round(self._demand_wait_ewma_ms, 3),
                "samples": self._demand_wait_samples,
            }

    def service_bytes(self, tenant: str) -> int:
        """Cumulative admitted bytes for ``tenant`` (fairness gauges
        delta this around a saturation window)."""
        with self._cv:
            self._state_shared.read()
            return self._tenant_service.get(tenant, 0)


_shared_gate: Optional[AdmissionGate] = None
_shared_gate_lock = threading.Lock()


def shared_gate() -> AdmissionGate:
    """Process-wide admission gate every scheduler without an explicit
    gate/budget shares — the storm-wide concurrency, priority and
    fairness decisions are per NODE, not per blob."""
    global _shared_gate
    with _shared_gate_lock:
        if _shared_gate is not None:
            return _shared_gate
    # Build outside the lock (shared_budget takes its own module lock —
    # never nest the two); publish first-wins.
    max_c, reserve, weights = resolve_admission()
    gate = AdmissionGate(
        budget=shared_budget(),
        max_concurrent=max_c,
        demand_reserve=reserve,
        weights=weights,
        name="shared",
        tier_budgets=resolve_tier_budgets(),
    )
    with _shared_gate_lock:
        if _shared_gate is None:
            _shared_gate = gate
        return _shared_gate


# ---------------------------------------------------------------------------
# Tail-latency hedging (the peer tier's demand lane)
# ---------------------------------------------------------------------------


class RollingPercentile:
    """Rolling latency percentile over the last ``window`` samples — the
    trace exemplar reservoir's discipline (trace/export.py ExemplarStore):
    a bounded deque, sorted lazily by the reader. Below ``min_samples``
    there is no estimate at all — with no history every flight "exceeds
    p99" and a hedge trigger would be pure noise."""

    __slots__ = ("_samples", "min_samples")

    def __init__(
        self,
        window: int = DEFAULT_HEDGE_WINDOW,
        min_samples: int = HEDGE_MIN_SAMPLES,
    ):
        self._samples: deque = deque(maxlen=max(8, int(window)))
        self.min_samples = max(1, int(min_samples))

    def __len__(self) -> int:
        return len(self._samples)

    def record(self, ms: float) -> None:
        self._samples.append(float(ms))

    def percentile(self, q: float = HEDGE_PERCENTILE) -> Optional[float]:
        snap = sorted(self._samples)
        if len(snap) < self.min_samples:
            return None
        return snap[min(len(snap) - 1, int(q * len(snap)))]


class Hedger:
    """Hedged second requests for slow demand-lane peer reads.

    A flight that exceeds its tier's rolling p99 fires ONE hedge at the
    next tier (or origin); the first good response wins. The loser is
    cancelled by ACCOUNTING, not interruption: the hedge admits its own
    bytes through a non-blocking :meth:`AdmissionGate.try_acquire` (a
    saturated node skips the hedge rather than queueing it behind
    first-request traffic) and the hedge thread releases that charge in
    its own ``finally`` — win or lose — so a hedged flight can never
    double-charge the MemoryBudget (property-tested across 1k flights in
    tests/test_peer_hedge.py). Because the trigger is the rolling p99,
    at most ~1% of flights hedge: added egress is bounded by
    construction, which is the analytic bound the storm profile gates.

    The ``peer.hedge`` failpoint fires at the hedge-launch boundary; an
    armed failure aborts the hedge and the primary proceeds exactly as
    an unhedged flight (docs/robustness.md).
    """

    def __init__(
        self,
        gate: Optional[AdmissionGate] = None,
        enabled: bool = True,
        window: int = DEFAULT_HEDGE_WINDOW,
        percentile: float = HEDGE_PERCENTILE,
        name: str = "hedge",
    ):
        self.gate = gate if gate is not None else shared_gate()
        self.enabled = enabled
        self.percentile = percentile
        self.window = max(8, int(window))
        self._mu = _an.make_lock(f"fetch.hedge[{name}]")
        # Lockset annotation: per-tier latency windows and the outcome
        # counters only mutate under self._mu (NTPU_ANALYZE=1 verifies).
        self._state_shared = _an.shared(f"fetch.hedge.state[{name}]")
        self._lat: dict[str, RollingPercentile] = {}
        self._counts: dict[str, int] = {}

    def record(self, tier: str, ms: float) -> None:
        with self._mu:
            self._state_shared.write()
            rp = self._lat.get(tier)
            if rp is None:
                rp = self._lat[tier] = RollingPercentile(self.window)
            rp.record(ms)

    def threshold_ms(self, tier: str) -> Optional[float]:
        """The tier's rolling p99, or None while the window is cold."""
        with self._mu:
            self._state_shared.read()
            rp = self._lat.get(tier)
            return rp.percentile(self.percentile) if rp is not None else None

    def _count(self, outcome: str) -> None:
        with self._mu:
            self._state_shared.write()
            self._counts[outcome] = self._counts.get(outcome, 0) + 1
        HEDGE_TOTAL.labels(outcome).inc()

    def counters(self) -> dict:
        with self._mu:
            self._state_shared.read()
            return {
                k: self._counts.get(k, 0)
                for k in ("fired", "won", "cancelled", "skipped", "error")
            }

    def fetch(
        self,
        size: int,
        tier: str,
        primary: Callable[[], bytes],
        hedge_tier: Optional[str] = None,
        hedge: Optional[Callable[[], bytes]] = None,
        tenant: str = DEFAULT_TENANT,
        lane: int = DEMAND,
        on_loser: Optional[Callable[[str, int], None]] = None,
    ) -> tuple[bytes, str]:
        """Run ``primary()``; past the tier's rolling p99, race
        ``hedge()`` against it. Returns ``(data, winner_tier)``. When
        both sides fail the PRIMARY error propagates, so the caller's
        tier waterfall degrades exactly as it does unhedged.

        A loser that *successfully* fetched is accounted exactly once —
        ``ntpu_peer_hedge_wasted_bytes_total`` plus the optional
        ``on_loser(loser_tier, nbytes)`` callback (the provenance
        ledger's hedge-loser waste record) — whether its result arrived
        before or after the winner was chosen."""
        threshold = self.threshold_ms(tier) if self.enabled else None
        t0 = perf_counter()
        if threshold is None or hedge is None:
            data = primary()
            self.record(tier, (perf_counter() - t0) * 1000.0)
            return data, tier

        cv = threading.Condition()
        results: dict[str, tuple] = {}
        decided: list[str] = []

        def lost(which: str, nbytes: int) -> None:
            HEDGE_WASTED_BYTES.inc(nbytes)
            if on_loser is not None:
                try:
                    on_loser(
                        (hedge_tier or "origin") if which == "hedge"
                        else tier,
                        nbytes,
                    )
                except Exception:  # noqa: BLE001 — accounting is advisory
                    pass

        def run(which: str, fn, charged: bool) -> None:
            t1 = perf_counter()
            try:
                out = (fn(), (perf_counter() - t1) * 1000.0, None)
            except BaseException as e:  # noqa: BLE001 — surfaced to the waiter
                out = (None, None, e)
            finally:
                if charged:
                    # Loser-cancellation invariant: the hedge's extra
                    # charge is released HERE, by the thread that owns
                    # it, win or lose — never by the winner's path.
                    self.gate.release(size, tenant=tenant, lane=lane)
            with cv:
                results[which] = out
                # Posted after the decision: this side lost the race and
                # its bytes are about to be discarded. (Posted before the
                # decision, the winner's path does this accounting — the
                # cv serializes the two, so exactly one side counts it.)
                late_loss = (
                    bool(decided)
                    and decided[0] != which
                    and out[2] is None
                    and out[0] is not None
                )
                cv.notify_all()
            if late_loss:
                lost(which, len(out[0]))

        threading.Thread(
            target=run,
            args=("primary", primary, False),
            name="ntpu-hedge-primary",
            daemon=True,
        ).start()
        with cv:
            cv.wait_for(
                lambda: "primary" in results, timeout=threshold / 1000.0
            )
            done = dict(results)
        hedged = False
        if "primary" not in done:
            # Past the tier's p99: fire the second request — IF the gate
            # admits its bytes right now (a hedge never queues) and the
            # chaos site lets it.
            try:
                failpoint.hit("peer.hedge")
                hedged = self.gate.try_acquire(size, tenant=tenant, lane=lane)
            except Exception:  # noqa: BLE001 — armed chaos aborts the
                hedged = False  # hedge, never the primary
            if hedged:
                self._count("fired")
                # Provenance: the bytes this flight delivers came out of
                # a hedge race (cause hedge_winner, whichever side wins).
                fetch_note("hedged", True)
                threading.Thread(
                    target=run,
                    args=("hedge", hedge, True),
                    name="ntpu-hedge-second",
                    daemon=True,
                ).start()
            else:
                self._count("skipped")
        want = {"primary", "hedge"} if hedged else {"primary"}
        while True:
            with cv:
                cv.wait_for(
                    lambda: len(results) > len(done)
                    or (want & set(results)) == want
                )
                done = dict(results)
            for which in ("hedge", "primary"):
                if which in done and done[which][2] is None:
                    if which == "hedge":
                        self._count("won")
                    elif hedged:
                        self._count("cancelled")
                    win_tier = tier if which == "primary" else (
                        hedge_tier or "origin"
                    )
                    other = "primary" if which == "hedge" else "hedge"
                    with cv:
                        decided.append(which)
                        o = results.get(other) if hedged else None
                    if o is not None and o[2] is None and o[0] is not None:
                        # The loser had already posted a good result when
                        # the race was decided: its bytes are waste.
                        lost(other, len(o[0]))
                    # Only the DELIVERED latency enters the rolling
                    # window: a cancelled loser's eventual completion
                    # was never observed by the caller, and recording
                    # it would let one persistently slow peer ratchet
                    # the p99 trigger up to its own latency, disarming
                    # the hedge that is routing around it.
                    self.record(win_tier, done[which][1])
                    return done[which][0], win_tier
            if (want & set(done)) == want:
                if hedged and done.get("hedge", (None, None, None))[2] is not None:
                    self._count("error")
                err = done["primary"][2]
                if isinstance(err, Exception):
                    raise err
                raise OSError(str(err))


_shared_hedger: Optional[Hedger] = None
_shared_hedger_lock = threading.Lock()


def shared_hedger() -> Hedger:
    """Process-wide hedger every peer-aware fetcher without an explicit
    one shares: the rolling per-tier latency windows are per NODE —
    every flight's sample sharpens every other flight's trigger."""
    global _shared_hedger
    with _shared_hedger_lock:
        if _shared_hedger is not None:
            return _shared_hedger
    # Build outside the lock (shared_gate takes its own module lock —
    # never nest the two); publish first-wins.
    enabled, window = resolve_hedge()
    hedger = Hedger(
        gate=shared_gate(), enabled=enabled, window=window, name="shared"
    )
    with _shared_hedger_lock:
        if _shared_hedger is None:
            _shared_hedger = hedger
        return _shared_hedger


def hedge_counters() -> dict:
    """Cumulative ``ntpu_peer_hedge_total`` values by outcome (ntpuctl
    and the storm profile delta these around a run)."""
    return {
        k: HEDGE_TOTAL.value(k)
        for k in ("fired", "won", "cancelled", "skipped", "error")
    }


# ---------------------------------------------------------------------------
# Flights + scheduler
# ---------------------------------------------------------------------------


class Flight:
    """One in-flight ranged fetch covering ``[start, end)``."""

    __slots__ = (
        "start", "end", "priority", "coalesced", "done", "error", "ctx",
        "tag",
    )

    def __init__(self, start: int, end: int, priority: int, coalesced: int = 1):
        self.start = start
        self.end = end
        self.priority = priority
        self.coalesced = coalesced  # miss gaps merged into this fetch
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        # Trace context of the read that PLANNED this flight — a
        # background readahead fetch thereby records which trace spawned
        # it, even though it executes on a worker thread later.
        self.ctx = None
        # Provenance cause override captured at plan time (fetch_tag),
        # carried the same way the trace context is.
        self.tag: Optional[str] = None

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)


class FetchScheduler:
    """Per-blob singleflight table + coalescing planner + worker pool.

    The scheduler shares its caller's lock (the CachedBlob lock): every
    ``plan_locked`` call and every delivery runs under that one lock, so
    interval state, the flight table and the cache file never disagree.
    ``fetch_range(offset, size)`` runs concurrently on worker threads and
    must be thread-safe; ``deliver(offset, data)`` is called back under
    the lock to persist a completed fetch.
    """

    def __init__(
        self,
        lock: threading.Lock,
        intervals: IntervalSet,
        fetch_range: Callable[[int, int], bytes],
        deliver: Callable[[int, bytes], None],
        config: Optional[FetchConfig] = None,
        budget: Optional[MemoryBudget] = None,
        name: str = "",
        gate: Optional[AdmissionGate] = None,
        tenant: str = DEFAULT_TENANT,
        on_fetched: Optional[Callable[["Flight", int], None]] = None,
    ):
        self.cfg = config or resolve_config()
        # QoS admission: an explicit gate wins; an explicit budget gets a
        # private pass-through gate (pre-QoS byte semantics preserved for
        # callers that isolate their budget); otherwise the process gate.
        if gate is not None:
            self.gate = gate
        elif budget is not None:
            self.gate = AdmissionGate(budget=budget, name=name or "private")
        else:
            self.gate = shared_gate()
        self.budget = self.gate.budget
        self.tenant = tenant
        self.name = name
        self._lock = lock
        self._cv = threading.Condition(lock)
        self._intervals = intervals
        self._fetch_range = fetch_range
        self._deliver = deliver
        # Called under the shared lock right after a delivery, with the
        # flight and its byte count — the provenance attribution hook.
        self._on_fetched = on_fetched
        self._flights: list[Flight] = []  # active (queued or fetching)
        # One FIFO per priority lane, popped in lane order.
        self._queues: tuple[deque[Flight], ...] = tuple(
            deque() for _ in range(N_LANES)
        )
        # Lockset annotation: flight table + queues must only ever be
        # touched under the shared lock (NTPU_ANALYZE=1 verifies).
        self._flights_shared = _an.shared(f"fetch.flights[{name}]")
        self._threads: list[threading.Thread] = []
        self._idle = 0
        self._closed = False

    # -- planning (caller holds the shared lock) ----------------------------

    def overlapping_flights(self, start: int, end: int) -> list[Flight]:
        return [f for f in self._flights if f.start < end and f.end > start]

    def plan_locked(
        self, start: int, end: int, priority: int = DEMAND
    ) -> list[Flight]:
        """Ensure ``[start, end)`` becomes resident: returns every flight
        the caller must wait on (pre-existing overlaps + newly created
        gap fetches). Caller holds the shared lock."""
        if self._closed:
            raise OSError(f"fetch scheduler {self.name!r} is closed")
        self._flights_shared.write()
        waiters = self.overlapping_flights(start, end)
        if waiters and priority == DEMAND:
            SINGLEFLIGHT_WAITS.inc()
            self._promote(waiters)
        # Gaps = uncovered minus already in flight.
        gaps: list[tuple[int, int]] = []
        for s, e in self._intervals.missing(start, end):
            pos = s
            for f in sorted(self.overlapping_flights(s, e), key=lambda f: f.start):
                if f.start > pos:
                    gaps.append((pos, f.start))
                pos = max(pos, f.end)
            if pos < e:
                gaps.append((pos, e))
        new = self._coalesce(gaps, priority)
        ctx = trace.capture() if new else None
        tag = current_fetch_tag() if new else None
        for f in new:
            f.ctx = ctx
            f.tag = tag
            self._flights.append(f)
            self._queues[f.priority].append(f)
        if new:
            self._spawn_workers(len(new))
            self._cv.notify_all()
        return waiters + new

    def _coalesce(self, gaps: list[tuple[int, int]], priority: int) -> list[Flight]:
        flights: list[Flight] = []
        for s, e in gaps:
            if (
                flights
                and s - flights[-1].end <= self.cfg.merge_gap
                and flights[-1].priority == priority
            ):
                failpoint.hit("blobcache.coalesce")
                flights[-1].end = e
                flights[-1].coalesced += 1
            else:
                flights.append(Flight(s, e, priority))
        return flights

    def _promote(self, flights: list[Flight]) -> None:
        """A demand read waits on these: lower-lane flights still queued
        jump to the demand queue so the reader isn't stuck behind other
        warming or peer-serve work."""
        for f in flights:
            if f.priority != DEMAND and f in self._queues[f.priority]:
                self._queues[f.priority].remove(f)
                f.priority = DEMAND
                self._queues[DEMAND].append(f)

    # -- worker pool ---------------------------------------------------------

    def _spawn_workers(self, backlog: int) -> None:
        if self._idle >= backlog:
            return
        want = min(self.cfg.fetch_workers, len(self._threads) + backlog - self._idle)
        while len(self._threads) < want:
            t = threading.Thread(
                target=self._worker,
                name=f"ntpu-fetch-{self.name}-{len(self._threads)}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._closed and not any(self._queues):
                    self._idle += 1
                    try:
                        self._cv.wait()
                    finally:
                        self._idle -= 1
                if self._closed and not any(self._queues):
                    return
                self._flights_shared.write()
                flight = next(q for q in self._queues if q).popleft()
            self._run_flight(flight)

    def _run_flight(self, flight: Flight) -> None:
        n = flight.end - flight.start
        acquired = False
        t0 = perf_counter()
        with trace.with_context(flight.ctx), trace.span(
            "blobcache.fetch",
            blob=self.name,
            offset=flight.start,
            bytes=n,
            coalesced=flight.coalesced,
            lane=LANE_NAMES[flight.priority],
            background=flight.priority != DEMAND,
        ) as sp:
            try:
                waited = self.gate.acquire(
                    n,
                    tenant=self.tenant,
                    lane=flight.priority,
                    aborted=lambda: self._closed,
                )
                acquired = True
                if waited > 0.001:
                    sp.annotate(admission_wait_ms=round(waited * 1000.0, 3))
                INFLIGHT_BYTES.set(self.budget.held)
                failpoint.hit("blobcache.fetch")
                take_fetch_notes()  # a prior flight's notes never leak in
                data = self._fetch_range(flight.start, n)
                FETCH_REQUESTS.inc()
                if flight.coalesced > 1:
                    COALESCED_REQUESTS.inc()
                MISS_BYTES.inc(n)
                with self._lock:
                    if not self._closed:
                        self._deliver(flight.start, data)
                        if self._on_fetched is not None:
                            # Attribution hook: same thread as the fetch
                            # (fetch notes are still this thread's), same
                            # lock as the delivery.
                            self._on_fetched(flight, len(data))
            except BaseException as e:  # noqa: BLE001 — surfaced to waiters
                flight.error = e if isinstance(e, Exception) else OSError(str(e))
                sp.annotate(error=repr(flight.error))
            finally:
                if acquired:
                    self.gate.release(
                        n, tenant=self.tenant, lane=flight.priority
                    )
                    INFLIGHT_BYTES.set(self.budget.held)
                with self._cv:
                    self._flights_shared.write()
                    try:
                        self._flights.remove(flight)
                    except ValueError:
                        pass
                    self._cv.notify_all()
                flight.done.set()
        OP_HIST.labels("fetch").observe((perf_counter() - t0) * 1000.0)

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Abort queued flights, wake workers, join the pool. Caller must
        NOT hold the shared lock (workers need it to finish delivering)."""
        with self._cv:
            self._closed = True
            self._flights_shared.write()
            aborted = [f for q in self._queues for f in q]
            for q in self._queues:
                q.clear()
            for f in aborted:
                try:
                    self._flights.remove(f)
                except ValueError:
                    pass
                f.error = OSError(f"fetch scheduler {self.name!r} closed")
                f.done.set()
            self._cv.notify_all()
        for t in self._threads:
            t.join()
        self._threads.clear()


# ---------------------------------------------------------------------------
# Background prefetch replay
# ---------------------------------------------------------------------------


class PrefetchReplayer:
    """Replays a prefetch file list through the bootstrap chunk index to
    warm blob caches off the critical path.

    ``warm_chunk(rec)`` is provided by the owner (daemon/server.py): for
    registry-backed blobs it routes the chunk's compressed extent through
    the fetch scheduler at BACKGROUND priority; any other backend falls
    back to a plain read. The replayer owns cancellation: ``cancel()``
    (umount/close) stops the walk between chunks and is also observed by
    in-flight waits, so teardown never blocks on a cold registry.
    """

    def __init__(
        self,
        bootstrap,
        by_path: dict,
        warm_chunk: Callable[[object], int],
        name: str = "",
        on_file: Optional[Callable[[], None]] = None,
    ):
        self.bootstrap = bootstrap
        self.by_path = by_path
        self.warm_chunk = warm_chunk
        self.name = name
        self.on_file = on_file  # e.g. one batched chunk-map flush per file
        self.warmed_bytes = 0
        self.files_replayed = 0
        self._cancel = threading.Event()

    @property
    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def cancel(self) -> None:
        self._cancel.set()

    @staticmethod
    def paths_from_trace(trace_path: str, strip_prefix: str = "") -> list[str]:
        """Fanotify/optimizer access trace → ordered path list (first
        access first — that IS the replay priority)."""
        from nydus_snapshotter_tpu.prefetch.prefetch import patterns_from_trace

        text = patterns_from_trace(trace_path, strip_prefix=strip_prefix)
        return [p for p in text.split("\n") if p]

    def replay(self, paths: list[str]) -> int:
        """Warm every chunk of every path, in order; returns bytes warmed.
        Per-file errors are contained (prefetch lists are hints)."""
        import logging

        log = logging.getLogger(__name__)
        for path in paths:
            if self._cancel.is_set():
                break
            failpoint.hit("blobcache.replay")
            inode = self.by_path.get(path)
            if inode is None:
                continue
            if inode.hardlink_target:
                inode = self.by_path.get(inode.hardlink_target) or inode
            try:
                for rec in self.bootstrap.chunks[
                    inode.chunk_index : inode.chunk_index + inode.chunk_count
                ]:
                    if self._cancel.is_set():
                        break
                    n = self.warm_chunk(rec)
                    self.warmed_bytes += n
                    PREFETCH_BYTES.inc(n)
            except Exception:  # noqa: BLE001 — one bad hint must not
                # abandon the rest of the list
                log.warning("prefetch replay of %s failed", path, exc_info=True)
                continue
            if self._cancel.is_set():
                break  # cancelled mid-file: it was not fully replayed
            self.files_replayed += 1
            if self.on_file is not None:
                self.on_file()
        return self.warmed_bytes
